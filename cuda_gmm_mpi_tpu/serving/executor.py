"""AOT-compiled scoring executables with a bucketed LRU cache.

The latency problem this solves: ``jax.jit`` keys its executable cache on
input SHAPES, so a scoring service whose requests vary in row count N
retraces-and-recompiles on every new N -- tens of milliseconds to seconds
of tracing in front of a sub-millisecond posterior pass, on EVERY
distinct batch size. This module makes both halves of that cost
front-loadable and bounded:

- **Bucketing** (the PR-2 pow2 policy, ``state.bucket_width``, applied to
  the EVENT axis): a request of N rows is padded up to the smallest
  power-of-two block >= N (clamped to [min_block, max_block]; larger
  requests split into max_block slices), and the model's K axis is padded
  to its pow2 bucket with algebraically inert inactive slots
  (``parallel.sharded_em.pad_state_clusters``). The executable universe
  is therefore (kinds x log2 blocks x log2 K-buckets) -- small, and
  *independent of traffic*.
- **AOT compilation**: each bucket's executable is built ONCE via
  ``jit(...).lower(shapes).compile()`` -- explicit ahead-of-time
  lowering, so a warmed bucket can never trace or compile again, and a
  cold server can pre-compile its buckets before taking traffic
  (:meth:`ScoringExecutor.warmup`).
- **Donation**: the padded request block is donated to the executable
  (``donate_argnums``), so the scoring pass reuses the input buffer in
  place instead of allocating a second [B, D] block per request.
- **LRU bound**: at most ``max_executables`` live compiled programs;
  least-recently-used ones are dropped (and re-compiled on next use --
  counted, so an undersized cache is observable, not silent).

Hit/miss/compile/eviction counters are plain attributes; the serving
loop folds them into the telemetry stream and the warm-path
zero-recompile tests assert on ``compile_count`` directly.
"""

from __future__ import annotations

import collections
import functools
from typing import Dict, Optional, Tuple

import numpy as np

from ..state import GMMState
from ..telemetry import profiling as tl_profiling

# Executable kinds: 'proba' returns (responsibilities [B, K], logZ [B]);
# 'assign' returns (argmax labels int32 [B], logZ [B]) -- the hard-
# assignment path never transfers the [B, K] posterior block.
KINDS = ("proba", "assign")


def pow2_bucket(n: int, lo: int = 1, hi: Optional[int] = None) -> int:
    """Smallest power of two >= ``n``, clamped to [lo, hi].

    The event-axis spelling of the sweep's ``state.bucket_width`` pow2
    policy: both bound the distinct compiled shapes to one per octave.
    ``hi`` callers split/pad beyond the cap themselves.
    """
    b = 1 << max(0, int(n) - 1).bit_length()
    b = max(b, int(lo))
    if hi is not None:
        b = min(b, int(hi))
    return b


class ScoringExecutor:
    """Bucketed AOT executable cache for predict/score under one numeric
    family (dtype x covariance structure x quad layout x precision).

    One executor serves any number of models sharing the family: the
    compiled programs are keyed by (kind, block, K-bucket, D), so two
    16-cluster models of the same D share every executable.
    """

    def __init__(self, *, dtype: str = "float32", diag_only: bool = False,
                 quad_mode: str = "expanded",
                 matmul_precision: str = "highest",
                 min_block: int = 256, max_block: int = 65536,
                 max_executables: int = 32):
        if min_block < 1 or max_block < min_block:
            raise ValueError(
                f"need 1 <= min_block <= max_block, got "
                f"{min_block}/{max_block}")
        if max_executables < 1:
            raise ValueError("max_executables must be >= 1")
        self._dtype = np.dtype(dtype)
        self._diag_only = bool(diag_only)
        self._quad_mode = quad_mode
        self._precision = matmul_precision
        self._min_block = int(min_block)
        self._max_block = int(max_block)
        self._max_execs = int(max_executables)
        # key -> compiled executable, LRU order (oldest first).
        self._cache: "collections.OrderedDict[tuple, object]" = \
            collections.OrderedDict()
        # (id(state), k_bucket) -> (state ref, padded+cast state). The
        # strong state ref pins the id against recycling; bounded LRU.
        self._state_memo: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()
        # Device-resident route states (docs/SERVING.md "Device-resident
        # routes"): same key shape as the memo, but EXEMPT from its LRU
        # bound -- a pinned route's prepared state stays resident until
        # release_state, so warm dispatches never re-place leaves
        # host->device. Bounded by the served route set, which the
        # server already bounds.
        self._pinned: Dict[tuple, tuple] = {}
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.evictions = 0
        # Dispatch-time state preparations that could NOT be served from
        # a pinned entry -- the silent fallback to per-request staging
        # the serve.host_staging counter makes observable.
        self.host_stagings = 0

    # -- observability ---------------------------------------------------

    @property
    def compile_count(self) -> int:
        """Total AOT compilations so far (the zero-recompile assertion
        target: warm traffic must not move this)."""
        return self.compiles

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "compiles": self.compiles, "evictions": self.evictions,
                "live_executables": len(self._cache),
                "pinned_states": len(self._pinned),
                "host_stagings": self.host_stagings}

    def cached_keys(self) -> Tuple[tuple, ...]:
        return tuple(self._cache.keys())

    # -- bucketing -------------------------------------------------------

    def block_for(self, n: int) -> int:
        """The padded block size an ``n``-row slice dispatches at."""
        return pow2_bucket(n, lo=self._min_block, hi=self._max_block)

    def blocks_for(self, n: int):
        """(start, length, block) slices covering an N-row request."""
        out = []
        start = 0
        while start < n:
            m = min(n - start, self._max_block)
            out.append((start, m, self.block_for(m)))
            start += m
        return out or [(0, 0, self._min_block)]

    def padded_rows(self, n: int) -> int:
        """Total dispatched rows for an N-row request (telemetry)."""
        return sum(b for _, _, b in self.blocks_for(n)) if n else 0

    # -- state preparation ----------------------------------------------

    def _resolve_bucket(self, state: GMMState,
                        k_bucket: Optional[int]) -> int:
        kb = pow2_bucket(state.num_clusters_padded)
        if k_bucket is not None:
            kb = max(kb, int(k_bucket))
        return kb

    def _prepare(self, state: GMMState, kb: int) -> GMMState:
        """Cast ``state`` to the executor dtype and K-pad to ``kb`` with
        inert inactive slots -- the one host->device placement both the
        memo and pin planes cache."""
        import jax.numpy as jnp

        from ..parallel.sharded_em import pad_state_clusters

        dt = jnp.dtype(self._dtype)
        cast = state.replace(
            N=jnp.asarray(state.N, dt), pi=jnp.asarray(state.pi, dt),
            constant=jnp.asarray(state.constant, dt),
            avgvar=jnp.asarray(state.avgvar, dt),
            means=jnp.asarray(state.means, dt),
            R=jnp.asarray(state.R, dt), Rinv=jnp.asarray(state.Rinv, dt),
            active=jnp.asarray(state.active, bool))
        return pad_state_clusters(cast, kb)

    def pin_state(self, state: GMMState,
                  k_bucket: Optional[int] = None) -> GMMState:
        """Pin ``state``'s prepared form device-resident (the route-
        prepare half of the device-resident serving plane): later
        dispatches hit the resident handle instead of re-placing leaves,
        and the entry survives any amount of cross-route traffic --
        unlike the LRU-8 dispatch memo. Idempotent per (state, bucket);
        released by :meth:`release_state` exactly as the memo is."""
        kb = self._resolve_bucket(state, k_bucket)
        key = (id(state), kb)
        hit = self._pinned.get(key)
        if hit is not None and hit[0] is state:
            return hit[1]
        padded = self._prepare(state, kb)
        self._pinned[key] = (state, padded)
        return padded

    def prepared_state(self, state: GMMState,
                       k_bucket: Optional[int] = None) -> GMMState:
        """``state`` cast to the executor dtype and K-padded to its pow2
        bucket with inert inactive slots; served from the pinned plane
        when the route was pinned (:meth:`pin_state`), else memoized per
        state object.

        ``k_bucket`` overrides the bucket upward (stacked cross-model
        dispatches pad every participant to the family's shared width;
        inactive slots are algebraically inert, so a wider pad never
        changes a model's scores). A wider-bucket variant of a PINNED
        state pins too -- the route is resident, so its stacked pad
        should be -- while preparing an unpinned state at dispatch time
        counts ``host_stagings``: the observable fallback to
        per-request staging."""
        kb = self._resolve_bucket(state, k_bucket)
        key = (id(state), kb)
        hit = self._pinned.get(key)
        if hit is not None and hit[0] is state:
            return hit[1]
        hit = self._state_memo.get(key)
        if hit is not None and hit[0] is state:
            self._state_memo.move_to_end(key)
            return hit[1]
        padded = self._prepare(state, kb)
        if any(v[0] is state for v in self._pinned.values()):
            self._pinned[key] = (state, padded)
            return padded
        self.host_stagings += 1
        self._state_memo[key] = (state, padded)
        while len(self._state_memo) > 8:
            self._state_memo.popitem(last=False)
        return padded

    def release_state(self, state: GMMState) -> int:
        """Drop ``state``'s prepared-state memo AND pinned entries (a
        hot-reload replaced its registry version, serving/server.py).
        Compiled executables stay -- they are keyed by shapes and shared
        across models -- and a later pinned-version request simply
        re-prepares the state. Returns the number of entries released."""
        dead = [k for k, v in self._state_memo.items() if v[0] is state]
        for k in dead:
            del self._state_memo[k]
        pinned_dead = [k for k, v in self._pinned.items()
                       if v[0] is state]
        for k in pinned_dead:
            del self._pinned[k]
        return len(dead) + len(pinned_dead)

    # -- executables -----------------------------------------------------

    def _executable(self, kind: str, block: int, kb: int, d: int):
        key = (kind, block, kb, d)
        fn = self._cache.get(key)
        if fn is not None:
            self.hits += 1
            self._cache.move_to_end(key)
            return fn
        self.misses += 1
        # site_compile (rev v2.2): a passthrough with no CompileWatch
        # active; under one, the build is timed and its cost/memory
        # analyses land on the stream as an enriched ``compile`` event.
        fn = tl_profiling.site_compile(
            "serve", lambda: self._build(kind, block, kb, d),
            key=f"{kind}:{block}x{d}:k{kb}")
        self.compiles += 1
        self._cache[key] = fn
        while len(self._cache) > self._max_execs:
            self._cache.popitem(last=False)
            self.evictions += 1
        return fn

    def _build(self, kind: str, block: int, kb: int, d: int):
        """Lower-and-compile one (kind, shapes) scoring program.

        Explicit AOT: ``jit(...).lower(abstract shapes).compile()`` --
        the compiled object is shape-committed, so serving it can never
        trace. The request block (argument 1) is donated.
        """
        import jax
        import jax.numpy as jnp

        from ..ops.estep import posteriors

        if kind not in KINDS:
            raise ValueError(f"unknown executable kind {kind!r}")
        if self._dtype == np.float64 and not jax.config.jax_enable_x64:
            # Same guard as the fit path: a float64 model silently served
            # in float32 would score under truncated densities.
            raise ValueError(
                "dtype='float64' needs jax_enable_x64; set "
                "jax.config.update('jax_enable_x64', True) at startup")
        post = functools.partial(
            posteriors, diag_only=self._diag_only,
            quad_mode=self._quad_mode,
            matmul_precision=self._precision)

        if kind == "assign":
            def fn(state, x):
                w, logz = post(state, x)
                return jnp.argmax(w, axis=1).astype(jnp.int32), logz
        else:
            def fn(state, x):
                return post(state, x)

        dt = jnp.dtype(self._dtype)
        sds = jax.ShapeDtypeStruct
        state_struct = GMMState(
            N=sds((kb,), dt), pi=sds((kb,), dt), constant=sds((kb,), dt),
            avgvar=sds((kb,), dt), means=sds((kb, d), dt),
            R=sds((kb, d, d), dt), Rinv=sds((kb, d, d), dt),
            active=sds((kb,), jnp.bool_))
        x_struct = sds((block, d), dt)
        # Donate the request block where donation exists (the CPU backend
        # has no aliasing support and would warn on every compile).
        donate = (1,) if jax.default_backend() != "cpu" else ()
        return jax.jit(fn, donate_argnums=donate).lower(
            state_struct, x_struct).compile()

    def _executable_stacked(self, models: int, block: int, kb: int,
                            d: int):
        """Lower-and-compile one STACKED scoring program: ``models``
        lanes of (state, request block) scored by a ``lax.map`` over the
        model axis -- ONE dispatch for several different models of one
        numeric family (the cross-model coalescing the tick loop's
        per-(model, version) grouping alone cannot get). ``lax.map``
        (not vmap) keeps each lane's arithmetic the exact HLO of the
        solo 'proba' executable, so stacked responses are BIT-IDENTICAL
        to per-model dispatches (the parity contract,
        tests/test_serving.py). Shares the LRU cache/counters with the
        per-model executables under key ('stacked', M, block, kb, d).
        """
        key = ("stacked", models, block, kb, d)
        fn = self._cache.get(key)
        if fn is not None:
            self.hits += 1
            self._cache.move_to_end(key)
            return fn
        self.misses += 1

        import jax
        import jax.numpy as jnp

        from ..ops.estep import posteriors

        if self._dtype == np.float64 and not jax.config.jax_enable_x64:
            raise ValueError(
                "dtype='float64' needs jax_enable_x64; set "
                "jax.config.update('jax_enable_x64', True) at startup")
        post = functools.partial(
            posteriors, diag_only=self._diag_only,
            quad_mode=self._quad_mode,
            matmul_precision=self._precision)

        def stacked(states, x):
            return jax.lax.map(lambda args: post(args[0], args[1]),
                               (states, x))

        dt = jnp.dtype(self._dtype)
        sds = jax.ShapeDtypeStruct
        state_struct = GMMState(
            N=sds((models, kb), dt), pi=sds((models, kb), dt),
            constant=sds((models, kb), dt),
            avgvar=sds((models, kb), dt),
            means=sds((models, kb, d), dt),
            R=sds((models, kb, d, d), dt),
            Rinv=sds((models, kb, d, d), dt),
            active=sds((models, kb), jnp.bool_))
        x_struct = sds((models, block, d), dt)
        donate = (1,) if jax.default_backend() != "cpu" else ()
        fn = tl_profiling.site_compile(
            "serve_stacked",
            lambda: jax.jit(stacked, donate_argnums=donate).lower(
                state_struct, x_struct).compile(),
            key=f"stacked{models}:{block}x{d}:k{kb}")
        self.compiles += 1
        self._cache[key] = fn
        while len(self._cache) > self._max_execs:
            self._cache.popitem(last=False)
            self.evictions += 1
        return fn

    def stackable_rows(self, n: int) -> bool:
        """Whether an ``n``-row request fits one stacked lane (requests
        past ``max_block`` split into slices, which the stacked layout
        does not model -- they dispatch per-model instead)."""
        return 0 < int(n) <= self._max_block

    def infer_stacked(self, states, Xs):
        """Score several DIFFERENT models' requests in one dispatch.

        ``states[i]`` scores ``Xs[i]`` ([n_i, D], all same D and all
        within ``max_block``). Every lane pads to the family-shared
        (row-block, K-bucket) -- pad rows/slots are discarded before
        return, and the model axis pads to its pow2 bucket with
        duplicate lanes, so the executable universe stays bounded at
        (log2 models x log2 blocks x log2 K-buckets). Returns
        ``([(w [n_i, K_bucket_i], logz [n_i]), ...], padded_block)``
        with per-lane host numpy arrays sliced back to each model's own
        rows and K bucket.
        """
        import jax
        import jax.numpy as jnp

        if len(states) != len(Xs) or not states:
            raise ValueError("infer_stacked needs one X per state")
        M = len(states)
        xs = [np.ascontiguousarray(np.asarray(x, self._dtype))
              for x in Xs]
        d = xs[0].shape[1]
        for x in xs:
            if x.ndim != 2 or x.shape[1] != d:
                raise ValueError(
                    f"stacked requests must share D={d}, got {x.shape}")
            if not self.stackable_rows(x.shape[0]):
                raise ValueError(
                    f"stacked lane of {x.shape[0]} rows exceeds "
                    f"max_block={self._max_block}")
        block = max(self.block_for(x.shape[0]) for x in xs)
        own_kb = [pow2_bucket(s.num_clusters_padded) for s in states]
        kb = max(own_kb)
        prepared = [self.prepared_state(s, k_bucket=kb) for s in states]
        mb = pow2_bucket(M)
        lanes = prepared + [prepared[0]] * (mb - M)
        stacked_state = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *lanes)
        xb = np.zeros((mb, block, d), self._dtype)
        for i, x in enumerate(xs):
            xb[i, :x.shape[0]] = x
        run = self._executable_stacked(mb, block, kb, d)
        w, logz = run(stacked_state, jnp.asarray(xb))
        w, logz = jax.device_get((w, logz))
        out = []
        for i, x in enumerate(xs):
            n = x.shape[0]
            out.append((np.asarray(w)[i, :n, :own_kb[i]],
                        np.asarray(logz)[i, :n]))
        return out, block

    def warmup(self, state: GMMState, d: Optional[int] = None,
               kinds=("proba",), blocks=None) -> int:
        """Pre-compile the executables a model's traffic will hit (cold
        servers call this before accepting requests). Returns the number
        of NEW compilations."""
        ps = self.prepared_state(state)
        d = int(d or ps.num_dimensions)
        kb = ps.num_clusters_padded
        before = self.compiles
        for kind in kinds:
            for block in (blocks or (self._min_block,)):
                self._executable(kind, int(block), kb, d)
        return self.compiles - before

    # -- inference -------------------------------------------------------

    def infer(self, state: GMMState, X, *, want: str = "proba"):
        """Score ``X`` [N, D] under ``state``; returns host numpy arrays.

        ``want='proba'`` -> (w [N, K_bucket], logz [N]);
        ``want='assign'`` -> (labels int32 [N], logz [N]).
        N is bucketed/split per the block policy; every padded row is
        garbage discarded before return (rows are independent through
        the per-event log-sum-exp, so padding never perturbs real rows).
        """
        import jax

        X = np.ascontiguousarray(np.asarray(X, self._dtype))
        if X.ndim != 2:
            raise ValueError(f"X must be [n_events, n_dims], got {X.shape}")
        n, d = X.shape
        ps = self.prepared_state(state)
        if d != ps.num_dimensions:
            raise ValueError(
                f"model has D={ps.num_dimensions} but X has D={d}")
        kb = ps.num_clusters_padded
        if n == 0:
            first = (np.zeros((0, kb), self._dtype) if want == "proba"
                     else np.zeros((0,), np.int32))
            return first, np.zeros((0,), self._dtype)
        outs_a, outs_z = [], []
        for start, m, block in self.blocks_for(n):
            xb = np.zeros((block, d), self._dtype)
            xb[:m] = X[start:start + m]
            run = self._executable(want, block, kb, d)
            a, z = run(ps, xb)
            a, z = jax.device_get((a, z))
            outs_a.append(np.asarray(a)[:m])
            outs_z.append(np.asarray(z)[:m])
        return (np.concatenate(outs_a, axis=0),
                np.concatenate(outs_z, axis=0))

    def predict_proba(self, state: GMMState, X, k: Optional[int] = None):
        """Posterior responsibilities [N, k] (k = the model's true
        cluster count; defaults to the state's padded width)."""
        w, _ = self.infer(state, X, want="proba")
        return w[:, :int(k or state.num_clusters_padded)]

    def predict(self, state: GMMState, X):
        labels, _ = self.infer(state, X, want="assign")
        return labels

    def score_samples(self, state: GMMState, X):
        return self.infer(state, X, want="assign")[1]

    def score(self, state: GMMState, X) -> float:
        return float(np.mean(self.score_samples(state, X)))


@functools.lru_cache(maxsize=None)
def _shared_executor(dtype: str, diag_only: bool, quad_mode: str,
                     matmul_precision: str, max_block: int,
                     min_block: int = 256) -> ScoringExecutor:
    max_block = max(1, int(max_block))
    return ScoringExecutor(dtype=dtype, diag_only=diag_only,
                           quad_mode=quad_mode,
                           matmul_precision=matmul_precision,
                           # Small-chunk configs (tests fit with
                           # chunk_size < 256) cap the floor too.
                           min_block=min(int(min_block), max_block),
                           max_block=max_block)


def executor_for_config(config) -> ScoringExecutor:
    """The process-shared executor for one :class:`GMMConfig` family.

    Keyed by the fields that change compiled code (dtype, covariance
    structure, quad layout, precision, block cap) so every estimator of
    a family shares one executable cache -- N estimators cost one
    compile per bucket, not N.
    """
    return _shared_executor(config.dtype, bool(config.diag_only),
                            config.quad_mode, config.matmul_precision,
                            int(config.chunk_size))


def executor_for_model(model: "ServedModel",
                       **kw) -> ScoringExecutor:  # noqa: F821
    """The shared executor for one registry :class:`ServedModel`.

    ``min_block``/``max_block`` overrides come from the serving
    autotuner (``tuning.resolve_serving_blocks``) when the server runs
    with ``--autotune db``; the defaults are the hand-set pre-tuner
    geometry.
    """
    return _shared_executor(model.dtype, model.diag_only,
                            kw.pop("quad_mode", "expanded"),
                            kw.pop("matmul_precision", "highest"),
                            kw.pop("max_block", 65536),
                            kw.pop("min_block", 256))
