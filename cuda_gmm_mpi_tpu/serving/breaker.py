"""Per-route circuit breakers for the serving loop.

A registry model whose artifact scores non-finite densities (or whose
version directory went unreadable under the server) would otherwise fail
EVERY request routed to it, forever, while paying the full dispatch cost
each time -- the serving analog of the fit path's NaN-"converges" hole
(docs/ROBUSTNESS.md). The breaker contains that failure to its own
(model, version) route:

```
          consecutive failures >= threshold
 CLOSED  ----------------------------------->  OPEN
   ^                                            |
   | success                                    | backoff elapsed
   |                                            v
   +------------------------------------  HALF_OPEN
                     (a failed probe re-opens with doubled backoff)
```

- **closed**: requests dispatch normally; any success clears the
  consecutive-failure count.
- **open**: requests fast-fail with ``circuit_open`` BEFORE model
  resolution or dispatch -- a poisoned model costs a dict lookup, not an
  executor call -- while every other route keeps serving.
- **half-open**: after a jittered exponential backoff (the
  ``checkpoint_retries`` shape from utils/checkpoint.py: doubling base
  with +-25% deterministic jitter, seeded per (route, trip) so a fleet
  of servers desynchronizes their probes), traffic is admitted again;
  the first recorded outcome decides -- success closes the breaker,
  failure re-opens it with a doubled backoff.

What counts as a route failure is the caller's contract
(serving/server.py): a ``RegistryError`` at resolve, an executor
dispatch/compile error, or the cheap post-dispatch non-finite score
check. Request-content errors (bad D, NaN rows in ``x``) never touch
the breaker -- they are the client's fault, not the model's.

State transitions emit ``circuit`` telemetry events (stream rev v1.7,
docs/OBSERVABILITY.md) so an opened route is observable in the stream,
not just as a burst of failed requests.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Hashable, Optional, Tuple

# First-reopen backoff; doubles per consecutive trip of one route.
BACKOFF_BASE_S = 1.0
BACKOFF_MAX_S = 60.0


def _jitter(route: Hashable, trip: int) -> float:
    """+-25% deterministic jitter (the checkpoint-retry recipe), seeded
    per (route, trip) so concurrent servers' half-open probes spread."""
    seed = hash((route, int(trip))) & 0xFFFFFFFF
    return 0.75 + 0.5 * random.Random(seed).random()


class _Route:
    __slots__ = ("state", "failures", "trips", "until", "last_reason")

    def __init__(self):
        self.state = "closed"
        self.failures = 0     # consecutive failures since the last success
        self.trips = 0        # consecutive opens (resets on close)
        self.until = 0.0      # monotonic time the open state ends
        self.last_reason: Optional[str] = None


class CircuitBreakers:
    """Breaker state for every (model, version) route of one server.

    ``threshold`` consecutive failures open a route; ``backoff_base_s``
    seeds the open window, doubling per consecutive trip up to
    ``backoff_max_s``. All methods are single-lock cheap -- the serve
    tick loop calls them on every dispatch.
    """

    def __init__(self, *, threshold: int = 3,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 backoff_max_s: float = BACKOFF_MAX_S):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._routes: Dict[Hashable, _Route] = {}
        self._lock = threading.Lock()
        self.trips = 0        # total opens across every route
        self.closes = 0       # total recoveries (open/half-open -> closed)

    # -- admission --------------------------------------------------------

    def admit(self, route: Tuple[str, Optional[int]]
              ) -> Optional[Dict[str, Any]]:
        """None when ``route`` may dispatch; a fast-fail info dict
        (``{"retry_in_s": ...}``) while its breaker is open.

        An open route whose backoff elapsed transitions to half-open and
        IS admitted -- that dispatch is the probe whose outcome closes or
        re-opens the breaker.
        """
        with self._lock:
            r = self._routes.get(route)
            if r is None or r.state == "closed":
                return None
            if r.state == "open":
                now = time.monotonic()
                if now < r.until:
                    return {"retry_in_s": max(0.0, r.until - now)}
                r.state = "half_open"
                self._emit(route, r, "half_open")
                return None
            return None  # half_open: admit; the recorded outcome decides

    # -- outcomes ---------------------------------------------------------

    def record_success(self, route) -> None:
        """A dispatch on ``route`` produced finite scores: close."""
        with self._lock:
            r = self._routes.get(route)
            if r is None:
                return
            r.failures = 0
            if r.state != "closed":
                r.state = "closed"
                r.trips = 0
                self.closes += 1
                self._emit(route, r, "closed")

    def record_failure(self, route, reason: str) -> bool:
        """A dispatch (or resolve) on ``route`` failed; True when the
        route is now open. A half-open probe failure re-opens
        immediately with a doubled backoff."""
        with self._lock:
            r = self._routes.setdefault(route, _Route())
            r.failures += 1
            r.last_reason = reason
            if r.state != "half_open" and r.failures < self.threshold:
                return False
            r.trips += 1
            backoff = min(self.backoff_base_s * (2.0 ** (r.trips - 1)),
                          self.backoff_max_s) * _jitter(route, r.trips)
            r.state = "open"
            r.until = time.monotonic() + backoff
            self.trips += 1
            self._emit(route, r, "open", backoff_s=round(backoff, 4))
            return True

    def reset(self, route) -> None:
        """Forget ``route``'s state (hot-reload swapped its model: the
        new version starts with a clean, closed breaker)."""
        with self._lock:
            self._routes.pop(route, None)

    # -- observability ----------------------------------------------------

    def state(self, route) -> str:
        with self._lock:
            r = self._routes.get(route)
            return r.state if r is not None else "closed"

    def open_routes(self) -> int:
        with self._lock:
            return sum(1 for r in self._routes.values()
                       if r.state != "closed")

    def stats(self) -> Dict[str, int]:
        return {"trips": int(self.trips), "closes": int(self.closes),
                "open_routes": self.open_routes()}

    def _emit(self, route, r: _Route, state: str, **extra) -> None:
        # Called under self._lock; the recorder has its own lock and
        # never calls back into the breaker.
        from .. import telemetry

        rec = telemetry.current()
        if not rec.active:
            return
        name, version = route
        fields: Dict[str, Any] = {"model": name, "state": state,
                                  "failures": int(r.failures),
                                  "trips": int(r.trips)}
        if version is not None:
            fields["version"] = int(version)
        if r.last_reason:
            fields["reason"] = r.last_reason
        fields.update(extra)
        rec.emit("circuit", **fields)
        if state == "open":
            rec.metrics.count("serve_breaker_trips")
