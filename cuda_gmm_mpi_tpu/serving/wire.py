"""Zero-copy binary row payloads: the ``application/x-gmm-rows`` frame.

The serving data plane's JSON bottleneck is the request body: a D=16
batch of 4096 rows costs ~65k float literals parsed one at a time into
Python objects, then a list-of-lists walk in ``np.asarray``. This module
defines the versioned little-endian frame both network front ends accept
instead (docs/SERVING.md "Binary payloads"), decoded straight into the
dispatch buffer via ``np.frombuffer`` -- no JSON float parsing, no
intermediate Python lists.

Frame layout (16-byte header, little-endian, then packed rows)::

    offset  size  field
    0       4     magic  b"GMR1" (format version rides in the magic)
    4       1     dtype  0 = float32, 1 = float64
    5       1     reserved (must be 0)
    6       2     reserved (must be 0)
    8       4     u32 D  columns per row
    12      4     u32 N  rows
    16      N*D*itemsize  row-major packed rows

Transport bindings:

- **HTTP** (serving/http.py): a scoring POST with ``Content-Type:
  application/x-gmm-rows`` carries one frame as its entire body; model,
  op, and version ride the URL exactly as for JSON bodies, and the
  deadline rides the ``X-GMM-Deadline-Ms`` header. Responses stay JSON
  either way -- the bit-identity contract is on response bytes.
- **JSONL socket** (serving/server.py): a header line
  ``{"model": ..., "op": ..., "x_bytes": <frame length>}`` -- ``x_bytes``
  REPLACING ``"x"`` -- is followed immediately by exactly that many raw
  frame bytes (a length-prefixed frame; the JSONL framing itself is
  unchanged for JSON requests).

Error taxonomy: a malformed frame (bad magic, truncated or trailing
bytes, absurd shape) answers the machine token ``bad_frame`` -- HTTP 400
via the ``status_for_error`` default -- and an oversized declared frame
answers ``frame_too_large`` before any buffering.

Bit-parity: the JSON path parses ``x`` to float64 before the executor
cast, so a float64 frame of the same values dispatches bit-identically
to its JSON spelling (the parity tests in tests/test_wire.py). A
float32 frame skips the double rounding -- use it only when the client
already holds float32 rows.
"""

from __future__ import annotations

import struct

import numpy as np

#: The HTTP media type a binary scoring body declares.
CONTENT_TYPE = "application/x-gmm-rows"

MAGIC = b"GMR1"
HEADER = struct.Struct("<4sBBHII")  # magic, dtype, pad8, pad16, D, N
HEADER_BYTES = HEADER.size  # 16

_DTYPE_CODES = {0: np.dtype("<f4"), 1: np.dtype("<f8")}
_CODE_FOR = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}


class WireError(ValueError):
    """A frame that violates the x-gmm-rows layout (bad magic, truncated
    or trailing payload, unknown dtype code, zero-D shape)."""


def encode_rows(x: np.ndarray) -> bytes:
    """Pack a ``[n, d]`` float32/float64 row block into one frame.

    Any other dtype (ints, a JSON-parsed object array) is encoded as
    float64 -- exactly the dtype the JSON request path parses into, so
    the two spellings of one request stay bit-identical.
    """
    x = np.asarray(x)
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim != 2:
        raise WireError(f"rows must be [n, d], got shape {x.shape}")
    if x.dtype not in _CODE_FOR:
        x = x.astype(np.float64)
    x = np.ascontiguousarray(x, dtype=x.dtype.newbyteorder("<"))
    header = HEADER.pack(MAGIC, _CODE_FOR[np.dtype(x.dtype.name)], 0, 0,
                         int(x.shape[1]), int(x.shape[0]))
    return header + x.tobytes()


def frame_bytes(n: int, d: int, dtype) -> int:
    """Total frame size for an ``[n, d]`` block of ``dtype`` rows."""
    return HEADER_BYTES + int(n) * int(d) * np.dtype(dtype).itemsize


def decode_rows(buf: bytes) -> np.ndarray:
    """Unpack one frame into a read-only ``[n, d]`` ndarray view.

    The row block is a ``np.frombuffer`` view over ``buf`` -- zero-copy;
    the serving dispatch concatenates/shifts it into its own buffer, so
    the view's read-only flag never bites. Raises :class:`WireError` on
    any layout violation; the buffer must contain EXACTLY one frame
    (trailing bytes are an error, not ignored -- a client that
    mis-computed ``x_bytes`` must hear about it).
    """
    if len(buf) < HEADER_BYTES:
        raise WireError(
            f"frame truncated: {len(buf)} bytes is shorter than the "
            f"{HEADER_BYTES}-byte header")
    magic, code, pad8, pad16, d, n = HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {MAGIC!r})")
    if pad8 or pad16:
        raise WireError("reserved header bytes must be zero")
    dt = _DTYPE_CODES.get(code)
    if dt is None:
        raise WireError(f"unknown dtype code {code} (0=f32, 1=f64)")
    if d <= 0:
        raise WireError("frame declares D=0 columns")
    want = HEADER_BYTES + n * d * dt.itemsize
    if len(buf) < want:
        raise WireError(
            f"frame truncated: header declares {n}x{d} "
            f"{dt.name} rows ({want} bytes), got {len(buf)}")
    if len(buf) > want:
        raise WireError(
            f"frame has {len(buf) - want} trailing bytes past the "
            f"declared {n}x{d} {dt.name} payload")
    rows = np.frombuffer(buf, dtype=dt, count=n * d,
                         offset=HEADER_BYTES)
    return rows.reshape(n, d)
