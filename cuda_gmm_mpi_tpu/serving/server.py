"""Micro-batched scoring server: the ``gmm serve`` request loop.

The third serving layer (docs/SERVING.md): a JSONL request protocol over
stdin/stdout (default), a request file, or a UNIX socket, feeding a
micro-batching dispatcher that coalesces concurrent score requests into
ONE padded executor dispatch per tick and routes per-model.

Protocol -- one JSON object per line, one response line per request::

    {"id": 7, "model": "cells", "op": "score_samples", "x": [[...], ...]}
    -> {"id": 7, "ok": true, "model": "cells", "version": 2,
        "op": "score_samples", "n": 2, "result": [...],
        "latency_ms": 0.8}

``op`` is one of ``predict`` / ``predict_proba`` / ``score_samples`` /
``score`` (the estimator surface); ``version`` pins a registry version
(default: newest); ``{"op": "shutdown"}`` stops the server after
draining. Errors come back on the same id with ``ok: false`` and an
``error`` message -- a malformed request never kills the loop.

Micro-batching: requests arriving within one tick (``tick_s``) are
grouped by (model, version) and each group's rows are concatenated into
a single bucketed executor dispatch; per-request results are sliced back
out. All four ops ride the SAME 'proba' executable, so a mixed batch
(score + predict for one model) still coalesces into one dispatch --
the batched dispatch is bit-identical to per-request dispatches because
rows are independent through the per-event log-sum-exp (the coalescing
parity test, tests/test_serving.py).

Telemetry (stream rev v1.6, docs/OBSERVABILITY.md): ``serve_request``
per request, ``serve_batch`` per coalesced dispatch, and a closing
``serve_summary`` with QPS + latency percentiles + the MetricsRegistry
snapshot -- rendered by ``gmm report``.

Resilience layer (docs/ROBUSTNESS.md "Serving"; stream rev v1.7):

- **graceful drain** -- ``serve_main`` runs under ``supervisor.use()``,
  so SIGTERM/SIGINT and ``--max-runtime`` flip a drain instead of
  killing the loop: accepted requests are flushed, post-drain arrivals
  answer ``{"ok": false, "error": "shutting_down"}``, the
  ``serve_summary`` is emitted, and the process exits 75 (the PR-4
  ``EX_TEMPFAIL`` contract -- a batch scheduler restarts it blindly).
- **admission control** -- ``--max-queue-rows`` bounds the batching
  queue; arrivals past the bound shed with ``overloaded`` (queued
  survivors are unaffected). ``--default-deadline-ms`` / a per-request
  ``deadline_ms`` give each request a budget: a request whose budget
  expires while queued is rejected with ``deadline_expired`` BEFORE its
  dispatch, and the coalescing window never outwaits the first
  request's remaining budget.
- **registry hot-reload** -- an opt-in ``--reload-interval-s`` loop
  polls the registry (manifest mtime/size fingerprints) BETWEEN ticks
  on the loop thread, so an export while serving atomically swaps the
  ``version=None`` route with in-flight ticks finished on the old
  version; explicitly pinned versions keep serving bit-identically.
- **per-model circuit breakers** (serving/breaker.py) -- repeated
  route failures (non-finite scores via a cheap post-dispatch check,
  ``RegistryError``, executor errors) open the route: requests
  fast-fail with ``circuit_open`` while every other model keeps
  serving; a jittered backoff half-opens it and a healthy probe closes
  it.

Resilience rejections reply with a machine-readable token in ``error``
(``overloaded`` / ``shutting_down`` / ``deadline_expired`` /
``circuit_open``) and the human detail in ``detail``.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import supervisor as supervisor_mod
from .. import telemetry
from ..telemetry import exporter as tl_exporter
from ..telemetry import profiling as tl_profiling
from ..telemetry import sketch as tl_sketch
from ..telemetry import spans as tl_spans
from ..testing import faults
from . import wire
from .breaker import CircuitBreakers
from .executor import ScoringExecutor, executor_for_model
from .registry import ModelRegistry, RegistryError, ServedModel

OPS = ("predict", "predict_proba", "score_samples", "score")


class _BadRequest(ValueError):
    """A request body that is not even a numeric row matrix (ragged
    rows, strings, a dict): answered with the machine token
    ``bad_request`` at ADMISSION -- HTTP 400 via ``status_for_error`` --
    instead of raising from the tick loop's decode."""


def _decode_x(raw) -> np.ndarray:
    """Decode one request's ``x`` into the ``[n, d]`` float64/float32
    block the dispatch concatenates. Accepts an ndarray (the binary
    wire path hands the ``np.frombuffer`` view straight through -- no
    JSON parsing, no Python lists) or anything ``np.asarray`` can make
    numeric. Raises :class:`_BadRequest` for non-numeric/ragged input
    and ``ValueError`` for shape/NaN violations (those keep their
    established error spellings)."""
    if isinstance(raw, np.ndarray):
        x = raw
        if x.dtype not in (np.float32, np.float64):
            x = x.astype(np.float64)
    else:
        try:
            x = np.asarray(raw, np.float64)
        except (ValueError, TypeError) as e:
            raise _BadRequest(
                f"'x' is not a numeric [n, d] row matrix: {e}") from e
    if x.ndim == 1 and x.size:
        x = x[None, :]
    if x.ndim != 2 or x.shape[0] == 0:
        raise ValueError(
            f"'x' must be a non-empty [n, d] row list, got "
            f"shape {x.shape}")
    if not np.isfinite(x).all():
        raise ValueError("'x' contains NaN/Inf rows")
    return x

# Latency samples kept for the summary percentiles (bounded).
_LATENCY_CAP = 100_000

# Auto-stacking hysteresis (adaptive micro-batching): consecutive
# windows with a stackable same-family pair before stacked dispatch
# flips on, and consecutive windows without one before it flips off.
_AUTO_STACK_ON_STREAK = 3
_AUTO_STACK_OFF_STREAK = 16


class _Pending:
    """One in-flight request: the decoded body, where to reply, when it
    arrived, when its budget runs out (None = no deadline), and -- under
    the live plane (rev v2.1) -- its minted trace identity. ``x`` holds
    the admission-decoded row block when the front end decoded it on the
    reader thread (the data-plane fast path); None falls back to the
    tick loop's decode."""

    __slots__ = ("req", "reply", "t0", "deadline", "trace_id", "x")

    def __init__(self, req: dict, reply: Callable[[dict], None],
                 default_deadline_ms: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 x: Optional[np.ndarray] = None):
        self.req = req
        self.reply = reply
        self.t0 = time.perf_counter()
        self.trace_id = trace_id
        self.x = x
        ms = default_deadline_ms
        if isinstance(req, dict):
            raw = req.get("deadline_ms")
            if isinstance(raw, (int, float)) and not isinstance(raw, bool):
                ms = float(raw)
        self.deadline = (self.t0 + ms / 1e3) if ms and ms > 0 else None


class GMMServer:
    """Per-model routed, micro-batched scoring over a model registry."""

    def __init__(self, registry: ModelRegistry, *,
                 max_batch_rows: int = 8192, tick_s: float = 0.002,
                 tick_s_min: Optional[float] = None,
                 tick_s_max: Optional[float] = None,
                 executor: Optional[ScoringExecutor] = None,
                 warm: bool = True,
                 max_queue_rows: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 breaker_threshold: int = 3,
                 breaker_backoff_s: float = 1.0,
                 stack_models: bool = False,
                 trace_requests: bool = False,
                 drift_interval_s: Optional[float] = None,
                 drift_psi_threshold: Optional[float] = 0.2,
                 autotune: str = "off",
                 tuning_db: Optional[str] = None,
                 lifecycle=None):
        if autotune not in ("off", "db"):
            raise ValueError(
                f"serving autotune must be 'off' or 'db', got {autotune!r}"
                " (the probe rung belongs to `gmm tune`, not a live "
                "scoring loop)")
        # Profile-guided executor geometry (docs/PERF.md "Autotuning"):
        # 'db' resolves each served family's min/max event-block bounds
        # from the tuning database (nearest recorded serve row; static
        # defaults otherwise) and emits one `tune` event per decision on
        # the serve stream. 'off' keeps the hand-set defaults and a
        # byte-identical stream.
        self._autotune = autotune
        self._tuning_db = tuning_db
        self._registry = registry
        self._max_batch_rows = max(1, int(max_batch_rows))
        self._tick_s = max(0.0, float(tick_s))
        # Adaptive micro-batching (docs/SERVING.md "Adaptive window"):
        # passing either bound replaces the FIXED gather window with a
        # bounded controller -- deep backlog snaps the window to
        # tick_s_min (dispatch immediately), an idle queue widens it
        # toward tick_s_max to coalesce more rows per executor call.
        # Off (both None, the default) keeps the fixed tick_s path and
        # a byte-identical stream.
        self._adaptive = (tick_s_min is not None
                          or tick_s_max is not None)
        if self._adaptive:
            lo = max(0.0, float(tick_s_min if tick_s_min is not None
                                else 0.0))
            hi = float(tick_s_max if tick_s_max is not None
                       else max(self._tick_s, lo))
            if hi < lo:
                raise ValueError(
                    f"adaptive window needs tick_s_min <= tick_s_max, "
                    f"got {lo}/{hi}")
            self._tick_min = lo
            self._tick_max = hi
            self._tick_cur = min(max(self._tick_s, lo), hi)
        self._arrivals = 0
        self._arrival_rate = 0.0
        self._last_window_t = time.perf_counter()
        self.window_adaptations = 0
        # Auto-stacking (adaptive mode): windows that repeatedly carry
        # >= 2 routes of one numeric family flip stacked dispatch on
        # without --stack-models; sustained single-family windows flip
        # it back off.
        self._auto_stack = False
        self._stack_streak = 0
        self._unstack_streak = 0
        # Device-resident routes: dispatch-time state preparations that
        # missed the pinned plane (executor host_stagings delta), the
        # serve.host_staging observability counter.
        self.host_stagings = 0
        self._host_staging_seen = 0
        # Family executors are process-shared (executor_for_model) --
        # an embedded server must not inherit staging counts from the
        # estimator surface or a sibling server, so each executor's
        # count is baselined at adoption and reported as a delta.
        self._staging_base: Dict[int, int] = {}
        self._executor_override = executor
        if executor is not None:
            self._adopt_executor(executor)
        self._warm = bool(warm)
        self._models: Dict[Tuple[str, Optional[int]], ServedModel] = {}
        self._executors: Dict[tuple, ScoringExecutor] = {}
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._stop = threading.Event()
        self._latencies: collections.deque = collections.deque(
            maxlen=_LATENCY_CAP)
        self._t_start = time.perf_counter()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.errors = 0
        # -- resilience layer (docs/ROBUSTNESS.md "Serving") --
        self._max_queue_rows = (int(max_queue_rows)
                                if max_queue_rows else None)
        self._default_deadline_ms = (float(default_deadline_ms)
                                     if default_deadline_ms else None)
        self._adm_lock = threading.Lock()
        self._queued_rows = 0  # rows admitted but not yet popped
        self._draining = threading.Event()
        self.drain_reason: Optional[str] = None
        self.breaker = CircuitBreakers(threshold=breaker_threshold,
                                       backoff_base_s=breaker_backoff_s)
        # name -> (version, fingerprint) of the newest registry version
        # observed; maybe_reload polls against it.
        self._route_snapshot: Dict[str, Tuple[int, str]] = {}
        self.shed = 0
        self.deadline_expired = 0
        self.reloads = 0
        self.breaker_fastfails = 0
        # Cross-model stacked dispatch (docs/TENANCY.md "Serving the
        # fleet"): one tick's groups for DIFFERENT models of one numeric
        # family coalesce into a single lax.map-stacked executable call
        # (ScoringExecutor.infer_stacked) -- bit-identical to per-model
        # dispatches, parity-tested. Opt-in (--stack-models).
        self._stack_models = bool(stack_models)
        self.stacked_batches = 0
        self.stacked_fallthrough = 0
        # Live plane (rev v2.1; --metrics-port): mint a trace_id per
        # admitted request (echoed in its response + tagged on its
        # serve_request record) and emit spans around the route path.
        # Off by default -- responses and streams stay byte-identical.
        self._trace_requests = bool(trace_requests)
        # Drift observability plane (stream rev v2.4; --drift-interval-s,
        # docs/OBSERVABILITY.md "Drift detection"): per-(model, version)
        # windowed sketches of request scores + argmax-assignment
        # occupancy, compared against each version's TRAINING envelope
        # (registry envelope.json) every interval as a `drift` event
        # (PSI / KS / occupancy L1). Sampling is FREE by construction:
        # every op already rides the one AOT 'proba' dispatch, so the
        # window folds in the (w, logz) block the answers are sliced
        # from -- no extra executor call, no new compiles. PSI past
        # ``drift_psi_threshold`` raises a `drift_alarm` event --
        # observational only: it never trips the circuit breaker. Off
        # by default -- responses, streams, and /metrics stay
        # byte-identical (the PR-13 plane-off contract).
        self._drift_interval_s = (float(drift_interval_s)
                                  if drift_interval_s else None)
        self._drift_psi_threshold = (
            float(drift_psi_threshold)
            if drift_psi_threshold is not None else None)
        # (name, actual version) -> {"sketch", "occ", "env", "version"}
        self._drift_windows: Dict[Tuple[str, int], dict] = {}
        # Closed-loop lifecycle (rev v2.6; --lifecycle policy.json,
        # lifecycle/controller.py, docs/ROBUSTNESS.md "Model
        # lifecycle"): drift alarms feed its debounce, answered
        # dispatches feed its spool / canary shadow window / watch
        # gate, and run_loop ticks its state machine between coalesced
        # dispatches -- all on the tick-loop thread. None (the default)
        # keeps responses, streams, and /metrics byte-identical.
        self._lifecycle = lifecycle
        if lifecycle is not None:
            lifecycle.bind(self)
        self._drift_last: Dict[str, dict] = {}  # "name@v" -> last stats
        self.drift_events = 0
        self.drift_alarms = 0

    # -- model / executor resolution ------------------------------------

    def resolve(self, name: str, version: Optional[int] = None
                ) -> ServedModel:
        """The (cached) served model for one (name, version) route.

        ``version=None`` pins the newest version at first use; with the
        opt-in hot-reload loop (``--reload-interval-s``,
        :meth:`maybe_reload`) a later export atomically re-pins that
        default route to the new version between ticks. Explicit
        versions stay pinned forever."""
        key = (name, version)
        m = self._models.get(key)
        if m is None:
            m = self._registry.load(name, version)
            self._models[key] = m
            self._models.setdefault((name, m.version), m)
            if version is None:
                fp = self._registry.latest_fingerprint(name)
                if fp is not None:
                    self._route_snapshot[name] = fp
            # Device-resident route: place the prepared state ONCE at
            # route-prepare time; every later dispatch hits the
            # resident handle (executor.pin_state) instead of
            # re-placing leaves per tick. Released on hot-reload
            # exactly as the dispatch memo is (maybe_reload ->
            # release_state).
            ex = self._executor_for(m)
            ex.pin_state(m.state)
            if self._warm:
                ex.warmup(m.state)
        return m

    def maybe_reload(self) -> List[dict]:
        """Poll the registry and swap every ``version=None`` route whose
        model grew a new readable version; returns the swap audit list.

        Runs on the TICK-LOOP THREAD between coalesced dispatches
        (run_loop's ``reload_interval_s``), which is the bit-parity
        guarantee: an in-flight tick always finishes on the version it
        resolved. The old version's prepared executor state is released
        (recomputable -- a pinned request re-prepares it) and its
        default-route breaker resets so the new version starts closed.
        """
        changed = self._registry.poll(self._route_snapshot)
        swaps: List[dict] = []
        rec = telemetry.current()
        for name, fp in sorted(changed.items()):
            self._route_snapshot[name] = fp
            cur = self._models.get((name, None))
            if cur is None:
                continue  # not an active default route; nothing pinned
            try:
                new_m = self._registry.load(name)
            except (RegistryError, OSError) as e:
                # The newest version is torn/unreadable: keep serving
                # the current one; the next poll retries.
                from ..utils.logging_ import get_logger

                get_logger().warning(
                    "hot-reload of %r skipped: %s", name, e)
                continue
            if new_m.version == cur.version:
                continue  # walk-back landed on the already-served version
            new_ex = self._executor_for(new_m)
            new_ex.pin_state(new_m.state)
            if self._warm:
                new_ex.warmup(new_m.state)
            self._models[(name, None)] = new_m  # the atomic route swap
            self._models.setdefault((name, new_m.version), new_m)
            self.breaker.reset((name, None))
            self._executor_for(cur).release_state(cur.state)
            self.reloads += 1
            swap = {"model": name, "from_version": cur.version,
                    "to_version": new_m.version}
            swaps.append(swap)
            if rec.active:
                rec.emit("serve_reload", fingerprint=fp[1], **swap)
                rec.metrics.count("serve_reloads")
        return swaps

    def _executor_for(self, m: ServedModel) -> ScoringExecutor:
        if self._executor_override is not None:
            return self._executor_override
        key = (m.dtype, m.diag_only)
        ex = self._executors.get(key)
        if ex is None:
            kw = {}
            if self._autotune == "db":
                from ..tuning import resolve_serving_blocks

                blocks, _ = resolve_serving_blocks(
                    m.dtype, m.diag_only, m.d, m.k,
                    tuning_db=self._tuning_db)
                kw.update(blocks)
            ex = self._executors[key] = executor_for_model(m, **kw)
            self._adopt_executor(ex)
        return ex

    def _adopt_executor(self, ex: ScoringExecutor) -> None:
        """Record the executor's host_stagings at adoption: stagings
        that predate this server are other surfaces' traffic, not this
        route plane's fallbacks."""
        self._staging_base.setdefault(
            id(ex), ex.stats().get("host_stagings", 0))

    def executor_stats(self) -> Dict[str, int]:
        """Aggregated executor counters across every family served;
        ``host_stagings`` is since-adoption (process-shared executors
        carry other surfaces' counts)."""
        execs = ([self._executor_override] if self._executor_override
                 else list(self._executors.values()))
        tot: Dict[str, int] = {}
        for ex in execs:
            base = self._staging_base.get(id(ex), 0)
            for k, v in ex.stats().items():
                if k == "host_stagings":
                    v -= base
                tot[k] = tot.get(k, 0) + v
        return tot

    # -- request handling ------------------------------------------------

    def handle_requests(self, requests: List[dict], *,
                        coalesce: bool = True) -> List[dict]:
        """Synchronous convenience: score a request list, return the
        responses in request order. ``coalesce=False`` dispatches one
        request at a time (the parity baseline the micro-batch is tested
        against)."""
        responses: List[Optional[dict]] = [None] * len(requests)
        pendings = []
        for i, req in enumerate(requests):
            def reply(resp, _i=i):
                responses[_i] = resp
            pendings.append(_Pending(req, reply,
                                     trace_id=self._mint_trace_id()))
        if coalesce:
            self._process(pendings)
        else:
            for p in pendings:
                self._process([p])
        return [r for r in responses if r is not None]

    def _mint_trace_id(self) -> Optional[str]:
        return tl_spans.mint_trace_id() if self._trace_requests else None

    @contextlib.contextmanager
    def _route_trace(self, name: str, items=None):
        """Span scope for one route's dispatch (rev v2.1): activates a
        trace -- joining the first request's minted trace_id so a client
        holding that id finds the server-side spans -- and opens the
        ``serve_route`` root span. No-op unless trace_requests is on."""
        if not self._trace_requests:
            yield
            return
        tid = None
        if items:
            tid = getattr(items[0][0], "trace_id", None)
        with tl_spans.trace(tid), tl_spans.span("serve_route", model=name):
            yield

    def live_gauges(self) -> Dict[str, float]:
        """Point-in-time server gauges for the /metrics exporter (rev
        v2.1). Reads only python-side counters -- safe to call from the
        exporter's HTTP thread while the tick loop dispatches."""
        ex = self.executor_stats()
        lookups = ex.get("hits", 0) + ex.get("misses", 0)
        br = self.breaker.stats()
        # Drift gauges (rev v2.4) appear ONLY when the drift plane is
        # on: a drift-off server's /metrics text stays byte-identical.
        drift: Dict[str, float] = {}
        if self._drift_interval_s is not None:
            last = list(self._drift_last.values())
            drift = {
                "gmm_drift_psi": float(max(
                    (r["psi"] for r in last), default=0.0)),
                "gmm_drift_ks": float(max(
                    (r["ks"] for r in last), default=0.0)),
                "gmm_drift_events_total": float(self.drift_events),
                "gmm_drift_alarms_total": float(self.drift_alarms),
            }
        # Adaptive-window gauges appear ONLY when the controller is on:
        # a fixed-tick server's /metrics text stays byte-identical.
        window: Dict[str, float] = {}
        if self._adaptive:
            window = {
                "gmm_serve_window_ms": float(
                    round(self._tick_cur * 1e3, 4)),
                "gmm_serve_window_adaptations": float(
                    self.window_adaptations),
                "gmm_serve_arrival_per_s": float(
                    round(self._arrival_rate, 3)),
                "gmm_serve_auto_stack": float(self._auto_stack),
            }
        return {
            **drift,
            **window,
            "gmm_serve_queue_rows": float(self._queued_rows),
            "gmm_serve_requests": float(self.requests),
            "gmm_serve_batches": float(self.batches),
            "gmm_serve_rows": float(self.rows),
            "gmm_serve_errors": float(self.errors),
            "gmm_serve_shed": float(self.shed),
            "gmm_serve_deadline_expired": float(self.deadline_expired),
            "gmm_serve_reloads": float(self.reloads),
            "gmm_serve_breaker_fastfails": float(self.breaker_fastfails),
            "gmm_serve_breaker_open_routes": float(br["open_routes"]),
            "gmm_serve_breaker_trips": float(br["trips"]),
            "gmm_serve_stacked_batches": float(self.stacked_batches),
            "gmm_serve_host_stagings": float(
                ex.get("host_stagings", 0)),
            "gmm_executor_pinned_states": float(
                ex.get("pinned_states", 0)),
            "gmm_serve_draining": float(self._draining.is_set()),
            "gmm_executor_cache_hit_rate": (
                float(ex.get("hits", 0)) / lookups if lookups else 0.0),
            "gmm_executor_live_executables": float(
                ex.get("live_executables", 0)),
            "gmm_executor_compiles": float(ex.get("compiles", 0)),
        }

    def _expire(self, p: _Pending) -> bool:
        """Reject ``p`` with ``deadline_expired`` when its budget ran
        out while queued (checked per coalesced tick, BEFORE dispatch --
        an expired request never costs an executor call)."""
        if p.deadline is None or time.perf_counter() <= p.deadline:
            return False
        waited_ms = (time.perf_counter() - p.t0) * 1e3
        deadline_ms = (p.deadline - p.t0) * 1e3
        self.deadline_expired += 1
        req = p.req if isinstance(p.req, dict) else {}
        rec = telemetry.current()
        if rec.active:
            rec.emit("serve_deadline",
                     deadline_ms=round(deadline_ms, 3),
                     waited_ms=round(waited_ms, 3),
                     model=req.get("model"), op=req.get("op"))
            rec.metrics.count("serve_deadline_expired")
        self._reply_error(
            p, "deadline_expired",
            detail=f"request budget of {deadline_ms:.1f} ms expired "
            f"after {waited_ms:.1f} ms in queue")
        return True

    def _process(self, pendings: List[_Pending]) -> None:
        """Group one tick's requests per (model, version) and dispatch
        each group as a single coalesced executor call."""
        groups: "collections.OrderedDict[tuple, list]" = \
            collections.OrderedDict()
        for p in pendings:
            req = p.req
            if not isinstance(req, dict):
                self._reply_error(p, "request is not a JSON object")
                continue
            if self._expire(p):
                continue
            raw_deadline = req.get("deadline_ms")
            if raw_deadline is not None and (
                    isinstance(raw_deadline, bool)
                    or not isinstance(raw_deadline, (int, float))):
                self._reply_error(p, "'deadline_ms' must be a number")
                continue
            op = req.get("op")
            if op == "shutdown":
                self._stop.set()
                self._reply(p, {"id": req.get("id"), "ok": True,
                                "op": "shutdown"})
                continue
            if op == "ping":
                self._reply(p, {"id": req.get("id"), "ok": True,
                                "op": "ping"})
                continue
            if op not in OPS:
                self._reply_error(
                    p, f"unknown op {op!r} (expected one of "
                    f"{', '.join(OPS)}, ping, shutdown)")
                continue
            name = req.get("model")
            version = req.get("version")
            if not isinstance(name, str):
                self._reply_error(p, "request needs a 'model' name")
                continue
            if version is not None and not isinstance(version, int):
                self._reply_error(p, "'version' must be an integer")
                continue
            x = p.x
            if x is None:
                # Front ends decode at admission (reader thread); this
                # is the fallback for direct handle_requests callers.
                try:
                    x = _decode_x(req.get("x"))
                except _BadRequest as e:
                    self._reply_error(p, "bad_request", detail=str(e))
                    continue
                except (ValueError, TypeError) as e:
                    self._reply_error(p, f"bad 'x': {e}")
                    continue
            groups.setdefault((name, version), []).append((p, x))
        if self._adaptive and not self._stack_models:
            self._observe_stacking(groups)
        stack = self._stack_models or (self._adaptive
                                       and self._auto_stack)
        if stack and len(groups) > 1:
            self._dispatch_stacked(list(groups.items()))
        else:
            for (name, version), items in groups.items():
                self._dispatch(name, version, items)

    # -- adaptive micro-batching (rev v2.8) ------------------------------

    def _emit_window(self, reason: str, *, prev_ms: Optional[float]
                     = None, queue_rows: int = 0, requests: int = 0,
                     stacked_auto: Optional[bool] = None,
                     streak: Optional[int] = None) -> None:
        """One ``serve_window`` record (stream rev v2.8) per controller
        adaptation: window moves and auto-stacking flips, rendered by
        ``gmm report`` and folded by ``gmm diff``."""
        self.window_adaptations += 1
        rec = telemetry.current()
        if not rec.active:
            return
        rec.emit(
            "serve_window",
            window_ms=round(self._tick_cur * 1e3, 4), reason=reason,
            arrival_per_s=round(self._arrival_rate, 3),
            queue_rows=int(queue_rows), requests=int(requests),
            **({"prev_window_ms": round(prev_ms * 1e3, 4)}
               if prev_ms is not None else {}),
            **({"stacked_auto": bool(stacked_auto)}
               if stacked_auto is not None else {}),
            **({"streak": int(streak)} if streak is not None else {}))
        rec.metrics.count("serve_window_adaptations")
        rec.metrics.gauge("serve.window_ms",
                          round(self._tick_cur * 1e3, 4))

    def _observe_window(self, requests: int) -> None:
        """The bounded window controller, run once per gathered batch:
        backlog left in the queue after a full gather snaps the next
        window to ``tick_s_min`` (a deep queue must dispatch
        immediately), a window that coalesced nothing widens toward
        ``tick_s_max`` (idle traffic can afford to wait for more rows
        per executor call). The window NEVER leaves [tick_s_min,
        tick_s_max] -- both moves clamp -- and the gather loop still
        bounds every window by the first request's deadline budget."""
        now = time.perf_counter()
        dt = now - self._last_window_t
        self._last_window_t = now
        arrived, self._arrivals = self._arrivals, 0
        if dt > 0:
            self._arrival_rate = (0.7 * self._arrival_rate
                                  + 0.3 * (arrived / dt))
        # Row accounting only runs under --max-queue-rows; the queue
        # depth (pending requests) is the always-on backlog signal.
        backlog = (self._queued_rows if self._max_queue_rows is not None
                   else self._queue.qsize())
        prev = self._tick_cur
        if backlog > 0:
            if prev > self._tick_min:
                self._tick_cur = self._tick_min
                self._emit_window("backlog", prev_ms=prev,
                                  queue_rows=backlog,
                                  requests=requests)
        elif requests <= 1:
            widened = min(self._tick_max,
                          max(prev * 2.0, self._tick_min,
                              self._tick_max / 64.0))
            if widened > prev:
                self._tick_cur = widened
                self._emit_window("idle", prev_ms=prev,
                                  queue_rows=backlog,
                                  requests=requests)

    def _observe_stacking(self, groups) -> None:
        """Auto-stacking streaks (adaptive mode, --stack-models off):
        a window carrying >= 2 routes of one numeric family (shared
        dtype x covariance structure x D -- the ``infer_stacked``
        admission rule) counts toward flipping stacked dispatch ON;
        sustained windows without such a pair flip it back OFF. Both
        flips emit ``serve_window`` so the controller's behavior is
        visible in ``gmm report`` / ``gmm diff``."""
        if len(groups) > 1 and self._window_stackable(groups):
            self._stack_streak += 1
            self._unstack_streak = 0
            if (not self._auto_stack
                    and self._stack_streak >= _AUTO_STACK_ON_STREAK):
                self._auto_stack = True
                self._emit_window("auto_stack_on", stacked_auto=True,
                                  streak=self._stack_streak,
                                  requests=sum(
                                      len(v) for v in groups.values()))
        elif groups:
            self._unstack_streak += 1
            self._stack_streak = 0
            if (self._auto_stack
                    and self._unstack_streak >= _AUTO_STACK_OFF_STREAK):
                self._auto_stack = False
                self._emit_window("auto_stack_off", stacked_auto=False,
                                  streak=self._unstack_streak,
                                  requests=sum(
                                      len(v) for v in groups.values()))

    def _window_stackable(self, groups) -> bool:
        """Whether this window's groups hold >= 2 already-resolved
        routes of one stacked family. Unresolved routes don't count --
        the check must stay free of registry IO on the tick loop."""
        fams: Dict[tuple, int] = {}
        for (name, version) in groups:
            m = self._models.get((name, version))
            if m is None:
                continue
            key = (m.dtype, m.diag_only, m.d)
            fams[key] = fams.get(key, 0) + 1
            if fams[key] >= 2:
                return True
        return False

    def _prepare_route(self, name: str, version: Optional[int],
                       items: List[Tuple[_Pending, np.ndarray]]):
        """The dispatch front half shared by the per-model and stacked
        paths: breaker admission, registry resolve, per-request D
        validation, and the shifted row block. Returns ``(m, good,
        rows, t0)`` or None when every request was already answered
        (fast-fail / resolve error / all-bad rows)."""
        with tl_spans.span("prepare", model=name):
            return self._prepare_route_inner(name, version, items)

    def _prepare_route_inner(self, name: str, version: Optional[int],
                             items: List[Tuple[_Pending, np.ndarray]]):
        rec = telemetry.current()
        t0 = time.perf_counter()
        route = (name, version)
        denial = self.breaker.admit(route)
        if denial is not None:
            self.breaker_fastfails += 1
            if rec.active:
                rec.metrics.count("serve_breaker_fastfails",
                                  len(items))
            for p, _ in items:
                self._reply_error(
                    p, "circuit_open", model=name,
                    detail=f"model {name!r}"
                    + (f" v{version}" if version is not None else "")
                    + " is failing; retry in "
                    f"{denial['retry_in_s']:.1f}s")
            return None
        try:
            m = self.resolve(name, version)
        except (RegistryError, OSError) as e:
            self.breaker.record_failure(route, "registry")
            for p, _ in items:
                self._reply_error(p, str(e), model=name)
            return None
        d = m.d
        bad, good = [], []
        for p, x in items:
            if x.shape[1] != d:
                bad.append((p, f"model {name!r} has D={d} but 'x' rows "
                            f"have D={x.shape[1]}"))
            else:
                good.append((p, x))
        for p, msg in bad:
            self._reply_error(p, msg, model=name)
        if not good:
            return None
        xs = [x for _, x in good]
        rows = np.concatenate(xs, axis=0).astype(
            np.dtype(m.dtype), copy=False)
        rows = rows - m.data_shift[None, :].astype(rows.dtype)
        slow = faults.take("serve_slow", model=name)
        if slow is not None:
            time.sleep(float(slow.get("ms", 0)) / 1e3)
        crash = faults.take(
            "worker_crash", model=name,
            worker=int(os.environ.get("GMM_SERVE_WORKER", "-1") or -1),
            gen=int(os.environ.get("GMM_SERVE_WORKER_GEN", "-1") or -1))
        if crash is not None:
            # Hard process death mid-dispatch (no flush, no summary, no
            # atexit) -- indistinguishable from a SIGKILL'd or OOM'd pool
            # worker, which is the point: the worker pool's sibling
            # retry + respawn arc (serving/pool.py) must contain exactly
            # this.
            os._exit(int(crash.get("exitcode", 9)))
        return m, good, rows, t0

    def _dispatch(self, name: str, version: Optional[int],
                  items: List[Tuple[_Pending, np.ndarray]]) -> None:
        """One coalesced dispatch: concatenate every request's rows,
        score once, slice per request, answer per op.

        Route failures -- RegistryError at resolve, an executor error,
        or non-finite scores (the cheap post-dispatch poison check) --
        feed the (model, version) circuit breaker; while its breaker is
        open the whole group fast-fails with ``circuit_open`` before any
        of that cost. Client-content errors (wrong D) never touch the
        breaker."""
        with self._route_trace(name, items):
            prep = self._prepare_route(name, version, items)
            if prep is None:
                return
            m, good, rows, t0 = prep
            ex = self._executor_for(m)
            compiles_before = ex.compile_count
            try:
                with tl_spans.span("dispatch", model=name), \
                        tl_profiling.watermark("serve_dispatch"):
                    w, logz = ex.infer(m.state, rows, want="proba")
            except Exception as e:  # executor/compile failure
                self.breaker.record_failure((name, version), "executor")
                for p, _ in good:
                    self._reply_error(p, f"dispatch failed: {e}",
                                      model=name)
                return
            compiled = ex.compile_count - compiles_before
            self._answer_route(name, version, m, good, rows, w, logz,
                               t0, compiled,
                               int(ex.padded_rows(rows.shape[0])))

    def _dispatch_stacked(self, routes) -> None:
        """Cross-model coalescing (docs/TENANCY.md "Serving the fleet"):
        one tick's per-(model, version) groups partition by numeric
        family -- shared executor (dtype x covariance structure) and D
        -- and each family of >= 2 routes scores through ONE stacked
        executable call (``ScoringExecutor.infer_stacked``; lax.map over
        the model axis, so responses stay bit-identical to per-model
        dispatches). Per-route error isolation is unchanged: breaker
        admission, registry errors, and the non-finite poison check all
        stay per (model, version)."""
        with self._route_trace(
                "stacked", routes[0][1] if routes else None):
            self._dispatch_stacked_inner(routes)

    def _dispatch_stacked_inner(self, routes) -> None:
        preps = []
        for (name, version), items in routes:
            prep = self._prepare_route(name, version, items)
            if prep is not None:
                preps.append((name, version) + prep)
        families: "collections.OrderedDict[tuple, list]" = \
            collections.OrderedDict()
        singles = []
        fallthrough = 0
        for entry in preps:
            name, version, m, good, rows, t0 = entry
            ex = self._executor_for(m)
            if not ex.stackable_rows(rows.shape[0]):
                # Oversized group: it splits into max_block slices,
                # which the stacked layout does not model. COUNTED, not
                # silent -- its solo dispatch emits `serve_batch` with
                # `stacked` absent, and serve_summary.stacked_fallthrough
                # reconciles stacked_batches against dispatch counts.
                fallthrough += 1
                singles.append(entry)
            else:
                families.setdefault((id(ex), m.d), []).append(entry)
        if fallthrough:
            self.stacked_fallthrough += fallthrough
            rec_ft = telemetry.current()
            if rec_ft.active:
                rec_ft.metrics.count("serve_stacked_fallthrough",
                                     fallthrough)
        for fam in families.values():
            if len(fam) < 2:
                singles.extend(fam)
                continue
            ex = self._executor_for(fam[0][2])
            compiles_before = ex.compile_count
            try:
                with tl_spans.span("dispatch", stacked=len(fam)), \
                        tl_profiling.watermark("serve_dispatch"):
                    outs, padded = ex.infer_stacked(
                        [m.state for _, _, m, _, _, _ in fam],
                        [rows for _, _, _, _, rows, _ in fam])
            except Exception as e:
                for name, version, m, good, rows, t0 in fam:
                    self.breaker.record_failure((name, version),
                                                "executor")
                    for p, _ in good:
                        self._reply_error(p, f"dispatch failed: {e}",
                                          model=name)
                continue
            compiled = ex.compile_count - compiles_before
            self.stacked_batches += 1
            rec = telemetry.current()
            if rec.active:
                rec.metrics.count("serve_stacked_batches")
            for (name, version, m, good, rows, t0), (w, logz) in zip(
                    fam, outs):
                self._answer_route(name, version, m, good, rows, w,
                                   logz, t0, compiled, int(padded),
                                   stacked=len(fam))
        for name, version, m, good, rows, t0 in singles:
            ex = self._executor_for(m)
            compiles_before = ex.compile_count
            try:
                with tl_spans.span("dispatch", model=name), \
                        tl_profiling.watermark("serve_dispatch"):
                    w, logz = ex.infer(m.state, rows, want="proba")
            except Exception as e:
                self.breaker.record_failure((name, version), "executor")
                for p, _ in good:
                    self._reply_error(p, f"dispatch failed: {e}",
                                      model=name)
                continue
            compiled = ex.compile_count - compiles_before
            self._answer_route(name, version, m, good, rows, w, logz,
                               t0, compiled,
                               int(ex.padded_rows(rows.shape[0])))

    def _answer_route(self, name: str, version: Optional[int], m,
                      good, rows, w, logz, t0, compiled: int,
                      padded_rows: int,
                      stacked: Optional[int] = None) -> None:
        """The dispatch back half: poison check -> breaker verdict ->
        telemetry -> per-request slicing and replies (identical for
        per-model and stacked dispatches)."""
        with tl_spans.span("answer", model=name):
            self._answer_route_inner(name, version, m, good, rows, w,
                                     logz, t0, compiled, padded_rows,
                                     stacked)

    def _answer_route_inner(self, name: str, version: Optional[int], m,
                            good, rows, w, logz, t0, compiled: int,
                            padded_rows: int,
                            stacked: Optional[int] = None) -> None:
        rec = telemetry.current()
        if faults.take("serve_nan", model=name) is not None:
            w = np.full_like(w, np.nan)
            logz = np.full_like(logz, np.nan)
        if not np.isfinite(logz).all():
            # The poisoned-artifact containment: logz is [rows], so the
            # check is O(rows) against the O(rows x K x D^2) dispatch,
            # and every op's result derives from the same densities. In
            # a stacked call the check is PER LANE: one poisoned model
            # trips only its own route's breaker.
            self.breaker.record_failure((name, version), "non_finite")
            if rec.active:
                rec.metrics.count("serve_nonfinite_batches")
            for p, _ in good:
                self._reply_error(
                    p, "non_finite_scores", model=name,
                    detail=f"model {name!r} v{m.version} scored "
                    "non-finite densities; its route breaker counts "
                    "the failure")
            return
        self.breaker.record_success((name, version))
        if self._drift_interval_s is not None:
            self._drift_observe(name, m, w, logz)
        if self._lifecycle is not None and version is None:
            # Lifecycle feed (rev v2.6): spools request rows and -- in
            # a canary/watch window -- shadow-scores THIS block under
            # the candidate. Replies are already computed from (w,
            # logz) slices; the hook reads, never mutates.
            self._lifecycle.observe_dispatch(name, m, rows, logz)
        wall_ms = (time.perf_counter() - t0) * 1e3
        self.batches += 1
        self.rows += int(rows.shape[0])
        # Device-resident audit: any state preparation this dispatch
        # performed OUTSIDE the pinned plane is a fallback to
        # per-request host->device staging -- counted so it can never
        # be silent (the serve.host_staging diff gate).
        staged = self.executor_stats().get("host_stagings", 0)
        if staged > self._host_staging_seen:
            delta = staged - self._host_staging_seen
            self._host_staging_seen = staged
            self.host_stagings += delta
            if rec.active:
                rec.metrics.count("serve_host_staging", delta)
        if rec.active:
            rec.emit("serve_batch", model=name, version=m.version,
                     requests=len(good), rows=int(rows.shape[0]),
                     padded_rows=int(padded_rows),
                     wall_ms=round(wall_ms, 3), compiled=int(compiled),
                     **({"stacked": int(stacked)}
                        if stacked is not None else {}))
            rec.metrics.count("serve_batches")
            rec.metrics.count("serve_rows", int(rows.shape[0]))
            rec.metrics.count("serve_compiles", int(compiled))
            rec.metrics.observe("serve.batch_ms", wall_ms)
            rec.metrics.observe("serve.batch_rows", int(rows.shape[0]))
        start = 0
        for p, x in good:
            n = int(x.shape[0])
            wi = w[start:start + n, :m.k]
            zi = logz[start:start + n]
            start += n
            op = p.req["op"]
            if op == "predict":
                result: Any = np.argmax(wi, axis=1).tolist()
            elif op == "predict_proba":
                result = wi.tolist()
            elif op == "score_samples":
                result = zi.tolist()
            else:  # score
                result = float(np.mean(zi))
            self._reply(p, {
                "id": p.req.get("id"), "ok": True, "model": name,
                "version": m.version, "op": op, "n": n,
                "result": result,
            })

    # -- drift plane (rev v2.4) ------------------------------------------

    def _drift_observe(self, name: str, m, w, logz) -> None:
        """Fold one answered dispatch's (w, logz) block into the route's
        drift window. Zero-dispatch-cost by design: the block is the
        same host array the per-request answers are sliced from.
        Versions without a training envelope are skipped -- there is
        nothing to compare against (backfill with `gmm drift
        --rebuild-envelope`)."""
        env = m.envelope
        if not env or not env.get("score"):
            return
        key = (name, int(m.version))
        win = self._drift_windows.get(key)
        if win is None:
            # Window sketches adopt the ENVELOPE's bucket ladder, so
            # PSI/KS compare bucket-for-bucket by construction.
            win = self._drift_windows[key] = {
                "sketch": tl_sketch.StreamSketch(env["score"]["bounds"]),
                "occ": np.zeros(int(env.get("k", m.k)), np.int64),
                "env": env,
            }
        win["sketch"].update(logz)
        k = min(int(m.k), len(win["occ"]))
        win["occ"] += np.bincount(
            np.argmax(np.asarray(w)[:, :k], axis=1),
            minlength=len(win["occ"])).astype(np.int64)

    def flush_drift(self) -> List[dict]:
        """Close every non-empty drift window: emit one ``drift`` event
        per route (PSI / KS / occupancy L1 vs the training envelope),
        raise ``drift_alarm`` where PSI crossed the threshold, reset the
        windows, and return the stats list. Runs on the tick-loop thread
        (run_loop's drift timer) and once more at serve shutdown so a
        short-lived serve still reports its traffic. Observational only
        -- the breaker is never touched."""
        if self._drift_interval_s is None:
            return []
        rec = telemetry.current()
        out: List[dict] = []
        for (name, version), win in self._drift_windows.items():
            sk = win["sketch"]
            if sk.count == 0:
                continue
            stats = tl_sketch.compare_to_envelope(win["env"], sk,
                                                  win["occ"])
            thr = self._drift_psi_threshold
            alarm = thr is not None and stats["psi"] > thr
            self.drift_events += 1
            row = dict(stats, model=name, version=int(version),
                       alarm=bool(alarm))
            self._drift_last[f"{name}@{version}"] = row
            out.append(row)
            if rec.active:
                rec.emit(
                    "drift", model=name, version=int(version),
                    alarm=bool(alarm),
                    # The window's raw mergeable summary rides along so
                    # `gmm drift` can re-aggregate a recorded stream
                    # offline at any window granularity.
                    score_sketch=sk.to_dict(),
                    occupancy=[int(c) for c in win["occ"]],
                    train_rows=int(win["env"]["score"].get("count", 0)),
                    **({"threshold": thr} if thr is not None else {}),
                    **stats)
                rec.metrics.count("drift_windows")
                rec.metrics.series("drift_psi", stats["psi"])
            if alarm:
                self.drift_alarms += 1
                if rec.active:
                    # Health-event conventions (named flags, counted,
                    # instants in `gmm timeline`) WITHOUT being a
                    # health.py fault lane: drift is a property of the
                    # traffic, not of the numerics.
                    rec.emit("drift_alarm", model=name,
                             version=int(version), psi=stats["psi"],
                             threshold=float(thr), ks=stats["ks"],
                             occupancy_l1=stats["occupancy_l1"],
                             window_rows=stats["window_rows"],
                             flag_names=["drift_psi"])
                    rec.metrics.count("drift_alarms")
                if self._lifecycle is not None:
                    # The closed loop's trigger feed (rev v2.6): the
                    # controller debounces and reacts on later ticks;
                    # this call never touches the serving path.
                    self._lifecycle.observe_alarm(name, int(version),
                                                  stats)
            win["sketch"] = tl_sketch.StreamSketch(sk.bounds)
            win["occ"] = np.zeros_like(win["occ"])
        return out

    def drift_stats(self) -> Dict[str, Any]:
        """The rev v2.4 drift rollup (serve_summary.drift): windows
        emitted, alarms raised, and each route's last window stats."""
        return {
            "windows": int(self.drift_events),
            "alarms": int(self.drift_alarms),
            "threshold": self._drift_psi_threshold,
            "last": dict(self._drift_last),
        }

    def _reply(self, p: _Pending, resp: dict) -> None:
        latency_ms = (time.perf_counter() - p.t0) * 1e3
        resp.setdefault("latency_ms", round(latency_ms, 3))
        if p.trace_id is not None:
            # Echo the request's trace identity so a client can join its
            # response to the server-side span/serve_request records.
            resp.setdefault("trace_id", p.trace_id)
        self.requests += 1
        self._latencies.append(latency_ms)
        rec = telemetry.current()
        if rec.active:
            rec.emit("serve_request",
                     model=resp.get("model", p.req.get("model")),
                     op=resp.get("op", p.req.get("op")),
                     n=int(resp.get("n", 0)),
                     latency_ms=round(latency_ms, 3),
                     ok=bool(resp.get("ok")),
                     **({"version": resp["version"]}
                        if "version" in resp else {}),
                     **({"error": resp["error"]}
                        if "error" in resp else {}),
                     **({"trace_id": p.trace_id}
                        if p.trace_id is not None else {}))
            rec.metrics.count("serve_requests")
            rec.metrics.observe("serve.latency_ms", latency_ms)
        try:
            p.reply(resp)
        except Exception:
            # The reply callback crosses into front-end-owned I/O (a
            # socket wfile, an HTTP handler's event). A client that
            # vanished mid-flight must cost us one undeliverable
            # response, never the tick loop or the process.
            if rec.active:
                rec.metrics.count("serve_reply_failed")

    def _reply_error(self, p: _Pending, msg: str, model=None,
                     detail: Optional[str] = None) -> None:
        self.errors += 1
        rec = telemetry.current()
        if rec.active:
            rec.metrics.count("serve_errors")
        self._reply(p, {"id": (p.req.get("id")
                               if isinstance(p.req, dict) else None),
                        "ok": False, "error": msg,
                        **({"detail": detail} if detail else {}),
                        **({"model": model} if model else {})})

    # -- summary ---------------------------------------------------------

    def latency_summary(self) -> Dict[str, float]:
        lat = np.asarray(self._latencies, np.float64)
        if lat.size == 0:
            return {"p50": 0.0, "p99": 0.0, "mean": 0.0, "max": 0.0}
        return {
            "p50": round(float(np.percentile(lat, 50)), 3),
            "p99": round(float(np.percentile(lat, 99)), 3),
            "mean": round(float(lat.mean()), 3),
            "max": round(float(lat.max()), 3),
        }

    def resilience_stats(self) -> Dict[str, Any]:
        """The v1.7 resilience counters (serve_summary + bench --serve):
        shed / deadline-expired request counts, breaker trips and
        fast-fails, and hot-reload swaps."""
        return {
            "shed": int(self.shed),
            "deadline_expired": int(self.deadline_expired),
            "reloads": int(self.reloads),
            "breaker": dict(self.breaker.stats(),
                            fastfails=int(self.breaker_fastfails)),
        }

    def emit_summary(self, **extra) -> Optional[dict]:
        """The closing ``serve_summary`` record (run_summary's serving
        sibling): volume, QPS, latency percentiles, executor counters,
        the resilience counters (rev v1.7), and the metrics-registry
        snapshot. ``extra`` carries opt-in plane rollups (the HTTP front
        end's ``http`` block, rev v2.7); an empty extra keeps the record
        byte-identical to pre-v2.7 streams."""
        rec = telemetry.current()
        wall = time.perf_counter() - self._t_start
        # Close out any partial drift windows first (rev v2.4): a serve
        # session shorter than one drift interval still reports what it
        # saw, and the drift events precede the summary in the stream.
        self.flush_drift()
        if not rec.active:
            return None
        watch = tl_profiling.active()
        return rec.emit(
            "serve_summary",
            requests=int(self.requests), batches=int(self.batches),
            rows=int(self.rows), errors=int(self.errors),
            wall_s=round(wall, 6),
            qps=round(self.requests / wall, 3) if wall > 0 else 0.0,
            latency_ms=self.latency_summary(),
            models=sorted({f"{n}@{m.version}"
                           for (n, _), m in self._models.items()}),
            executor=self.executor_stats(),
            stacked_batches=int(self.stacked_batches),
            **({"stacked_fallthrough": int(self.stacked_fallthrough)}
               if self.stacked_fallthrough else {}),
            **({"window": {
                "adaptations": int(self.window_adaptations),
                "window_ms": round(self._tick_cur * 1e3, 4),
                "min_ms": round(self._tick_min * 1e3, 4),
                "max_ms": round(self._tick_max * 1e3, 4),
                "auto_stack": bool(self._auto_stack),
            }} if self._adaptive else {}),
            metrics=rec.metrics.snapshot(),
            # CompileWatch rollup (rev v2.2): run_summary.profile's
            # serving sibling -- AOT compile counts/seconds + cost and
            # memory analyses + serve-dispatch HBM watermarks.
            **({"profile": watch.snapshot()} if watch is not None
               else {}),
            # Drift rollup (rev v2.4): only when the plane is on, so
            # drift-off streams stay byte-identical.
            **({"drift": self.drift_stats()}
               if self._drift_interval_s is not None else {}),
            **self.resilience_stats(),
            **extra,
        )

    # -- streaming loops -------------------------------------------------

    def submit_line(self, line: str, reply: Callable[[dict], None]) -> None:
        """Decode one protocol line through admission control (reader
        threads call this; the tick loop drains the queue)."""
        line = line.strip()
        if not line:
            return
        try:
            req = json.loads(line)
        except ValueError as e:
            p = _Pending({}, reply)
            self._reply_error(p, f"not JSON: {e}")
            return
        self.admit_request(req, reply)

    def admit_request(self, req, reply: Callable[[dict], None], *,
                      trace_id: Optional[str] = None) -> bool:
        """Admit one decoded request dict: scoring ops decode ``x`` HERE
        -- on the reader thread, at admission -- so a ragged or
        non-numeric body answers ``bad_request`` immediately (never
        raising from the tick loop) and the JSON-list -> ndarray
        conversion cost stays off the dispatch path. Returns True when
        queued."""
        p = _Pending(req, reply, self._default_deadline_ms,
                     trace_id=(trace_id if trace_id is not None
                               else self._mint_trace_id()))
        if isinstance(req, dict) and req.get("op") in OPS:
            try:
                p.x = _decode_x(req.get("x"))
            except _BadRequest as e:
                self._reply_error(p, "bad_request", detail=str(e))
                return False
            except (ValueError, TypeError) as e:
                self._reply_error(p, f"bad 'x': {e}")
                return False
        return self.submit(p)

    def submit_frame(self, req: dict, frame: bytes,
                     reply: Callable[[dict], None], *,
                     trace_id: Optional[str] = None) -> bool:
        """Admit one binary-payload request: a header dict (the JSONL
        header line minus its ``x_bytes``, or the HTTP URL-derived
        fields) plus one ``application/x-gmm-rows`` frame, decoded
        straight into the dispatch block via ``np.frombuffer``
        (serving/wire.py) -- no JSON float parsing, no intermediate
        Python lists. A malformed frame answers ``bad_frame``."""
        p = _Pending(req, reply, self._default_deadline_ms,
                     trace_id=(trace_id if trace_id is not None
                               else self._mint_trace_id()))
        try:
            rows = wire.decode_rows(frame)
        except wire.WireError as e:
            self._reply_error(p, "bad_frame", detail=str(e))
            return False
        req.pop("x_bytes", None)
        req["x"] = rows
        if req.get("op") in OPS:
            try:
                p.x = _decode_x(rows)
            except _BadRequest as e:
                self._reply_error(p, "bad_request", detail=str(e))
                return False
            except (ValueError, TypeError) as e:
                self._reply_error(p, f"bad 'x': {e}")
                return False
        return self.submit(p)

    def submit(self, p: _Pending) -> bool:
        """Admit ``p`` onto the batching queue, or shed it.

        Two rejection gates, both answered immediately on the reader
        thread (an overloaded or draining server must not buffer the
        very traffic it cannot take): ``shutting_down`` once the drain
        began, and ``overloaded`` when the queued row count would pass
        ``max_queue_rows`` (a request wider than the whole bound is
        still admitted when the queue is empty -- it can never fit
        better later). Returns True when queued.
        """
        self._arrivals += 1
        if self._draining.is_set():
            self._shed(p, "shutting_down")
            return False
        rows = _rows_of(p)
        if self._max_queue_rows is not None:
            with self._adm_lock:
                if (self._queued_rows > 0
                        and self._queued_rows + rows > self._max_queue_rows):
                    self._shed(p, "overloaded", rows=rows)
                    return False
                self._queued_rows += rows
        self._queue.put(p)
        return True

    def _shed(self, p: _Pending, reason: str, rows: int = 0) -> None:
        self.shed += 1
        req = p.req if isinstance(p.req, dict) else {}
        rec = telemetry.current()
        if rec.active:
            fields: Dict[str, Any] = {"reason": reason,
                                      "model": req.get("model")}
            if reason == "overloaded":
                fields.update(rows=int(rows),
                              queued_rows=int(self._queued_rows),
                              max_queue_rows=int(self._max_queue_rows))
            rec.emit("serve_shed", **fields)
            rec.metrics.count("serve_sheds")
        detail = ("server is draining; no new requests accepted"
                  if reason == "shutting_down" else
                  f"admission queue is full ({self._queued_rows} of "
                  f"{self._max_queue_rows} rows queued)")
        self._reply_error(p, reason, model=req.get("model"),
                          detail=detail)

    def _pop(self, timeout: Optional[float]) -> Optional[_Pending]:
        """One queue pop (None timeout = nonblocking), releasing the
        popped request's admission rows. Raises ``queue.Empty``."""
        p = (self._queue.get_nowait() if timeout is None
             else self._queue.get(timeout=timeout))
        if p is not None and self._max_queue_rows is not None:
            with self._adm_lock:
                self._queued_rows = max(0, self._queued_rows - _rows_of(p))
        return p

    def begin_drain(self, reason: str) -> None:
        """Flip the drain: stop admitting, keep flushing what was
        accepted. Idempotent; the first reason wins."""
        if not self._draining.is_set():
            self.drain_reason = reason
            self._draining.set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def run_loop(self, *, max_requests: Optional[int] = None,
                 idle_timeout_s: Optional[float] = None,
                 draining: Optional[Callable[[], bool]] = None,
                 reload_interval_s: Optional[float] = None) -> str:
        """The micro-batching tick loop: block for the first pending
        request, gather everything that arrives within one tick (bounded
        by ``max_batch_rows`` and the first request's deadline budget),
        dispatch the coalesced groups, repeat.

        Returns the stop reason: ``"shutdown"`` (protocol op),
        ``"max_requests"``, ``"idle"`` (``idle_timeout_s`` with an empty
        queue), ``"eof"`` (``draining`` callback true with an empty
        queue -- stdin exhausted), or ``"preempted"`` (the ambient
        supervisor's stop flag: SIGTERM/SIGINT/--max-runtime -- the
        caller exits 75 after the flush). Every exit first flushes the
        already-admitted queue; post-drain arrivals are shed with
        ``shutting_down``. ``reload_interval_s`` opts into the registry
        hot-reload poll between ticks (:meth:`maybe_reload`).
        """
        sup = supervisor_mod.current()
        reason = "shutdown"
        next_reload = (time.perf_counter() + reload_interval_s
                       if reload_interval_s else None)
        # Drift windows close on the tick-loop thread too (rev v2.4),
        # so window state never needs a lock.
        next_drift = (time.perf_counter() + self._drift_interval_s
                      if self._drift_interval_s else None)
        idle_since = time.perf_counter()
        while True:
            if self._stop.is_set():
                reason = "shutdown"
                break
            if sup.active and sup.poll(where="serve"):
                reason = "preempted"
                self.begin_drain(sup.stop_reason or "preempt")
                break
            if max_requests is not None and self.requests >= max_requests:
                reason = "max_requests"
                break
            if (next_reload is not None
                    and time.perf_counter() >= next_reload):
                self.maybe_reload()
                next_reload = time.perf_counter() + reload_interval_s
            if (next_drift is not None
                    and time.perf_counter() >= next_drift):
                self.flush_drift()
                next_drift = time.perf_counter() + self._drift_interval_s
            if self._lifecycle is not None:
                # Lifecycle state machine (rev v2.6): same thread as
                # drift windows and hot-reload, so retrain / canary /
                # promote / rollback transitions interleave between
                # coalesced dispatches without locks. Cheap when
                # nothing is scheduled.
                self._lifecycle.on_tick()
            # Bounded wait so signals/deadline/reload stay responsive
            # even on an idle queue.
            wait = 0.1 if idle_timeout_s is None else min(
                0.1, idle_timeout_s)
            try:
                first = self._pop(timeout=wait)
            except queue.Empty:
                now = time.perf_counter()
                if (idle_timeout_s is not None
                        and now - idle_since >= idle_timeout_s):
                    reason = "idle"
                    break
                if draining is not None and draining():
                    reason = "eof"
                    break
                continue
            idle_since = time.perf_counter()
            if first is None:
                reason = "shutdown"
                break
            batch = [first]
            rows = _rows_of(first)
            tick = self._tick_cur if self._adaptive else self._tick_s
            tick_end = time.perf_counter() + tick
            if first.deadline is not None:
                # Never let the gather window outwait the first
                # request's remaining budget. Adaptive windows can be
                # WIDER than a request's whole budget, so the
                # controller only ever spends half the remaining
                # budget gathering -- the other half stays for the
                # dispatch to answer inside the deadline. Fixed mode
                # keeps the original cap (tick_s is normally orders of
                # magnitude under any real deadline).
                if self._adaptive:
                    now = time.perf_counter()
                    budget = first.deadline - now
                    tick_end = min(tick_end,
                                   now + max(0.0, budget / 2.0))
                else:
                    tick_end = min(tick_end, first.deadline)
            while rows < self._max_batch_rows:
                remaining = tick_end - time.perf_counter()
                try:
                    p = self._pop(None if remaining <= 0 else remaining)
                except queue.Empty:
                    break
                if p is None:
                    self._stop.set()
                    break
                batch.append(p)
                rows += _rows_of(p)
            if self._adaptive:
                self._observe_window(len(batch))
            self._process(batch)
        # Flush whatever was admitted before the stop (EOF/shutdown/
        # preemption must not drop accepted requests on the floor). On a
        # TERMINAL exit the drain flag flips first so concurrent
        # arrivals shed with shutting_down instead of racing the flush;
        # idle/max_requests exits stay resumable (benchmarks re-enter
        # the loop).
        if reason in ("preempted", "shutdown", "eof"):
            self.begin_drain(reason)
        leftovers = []
        while True:
            try:
                p = self._pop(None)
            except queue.Empty:
                break
            if p is not None:
                leftovers.append(p)
        if leftovers:
            self._process(leftovers)
        return reason


def _rows_of(p: _Pending) -> int:
    if p.x is not None:
        return max(int(p.x.shape[0]), 1)
    x = p.req.get("x") if isinstance(p.req, dict) else None
    try:
        return max(len(x), 1)
    except TypeError:
        return 1


def _stdout_replier(out, lock: threading.Lock) -> Callable[[dict], None]:
    def reply(resp: dict) -> None:
        line = json.dumps(resp, default=_json_default)
        with lock:
            out.write(line + "\n")
            out.flush()
    return reply


def _json_default(o):
    item = getattr(o, "item", None)
    if callable(item):
        return o.item()
    tolist = getattr(o, "tolist", None)
    if callable(tolist):
        return o.tolist()
    return str(o)


#: Per-connection read deadline and line bound shared by the UNIX-socket
#: and HTTP front ends (serving/http.py mirrors them as body bounds): a
#: stalled client must time out instead of wedging a reader thread, and
#: an unbounded line must be rejected instead of buffered.
READ_TIMEOUT_S = 30.0
MAX_LINE_BYTES = 8 << 20


def _serve_socket(server: GMMServer, path: str,
                  max_requests: Optional[int],
                  reload_interval_s: Optional[float] = None,
                  read_timeout_s: float = READ_TIMEOUT_S,
                  max_line_bytes: int = MAX_LINE_BYTES) -> str:
    """UNIX-socket front end: every connection speaks the same JSONL
    protocol; requests from ALL connections land on one batching queue,
    so concurrent clients coalesce into shared dispatches (the
    micro-batching win a per-connection loop could never get). Returns
    the tick loop's stop reason.

    Reader containment (rev v2.7): each connection's reads carry a
    deadline (``read_timeout_s``; a slowloris client used to park its
    reader thread on an unbounded ``readline()`` forever) and a line
    bound (``max_line_bytes``; an oversized request is answered
    ``line_too_long`` and the connection closed, instead of the line
    growing without bound in the read buffer)."""
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        # StreamRequestHandler.setup() applies this as the connection's
        # socket timeout; a stalled read raises instead of blocking.
        timeout = read_timeout_s

        def handle(self):
            lock = threading.Lock()

            def reply(resp: dict) -> None:
                line = json.dumps(resp, default=_json_default)
                try:
                    with lock:
                        self.wfile.write(line.encode() + b"\n")
                        self.wfile.flush()
                except (BrokenPipeError, OSError, ValueError):
                    # Client went away; the dispatch already ran. A
                    # closed BufferedWriter raises ValueError, not
                    # OSError -- missing it here once let an abandoned
                    # connection kill the whole worker process.
                    pass

            while True:
                try:
                    raw = self.rfile.readline(max_line_bytes + 1)
                except OSError:
                    # Read deadline hit (socket.timeout is an OSError) or
                    # the client vanished: release this reader thread.
                    break
                if not raw:
                    break  # clean EOF
                if len(raw) > max_line_bytes:
                    reply({"ok": False, "error": "line_too_long",
                           "detail": "request line exceeds the "
                           f"{max_line_bytes}-byte bound"})
                    # Drain the rest of the offending line (bounded:
                    # a few more chunks, never the whole stream) so
                    # closing doesn't RST the un-read reply away.
                    try:
                        for _ in range(64):
                            tail = self.rfile.readline(max_line_bytes + 1)
                            if not tail or tail.endswith(b"\n"):
                                break
                    except OSError:
                        pass
                    break
                # Binary payload (docs/SERVING.md "Binary payloads"): a
                # header line declaring "x_bytes" is followed by exactly
                # that many raw x-gmm-rows frame bytes. The substring
                # probe keeps the JSON-only fast path single-pass.
                if b'"x_bytes"' in raw:
                    if self._handle_frame(raw, reply):
                        continue
                    break  # unrecoverable framing: close the stream
                server.submit_line(raw.decode("utf-8", "replace"), reply)
                if server._stop.is_set():
                    break

        def _handle_frame(self, raw: bytes, reply) -> bool:
            """One length-prefixed binary request. Returns False when
            the connection must close (the raw byte stream can no
            longer be trusted to be line-aligned)."""
            try:
                req = json.loads(raw)
            except ValueError as e:
                reply({"ok": False, "error": f"not JSON: {e}"})
                return True
            n = req.get("x_bytes") if isinstance(req, dict) else None
            if (isinstance(n, bool) or not isinstance(n, int)
                    or n <= 0):
                reply({"ok": False, "error": "bad_frame",
                       "detail": "'x_bytes' must declare a positive "
                       "frame length in bytes"})
                return True
            if n > max_line_bytes:
                # Reject BEFORE buffering; the unread frame bytes make
                # the stream unusable, so the connection closes (the
                # reply flushes first), exactly like line_too_long.
                reply({"ok": False, "error": "frame_too_large",
                       "detail": f"declared frame of {n} bytes exceeds "
                       f"the {max_line_bytes}-byte bound"})
                return False
            try:
                frame = self.rfile.read(n)
            except OSError:
                return False  # read deadline / client vanished
            if len(frame) < n:
                reply({"ok": False, "error": "bad_frame",
                       "detail": f"stream ended after {len(frame)} of "
                       f"{n} declared frame bytes"})
                return False
            server.submit_frame(req, frame, reply)
            return not server._stop.is_set()

    class Srv(socketserver.ThreadingMixIn,
              socketserver.UnixStreamServer):
        daemon_threads = True

    if os.path.exists(path):
        os.remove(path)
    with Srv(path, Handler) as srv:
        t = threading.Thread(target=srv.serve_forever,
                             kwargs={"poll_interval": 0.05}, daemon=True)
        t.start()
        try:
            return server.run_loop(max_requests=max_requests,
                                   reload_interval_s=reload_interval_s)
        finally:
            srv.shutdown()
            try:
                os.remove(path)
            except OSError:
                pass


def _write_port_file(path: Optional[str], port: Optional[int]) -> None:
    """Atomically publish the bound HTTP port (resolves ``--http 0``)."""
    if not path or port is None:
        return
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(str(int(port)))
    os.replace(tmp, path)


def _worker_argv(args, worker_sock: str) -> List[str]:
    """One pool worker's command line: the SAME serve CLI, minus the
    pool/http flags, plus its own --socket -- every already-tested
    single-process behavior (coalescing, breakers, drift, lifecycle,
    drain-on-SIGTERM) carries over unchanged."""
    cmd = [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "serve",
           "--registry", args.registry, "--socket", worker_sock,
           "--max-batch-rows", str(args.max_batch_rows),
           "--tick-ms", str(args.tick_ms),
           "--read-timeout-s", str(args.read_timeout_s),
           "--max-body-bytes", str(args.max_body_bytes),
           "--breaker-threshold", str(args.breaker_threshold),
           "--breaker-backoff-s", str(args.breaker_backoff_s)]
    if args.tick_min_ms is not None:
        cmd += ["--tick-min-ms", str(args.tick_min_ms)]
    if args.tick_max_ms is not None:
        cmd += ["--tick-max-ms", str(args.tick_max_ms)]
    if args.models is not None:
        cmd += ["--models", *args.models]
    if args.no_warmup:
        cmd.append("--no-warmup")
    if args.device:
        cmd += ["--device", args.device]
    if args.autotune != "off":
        cmd += ["--autotune", args.autotune]
    if args.tuning_db:
        cmd += ["--tuning-db", args.tuning_db]
    if args.max_queue_rows is not None:
        cmd += ["--max-queue-rows", str(args.max_queue_rows)]
    if args.default_deadline_ms is not None:
        cmd += ["--default-deadline-ms", str(args.default_deadline_ms)]
    if args.reload_interval_s is not None:
        cmd += ["--reload-interval-s", str(args.reload_interval_s)]
    if args.drift_interval_s is not None:
        cmd += ["--drift-interval-s", str(args.drift_interval_s),
                "--drift-psi-threshold", str(args.drift_psi_threshold)]
    if args.lifecycle:
        cmd += ["--lifecycle", args.lifecycle]
    if args.stack_models:
        cmd.append("--stack-models")
    return cmd


def _serve_pool_main(args) -> int:
    """``gmm serve --http PORT --workers N``: the supervised pool mode.

    The parent is a router + supervisor only (serving/pool.py owns the
    containment arc); its telemetry stream carries the HTTP edge --
    http_request / worker_spawn / worker_exit events and a closing
    serve_summary whose ``http`` rollup ``gmm diff`` gates on. Worker
    streams land next to the parent's (``<base>.worker<i>.jsonl``)."""
    import tempfile

    from .http import HTTPFrontEnd
    from .pool import WorkerPool

    worker_dir = args.worker_dir or tempfile.mkdtemp(
        prefix="gmm-serve-pool-")

    def command_for(idx: int, sock: str) -> List[str]:
        cmd = _worker_argv(args, sock)
        if args.metrics_file:
            base, ext = os.path.splitext(args.metrics_file)
            cmd += ["--metrics-file",
                    f"{base}.worker{idx}{ext or '.jsonl'}"]
        return cmd

    rec = (telemetry.RunRecorder(args.metrics_file)
           if args.metrics_file else telemetry.RunRecorder())
    rec.set_context(path="serve")
    sup = supervisor_mod.RunSupervisor(max_runtime_s=args.max_runtime)
    pool = WorkerPool(args.workers, worker_dir, command_for,
                      backoff_base_s=args.worker_backoff_s,
                      quarantine_after=args.worker_quarantine_after)
    t_start = time.perf_counter()
    with telemetry.use(rec), rec, supervisor_mod.use(sup), \
            tl_exporter.live_plane(
                args.metrics_port,
                registry_provider=lambda: telemetry.current().metrics,
                gauges_provider=pool.gauges,
                recorder=rec):
        rec.heartbeat("serve")
        try:
            pool.start()
        except (RuntimeError, OSError) as e:
            print(f"worker pool failed to start: {e}", file=sys.stderr)
            pool.close()
            return 1
        front = HTTPFrontEnd(
            pool, host=args.http_host, port=args.http,
            max_body_bytes=args.max_body_bytes,
            read_timeout_s=args.read_timeout_s,
            max_connections=args.http_max_connections,
            stopping=lambda: sup.stop_requested)
        front.start()
        _write_port_file(args.http_port_file, front.port)
        try:
            reason = "max_requests"
            while True:
                if sup.active and sup.poll(where="serve"):
                    reason = "preempted"
                    break
                if (args.max_requests is not None
                        and front.requests >= args.max_requests):
                    reason = "max_requests"
                    break
                time.sleep(0.05)
            # Drain order is the /readyz contract: the probe already
            # flips 503 (sup.stop_requested / pool.draining), THEN the
            # workers flush their queues and exit 75, THEN we summarize.
            pool.begin_drain()
            pool.wait(timeout_s=60.0)
        finally:
            front.stop()
            pool.close()
        if rec.active:
            wall = time.perf_counter() - t_start
            rec.emit(
                "serve_summary",
                requests=int(front.requests), batches=0,
                rows=int(front.rows), errors=int(front.errors_5xx),
                wall_s=round(wall, 6),
                qps=(round(front.requests / wall, 3) if wall > 0
                     else 0.0),
                latency_ms=front.latency_summary(),
                metrics=rec.metrics.snapshot(),
                http=front.http_rollup())
        if reason == "preempted":
            stop_reason = sup.stop_reason or "preempt"
            if rec.active:
                rec.emit("shutdown", reason=stop_reason,
                         checkpointed=False)
            print(f"Preempted -- worker pool drained ({stop_reason}); "
                  "workers flushed their queues", file=sys.stderr)
            return supervisor_mod.EX_TEMPFAIL
    return 0


def serve_main(argv=None) -> int:
    """``gmm serve``: run the micro-batched scoring loop over a registry."""
    import argparse

    p = argparse.ArgumentParser(
        prog="gmm serve",
        description="Serve registry models over the JSONL request "
        "protocol: stdin/stdout by default, a request file with "
        "--input, or a UNIX socket with --socket (docs/SERVING.md).")
    p.add_argument("--registry", required=True,
                   help="model registry root directory (gmm export)")
    p.add_argument("--models", nargs="*", default=None,
                   metavar="NAME[@VERSION]",
                   help="models to load (and AOT-warm) at startup; "
                   "default: every registered model's newest version. "
                   "Requests may still address any registry model")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="serve a UNIX stream socket instead of "
                   "stdin/stdout (concurrent clients share the "
                   "micro-batch queue)")
    p.add_argument("--input", default=None, metavar="FILE.jsonl",
                   help="read requests from a file instead of stdin")
    p.add_argument("--output", default=None, metavar="FILE.jsonl",
                   help="write responses to a file instead of stdout")
    p.add_argument("--max-batch-rows", type=int, default=8192,
                   help="coalesced rows per dispatch tick (default 8192)")
    p.add_argument("--tick-ms", type=float, default=2.0,
                   help="micro-batch gather window in milliseconds "
                   "(default 2). Fixed unless an adaptive bound is "
                   "given (--tick-min-ms / --tick-max-ms)")
    p.add_argument("--tick-min-ms", type=float, default=None,
                   metavar="MS",
                   help="adaptive micro-batching lower bound: passing "
                   "this (or --tick-max-ms) replaces the fixed tick "
                   "with a bounded controller -- a backlogged queue "
                   "snaps the gather window down to this floor "
                   "(dispatch immediately). Default: off -- fixed "
                   "--tick-ms, byte-identical stream")
    p.add_argument("--tick-max-ms", type=float, default=None,
                   metavar="MS",
                   help="adaptive micro-batching upper bound: idle "
                   "traffic widens the gather window toward this "
                   "ceiling to coalesce more rows per executor call. "
                   "Windows repeatedly carrying >= 2 same-family "
                   "routes auto-enable stacked dispatch. Each "
                   "adaptation emits a `serve_window` event (rev v2.8)")
    p.add_argument("--max-requests", type=int, default=None,
                   help="exit after this many responses (benchmarks, "
                   "tests)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip AOT pre-compilation of loaded models "
                   "(first request pays the compile)")
    p.add_argument("--device", default=None,
                   help="JAX platform: tpu | cpu | gpu (default: auto)")
    p.add_argument("--metrics-file", default=None, metavar="FILE.jsonl",
                   help="serve telemetry stream: serve_request / "
                   "serve_batch / serve_summary plus the v1.7 "
                   "resilience events (serve_shed / serve_deadline / "
                   "serve_reload / circuit); render with `gmm report`")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="live observability plane (rev v2.1): serve "
                   "Prometheus/OpenMetrics text on "
                   "127.0.0.1:PORT/metrics (0 = OS-assigned), sample "
                   "host RSS + device memory onto heartbeat records, "
                   "emit route spans, and echo a trace_id in every "
                   "response (default: off; responses and streams stay "
                   "byte-identical)")
    p.add_argument("--autotune", default="off", choices=["off", "db"],
                   help="resolve executor block bounds per served "
                   "family from the tuning database (nearest recorded "
                   "serve row; docs/PERF.md 'Autotuning'). Decisions "
                   "land on the serve stream as `tune` events. Default "
                   "off: hand-set geometry, byte-identical stream")
    p.add_argument("--tuning-db", default=None, metavar="PATH",
                   help="tuning database path (default GMM_TUNING_DB or "
                   "~/.cache/gmm/tuning.json)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the serve "
                   "loop into DIR (view with TensorBoard or Perfetto)")
    net = p.add_argument_group(
        "network front end (docs/SERVING.md \"HTTP front end\")")
    net.add_argument("--http", type=int, default=None, metavar="PORT",
                     help="serve POST /v1/models/NAME[@VER]:OP over "
                     "HTTP on this port (0 = OS-assigned; see "
                     "--http-port-file), with /healthz /readyz "
                     "/metrics probes. Requests ride the same "
                     "micro-batch queue, deadlines, and breakers as "
                     "the JSONL protocol. Default: off -- responses "
                     "and streams stay byte-identical")
    net.add_argument("--http-host", default="127.0.0.1", metavar="HOST",
                     help="HTTP bind address (default 127.0.0.1; bind "
                     "0.0.0.0 only behind a load balancer you trust)")
    net.add_argument("--workers", type=int, default=0, metavar="N",
                     help="fork N supervised worker processes behind "
                     "the HTTP front end (requires --http): consistent "
                     "(model,version)->worker routing, sibling retry "
                     "of a crashed worker's in-flight requests, "
                     "jittered-doubling respawn, crash-loop "
                     "quarantine (docs/ROBUSTNESS.md). Default 0: "
                     "serve in-process")
    net.add_argument("--http-port-file", default=None, metavar="FILE",
                     help="write the BOUND http port here once "
                     "listening (resolves --http 0 for tests/benches)")
    net.add_argument("--http-max-connections", type=int, default=64,
                     metavar="N",
                     help="live HTTP connection cap; arrivals past it "
                     "shed 503 + Retry-After instead of exhausting "
                     "handler threads (default 64)")
    net.add_argument("--max-body-bytes", type=int, default=MAX_LINE_BYTES,
                     metavar="BYTES",
                     help="bound on one HTTP request body / one JSONL "
                     "socket line; oversized requests are rejected "
                     "(413 / line_too_long) before buffering "
                     "(default 8 MiB)")
    net.add_argument("--read-timeout-s", type=float,
                     default=READ_TIMEOUT_S, metavar="SECONDS",
                     help="per-connection read deadline for the HTTP "
                     "and UNIX-socket front ends: a stalled (slowloris) "
                     "client times out instead of wedging a reader "
                     "thread forever (default 30)")
    net.add_argument("--worker-dir", default=None, metavar="DIR",
                     help="worker pool state directory: per-worker "
                     "sockets, {pid, socket, gen} state files, logs, "
                     "and quarantine reason files (default: a fresh "
                     "temp directory)")
    net.add_argument("--worker-backoff-s", type=float, default=0.5,
                     metavar="SECONDS",
                     help="base respawn backoff after a worker crash; "
                     "doubles per consecutive crash with deterministic "
                     "jitter (default 0.5)")
    net.add_argument("--worker-quarantine-after", type=int, default=5,
                     metavar="N",
                     help="consecutive crashes that quarantine a "
                     "worker slot (reason file written; siblings keep "
                     "serving; default 5)")
    r = p.add_argument_group(
        "resilience (docs/ROBUSTNESS.md \"Serving\")")
    r.add_argument("--max-runtime", type=float, default=None,
                   metavar="SECONDS",
                   help="wall-clock budget: reaching it drains like "
                   "SIGTERM does -- flush the queue, answer "
                   "shutting_down to late arrivals, exit 75 "
                   "(EX_TEMPFAIL; the fit CLI's preemption contract)")
    r.add_argument("--max-queue-rows", type=int, default=None,
                   metavar="ROWS",
                   help="admission bound on queued request rows; "
                   "arrivals past it shed immediately with "
                   "'overloaded' instead of growing the queue without "
                   "bound (default: unbounded)")
    r.add_argument("--default-deadline-ms", type=float, default=None,
                   metavar="MS",
                   help="per-request budget for requests that carry no "
                   "deadline_ms of their own; a request whose budget "
                   "expires while queued is rejected with "
                   "'deadline_expired' before dispatch")
    r.add_argument("--reload-interval-s", type=float, default=None,
                   metavar="SECONDS",
                   help="opt-in registry hot-reload: poll the registry "
                   "at this cadence and atomically swap version-less "
                   "routes to newly exported versions between ticks "
                   "(pinned versions are untouched; default: off -- "
                   "versions pin at first use)")
    r.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive route failures (non-finite "
                   "scores, registry/executor errors) that open a "
                   "(model, version) circuit breaker (default 3)")
    r.add_argument("--breaker-backoff-s", type=float, default=1.0,
                   help="base seconds an open breaker fast-fails "
                   "before half-opening; doubles per consecutive "
                   "trip with deterministic jitter (default 1)")
    dr = p.add_argument_group(
        "drift observability (docs/OBSERVABILITY.md \"Drift "
        "detection\")")
    dr.add_argument("--drift-interval-s", type=float, default=None,
                    metavar="SECONDS",
                    help="opt-in drift plane (stream rev v2.4): sketch "
                    "every route's request scores + cluster occupancy "
                    "and emit a `drift` event per interval -- PSI/KS "
                    "vs the model's training envelope "
                    "(envelope.json) plus occupancy L1 shift. Free on "
                    "the dispatch path (rides the answered 'proba' "
                    "block); default: off -- responses, streams, and "
                    "/metrics stay byte-identical")
    dr.add_argument("--drift-psi-threshold", type=float, default=0.2,
                    metavar="PSI",
                    help="PSI above this raises a `drift_alarm` event "
                    "(observational only -- never trips the breaker; "
                    "default 0.2, the conventional major-shift line)")
    dr.add_argument("--lifecycle", default=None, metavar="POLICY.json",
                    help="opt-in closed-loop lifecycle (rev v2.6, "
                    "docs/ROBUSTNESS.md \"Model lifecycle\"): "
                    "debounced drift alarms trigger a shadow "
                    "minibatch-EM retrain, canary gates + a "
                    "duplicate-dispatch shadow window guard promotion, "
                    "and a post-promotion probation auto-rolls back on "
                    "a breaker trip / drift alarm / score regression. "
                    "Requires --drift-interval-s (alarms are the "
                    "trigger). Default: off -- responses and streams "
                    "stay byte-identical")
    p.add_argument("--stack-models", action="store_true",
                   help="cross-model coalescing: one tick's requests "
                   "for DIFFERENT models of one numeric family score "
                   "through a single stacked executable call "
                   "(bit-identical to per-model dispatch; "
                   "docs/TENANCY.md \"Serving the fleet\")")
    args = p.parse_args(argv)

    if args.socket and (args.input or args.output):
        # Loud conflict, not a silent ignore: socket mode replies on
        # each client's own connection, so --input/--output could never
        # take effect.
        p.error("--socket conflicts with --input/--output (socket "
                "clients carry their own request/response streams)")
    if args.http is not None and (args.socket or args.input
                                  or args.output):
        p.error("--http conflicts with --socket/--input/--output "
                "(HTTP clients carry their own request/response "
                "streams)")
    if (args.tick_min_ms is not None and args.tick_max_ms is not None
            and args.tick_max_ms < args.tick_min_ms):
        p.error("--tick-max-ms must be >= --tick-min-ms")
    if args.workers and args.http is None:
        p.error("--workers forks processes behind the HTTP front end; "
                "it requires --http")
    if args.workers < 0:
        p.error("--workers must be >= 0")

    if args.http is not None and args.workers > 0:
        # Pool mode: this process becomes a pure HTTP router +
        # supervisor over N forked `gmm serve --socket` workers. It
        # never loads a model or touches an executor, so a worker's
        # death can never take the front end with it.
        return _serve_pool_main(args)

    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device
        import jax

        jax.config.update("jax_platforms", args.device)

    registry = ModelRegistry(args.registry)
    lifecycle = None
    if args.lifecycle:
        if args.drift_interval_s is None:
            p.error("--lifecycle consumes drift alarms; it requires "
                    "--drift-interval-s")
        from ..lifecycle import LifecycleController, LifecycleError
        from ..lifecycle import LifecyclePolicy

        try:
            lifecycle = LifecycleController(
                registry, LifecyclePolicy.from_file(args.lifecycle))
        except LifecycleError as e:
            p.error(str(e))
    server = GMMServer(registry,
                       max_batch_rows=args.max_batch_rows,
                       tick_s=args.tick_ms / 1e3,
                       tick_s_min=(args.tick_min_ms / 1e3
                                   if args.tick_min_ms is not None
                                   else None),
                       tick_s_max=(args.tick_max_ms / 1e3
                                   if args.tick_max_ms is not None
                                   else None),
                       warm=not args.no_warmup,
                       max_queue_rows=args.max_queue_rows,
                       default_deadline_ms=args.default_deadline_ms,
                       breaker_threshold=args.breaker_threshold,
                       breaker_backoff_s=args.breaker_backoff_s,
                       stack_models=args.stack_models,
                       trace_requests=args.metrics_port is not None,
                       drift_interval_s=args.drift_interval_s,
                       drift_psi_threshold=args.drift_psi_threshold,
                       autotune=args.autotune,
                       tuning_db=args.tuning_db,
                       lifecycle=lifecycle)

    rec = (telemetry.RunRecorder(args.metrics_file)
           if args.metrics_file else telemetry.RunRecorder())
    rec.set_context(path="serve")

    # The run supervisor gives `gmm serve` the fit CLI's preemption
    # contract (docs/ROBUSTNESS.md "Run lifecycle"): SIGTERM/SIGINT and
    # the --max-runtime deadline flip a graceful drain observed by the
    # tick loop, never a mid-dispatch kill. Signal handlers install on
    # the main thread only (library/thread callers keep deadline
    # support).
    sup = supervisor_mod.RunSupervisor(max_runtime_s=args.max_runtime)

    from ..utils.profiling import trace as profiler_trace

    with telemetry.use(rec), rec, supervisor_mod.use(sup), \
            tl_exporter.live_plane(
                args.metrics_port,
                registry_provider=lambda: telemetry.current().metrics,
                gauges_provider=server.live_gauges,
                recorder=rec), \
            (tl_profiling.watch() if rec.active
             else contextlib.nullcontext()), \
            profiler_trace(args.trace_dir):
        # Head-of-stream heartbeat (rev v2.3): the serve stream's first
        # record, so it carries the clock/clock0 anchor pair that lets
        # `gmm timeline` align this stream against a fit stream. The
        # rate limiter starts open, so this emits immediately.
        rec.heartbeat("serve")
        # Pre-resolve (and AOT-warm) the requested model set so the first
        # request never pays registry IO or a compile.
        names = args.models
        if names is None:
            names = registry.models()
        try:
            for spec in names:
                name, _, ver = spec.partition("@")
                server.resolve(name, int(ver) if ver else None)
        except (RegistryError, ValueError) as e:
            print(f"cannot load {spec!r}: {e}", file=sys.stderr)
            return 1

        front = None
        if args.http is not None:
            from .http import HTTPFrontEnd, InprocBackend

            front = HTTPFrontEnd(
                InprocBackend(server), host=args.http_host,
                port=args.http, max_body_bytes=args.max_body_bytes,
                read_timeout_s=args.read_timeout_s,
                max_connections=args.http_max_connections,
                # /readyz flips the instant the stop flag trips (signal
                # time), BEFORE the tick loop notices and flushes: a
                # load balancer stops routing while the drain answers
                # what it already admitted.
                stopping=lambda: sup.stop_requested)
            front.start()
            _write_port_file(args.http_port_file, front.port)
            try:
                reason = server.run_loop(
                    max_requests=args.max_requests,
                    reload_interval_s=args.reload_interval_s)
            finally:
                front.stop()
        elif args.socket:
            reason = _serve_socket(server, args.socket, args.max_requests,
                                   args.reload_interval_s,
                                   read_timeout_s=args.read_timeout_s,
                                   max_line_bytes=args.max_body_bytes)
        else:
            out = (open(args.output, "w", encoding="utf-8")
                   if args.output else sys.stdout)
            lock = threading.Lock()
            reply = _stdout_replier(out, lock)
            src = (open(args.input, encoding="utf-8")
                   if args.input else sys.stdin)
            eof = threading.Event()

            def read_all():
                try:
                    for line in src:
                        server.submit_line(line, reply)
                finally:
                    eof.set()

            t = threading.Thread(target=read_all, daemon=True)
            t.start()
            try:
                reason = server.run_loop(
                    max_requests=args.max_requests, draining=eof.is_set,
                    reload_interval_s=args.reload_interval_s)
            finally:
                if args.input:
                    src.close()
                if args.output:
                    out.close()
        server.emit_summary(**({"http": front.http_rollup()}
                               if front is not None else {}))
        if reason == "preempted":
            # The PR-4 exit contract: drained by signal/deadline ->
            # telemetry shutdown record + exit 75 (EX_TEMPFAIL), so a
            # batch scheduler restarts the server unconditionally.
            stop_reason = server.drain_reason or "preempt"
            if rec.active:
                rec.emit("shutdown", reason=stop_reason,
                         checkpointed=False)
            print(f"Preempted -- serve loop drained ({stop_reason}); "
                  "queued requests flushed", file=sys.stderr)
            return supervisor_mod.EX_TEMPFAIL
    return 0
