"""Micro-batched scoring server: the ``gmm serve`` request loop.

The third serving layer (docs/SERVING.md): a JSONL request protocol over
stdin/stdout (default), a request file, or a UNIX socket, feeding a
micro-batching dispatcher that coalesces concurrent score requests into
ONE padded executor dispatch per tick and routes per-model.

Protocol -- one JSON object per line, one response line per request::

    {"id": 7, "model": "cells", "op": "score_samples", "x": [[...], ...]}
    -> {"id": 7, "ok": true, "model": "cells", "version": 2,
        "op": "score_samples", "n": 2, "result": [...],
        "latency_ms": 0.8}

``op`` is one of ``predict`` / ``predict_proba`` / ``score_samples`` /
``score`` (the estimator surface); ``version`` pins a registry version
(default: newest); ``{"op": "shutdown"}`` stops the server after
draining. Errors come back on the same id with ``ok: false`` and an
``error`` message -- a malformed request never kills the loop.

Micro-batching: requests arriving within one tick (``tick_s``) are
grouped by (model, version) and each group's rows are concatenated into
a single bucketed executor dispatch; per-request results are sliced back
out. All four ops ride the SAME 'proba' executable, so a mixed batch
(score + predict for one model) still coalesces into one dispatch --
the batched dispatch is bit-identical to per-request dispatches because
rows are independent through the per-event log-sum-exp (the coalescing
parity test, tests/test_serving.py).

Telemetry (stream rev v1.6, docs/OBSERVABILITY.md): ``serve_request``
per request, ``serve_batch`` per coalesced dispatch, and a closing
``serve_summary`` with QPS + latency percentiles + the MetricsRegistry
snapshot -- rendered by ``gmm report``.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from .executor import ScoringExecutor, executor_for_model
from .registry import ModelRegistry, RegistryError, ServedModel

OPS = ("predict", "predict_proba", "score_samples", "score")

# Latency samples kept for the summary percentiles (bounded).
_LATENCY_CAP = 100_000


class _Pending:
    """One in-flight request: the decoded body, where to reply, when it
    arrived."""

    __slots__ = ("req", "reply", "t0")

    def __init__(self, req: dict, reply: Callable[[dict], None]):
        self.req = req
        self.reply = reply
        self.t0 = time.perf_counter()


class GMMServer:
    """Per-model routed, micro-batched scoring over a model registry."""

    def __init__(self, registry: ModelRegistry, *,
                 max_batch_rows: int = 8192, tick_s: float = 0.002,
                 executor: Optional[ScoringExecutor] = None,
                 warm: bool = True):
        self._registry = registry
        self._max_batch_rows = max(1, int(max_batch_rows))
        self._tick_s = max(0.0, float(tick_s))
        self._executor_override = executor
        self._warm = bool(warm)
        self._models: Dict[Tuple[str, Optional[int]], ServedModel] = {}
        self._executors: Dict[tuple, ScoringExecutor] = {}
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._stop = threading.Event()
        self._latencies: collections.deque = collections.deque(
            maxlen=_LATENCY_CAP)
        self._t_start = time.perf_counter()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.errors = 0

    # -- model / executor resolution ------------------------------------

    def resolve(self, name: str, version: Optional[int] = None
                ) -> ServedModel:
        """The (cached) served model for one (name, version) route.

        ``version=None`` pins the newest version AT FIRST USE -- a serve
        process is version-stable; export a new version and restart (or
        address it explicitly) to roll."""
        key = (name, version)
        m = self._models.get(key)
        if m is None:
            m = self._registry.load(name, version)
            self._models[key] = m
            self._models.setdefault((name, m.version), m)
            if self._warm:
                self._executor_for(m).warmup(m.state)
        return m

    def _executor_for(self, m: ServedModel) -> ScoringExecutor:
        if self._executor_override is not None:
            return self._executor_override
        key = (m.dtype, m.diag_only)
        ex = self._executors.get(key)
        if ex is None:
            ex = self._executors[key] = executor_for_model(m)
        return ex

    def executor_stats(self) -> Dict[str, int]:
        """Aggregated executor counters across every family served."""
        execs = ([self._executor_override] if self._executor_override
                 else list(self._executors.values()))
        tot: Dict[str, int] = {}
        for ex in execs:
            for k, v in ex.stats().items():
                tot[k] = tot.get(k, 0) + v
        return tot

    # -- request handling ------------------------------------------------

    def handle_requests(self, requests: List[dict], *,
                        coalesce: bool = True) -> List[dict]:
        """Synchronous convenience: score a request list, return the
        responses in request order. ``coalesce=False`` dispatches one
        request at a time (the parity baseline the micro-batch is tested
        against)."""
        responses: List[Optional[dict]] = [None] * len(requests)
        pendings = []
        for i, req in enumerate(requests):
            def reply(resp, _i=i):
                responses[_i] = resp
            pendings.append(_Pending(req, reply))
        if coalesce:
            self._process(pendings)
        else:
            for p in pendings:
                self._process([p])
        return [r for r in responses if r is not None]

    def _process(self, pendings: List[_Pending]) -> None:
        """Group one tick's requests per (model, version) and dispatch
        each group as a single coalesced executor call."""
        groups: "collections.OrderedDict[tuple, list]" = \
            collections.OrderedDict()
        for p in pendings:
            req = p.req
            if not isinstance(req, dict):
                self._reply_error(p, "request is not a JSON object")
                continue
            op = req.get("op")
            if op == "shutdown":
                self._stop.set()
                self._reply(p, {"id": req.get("id"), "ok": True,
                                "op": "shutdown"})
                continue
            if op == "ping":
                self._reply(p, {"id": req.get("id"), "ok": True,
                                "op": "ping"})
                continue
            if op not in OPS:
                self._reply_error(
                    p, f"unknown op {op!r} (expected one of "
                    f"{', '.join(OPS)}, ping, shutdown)")
                continue
            name = req.get("model")
            version = req.get("version")
            if not isinstance(name, str):
                self._reply_error(p, "request needs a 'model' name")
                continue
            if version is not None and not isinstance(version, int):
                self._reply_error(p, "'version' must be an integer")
                continue
            try:
                x = np.asarray(req.get("x"), np.float64)
                if x.ndim == 1 and x.size:
                    x = x[None, :]
                if x.ndim != 2 or x.shape[0] == 0:
                    raise ValueError(
                        f"'x' must be a non-empty [n, d] row list, got "
                        f"shape {x.shape}")
                if not np.isfinite(x).all():
                    raise ValueError("'x' contains NaN/Inf rows")
            except (ValueError, TypeError) as e:
                self._reply_error(p, f"bad 'x': {e}")
                continue
            groups.setdefault((name, version), []).append((p, x))
        for (name, version), items in groups.items():
            self._dispatch(name, version, items)

    def _dispatch(self, name: str, version: Optional[int],
                  items: List[Tuple[_Pending, np.ndarray]]) -> None:
        """One coalesced dispatch: concatenate every request's rows,
        score once, slice per request, answer per op."""
        rec = telemetry.current()
        t0 = time.perf_counter()
        try:
            m = self.resolve(name, version)
        except (RegistryError, OSError) as e:
            for p, _ in items:
                self._reply_error(p, str(e), model=name)
            return
        d = m.d
        bad, good = [], []
        for p, x in items:
            if x.shape[1] != d:
                bad.append((p, f"model {name!r} has D={d} but 'x' rows "
                            f"have D={x.shape[1]}"))
            else:
                good.append((p, x))
        for p, msg in bad:
            self._reply_error(p, msg, model=name)
        if not good:
            return
        ex = self._executor_for(m)
        xs = [x for _, x in good]
        rows = np.concatenate(xs, axis=0).astype(
            np.dtype(m.dtype), copy=False)
        rows = rows - m.data_shift[None, :].astype(rows.dtype)
        compiles_before = ex.compile_count
        w, logz = ex.infer(m.state, rows, want="proba")
        wall_ms = (time.perf_counter() - t0) * 1e3
        compiled = ex.compile_count - compiles_before
        self.batches += 1
        self.rows += int(rows.shape[0])
        if rec.active:
            rec.emit("serve_batch", model=name, version=m.version,
                     requests=len(good), rows=int(rows.shape[0]),
                     padded_rows=int(ex.padded_rows(rows.shape[0])),
                     wall_ms=round(wall_ms, 3), compiled=int(compiled))
            rec.metrics.count("serve_batches")
            rec.metrics.count("serve_rows", int(rows.shape[0]))
            rec.metrics.count("serve_compiles", int(compiled))
            rec.metrics.observe("serve.batch_ms", wall_ms)
            rec.metrics.observe("serve.batch_rows", int(rows.shape[0]))
        start = 0
        for p, x in good:
            n = int(x.shape[0])
            wi = w[start:start + n, :m.k]
            zi = logz[start:start + n]
            start += n
            op = p.req["op"]
            if op == "predict":
                result: Any = np.argmax(wi, axis=1).tolist()
            elif op == "predict_proba":
                result = wi.tolist()
            elif op == "score_samples":
                result = zi.tolist()
            else:  # score
                result = float(np.mean(zi))
            self._reply(p, {
                "id": p.req.get("id"), "ok": True, "model": name,
                "version": m.version, "op": op, "n": n,
                "result": result,
            })

    def _reply(self, p: _Pending, resp: dict) -> None:
        latency_ms = (time.perf_counter() - p.t0) * 1e3
        resp.setdefault("latency_ms", round(latency_ms, 3))
        self.requests += 1
        self._latencies.append(latency_ms)
        rec = telemetry.current()
        if rec.active:
            rec.emit("serve_request",
                     model=resp.get("model", p.req.get("model")),
                     op=resp.get("op", p.req.get("op")),
                     n=int(resp.get("n", 0)),
                     latency_ms=round(latency_ms, 3),
                     ok=bool(resp.get("ok")),
                     **({"version": resp["version"]}
                        if "version" in resp else {}),
                     **({"error": resp["error"]}
                        if "error" in resp else {}))
            rec.metrics.count("serve_requests")
            rec.metrics.observe("serve.latency_ms", latency_ms)
        p.reply(resp)

    def _reply_error(self, p: _Pending, msg: str, model=None) -> None:
        self.errors += 1
        rec = telemetry.current()
        if rec.active:
            rec.metrics.count("serve_errors")
        self._reply(p, {"id": (p.req.get("id")
                               if isinstance(p.req, dict) else None),
                        "ok": False, "error": msg,
                        **({"model": model} if model else {})})

    # -- summary ---------------------------------------------------------

    def latency_summary(self) -> Dict[str, float]:
        lat = np.asarray(self._latencies, np.float64)
        if lat.size == 0:
            return {"p50": 0.0, "p99": 0.0, "mean": 0.0, "max": 0.0}
        return {
            "p50": round(float(np.percentile(lat, 50)), 3),
            "p99": round(float(np.percentile(lat, 99)), 3),
            "mean": round(float(lat.mean()), 3),
            "max": round(float(lat.max()), 3),
        }

    def emit_summary(self) -> Optional[dict]:
        """The closing ``serve_summary`` record (run_summary's serving
        sibling): volume, QPS, latency percentiles, executor counters,
        and the metrics-registry snapshot."""
        rec = telemetry.current()
        wall = time.perf_counter() - self._t_start
        if not rec.active:
            return None
        return rec.emit(
            "serve_summary",
            requests=int(self.requests), batches=int(self.batches),
            rows=int(self.rows), errors=int(self.errors),
            wall_s=round(wall, 6),
            qps=round(self.requests / wall, 3) if wall > 0 else 0.0,
            latency_ms=self.latency_summary(),
            models=sorted({f"{n}@{m.version}"
                           for (n, _), m in self._models.items()}),
            executor=self.executor_stats(),
            metrics=rec.metrics.snapshot(),
        )

    # -- streaming loops -------------------------------------------------

    def submit_line(self, line: str, reply: Callable[[dict], None]) -> None:
        """Decode one protocol line onto the batching queue (reader
        threads call this; the tick loop drains it)."""
        line = line.strip()
        if not line:
            return
        try:
            req = json.loads(line)
        except ValueError as e:
            p = _Pending({}, reply)
            self._reply_error(p, f"not JSON: {e}")
            return
        self._queue.put(_Pending(req, reply))

    def run_loop(self, *, max_requests: Optional[int] = None,
                 idle_timeout_s: Optional[float] = None,
                 draining: Optional[Callable[[], bool]] = None) -> None:
        """The micro-batching tick loop: block for the first pending
        request, gather everything that arrives within one tick (bounded
        by ``max_batch_rows``), dispatch the coalesced groups, repeat.

        Ends on ``shutdown``, after ``max_requests`` replies, after
        ``idle_timeout_s`` with an empty queue, or -- with ``draining``
        supplied (stdin mode: True once EOF hit) -- when the input is
        exhausted and the queue is empty.
        """
        while not self._stop.is_set():
            if max_requests is not None and self.requests >= max_requests:
                break
            try:
                first = self._queue.get(timeout=idle_timeout_s or 0.1)
            except queue.Empty:
                if idle_timeout_s is not None:
                    break
                if draining is not None and draining():
                    break
                continue
            if first is None:
                break
            batch = [first]
            rows = _rows_of(first)
            deadline = time.perf_counter() + self._tick_s
            while rows < self._max_batch_rows:
                remaining = deadline - time.perf_counter()
                try:
                    p = (self._queue.get_nowait() if remaining <= 0
                         else self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
                if p is None:
                    self._stop.set()
                    break
                batch.append(p)
                rows += _rows_of(p)
            self._process(batch)
        # Drain whatever is still queued (EOF/shutdown must not drop
        # accepted requests on the floor).
        leftovers = []
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            if p is not None:
                leftovers.append(p)
        if leftovers:
            self._process(leftovers)


def _rows_of(p: _Pending) -> int:
    x = p.req.get("x") if isinstance(p.req, dict) else None
    try:
        return max(len(x), 1)
    except TypeError:
        return 1


def _stdout_replier(out, lock: threading.Lock) -> Callable[[dict], None]:
    def reply(resp: dict) -> None:
        line = json.dumps(resp, default=_json_default)
        with lock:
            out.write(line + "\n")
            out.flush()
    return reply


def _json_default(o):
    item = getattr(o, "item", None)
    if callable(item):
        return o.item()
    tolist = getattr(o, "tolist", None)
    if callable(tolist):
        return o.tolist()
    return str(o)


def _serve_socket(server: GMMServer, path: str,
                  max_requests: Optional[int]) -> None:
    """UNIX-socket front end: every connection speaks the same JSONL
    protocol; requests from ALL connections land on one batching queue,
    so concurrent clients coalesce into shared dispatches (the
    micro-batching win a per-connection loop could never get)."""
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            lock = threading.Lock()

            def reply(resp: dict) -> None:
                line = json.dumps(resp, default=_json_default)
                try:
                    with lock:
                        self.wfile.write(line.encode() + b"\n")
                        self.wfile.flush()
                except (BrokenPipeError, OSError):
                    pass  # client went away; the dispatch already ran

            for raw in self.rfile:
                server.submit_line(raw.decode("utf-8", "replace"), reply)
                if server._stop.is_set():
                    break

    class Srv(socketserver.ThreadingMixIn,
              socketserver.UnixStreamServer):
        daemon_threads = True

    if os.path.exists(path):
        os.remove(path)
    with Srv(path, Handler) as srv:
        t = threading.Thread(target=srv.serve_forever,
                             kwargs={"poll_interval": 0.05}, daemon=True)
        t.start()
        try:
            server.run_loop(max_requests=max_requests)
        finally:
            srv.shutdown()
            try:
                os.remove(path)
            except OSError:
                pass


def serve_main(argv=None) -> int:
    """``gmm serve``: run the micro-batched scoring loop over a registry."""
    import argparse

    p = argparse.ArgumentParser(
        prog="gmm serve",
        description="Serve registry models over the JSONL request "
        "protocol: stdin/stdout by default, a request file with "
        "--input, or a UNIX socket with --socket (docs/SERVING.md).")
    p.add_argument("--registry", required=True,
                   help="model registry root directory (gmm export)")
    p.add_argument("--models", nargs="*", default=None,
                   metavar="NAME[@VERSION]",
                   help="models to load (and AOT-warm) at startup; "
                   "default: every registered model's newest version. "
                   "Requests may still address any registry model")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="serve a UNIX stream socket instead of "
                   "stdin/stdout (concurrent clients share the "
                   "micro-batch queue)")
    p.add_argument("--input", default=None, metavar="FILE.jsonl",
                   help="read requests from a file instead of stdin")
    p.add_argument("--output", default=None, metavar="FILE.jsonl",
                   help="write responses to a file instead of stdout")
    p.add_argument("--max-batch-rows", type=int, default=8192,
                   help="coalesced rows per dispatch tick (default 8192)")
    p.add_argument("--tick-ms", type=float, default=2.0,
                   help="micro-batch gather window in milliseconds "
                   "(default 2)")
    p.add_argument("--max-requests", type=int, default=None,
                   help="exit after this many responses (benchmarks, "
                   "tests)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip AOT pre-compilation of loaded models "
                   "(first request pays the compile)")
    p.add_argument("--device", default=None,
                   help="JAX platform: tpu | cpu | gpu (default: auto)")
    p.add_argument("--metrics-file", default=None, metavar="FILE.jsonl",
                   help="serve telemetry stream: serve_request / "
                   "serve_batch / serve_summary records (schema rev "
                   "v1.6; render with `gmm report`)")
    args = p.parse_args(argv)

    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device
        import jax

        jax.config.update("jax_platforms", args.device)

    registry = ModelRegistry(args.registry)
    server = GMMServer(registry,
                       max_batch_rows=args.max_batch_rows,
                       tick_s=args.tick_ms / 1e3,
                       warm=not args.no_warmup)

    rec = (telemetry.RunRecorder(args.metrics_file)
           if args.metrics_file else telemetry.RunRecorder())
    rec.set_context(path="serve")

    with telemetry.use(rec), rec:
        # Pre-resolve (and AOT-warm) the requested model set so the first
        # request never pays registry IO or a compile.
        names = args.models
        if names is None:
            names = registry.models()
        try:
            for spec in names:
                name, _, ver = spec.partition("@")
                server.resolve(name, int(ver) if ver else None)
        except (RegistryError, ValueError) as e:
            print(f"cannot load {spec!r}: {e}", file=sys.stderr)
            return 1

        if args.socket:
            _serve_socket(server, args.socket, args.max_requests)
        else:
            out = (open(args.output, "w", encoding="utf-8")
                   if args.output else sys.stdout)
            lock = threading.Lock()
            reply = _stdout_replier(out, lock)
            src = (open(args.input, encoding="utf-8")
                   if args.input else sys.stdin)
            eof = threading.Event()

            def read_all():
                try:
                    for line in src:
                        server.submit_line(line, reply)
                finally:
                    eof.set()

            t = threading.Thread(target=read_all, daemon=True)
            t.start()
            try:
                server.run_loop(max_requests=args.max_requests,
                                draining=eof.is_set)
            finally:
                if args.input:
                    src.close()
                if args.output:
                    out.close()
        server.emit_summary()
    return 0
