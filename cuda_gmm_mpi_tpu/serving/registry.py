"""Model registry: versioned persistence of fitted mixtures for serving.

The reference is fit-and-exit -- its only artifact is the printf-rounded
``.summary``/``.results`` pair (gaussian.cu:1180-1197), which loses 3
decimals of every parameter and is never read back by the reference
itself. The registry closes that gap for the serving path: a fitted
mixture is persisted as a versioned artifact holding the EXACT
:class:`~cuda_gmm_mpi_tpu.state.GMMState` leaves (the atomic-npz format
shared with ``utils/checkpoint.py`` -- ``flatten_tree`` /
``write_npz_atomic`` / ``load_npz_tree``), so a re-hydrated model scores
bit-identically to the in-memory estimator it came from.

Layout (``<root>`` is the registry directory)::

    <root>/<name>/<version>/model.npz       # state leaves + data_shift
    <root>/<name>/<version>/manifest.json   # identity card (below)
    <root>/<name>/<version>/stage.candidate # marker: NOT live (lifecycle)
    <root>/<name>/<version>/quarantine.json # marker: rolled back / rejected

Versions are positive integers assigned monotonically per name;
``load(name)`` resolves the newest READABLE version (the checkpoint
walk-back semantics: a version torn by a crash warns and falls back to
the previous one instead of wedging the server; every version unreadable
raises :class:`RegistryError` with the aggregated failures). An
explicitly requested version never falls back -- a torn or mismatched
artifact is a loud :class:`RegistryError`.

The manifest records what the executor and the request router need
without opening the npz: K (active clusters), D, covariance_type, dtype,
the training run id, the final loglik, and -- for sweep-checkpoint
exports -- the model-order criterion and best score, so "which K won and
under which score" survives into serving (``gmm export``).

Staged versions (lifecycle, rev v2.6): a version saved with
``stage='candidate'`` carries a ``stage: candidate`` manifest stanza AND
a ``stage.candidate`` marker file, written BEFORE the npz so the version
is never transiently visible. Enumeration (:meth:`versions`,
:meth:`models`), the hot-reload poll (:meth:`latest_fingerprint` /
:meth:`poll`), and default :meth:`load` all skip marked versions --
candidates are invisible to every pre-lifecycle consumer -- while an
explicitly versioned ``load(name, v)`` still opens them (the canary
scorer's path). :meth:`promote` flips the stanza to ``stage: live``
first, then removes the marker: the marker is authoritative for
visibility, so a crash between the two steps (``promote_torn``) leaves
the candidate invisible and the flip retryable. :meth:`quarantine`
re-adds the marker plus a ``quarantine.json`` reason file;
:meth:`rollback` re-publishes a pinned prior version's exact leaves as
the newest live version (bit-identical scoring by the npz round-trip).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..state import GMMState
from ..utils.checkpoint import flatten_tree, load_npz_tree, write_npz_atomic

MODEL_FILE = "model.npz"
MANIFEST_FILE = "manifest.json"
# Training drift envelope (stream rev v2.4; telemetry/sketch.py): the
# fit data's score sketch + responsibility occupancy, persisted NEXT TO
# the model artifact. Optional by contract -- versions predating it (or
# fits that skipped the envelope pass) load fine without one, and `gmm
# drift --rebuild-envelope` can backfill it atomically without touching
# model.npz/manifest.json bit-identity.
ENVELOPE_FILE = "envelope.json"
# Lifecycle staging markers (rev v2.6). CANDIDATE_MARKER's PRESENCE is
# what enumeration skips -- a pure stat() check, so the hot-reload
# poll's "polling every few seconds is free" contract survives staging.
# QUARANTINE_FILE records WHY a version was pulled (rollback reason,
# failed canary gates); a quarantined version keeps the candidate
# marker so it can never be promoted or served again.
CANDIDATE_MARKER = "stage.candidate"
QUARANTINE_FILE = "quarantine.json"
MANIFEST_SCHEMA = 1

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class RegistryError(RuntimeError):
    """A registry artifact is missing, torn, or self-inconsistent.

    Raised loudly at save/load/export time -- a manifest whose K/D/dtype
    disagrees with the stored arrays must never be served quietly under
    the wrong densities (the same contract ``GaussianMixture.from_summary``
    enforces for the text format).
    """


@dataclasses.dataclass
class ServedModel:
    """One re-hydrated registry artifact, ready for the executor.

    ``state`` holds the exact fitted parameters (centered coordinates);
    ``data_shift`` is the fit-time centering shift that request data must
    be shifted by before scoring (``GMMResult.data_shift`` semantics).
    """

    name: str
    version: int
    state: GMMState
    data_shift: np.ndarray  # [D] float64
    manifest: Dict[str, Any]
    # Training drift envelope (envelope.json; rev v2.4) -- None for
    # versions that carry none. The server's drift plane compares
    # serve-time score/occupancy windows against it.
    envelope: Optional[Dict[str, Any]] = None

    @property
    def k(self) -> int:
        return int(self.manifest["k"])

    @property
    def d(self) -> int:
        return int(self.manifest["d"])

    @property
    def dtype(self) -> str:
        return str(self.manifest["dtype"])

    @property
    def covariance_type(self) -> str:
        return str(self.manifest["covariance_type"])

    @property
    def diag_only(self) -> bool:
        return self.covariance_type in ("diag", "spherical")


class ModelRegistry:
    """Versioned model store rooted at one directory."""

    def __init__(self, root: str):
        self._root = os.path.abspath(root)
        os.makedirs(self._root, exist_ok=True)

    @property
    def root(self) -> str:
        return self._root

    # -- enumeration -----------------------------------------------------

    def models(self) -> List[str]:
        """Registered model names (sorted).

        Names whose only versions are candidates are NOT listed --
        un-promoted lifecycle output is invisible here just as it is to
        the poll. A registry root deleted out from under a live server
        degrades to an empty listing (the tick loop's ``maybe_reload``
        must keep serving prepared state, not crash on a stat race).
        """
        try:
            entries = sorted(os.listdir(self._root))
        except OSError:
            return []
        out = []
        for name in entries:
            if _NAME_RE.match(name) and self.versions(name):
                out.append(name)
        return out

    def versions(self, name: str,
                 include_candidates: bool = False) -> List[int]:
        """Existing LIVE versions of ``name`` (ascending; [] when
        unknown). ``include_candidates=True`` adds versions still
        carrying the ``stage.candidate`` marker (lifecycle canaries and
        quarantined versions)."""
        d = os.path.join(self._root, self._check_name(name))
        try:
            entries = os.listdir(d)
        except OSError:
            return []
        return sorted(
            int(v) for v in entries
            if v.isdigit()
            and os.path.isfile(os.path.join(d, v, MODEL_FILE))
            and (include_candidates
                 or not os.path.exists(os.path.join(d, v,
                                                    CANDIDATE_MARKER))))

    def _check_name(self, name: str) -> str:
        if not _NAME_RE.match(name or ""):
            raise RegistryError(
                f"invalid model name {name!r} (letters, digits, '.', '_', "
                "'-' only; must not start with a separator)")
        return name

    # -- hot-reload polling ----------------------------------------------

    def latest_fingerprint(self, name: str) -> Optional[Tuple[int, str]]:
        """(newest version, its manifest fingerprint) for ``name``;
        None when the model has no complete version.

        The fingerprint is the manifest's mtime_ns:size -- the manifest
        is written LAST in the atomic save protocol, so its stat changes
        exactly when a new version becomes complete. Versions are
        immutable, so a changed (version, fingerprint) pair is always a
        NEW version (or a re-rooted registry), never a mutated one.
        """
        versions = self.versions(name)
        if not versions:
            return None
        v = versions[-1]
        man = os.path.join(self._root, name, str(v), MANIFEST_FILE)
        try:
            st = os.stat(man)
            fp = f"{st.st_mtime_ns}:{st.st_size}"
        except OSError:
            fp = ""  # torn mid-write; the next poll re-stats
        return (v, fp)

    def poll(self, snapshot: Dict[str, Tuple[int, str]]
             ) -> Dict[str, Tuple[int, str]]:
        """Models whose newest version changed vs ``snapshot``.

        ``snapshot`` maps name -> (version, fingerprint) as previously
        returned by :meth:`latest_fingerprint`; the result carries only
        the CHANGED entries with their new pair. The server's hot-reload
        loop (serving/server.py ``maybe_reload``) is the caller: it
        swaps the ``version=None`` route of each changed model and
        updates its snapshot. Pure stat()s -- no artifact is opened, so
        polling every few seconds is free.
        """
        changed: Dict[str, Tuple[int, str]] = {}
        for name in set(snapshot) | set(self.models()):
            cur = self.latest_fingerprint(name)
            if cur is not None and cur != snapshot.get(name):
                changed[name] = cur
        return changed

    # -- save ------------------------------------------------------------

    def save(self, name: str, result, *, config=None,
             covariance_type: Optional[str] = None,
             criterion: Optional[str] = None,
             run_id: Optional[str] = None,
             version: Optional[int] = None,
             source: str = "fit",
             stage: Optional[str] = None,
             extra: Optional[Dict[str, Any]] = None) -> int:
        """Persist a fitted :class:`GMMResult` as ``name``'s next version.

        ``config`` (the fit's :class:`GMMConfig`) supplies the covariance
        family and criterion when the explicit kwargs are absent; the
        dtype is read off the state itself. Returns the version number.
        The write is atomic (npz first, manifest last): a version whose
        manifest exists is complete, and a crash mid-save leaves only an
        ignorable orphan. ``stage='candidate'`` publishes a lifecycle
        canary: invisible to enumeration/poll/default-load until
        :meth:`promote` flips it live.
        """
        if stage not in (None, "live", "candidate"):
            raise RegistryError(
                f"unknown stage {stage!r} (live or candidate)")
        state = result.state
        k = int(result.ideal_num_clusters)
        d = int(result.num_dimensions) or int(state.num_dimensions)
        if int(state.num_clusters_padded) != k:
            # Registry artifacts store the COMPACT state (every slot
            # active) so K in the manifest is the arrays' leading axis.
            from ..state import compact

            state, k = compact(state)
        cov = covariance_type or (config.covariance_type if config
                                  else "full")
        crit = criterion or (config.criterion if config else None)
        manifest = {
            "schema": MANIFEST_SCHEMA,
            "name": self._check_name(name),
            "k": k,
            "d": d,
            "covariance_type": cov,
            "dtype": str(np.asarray(state.N).dtype),
            "loglik": _finite_or_none(result.final_loglik),
            "score": _finite_or_none(result.min_rissanen),
            "criterion": crit,
            "train_run_id": run_id,
            "num_events": int(getattr(result, "num_events", 0)),
            "source": source,
            "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }
        if extra:
            manifest.update(extra)
        if stage == "candidate":
            manifest["stage"] = "candidate"
        envelope = getattr(result, "envelope", None)
        if envelope is not None:
            # Small identity stanza only; the full envelope rides its
            # own sidecar file (ENVELOPE_FILE).
            from ..telemetry.sketch import envelope_stanza

            manifest["envelope"] = envelope_stanza(envelope)
        return self._write_version(name, version, state,
                                   np.asarray(result.data_shift,
                                              np.float64), manifest,
                                   envelope=envelope, stage=stage)

    def _write_version(self, name: str, version: Optional[int],
                       state: GMMState, data_shift: np.ndarray,
                       manifest: Dict[str, Any],
                       envelope: Optional[Dict[str, Any]] = None,
                       stage: Optional[str] = None) -> int:
        name = self._check_name(name)
        # Candidates occupy version numbers too -- a promotion must not
        # collide with a version assigned while it was invisible.
        existing = self.versions(name, include_candidates=True)
        if version is None:
            version = (existing[-1] + 1) if existing else 1
        elif version in existing:
            raise RegistryError(
                f"{name!r} version {version} already exists; versions are "
                "immutable -- save a new one")
        elif version < 1:
            raise RegistryError("versions are positive integers")
        manifest = dict(manifest, version=int(version))
        vdir = os.path.join(self._root, name, str(version))
        os.makedirs(vdir, exist_ok=True)
        if stage == "candidate":
            # Marker FIRST: the version directory must never be visible
            # to enumeration between the npz landing and the stage
            # becoming known. versions() requires MODEL_FILE, so an
            # orphan marker alone hides nothing it shouldn't.
            with open(os.path.join(vdir, CANDIDATE_MARKER), "w",
                      encoding="utf-8") as f:
                f.write("candidate\n")
        import jax

        host_state = jax.device_get(state)
        flat = flatten_tree({"state": host_state,
                             "data_shift": data_shift})
        write_npz_atomic(vdir, os.path.join(vdir, MODEL_FILE), flat)
        if envelope is not None:
            # Envelope sidecar BEFORE the manifest: the manifest stays
            # the one commit record, so a crash here leaves an
            # ignorable orphan, never a committed version missing its
            # declared envelope.
            _write_json_atomic(os.path.join(vdir, ENVELOPE_FILE),
                               envelope)
        # Manifest last: its presence is the commit record.
        tmp = os.path.join(vdir, MANIFEST_FILE + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(vdir, MANIFEST_FILE))
        return int(version)

    # -- load ------------------------------------------------------------

    def load(self, name: str, version: Optional[int] = None) -> ServedModel:
        """Re-hydrate ``name`` at ``version`` (default: newest readable).

        Explicit versions fail loudly on ANY problem; the default
        resolution walks back over torn versions with a warning (the
        ``utils/checkpoint.py`` restore semantics -- losing one version
        beats wedging the server) and raises an aggregated
        :class:`RegistryError` only when every version is unreadable.
        Each walk-back step also emits a counted ``registry_torn``
        telemetry event (rev v2.6) -- a silent walk-back is exactly what
        a botched promotion looks like, so it must show up in
        ``gmm report``/``/metrics`` (``gmm_registry_torn_total``).

        Default resolution sees LIVE versions only; an explicit
        ``version`` may name a candidate (the canary scorer's path).
        """
        if version is not None:
            if version not in self.versions(name,
                                            include_candidates=True):
                raise RegistryError(
                    f"{name!r} has no version {version} "
                    f"(existing: {self.versions(name)})")
            return self._load_version(name, int(version))
        versions = self.versions(name)
        if not versions:
            raise RegistryError(
                f"unknown model {name!r} in registry {self._root!r} "
                f"(registered: {', '.join(self.models()) or 'none'})")
        failures: List[Tuple[int, BaseException]] = []
        for v in reversed(versions):
            try:
                return self._load_version(name, v)
            except Exception as e:
                failures.append((v, e))
                warnings.warn(
                    f"registry model {name!r} version {v} unreadable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous version", RuntimeWarning)
                from .. import telemetry

                rec = telemetry.current()
                if rec.active:
                    rec.emit("registry_torn", model=name, version=int(v),
                             error=f"{type(e).__name__}: {e}")
                    rec.metrics.count("registry_torn")
        raise RegistryError(
            f"every version of {name!r} is unreadable: "
            + "; ".join(f"v{v}: {type(e).__name__}: {e}"
                        for v, e in failures)) from failures[0][1]

    def _load_version(self, name: str, version: int) -> ServedModel:
        from ..testing import faults

        if faults.take("registry_torn", name=name,
                       version=version) is not None:
            # Deterministic stand-in for an artifact torn on disk: the
            # walk-back, breaker, and hot-reload paths rehearse against
            # it (docs/ROBUSTNESS.md "Serving").
            raise RegistryError(
                f"{name!r} v{version}: injected registry_torn fault")
        vdir = os.path.join(self._root, self._check_name(name),
                            str(version))
        man_path = os.path.join(vdir, MANIFEST_FILE)
        try:
            with open(man_path, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(
                f"{name!r} v{version}: unreadable manifest: {e}") from e
        try:
            tree = load_npz_tree(os.path.join(vdir, MODEL_FILE),
                                 state_keys=("state",))
        except Exception as e:
            raise RegistryError(
                f"{name!r} v{version}: unreadable model artifact: "
                f"{e}") from e
        state = tree.get("state")
        if not isinstance(state, GMMState):
            raise RegistryError(
                f"{name!r} v{version}: artifact holds no state group")
        self._validate(name, version, manifest, state)
        shift = np.asarray(tree.get("data_shift",
                                    np.zeros((state.num_dimensions,))),
                           np.float64)
        # Envelope sidecar: optional by contract. Absent (pre-v2.4
        # versions, envelope-off fits) or unreadable -> None, never a
        # load failure -- drift observability must not break serving.
        envelope = None
        env_path = os.path.join(vdir, ENVELOPE_FILE)
        if os.path.isfile(env_path):
            try:
                with open(env_path, encoding="utf-8") as f:
                    envelope = json.load(f)
            except (OSError, ValueError) as e:
                warnings.warn(
                    f"registry model {name!r} v{version}: unreadable "
                    f"envelope.json ({e}); drift statistics unavailable "
                    "for this version", RuntimeWarning)
        return ServedModel(name=name, version=int(version), state=state,
                           data_shift=shift, manifest=manifest,
                           envelope=envelope)

    # -- drift envelopes -------------------------------------------------

    def load_envelope(self, name: str,
                      version: Optional[int] = None) -> Optional[dict]:
        """The training envelope of ``name``@``version`` (default:
        newest), or None when that version carries none."""
        return self.load(name, version).envelope

    def publish_envelope(self, name: str, version: int,
                         envelope: Dict[str, Any]) -> None:
        """Atomically (re)publish ``envelope.json`` for an EXISTING
        version -- the `gmm drift --rebuild-envelope` backfill path.

        Versions are immutable ARTIFACTS, not immutable directories:
        the envelope is observability metadata, so writing it must not
        (and does not) touch ``model.npz`` or ``manifest.json`` --
        their bytes, and therefore ``latest_fingerprint``'s
        mtime_ns:size commit record, stay bit-identical.
        """
        if version not in self.versions(self._check_name(name)):
            raise RegistryError(
                f"{name!r} has no version {version} "
                f"(existing: {self.versions(name)})")
        vdir = os.path.join(self._root, name, str(int(version)))
        _write_json_atomic(os.path.join(vdir, ENVELOPE_FILE), envelope)

    # -- lifecycle staging (rev v2.6) ------------------------------------

    def stage(self, name: str, version: int) -> str:
        """``'live'``, ``'candidate'``, or ``'quarantined'`` for an
        existing version (marker-file semantics; pure stat()s)."""
        vdir = os.path.join(self._root, self._check_name(name),
                            str(int(version)))
        if not os.path.isfile(os.path.join(vdir, MODEL_FILE)):
            raise RegistryError(
                f"{name!r} has no version {version} "
                f"(existing: {self.versions(name, include_candidates=True)})")
        if os.path.exists(os.path.join(vdir, QUARANTINE_FILE)):
            return "quarantined"
        if os.path.exists(os.path.join(vdir, CANDIDATE_MARKER)):
            return "candidate"
        return "live"

    def promote(self, name: str, version: int) -> None:
        """Atomically flip a candidate version live.

        Protocol: (1) rewrite the manifest with ``stage: live`` (tmp +
        fsync + rename -- this changes the manifest's mtime_ns:size, so
        once visible the version reads as NEW to every poll snapshot);
        (2) remove the candidate marker. The marker is authoritative for
        enumeration, so a crash between the steps -- the ``promote_torn``
        fault point -- leaves the candidate invisible and the promotion
        retryable; it can never publish a half-flipped version. The
        existing hot-reload path (``maybe_reload``) then does the actual
        route swap; breaker state deliberately carries over.
        """
        st = self.stage(name, version)
        if st == "quarantined":
            raise RegistryError(
                f"{name!r} v{version} is quarantined; it can never be "
                "promoted (see its quarantine.json)")
        if st == "live":
            raise RegistryError(f"{name!r} v{version} is already live")
        vdir = os.path.join(self._root, name, str(int(version)))
        man_path = os.path.join(vdir, MANIFEST_FILE)
        try:
            with open(man_path, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(
                f"{name!r} v{version}: unreadable manifest: {e}") from e
        manifest["stage"] = "live"
        manifest["promoted_utc"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        _write_json_atomic(man_path, manifest)
        from ..testing import faults

        if faults.take("promote_torn", name=name,
                       version=version) is not None:
            # Crash between the manifest flip and the marker removal:
            # the candidate stays invisible, the flip stays retryable.
            raise RegistryError(
                f"{name!r} v{version}: injected promote_torn fault "
                "(manifest flipped, marker still present)")
        os.remove(os.path.join(vdir, CANDIDATE_MARKER))

    def quarantine(self, name: str, version: int,
                   reason: Optional[Dict[str, Any]] = None) -> None:
        """Pull a version permanently: write a ``quarantine.json``
        reason file and (re)add the candidate marker so enumeration,
        the poll, and default load all skip it. Idempotent; works on
        candidates (failed canary) and on live versions (rollback of a
        bad promotion)."""
        vdir = os.path.join(self._root, self._check_name(name),
                            str(int(version)))
        if not os.path.isfile(os.path.join(vdir, MODEL_FILE)):
            raise RegistryError(
                f"{name!r} has no version {version} "
                f"(existing: {self.versions(name, include_candidates=True)})")
        marker = os.path.join(vdir, CANDIDATE_MARKER)
        if not os.path.exists(marker):
            with open(marker, "w", encoding="utf-8") as f:
                f.write("quarantined\n")
        _write_json_atomic(
            os.path.join(vdir, QUARANTINE_FILE),
            dict(reason or {}, name=name, version=int(version),
                 quarantined_utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())))

    def rollback(self, name: str, *, to_version: int,
                 bad_version: Optional[int] = None,
                 reason: Optional[Dict[str, Any]] = None) -> int:
        """Restore a pinned prior version as the NEWEST live version.

        Versions are immutable, so rollback RE-PUBLISHES ``to_version``'s
        exact leaves under a fresh version number (the npz round-trip is
        bit-exact, so the restored model scores bit-identically to the
        pinned one); ``bad_version`` (the promotion being undone) is
        quarantined with ``reason``. Returns the new version number --
        the next poll sees it as newest and the hot-reload path swaps
        the route back.
        """
        src = self._load_version(self._check_name(name), int(to_version))
        manifest = {k: v for k, v in src.manifest.items()
                    if k not in ("version", "stage", "promoted_utc")}
        manifest.update(
            source="rollback",
            restored_version=int(to_version),
            rollback_of=(int(bad_version) if bad_version is not None
                         else None))
        new_v = self._write_version(name, None, src.state,
                                    src.data_shift, manifest,
                                    envelope=src.envelope)
        if bad_version is not None:
            self.quarantine(name, int(bad_version),
                            dict(reason or {},
                                 restored_as=int(new_v),
                                 restored_version=int(to_version)))
        return int(new_v)

    def _validate(self, name, version, manifest, state: GMMState) -> None:
        """The loud manifest-vs-arrays contract: serving a model whose
        identity card lies about its shapes/family would score every
        request under the wrong densities."""
        where = f"{name!r} v{version}"
        k = int(manifest.get("k", -1))
        d = int(manifest.get("d", -1))
        if state.num_clusters_padded != k or state.num_dimensions != d:
            raise RegistryError(
                f"{where}: manifest says K={k} D={d} but the stored state "
                f"is K={state.num_clusters_padded} "
                f"D={state.num_dimensions}")
        dtype = str(manifest.get("dtype"))
        actual = str(np.asarray(state.N).dtype)
        if dtype != actual:
            raise RegistryError(
                f"{where}: manifest dtype {dtype!r} != stored {actual!r}")
        cov = manifest.get("covariance_type")
        if cov not in ("full", "diag", "spherical", "tied"):
            raise RegistryError(
                f"{where}: unknown covariance_type {cov!r}")
        if cov in ("diag", "spherical"):
            R = np.asarray(state.R)
            offdiag = R - np.stack([np.diag(np.diag(r)) for r in R])
            if np.abs(offdiag).max() > 0:
                raise RegistryError(
                    f"{where}: manifest says covariance_type={cov!r} but "
                    "the stored covariances carry nonzero off-diagonals")

    # -- export paths ----------------------------------------------------

    def export_result(self, name: str, result, **kw) -> int:
        """Alias of :meth:`save` (the library export entry point)."""
        return self.save(name, result, **kw)

    def export_checkpoint(self, checkpoint_dir: str, name: str, *,
                          version: Optional[int] = None,
                          run_id: Optional[str] = None) -> int:
        """Export the BEST-scoring model from an order-search sweep
        checkpoint directory.

        A sweep checkpoint's ``state`` is the in-flight K of the step it
        was taken at -- the LAST fitted K, usually not the winner.
        Export selects ``best_state`` (the best-criterion configuration
        so far, the ``saved_clusters`` analog) and records the score
        criterion, best score, and loglik in the manifest, so the served
        model is the one the sweep would have returned. Both the
        host-driven and fused-sweep checkpoint payloads are understood;
        a checkpoint predating the ``data_shift`` field exports with a
        zero shift and a loud warning (its fit may have centered data).
        """
        from ..models.order_search import (_COV_NAME, _CRITERION_NAME,
                                           GMMResult)
        from ..state import compact
        from ..utils.checkpoint import SweepCheckpointer

        sweep_dir = os.path.join(os.path.abspath(checkpoint_dir), "sweep")
        if not os.path.isdir(sweep_dir):
            raise RegistryError(
                f"{checkpoint_dir!r} holds no sweep checkpoints")
        restored = SweepCheckpointer(checkpoint_dir).restore()
        if restored is None:
            raise RegistryError(
                f"{checkpoint_dir!r} holds no restorable checkpoint step")
        best = restored["best_state"]
        if "fused_log" in restored:  # fused-sweep payload key names
            score = float(restored["best_riss"])
            loglik = float(restored["best_ll"])
        else:
            score = float(restored["min_rissanen"])
            loglik = float(restored["best_ll"])
        criterion = _CRITERION_NAME.get(
            int(restored.get("criterion_code", 0)), "rissanen")
        cov = _COV_NAME.get(int(restored.get("cov_code", 0)), "full")
        state, k_active = compact(best)
        if "data_shift" in restored:
            shift = np.asarray(restored["data_shift"], np.float64)
        else:
            shift = np.zeros((state.num_dimensions,), np.float64)
            warnings.warn(
                "checkpoint predates the data_shift field; exporting with "
                "a zero shift -- if the original fit centered its data "
                "(the default), served scores will be wrong. Re-fit or "
                "export from the .summary instead.", RuntimeWarning)
        result = GMMResult(
            state=state,
            ideal_num_clusters=k_active,
            min_rissanen=score,
            final_loglik=loglik,
            epsilon=float("nan"),
            num_events=0,
            num_dimensions=int(state.num_dimensions),
            data_shift=shift,
        )
        return self.save(
            name, result, covariance_type=cov, criterion=criterion,
            run_id=run_id, version=version, source="checkpoint",
            extra={"checkpoint_step": int(restored.get("step", -1)),
                   "checkpoint_dir": os.path.abspath(checkpoint_dir)})

    def export_fleet(self, fleet_dir: str, *,
                     version: Optional[int] = None) -> List[dict]:
        """Bulk export: one atomic version PER TENANT MODEL from a fleet
        fit's output directory (``gmm fleet --out-dir``).

        Reads ``<fleet_dir>/fleet.json`` and exports every fitted
        tenant's ``.summary`` under its tenant name. Partial failure is
        per tenant, never run-fatal: each row of the returned audit list
        carries either the assigned ``version`` or the ``error`` that
        skipped it (plus ``skipped: dropped`` rows for tenants the fleet
        itself dropped). Exact-state exports come from ``gmm fleet
        --registry`` in the fitting invocation; this path serves the
        decoupled fit-here-export-later workflow at the text format's
        precision.
        """
        manifest_path = os.path.join(os.path.abspath(fleet_dir),
                                     "fleet.json")
        try:
            with open(manifest_path, encoding="utf-8") as f:
                fleet = json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(
                f"cannot read fleet manifest {manifest_path!r}: {e}"
            ) from e
        rows = fleet.get("tenants")
        if not isinstance(rows, list) or not rows:
            raise RegistryError(
                f"{manifest_path!r} lists no tenants")
        audit: List[dict] = []
        for row in rows:
            name = str(row.get("name"))
            if row.get("dropped"):
                audit.append({"name": name, "skipped": "dropped",
                              "error": row.get("error")})
                continue
            summary = row.get("summary")
            try:
                if not summary:
                    raise RegistryError(
                        "fleet.json row carries no summary path (was "
                        "the fleet run without --out-dir?)")
                v = self.export_summary(
                    summary, name,
                    covariance_type=row.get("covariance_type", "full"),
                    dtype=row.get("dtype", "float32"),
                    version=version)
                entry = {"name": name, "version": int(v)}
                env_path = row.get("envelope")
                if env_path:
                    # Republish the fleet fit's per-tenant training
                    # envelope next to the exported version (rev v2.4).
                    # Per-tenant containment applies here too: a torn
                    # envelope file degrades to an envelope-less
                    # version, it does not void the export.
                    try:
                        with open(env_path, encoding="utf-8") as f:
                            self.publish_envelope(name, v, json.load(f))
                        entry["envelope"] = True
                    except (OSError, ValueError) as e:
                        entry["envelope_error"] = str(e)
                audit.append(entry)
            except (RegistryError, OSError, ValueError) as e:
                # Per-tenant containment: one torn summary must not
                # void its siblings' exports.
                audit.append({"name": name, "error": str(e)})
        return audit

    def export_summary(self, summary_path: str, name: str, *,
                       covariance_type: str = "full",
                       dtype: str = "float32",
                       version: Optional[int] = None) -> int:
        """Export a ``.summary`` model file (ours or the reference's own).

        Carries the text format's 3-decimal precision -- exact
        persistence comes from exporting the in-memory fit
        (:meth:`save`); this path exists so reference-produced models can
        be served too. Constants/Rinv are recomputed coherently from R
        (``from_summary`` semantics).
        """
        from ..config import GMMConfig
        from ..estimator import GaussianMixture

        gm = GaussianMixture.from_summary(
            summary_path, config=GMMConfig(dtype=dtype,
                                           covariance_type=covariance_type))
        return self.save(
            name, gm.result_, covariance_type=gm.config.covariance_type,
            version=version, source="summary",
            extra={"summary_path": os.path.abspath(summary_path)})


def _finite_or_none(x) -> Optional[float]:
    x = float(x)
    return x if np.isfinite(x) else None


def _write_json_atomic(path: str, obj: Any) -> None:
    """tmp + fsync + rename in the artifact's own directory (the
    manifest write discipline, shared by the envelope sidecar)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def export_main(argv=None) -> int:
    """``gmm export``: persist a model into a serving registry.

    Sources (exactly one): ``--checkpoint DIR`` (an order-search sweep
    checkpoint directory -- exports the best-scoring K, not the last
    step) or ``--summary FILE.summary`` (the text model format, 3-decimal
    precision).
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="gmm export",
        description="Export a fitted model into a serving registry "
        "(docs/SERVING.md); --fleet bulk-exports one version per tenant "
        "from a fleet fit (docs/TENANCY.md).")
    p.add_argument("--registry", required=True,
                   help="registry root directory (created if absent)")
    p.add_argument("--name", default=None, help="model name (single-"
                   "model sources; --fleet uses tenant names)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint", metavar="DIR",
                     help="order-search sweep checkpoint directory; "
                     "exports the best-scoring K with its criterion")
    src.add_argument("--summary", metavar="FILE.summary",
                     help="a .summary model file (ours or the "
                     "reference's)")
    src.add_argument("--fleet", metavar="DIR",
                     help="a `gmm fleet --out-dir` directory: bulk-"
                     "export ONE version per fitted tenant (per-model "
                     "atomic npz; per-tenant failures reported, not "
                     "run-fatal)")
    p.add_argument("--covariance-type", default="full",
                   choices=["full", "diag", "spherical", "tied"],
                   help="covariance family of a --summary model "
                   "(checkpoints record their own)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"],
                   help="dtype for a --summary model")
    p.add_argument("--version", type=int, default=None,
                   help="explicit version (default: next)")
    args = p.parse_args(argv)

    import sys

    if args.fleet:
        if args.name is not None:
            p.error("--fleet exports under tenant names; drop --name")
        reg = ModelRegistry(args.registry)
        try:
            audit = reg.export_fleet(args.fleet, version=args.version)
        except (RegistryError, OSError) as e:
            print(f"fleet export failed: {e}", file=sys.stderr)
            return 1
        ok = 0
        for row in audit:
            if "version" in row:
                ok += 1
                print(f"exported {row['name']!r} version "
                      f"{row['version']}")
            elif row.get("skipped") == "dropped":
                print(f"skipped {row['name']!r}: dropped by the fleet "
                      f"fit ({row.get('error')})", file=sys.stderr)
            else:
                print(f"export of {row['name']!r} failed: "
                      f"{row.get('error')}", file=sys.stderr)
        print(f"fleet export: {ok}/{len(audit)} tenants exported")
        return 0 if ok else 1
    if args.name is None:
        p.error("--name is required for single-model sources")

    reg = ModelRegistry(args.registry)
    try:
        if args.checkpoint:
            v = reg.export_checkpoint(args.checkpoint, args.name,
                                      version=args.version)
        else:
            v = reg.export_summary(args.summary, args.name,
                                   covariance_type=args.covariance_type,
                                   dtype=args.dtype,
                                   version=args.version)
    except (RegistryError, OSError, ValueError) as e:
        import sys

        print(f"export failed: {e}", file=sys.stderr)
        return 1
    m = reg.load(args.name, v).manifest
    crit = (f" {m['criterion']}={m['score']:.6e}"
            if m.get("criterion") and m.get("score") is not None else "")
    print(f"exported {args.name!r} version {v} "
          f"(K={m['k']}, D={m['d']}, {m['covariance_type']}, "
          f"{m['dtype']}{crit})")
    return 0
