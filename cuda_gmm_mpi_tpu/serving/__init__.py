"""Serving subsystem: registry, AOT scoring executables, micro-batching.

The inference half of the production story (docs/SERVING.md): the
training side fits mixtures; this package persists them as versioned
artifacts (:mod:`.registry`), compiles bucketed ahead-of-time scoring
executables so a warm request never traces or recompiles
(:mod:`.executor`), and serves coalesced micro-batched request traffic
per model (:mod:`.server`, the ``gmm serve`` CLI).
"""

from .breaker import CircuitBreakers
from .executor import (ScoringExecutor, executor_for_config,
                       executor_for_model, pow2_bucket)
from .registry import ModelRegistry, RegistryError, ServedModel
from .server import GMMServer, serve_main

__all__ = [
    "CircuitBreakers", "GMMServer", "ModelRegistry", "RegistryError",
    "ScoringExecutor", "ServedModel", "executor_for_config",
    "executor_for_model", "pow2_bucket", "serve_main",
]
