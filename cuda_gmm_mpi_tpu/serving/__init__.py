"""Serving subsystem: registry, AOT scoring executables, micro-batching.

The inference half of the production story (docs/SERVING.md): the
training side fits mixtures; this package persists them as versioned
artifacts (:mod:`.registry`), compiles bucketed ahead-of-time scoring
executables so a warm request never traces or recompiles
(:mod:`.executor`), and serves coalesced micro-batched request traffic
per model (:mod:`.server`, the ``gmm serve`` CLI).
"""

from .breaker import CircuitBreakers
from .client import GMMClient, GMMClientError
from .executor import (ScoringExecutor, executor_for_config,
                       executor_for_model, pow2_bucket)
from .http import HTTPFrontEnd, InprocBackend
from .pool import WorkerPool
from .registry import ModelRegistry, RegistryError, ServedModel
from .server import GMMServer, serve_main

__all__ = [
    "CircuitBreakers", "GMMClient", "GMMClientError", "GMMServer",
    "HTTPFrontEnd", "InprocBackend", "ModelRegistry", "RegistryError",
    "ScoringExecutor", "ServedModel", "WorkerPool",
    "executor_for_config", "executor_for_model", "pow2_bucket",
    "serve_main",
]
