"""Bounded microprobe: time 2-3 REAL EM iterations per candidate.

The probe is the measured rung of the fallback ladder a fresh machine
can always reach: no prior runs, no shipped database — fit the actual
data (or a synthetic stand-in of the same shape) for ``iters``
iterations per candidate and record what the clock said. Per candidate
the probe runs TWO pinned-iteration fits of the in-memory path: the
first call pays the executable compile (its wall minus the warm wall is
the recorded ``compile_s``), the second measures the steady-state
wall/iter. Candidates are visited in deterministic ascending order and
ties break toward the smaller candidate, so two probe runs over the
same data rank identically (the probe-determinism contract in
tests/test_tuning.py).

Cost: ``2 * iters * len(candidates)`` EM iterations at the probed
shape. ``autotune='probe'`` inside a fit bounds the ladder to a +/- 2
octave window around the incumbent chunk; ``gmm tune`` sweeps the full
ladder offline where the wall belongs to nobody's fit.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .cost import chunk_ladder, em_iteration_cost
from .db import TuningDB, TuningKey

#: knobs the microprobe can measure (the rest resolve db/static only).
PROBEABLE = ("chunk_size", "estep_backend")


def _probe_config(config, iters: int):
    """The candidate fit's config: same numeric family as the caller's,
    every observability/persistence surface stripped (the probe must
    never write the caller's stream or checkpoints), iterations pinned,
    single init, no sweep below the target K."""
    return dataclasses.replace(
        config,
        autotune="off",
        min_iters=iters, max_iters=iters,
        n_init=1, fused_sweep=False,
        metrics_file=None, metrics_port=None,
        checkpoint_dir=None, profile=False,
        envelope=False, enable_output=False, enable_print=False,
        max_runtime_s=None,
    )


def _time_fit(config, data, num_clusters: int) -> Tuple[float, float]:
    """(first_call_s, warm_call_s) of a pinned-iteration fit at the
    target K. Split out so tests can inject a deterministic clock."""
    from ..models.order_search import fit_gmm

    t0 = time.perf_counter()
    fit_gmm(data, num_clusters, num_clusters, config)
    t1 = time.perf_counter()
    fit_gmm(data, num_clusters, num_clusters, config)
    t2 = time.perf_counter()
    return t1 - t0, t2 - t1


def candidates_for(knob: str, config, n_events: int, platform: str,
                   full_ladder: bool = False) -> List[Any]:
    """Deterministic candidate list for one probeable knob."""
    if knob == "chunk_size":
        around = None if full_ladder else int(config.chunk_size)
        return chunk_ladder(n_events, platform, around=around)
    if knob == "estep_backend":
        # Interpret-mode Pallas off-TPU is a correctness tool, not a
        # performance candidate: probing it would pay minutes to learn
        # what routing already knows.
        return ["jnp", "pallas"] if platform == "tpu" else ["jnp"]
    raise ValueError(f"knob {knob!r} is not probeable")


def probe_knob(config, data, num_clusters: int, key: TuningKey,
               db: TuningDB, knob: str, iters: int = 3,
               full_ladder: bool = False,
               log=None) -> Optional[Dict[str, Any]]:
    """Measure every candidate for one knob, record into ``db``, and
    return the db row (``{chosen, candidates, source, ...}``).

    Returns None when the knob admits fewer than two candidates on this
    platform (nothing to compare — the static model answers for free).
    """
    n_events = int(data.shape[0])
    cands = candidates_for(knob, config, n_events, key.platform,
                           full_ladder=full_ladder)
    if len(cands) < 2:
        # Nothing to compare: let the static model answer for free
        # instead of burning 2*iters EM iterations on a foregone
        # conclusion.
        return None
    static = em_iteration_cost(
        n_events, key.d, num_clusters, key.covariance, key.dtype)
    for cand in cands:
        cfg = _probe_config(dataclasses.replace(config, **{knob: cand}),
                            iters)
        first_s, warm_s = _time_fit(cfg, data, num_clusters)
        profile = {
            "wall_per_iter_s": round(warm_s / max(iters, 1), 6),
            "compile_s": round(max(first_s - warm_s, 0.0), 6),
            "probe_iters": int(iters),
            "flops": static["flops"],
            "bytes": static["bytes"],
        }
        db.record(key, knob, cand, profile, source="probe")
        if log is not None:
            log.info("tune probe %s=%s: %.4fs/iter (compile %.3fs)",
                     knob, cand, profile["wall_per_iter_s"],
                     profile["compile_s"])
    return db.lookup(key, knob)
