"""Knob resolution: measured profile > microprobe > static cost model.

``GMMConfig.autotune`` gates everything: ``'off'`` (the default) makes
this module unreachable — every stream and result stays byte-identical
to pre-tuner behavior. ``'db'`` resolves each tunable knob from the
nearest recorded profile (``tuning.db``), ``'probe'`` measures missing
rows first (``tuning.probe``). Both fall back to the static cost model
(``tuning.cost``) when nothing measured applies, and BOTH leave any
knob the user set explicitly untouched — an explicit knob is one whose
value differs from the ``GMMConfig`` dataclass default (the CLI flags
feed fields 1:1, so a passed flag IS a non-default field; library
callers get the same contract).

Every resolved decision is emitted as a ``tune`` telemetry event
(schema rev v2.5): knob, chosen, candidate walls, source
(``db``/``probe``/``static``), the predicted wall/iter where one
exists, and the DB key that supplied it — so ``gmm report`` can render
the decision table and ``gmm diff``'s ``tune.regressions`` gate can
flag a tuned run that came in >20% slower than the profile that chose
its knobs (a stale DB pages instead of silently pessimizing).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from . import cost
from .db import TuningDB, TuningKey
from .probe import PROBEABLE, probe_knob

#: fit-path knobs the resolver may touch (serving/fleet have their own
#: entry points below).
FIT_KNOBS = ("chunk_size", "estep_backend", "sweep_k_buckets",
             "restart_batch_size")

_BACKENDS = ("auto", "pallas", "jnp")
_BUCKET_POLICIES = ("pow2", "off")
_FLEET_MODES = ("scan", "vmap")


def _defaults():
    from ..config import GMMConfig

    return GMMConfig()


def explicit_knobs(config, knobs=FIT_KNOBS) -> frozenset:
    """Knobs the user pinned: value differs from the dataclass default.

    (A flag passed with exactly the default value is indistinguishable
    from an unset one — and resolving it to the default it already
    holds is a no-op, so the ambiguity is harmless.)
    """
    d = _defaults()
    return frozenset(k for k in knobs
                     if getattr(config, k) != getattr(d, k))


def _typed(knob: str, chosen: Any) -> Any:
    """Parse a DB row's string choice back to the config's type; raises
    ValueError on garbage (the caller treats that row as absent)."""
    if knob in ("chunk_size", "serve_min_block", "serve_max_block"):
        v = int(chosen)
        if v < 1:
            raise ValueError(f"{knob} must be positive, got {v}")
        return v
    if knob == "restart_batch_size":
        if chosen in (None, "None", "auto"):
            return None
        v = int(chosen)
        if v < 1:
            raise ValueError(f"restart_batch_size must be >= 1, got {v}")
        return v
    chosen = str(chosen)
    allowed = {"estep_backend": _BACKENDS,
               "sweep_k_buckets": _BUCKET_POLICIES,
               "fleet_mode": _FLEET_MODES}.get(knob)
    if allowed is not None and chosen not in allowed:
        raise ValueError(f"bad recorded {knob} choice {chosen!r}")
    return chosen


def _candidate_walls(slot: Dict[str, Any]) -> Dict[str, Optional[float]]:
    """{candidate: wall_per_iter_s} summary of a DB row for the event."""
    out = {}
    for name, prof in (slot.get("candidates") or {}).items():
        wall = prof.get("wall_per_iter_s") if isinstance(prof, dict) \
            else None
        out[str(name)] = (round(float(wall), 6)
                          if isinstance(wall, (int, float)) else None)
    return out


def _platform_key(config, n_events, n_dims, num_clusters) -> TuningKey:
    import jax

    platform = jax.default_backend()
    try:
        device_kind = jax.devices()[0].device_kind
    except (IndexError, RuntimeError):
        device_kind = platform
    return TuningKey.for_shape(platform, device_kind, n_events, n_dims,
                               num_clusters, config.covariance_type,
                               config.dtype)


def _static_decision(knob: str, key: TuningKey, config,
                     n_events: int) -> Tuple[Any, Optional[float], dict]:
    """(chosen, predicted_s, candidate_predictions) from the cost model."""
    if knob == "chunk_size":
        walls = {
            str(c): round(cost.predict_iteration_wall(
                n_events, key.d, key.k_bucket, key.covariance,
                key.dtype, key.platform, c), 6)
            for c in cost.chunk_ladder(n_events, key.platform)}
        chosen = cost.static_chunk_size(n_events, key.d, key.k_bucket,
                                        key.covariance, key.dtype,
                                        key.platform)
        return chosen, walls.get(str(chosen)), walls
    if knob == "estep_backend":
        # Routing already knows this one statically: Pallas is a TPU
        # kernel; everywhere else interpret mode only loses.
        return ("pallas" if key.platform == "tpu"
                and key.dtype == "float32" else "jnp"), None, {}
    if knob == "sweep_k_buckets":
        return "pow2", None, {}  # the round-6 measured default
    if knob == "restart_batch_size":
        return None, None, {}  # keep the host-memory auto cap
    if knob == "fleet_mode":
        return "scan", None, {}  # bit-parity default; vmap needs a row
    if knob == "serve_min_block":
        return 256, None, {}
    if knob == "serve_max_block":
        return 65536, None, {}
    raise ValueError(f"unknown tuning knob {knob!r}")


def _resolve_knob(knob: str, config, key: TuningKey, db: TuningDB,
                  mode: str, data=None, num_clusters: Optional[int] = None,
                  n_events: Optional[int] = None,
                  log=None) -> Optional[Dict[str, Any]]:
    """One knob through the ladder: exact db > probe > nearest db >
    static. Returns the decision dict, or None when no source could
    produce a valid choice (never happens for known knobs — static
    always answers)."""
    n_events = int(n_events if n_events is not None else key.n_bucket)
    slot = db.lookup(key, knob)
    source = "db"
    if slot is None and mode == "probe" and knob in PROBEABLE \
            and data is not None and num_clusters is not None:
        try:
            slot = probe_knob(config, data, num_clusters, key, db, knob,
                              log=log)
            if slot is not None:
                db.save()
                source = "probe"
        except Exception as e:  # a failed probe degrades, never kills
            if log is not None:
                log.warning("tune probe for %s failed (%s); falling "
                            "back", knob, e)
            slot = None
    if slot is None:
        slot = db.nearest(key, knob)
    if slot is not None:
        try:
            chosen = _typed(knob, slot["chosen"])
        except (ValueError, KeyError):
            slot = None  # corrupt row: fall through to static
    if slot is not None:
        if slot.get("source") == "probe" and source != "probe":
            source = "db"  # a prior probe's row read back is a db hit
        prof = db.chosen_profile(slot) or {}
        wall = prof.get("wall_per_iter_s")
        return {
            "knob": knob,
            "chosen": chosen,
            "source": source,
            "candidates": _candidate_walls(slot),
            "predicted_s": (round(float(wall), 6)
                            if isinstance(wall, (int, float)) else None),
            "key": slot.get("key", key.as_str()),
            "distance": slot.get("distance"),
        }
    chosen, predicted, walls = _static_decision(knob, key, config,
                                                n_events)
    return {
        "knob": knob,
        "chosen": chosen,
        "source": "static",
        "candidates": walls,
        "predicted_s": predicted,
        "key": key.as_str(),
        "distance": None,
    }


def emit_decisions(decisions: List[Dict[str, Any]],
                   surface: str = "fit") -> None:
    """One ``tune`` event per resolved knob on the ambient recorder."""
    rec = telemetry.current()
    if not rec.active:
        return
    for d in decisions:
        rec.emit(
            "tune",
            knob=d["knob"],
            chosen=("auto" if d["chosen"] is None else d["chosen"]),
            source=d["source"],
            surface=surface,
            default=("auto" if d.get("default") is None
                     else d.get("default")),
            candidates=d.get("candidates") or {},
            **({"predicted_s": d["predicted_s"]}
               if d.get("predicted_s") is not None else {}),
            **({"key": d["key"]} if d.get("key") else {}),
        )
        rec.metrics.count("tune_decisions")


def resolve_fit_config_ex(config, data, num_clusters: int, log=None
                          ) -> Tuple[Any, List[Dict[str, Any]]]:
    """(resolved config, decisions) for one fit. The returned config has
    ``autotune='off'``: resolution happened here, and the restart /
    elastic sub-fits that re-enter ``fit_gmm`` with it must ride the
    decisions instead of re-probing (and re-emitting) per init."""
    mode = config.autotune
    if mode == "off":
        return config, []
    try:
        n_events, n_dims = (int(s) for s in data.shape)
    except (AttributeError, TypeError, ValueError):
        return dataclasses.replace(config, autotune="off"), []
    key = _platform_key(config, n_events, n_dims, num_clusters)
    db = TuningDB.open(config.tuning_db)
    if db.load_error and log is not None:
        log.warning("%s", db.load_error)
    explicit = explicit_knobs(config)
    decisions: List[Dict[str, Any]] = []
    updates: Dict[str, Any] = {}
    for knob in FIT_KNOBS:
        if knob in explicit:
            continue
        if knob == "restart_batch_size" and config.n_init <= 1:
            continue
        d = _resolve_knob(knob, config, key, db, mode, data=data,
                          num_clusters=num_clusters, n_events=n_events,
                          log=log)
        if d is None:
            continue
        d["default"] = getattr(config, knob)
        decisions.append(d)
        if d["chosen"] is not None and d["chosen"] != getattr(config,
                                                              knob):
            updates[knob] = d["chosen"]
    resolved = dataclasses.replace(config, autotune="off", **updates)
    emit_decisions(decisions, surface="fit")
    if log is not None and updates:
        log.info("autotune (%s): %s", mode,
                 ", ".join(f"{k}={v}" for k, v in updates.items()))
    return resolved, decisions


def resolve_fit_config(config, data, num_clusters: int, log=None):
    """The fit-path entry: resolved config only."""
    return resolve_fit_config_ex(config, data, num_clusters, log=log)[0]


def resolve_fleet_config_ex(config, n_events: int, n_dims: int,
                            num_clusters: int, log=None
                            ) -> Tuple[Any, List[Dict[str, Any]]]:
    """Fleet-path resolution: ``fleet_mode`` and ``chunk_size`` at the
    fleet's largest packed shape. No probe rung (a fleet fit is the
    wrong place to burn tenant wall); db > static only."""
    mode = config.autotune
    if mode == "off":
        return config, []
    key = _platform_key(config, n_events, n_dims, num_clusters)
    db = TuningDB.open(config.tuning_db)
    if db.load_error and log is not None:
        log.warning("%s", db.load_error)
    explicit = explicit_knobs(config, knobs=("fleet_mode", "chunk_size"))
    decisions: List[Dict[str, Any]] = []
    updates: Dict[str, Any] = {}
    for knob in ("chunk_size", "fleet_mode"):
        if knob in explicit:
            continue
        d = _resolve_knob(knob, config, key, db, "db",
                          n_events=n_events, log=log)
        if d is None:
            continue
        d["default"] = getattr(config, knob)
        decisions.append(d)
        if d["chosen"] is not None and d["chosen"] != getattr(config,
                                                              knob):
            updates[knob] = d["chosen"]
    resolved = dataclasses.replace(config, autotune="off", **updates)
    emit_decisions(decisions, surface="fleet")
    return resolved, decisions


def resolve_serving_blocks(dtype: str, diag_only: bool, n_dims: int,
                           num_clusters: int,
                           tuning_db: Optional[str] = None,
                           log=None) -> Tuple[Dict[str, int],
                                              List[Dict[str, Any]]]:
    """Serving executor block bounds from the DB: ``{min_block,
    max_block}`` + the decisions. Serve rows are keyed at the nominal
    64k-event batch shape; nearest-key matching bridges the rest."""
    import jax

    platform = jax.default_backend()
    try:
        device_kind = jax.devices()[0].device_kind
    except (IndexError, RuntimeError):
        device_kind = platform
    key = TuningKey.for_shape(platform, device_kind, 65536, n_dims,
                              num_clusters,
                              "diag" if diag_only else "full", dtype)
    db = TuningDB.open(tuning_db)
    if db.load_error and log is not None:
        log.warning("%s", db.load_error)
    blocks: Dict[str, int] = {}
    decisions: List[Dict[str, Any]] = []
    for knob, field in (("serve_min_block", "min_block"),
                        ("serve_max_block", "max_block")):
        d = _resolve_knob(knob, None, key, db, "db", log=log)
        if d is None:
            continue
        decisions.append(d)
        blocks[field] = int(d["chosen"])
    if blocks.get("min_block", 0) > blocks.get("max_block", 1 << 30):
        # A torn pair of rows must not build an impossible executor.
        blocks["min_block"] = blocks["max_block"]
    emit_decisions(decisions, surface="serve")
    return blocks, decisions
