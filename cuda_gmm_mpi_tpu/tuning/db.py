"""Persisted tuning database: measured per-shape knob profiles.

The reference hard-codes its launch geometry per GPU generation
(NUM_BLOCKS/THREADS in gaussian.h, tuned once for Tesla-era parts); the
port's analogs -- ``chunk_size``, the E-step backend, serving block
bounds -- were equally hand-set. This module is the measured half of the
fix: a small versioned JSON database of recorded candidate profiles
(wall/iter, compile seconds, modelled flops/bytes, HBM peak when the
CompileWatch saw one), keyed by the shape class a measurement transfers
across:

    (platform, device_kind, N-bucket, D, K-bucket, covariance, dtype)

N and K are pow2-bucketed (a 19k-event fit and a 23k-event fit share a
row; the executable-cache bucketing in serving/executor.py draws the
same equivalence classes). Resolution first tries the exact key, then
the NEAREST recorded key of the same (platform, device_kind,
covariance, dtype) -- distance is log2-octave distance over (N-bucket,
D, K-bucket) -- and falls back to the static cost model
(``tuning.cost``) when the database has nothing relevant.

Writes are atomic + durable via ``utils.checkpoint.write_json_atomic``
(tmp + fsync + rename + dir fsync -- the npz checkpoint contract's JSON
sibling), so a crashed ``gmm tune`` can never leave a torn database. An
unreadable/alien-version file is treated as empty with a warning, never
a crash: the tuner must degrade to static defaults, not take the fit
down with it.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Optional, Tuple

DB_VERSION = 1

#: knob names a database row may carry (fit-side and serve-side).
KNOBS = (
    "chunk_size",
    "estep_backend",
    "sweep_k_buckets",
    "restart_batch_size",
    "fleet_mode",
    "serve_min_block",
    "serve_max_block",
)


def default_db_path() -> str:
    """``GMM_TUNING_DB`` > ``$XDG_CACHE_HOME/gmm/tuning.json`` >
    ``~/.cache/gmm/tuning.json``."""
    env = os.environ.get("GMM_TUNING_DB")
    if env:
        return env
    cache = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(cache, "gmm", "tuning.json")


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (the shape equivalence class)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class TuningKey:
    """One shape class a measurement transfers across."""

    platform: str
    device_kind: str
    n_bucket: int
    d: int
    k_bucket: int
    covariance: str
    dtype: str

    @classmethod
    def for_shape(cls, platform: str, device_kind: str, n_events: int,
                  n_dims: int, num_clusters: int, covariance: str,
                  dtype: str) -> "TuningKey":
        return cls(platform=str(platform), device_kind=str(device_kind),
                   n_bucket=pow2_bucket(n_events), d=int(n_dims),
                   k_bucket=pow2_bucket(num_clusters),
                   covariance=str(covariance), dtype=str(dtype))

    def as_str(self) -> str:
        return (f"{self.platform}|{self.device_kind}|n{self.n_bucket}"
                f"|d{self.d}|k{self.k_bucket}|{self.covariance}"
                f"|{self.dtype}")

    @classmethod
    def from_str(cls, s: str) -> Optional["TuningKey"]:
        parts = s.split("|")
        if len(parts) != 7 or not parts[2].startswith("n") \
                or not parts[3].startswith("d") \
                or not parts[4].startswith("k"):
            return None
        try:
            return cls(platform=parts[0], device_kind=parts[1],
                       n_bucket=int(parts[2][1:]), d=int(parts[3][1:]),
                       k_bucket=int(parts[4][1:]), covariance=parts[5],
                       dtype=parts[6])
        except ValueError:
            return None

    def family_matches(self, other: "TuningKey") -> bool:
        """Same numeric family: measurements may transfer across shapes
        inside a family, never across platforms or dtypes."""
        return (self.platform == other.platform
                and self.device_kind == other.device_kind
                and self.covariance == other.covariance
                and self.dtype == other.dtype)

    def distance(self, other: "TuningKey") -> float:
        """log2-octave distance over (N-bucket, D, K-bucket)."""
        return (abs(math.log2(self.n_bucket) - math.log2(other.n_bucket))
                + abs(math.log2(max(self.d, 1))
                      - math.log2(max(other.d, 1)))
                + abs(math.log2(self.k_bucket)
                      - math.log2(other.k_bucket)))


class TuningDB:
    """In-memory view of one tuning.json, with atomic persistence.

    Layout (``version`` gates readers; rows are keyed by
    ``TuningKey.as_str()``, then knob name, then the candidate's string
    repr)::

        {"version": 1,
         "entries": {
           "cpu|cpu|n32768|d16|k8|full|float32": {
             "chunk_size": {
               "chosen": "8192",
               "source": "probe",
               "candidates": {
                 "8192": {"wall_per_iter_s": 0.011, "compile_s": 0.41,
                          "flops": 2.1e7, "bytes": 1.2e7, ...},
                 ...}}}}}
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_db_path()
        self.entries: Dict[str, Dict[str, Any]] = {}
        self.load_error: Optional[str] = None

    # -- persistence ----------------------------------------------------

    @classmethod
    def open(cls, path: Optional[str] = None) -> "TuningDB":
        db = cls(path)
        db.load()
        return db

    def load(self) -> None:
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, ValueError) as e:
            self.load_error = f"unreadable tuning db {self.path}: {e}"
            return
        if not isinstance(raw, dict) or raw.get("version") != DB_VERSION:
            self.load_error = (
                f"tuning db {self.path} has version "
                f"{raw.get('version') if isinstance(raw, dict) else '?'}, "
                f"expected {DB_VERSION}; ignoring it")
            return
        entries = raw.get("entries")
        if isinstance(entries, dict):
            self.entries = entries

    def save(self) -> None:
        from ..utils.checkpoint import write_json_atomic

        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        write_json_atomic(self.path,
                          {"version": DB_VERSION, "entries": self.entries})

    # -- recording ------------------------------------------------------

    def record(self, key: TuningKey, knob: str, choice: Any,
               profile: Dict[str, Any], source: str = "probe") -> None:
        """Add/refresh one measured candidate; ``chosen`` is recomputed
        as the wall/iter argmin over everything recorded so far (ties
        break toward the SMALLER candidate repr so reruns are stable)."""
        if knob not in KNOBS:
            raise ValueError(f"unknown tuning knob {knob!r}")
        row = self.entries.setdefault(key.as_str(), {})
        slot = row.setdefault(knob, {"candidates": {}})
        slot["candidates"][str(choice)] = dict(profile)
        slot["source"] = source

        def rank(item: Tuple[str, Dict[str, Any]]):
            name, prof = item
            wall = prof.get("wall_per_iter_s")
            wall = float("inf") if wall is None else float(wall)
            return (wall, name)

        slot["chosen"] = min(slot["candidates"].items(), key=rank)[0]

    # -- resolution -----------------------------------------------------

    def lookup(self, key: TuningKey, knob: str
               ) -> Optional[Dict[str, Any]]:
        """Exact-key row for one knob:
        ``{chosen, candidates, source, key, distance}`` or None."""
        slot = (self.entries.get(key.as_str()) or {}).get(knob)
        if not isinstance(slot, dict) or "chosen" not in slot:
            return None
        return dict(slot, key=key.as_str(), distance=0.0)

    def nearest(self, key: TuningKey, knob: str
                ) -> Optional[Dict[str, Any]]:
        """Exact match, else the nearest same-family recorded row
        (log2-octave distance over N-bucket/D/K-bucket; deterministic
        key-string tie-break)."""
        exact = self.lookup(key, knob)
        if exact is not None:
            return exact
        best: Optional[Tuple[float, str, Dict[str, Any]]] = None
        for key_str, row in self.entries.items():
            other = TuningKey.from_str(key_str)
            if other is None or not key.family_matches(other):
                continue
            slot = row.get(knob)
            if not isinstance(slot, dict) or "chosen" not in slot:
                continue
            d = key.distance(other)
            if best is None or (d, key_str) < (best[0], best[1]):
                best = (d, key_str, slot)
        if best is None:
            return None
        return dict(best[2], key=best[1], distance=best[0])

    def chosen_profile(self, slot: Dict[str, Any]
                       ) -> Optional[Dict[str, Any]]:
        """The chosen candidate's recorded profile for a lookup() row."""
        cands = slot.get("candidates") or {}
        prof = cands.get(str(slot.get("chosen")))
        return prof if isinstance(prof, dict) else None
