"""Static cost model: the tuner's fallback when nothing was measured.

docs/PERF.md "Cost model" fixes the conventions this module encodes:
one full-data EM iteration is ``2*N*K*(F+D)`` MACs = ``4*N*K*(F+D)``
FLOPs (F = D^2 expanded full-covariance features, D for diag families —
the same 1 MAC = 2 FLOPs rule XLA's ``cost_analysis()`` prices dots
with, so static predictions and measured ``run_summary.profile.cost``
numbers are directly comparable once trip counts are applied), and one
pass moves at least ``N*(F+K)`` feature/posterior elements.

The effective-throughput constants below are deliberately coarse — they
exist to RANK candidates when the tuning DB has no measurement, not to
predict absolute walls. The CPU number is anchored on the round-15
measured calibration (20k×8 f32 K=8 fits in the tens of milliseconds
per full-data iteration on this image); accelerator rows are the
envelope targets pending the tunnel's return. A measured DB row always
outranks these (the ``db > probe > static`` fallback ladder in
``tuning.autotune``).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# Effective sustained FLOP/s by platform (not peak: includes the
# exp/logsumexp transcendental tax of the E-step).
EFFECTIVE_FLOPS = {
    "cpu": 4.0e9,
    "gpu": 2.0e11,
    "tpu": 1.0e12,
}

# Fixed per-dispatch overhead of one chunk step inside the scanned EM
# body (host loop + launch latency), seconds.
DISPATCH_OVERHEAD_S = {
    "cpu": 2.0e-4,
    "gpu": 1.0e-4,
    "tpu": 5.0e-5,
}

# Working sets larger than this stop fitting in cache/VMEM and the
# effective rate degrades (CPU L2/L3-ish; accelerators stream from HBM
# so the penalty is mild).
CACHE_BYTES = {
    "cpu": 32 << 20,
    "gpu": 48 << 20,
    "tpu": 128 << 20,
}
CACHE_PENALTY = {"cpu": 0.6, "gpu": 0.9, "tpu": 0.9}

# Platform chunk defaults when NOTHING is known: the round-5 measured
# CPU sweep picked 4096; accelerators keep the reference-era 65536.
STATIC_CHUNK = {"cpu": 4096, "gpu": 65536, "tpu": 65536}


def feature_width(n_dims: int, covariance: str) -> int:
    """F: expanded quadratic-feature width per event."""
    d = int(n_dims)
    return d if covariance in ("diag", "spherical") else d * d


def em_iteration_cost(n_events: int, n_dims: int, num_clusters: int,
                      covariance: str, dtype: str) -> Dict[str, float]:
    """Modelled flops/bytes of ONE full-data EM iteration (docs/PERF.md
    conventions; what a DB row carries when no CompileWatch measured
    numbers exist)."""
    f = feature_width(n_dims, covariance)
    n, k, d = int(n_events), int(num_clusters), int(n_dims)
    itemsize = np.dtype(dtype).itemsize
    return {
        "flops": float(4 * n * k * (f + d)),
        "bytes": float(n * (f + k) * itemsize),
    }


def predict_iteration_wall(n_events: int, n_dims: int, num_clusters: int,
                           covariance: str, dtype: str, platform: str,
                           chunk_size: int) -> float:
    """Predicted wall seconds of one full-data EM iteration at a given
    chunk size: compute term + per-chunk dispatch overhead + a cache
    penalty once the per-chunk working set spills."""
    platform = platform if platform in EFFECTIVE_FLOPS else "cpu"
    cost = em_iteration_cost(n_events, n_dims, num_clusters,
                             covariance, dtype)
    chunk = max(1, min(int(chunk_size), int(n_events)))
    n_chunks = -(-int(n_events) // chunk)
    f = feature_width(n_dims, covariance)
    itemsize = np.dtype(dtype).itemsize
    working = chunk * (f + int(num_clusters)) * itemsize
    rate = EFFECTIVE_FLOPS[platform]
    if working > CACHE_BYTES[platform]:
        rate *= CACHE_PENALTY[platform]
    return (cost["flops"] / rate
            + n_chunks * DISPATCH_OVERHEAD_S[platform])


def static_chunk_size(n_events: int, n_dims: int, num_clusters: int,
                      covariance: str, dtype: str,
                      platform: str) -> int:
    """Model-ranked chunk choice over the standard pow2 ladder."""
    best: Optional[int] = None
    best_wall = float("inf")
    for c in chunk_ladder(n_events, platform):
        wall = predict_iteration_wall(n_events, n_dims, num_clusters,
                                      covariance, dtype, platform, c)
        if wall < best_wall:
            best, best_wall = c, wall
    return best if best is not None else STATIC_CHUNK.get(platform, 65536)


def chunk_ladder(n_events: int, platform: str,
                 around: Optional[int] = None) -> list:
    """Deterministic ascending pow2 candidate ladder, clamped to the
    data: the full [1024 .. 131072] octave range (``gmm tune``), or a
    +/- 2-octave window around ``around`` (the bounded in-fit probe)."""
    from .db import pow2_bucket

    hi_cap = pow2_bucket(max(1, int(n_events)))
    lo, hi = 1024, 131072
    if around is not None:
        base = pow2_bucket(int(around))
        lo, hi = max(lo, base // 4), min(hi, base * 4)
    ladder = []
    c = lo
    while c <= min(hi, max(hi_cap, lo)):
        ladder.append(c)
        c *= 2
    return ladder
