"""``gmm tune``: the offline candidate sweep + decision table.

Runs the microprobe ladder for every probeable knob at a requested (or
file-derived) shape, records the measurements into the tuning database,
and prints the decision table a subsequent ``--autotune db`` fit/serve
will resolve from. A fresh machine needs nothing but this command: the
probes ARE the measurements (no prior runs, no shipped DB), which is
the acceptance contract — and when the accelerator tunnel returns,
``gmm tune --envelope`` populates the TPU rows of the same database
with zero new code.

Exit codes: 0 = swept and wrote the DB, 1 = bad shape/flags, 2 = input
file unreadable (the fit CLI's convention).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional


def build_tune_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gmm tune",
        description="Probe candidate knob settings at a shape and write "
                    "the tuning database (docs/PERF.md 'Autotuning').")
    p.add_argument("infile", nargs="?", default=None,
                   help="optional event file (CSV/BIN): probe on the "
                   "real data; omit to probe a synthetic --n/--d shape")
    p.add_argument("--n", type=int, default=20000,
                   help="synthetic event count (ignored with infile)")
    p.add_argument("--d", type=int, default=16,
                   help="synthetic dimensionality (ignored with infile)")
    p.add_argument("--k", type=int, default=8,
                   help="cluster count the probe fits at")
    p.add_argument("--covariance-type", default="full",
                   choices=["full", "diag", "spherical", "tied"])
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--probe-iters", type=int, default=3,
                   help="EM iterations per timed candidate call (2-3 "
                   "bounds the sweep; the first call also pays compile)")
    p.add_argument("--tuning-db", default=None, metavar="PATH",
                   help="database path (default GMM_TUNING_DB or "
                   "~/.cache/gmm/tuning.json)")
    p.add_argument("--envelope", action="store_true",
                   help="probe at the paper's reference envelope shape "
                   "(K=512, D=32) instead of --n/--d/--k -- the TPU "
                   "row-population mode; on CPU this is SLOW")
    p.add_argument("--seed", type=int, default=0,
                   help="RNG seed for the synthetic probe data")
    p.add_argument("--json", action="store_true",
                   help="emit the decision table as one JSON object "
                   "instead of text")
    return p


def _probe_data(args):
    """The events the probe fits: the real file when given, else a
    deterministic synthetic mixture of the requested shape."""
    import numpy as np

    if args.infile is not None:
        from ..io import read_data

        return np.asarray(read_data(args.infile), dtype=args.dtype)
    rng = np.random.default_rng(args.seed)
    n, d, k = int(args.n), int(args.d), int(args.k)
    centers = rng.normal(size=(k, d)) * 4.0
    assign = rng.integers(0, k, size=n)
    return (centers[assign]
            + rng.normal(size=(n, d))).astype(args.dtype)


def render_decision_table(decisions: List[dict]) -> str:
    """The human decision table: one knob per row, candidates ranked."""
    lines = ["knob                 chosen    source  candidates "
             "(wall/iter s)"]
    for d in decisions:
        cands = d.get("candidates") or {}

        def _rank(item):
            wall = item[1]
            return (wall if isinstance(wall, (int, float))
                    else float("inf"), item[0])

        shown = "  ".join(
            f"{name}:{wall:.4f}" if isinstance(wall, (int, float))
            else f"{name}:-"
            for name, wall in sorted(cands.items(), key=_rank)) or "-"
        chosen = "auto" if d.get("chosen") is None else d["chosen"]
        lines.append(f"{d['knob']:<20} {str(chosen):<9} "
                     f"{d['source']:<7} {shown}")
    return "\n".join(lines)


def tune_main(argv: Optional[List[str]] = None) -> int:
    args = build_tune_parser().parse_args(argv)
    if args.envelope:
        args.n = max(int(args.n), 100_000)
        args.d, args.k = 32, 512
    if args.k < 1 or args.d < 1 or args.n < 2:
        print("tune: need n >= 2, d >= 1, k >= 1", file=sys.stderr)
        return 1
    if args.probe_iters < 1:
        print("tune: --probe-iters must be >= 1", file=sys.stderr)
        return 1
    if args.infile is not None and not os.path.isfile(args.infile):
        print("Invalid infile.\n", file=sys.stderr)
        return 2

    from ..config import GMMConfig
    from .autotune import _platform_key, _resolve_knob, FIT_KNOBS
    from .db import TuningDB
    from .probe import PROBEABLE, probe_knob

    try:
        config = GMMConfig(covariance_type=args.covariance_type,
                           dtype=args.dtype)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    data = _probe_data(args)
    n_events, n_dims = (int(s) for s in data.shape)
    key = _platform_key(config, n_events, n_dims, args.k)
    db = TuningDB.open(args.tuning_db)
    if db.load_error:
        print(db.load_error, file=sys.stderr)

    decisions = []
    for knob in FIT_KNOBS:
        if knob == "restart_batch_size":
            continue  # meaningful only under n_init > 1 fits
        if knob in PROBEABLE:
            probe_knob(config, data, args.k, key, db, knob,
                       iters=args.probe_iters, full_ladder=True)
        d = _resolve_knob(knob, config, key, db, "db",
                          n_events=n_events)
        if d is not None:
            decisions.append(d)
    db.save()

    if args.json:
        print(json.dumps({"key": key.as_str(), "db": db.path,
                          "decisions": decisions}))
    else:
        print(f"tuning db: {db.path}")
        print(f"key:       {key.as_str()}")
        print(render_decision_table(decisions))
    return 0
