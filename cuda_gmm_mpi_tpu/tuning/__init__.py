"""Profile-guided autotuning (docs/PERF.md "Autotuning").

The closed loop over PR 14's passive cost introspection: recorded
per-shape profiles (``tuning.db``) + a bounded microprobe
(``tuning.probe``) + a static cost model (``tuning.cost``) resolve the
port's hand-set launch-geometry analogs — ``chunk_size``, the E-step
backend, sweep bucketing, restart batching, serving block bounds, fleet
dispatch mode — per (platform, device_kind, shape) instead of per
editor session. ``GMMConfig.autotune='off'`` (the default) keeps every
stream and result byte-identical to pre-tuner behavior; ``'db'`` and
``'probe'`` resolve through ``tuning.autotune``'s fallback ladder and
emit one ``tune`` telemetry event per decision. ``gmm tune`` is the
offline sweep (``tuning.cli``).
"""

from .autotune import (  # noqa: F401
    FIT_KNOBS,
    emit_decisions,
    explicit_knobs,
    resolve_fit_config,
    resolve_fit_config_ex,
    resolve_fleet_config_ex,
    resolve_serving_blocks,
)
from .cost import em_iteration_cost, predict_iteration_wall  # noqa: F401
from .db import (  # noqa: F401
    KNOBS,
    TuningDB,
    TuningKey,
    default_db_path,
    pow2_bucket,
)
from .probe import PROBEABLE, probe_knob  # noqa: F401
