"""cuda_gmm_mpi_tpu: a TPU-native GMM-EM clustering framework.

A from-scratch JAX/XLA/Pallas re-design with the full capabilities of the
CUDA/MPI/OpenMP reference (Corv/CUDA-GMM-MPI): GMM fitting by EM over large
event x dimension matrices (four covariance families: full, diagonal,
spherical, tied) and a model-order search merging clusters from a starting
K down to a target K under a selectable criterion (Rissanen/MDL, BIC, AIC,
AICc),
with weighted events, warm starts, model-file round-trips, and
single-device through multi-host sharded execution.

See SURVEY.md at the repo root for the structural analysis of the reference and
the file:line provenance cited throughout this package.
"""

from ._version import __version__
from .config import DEFAULT_CONFIG, GMMConfig
from .estimator import GaussianMixture
from .health import NumericalFaultError
from .models import (GMMModel, GMMResult, compute_memberships, fit_gmm,
                     iter_memberships)
from .state import (GMMState, bucket_width, clone_state, compact,
                    compact_to, zeros_state)
from .supervisor import PeerLostError, PreemptedError, RunSupervisor
from .validation import InvalidInputError

__all__ = [
    "DEFAULT_CONFIG", "GMMConfig", "GaussianMixture",
    "GMMModel", "GMMResult", "compute_memberships", "fit_gmm", "iter_memberships",
    "GMMState", "bucket_width", "clone_state", "compact", "compact_to",
    "zeros_state",
    "InvalidInputError", "NumericalFaultError",
    "PeerLostError", "PreemptedError", "RunSupervisor",
    "__version__",
]
