"""Deterministic fault injection for the numerical-containment subsystem.

The reference has no way to rehearse its failure modes: a singular
covariance or NaN event appears only when real data produces one, so the
recovery paths (docs/ROBUSTNESS.md) would otherwise ship untested. This
module provides env/config-gated injection points that production code
consults; each fires a bounded number of times (``times``, default 1), so a
recovery retry observes the fault gone -- exactly the transient-fault shape
the escalation ladder exists for.

Supported fault kinds (the spec is ``{kind: {params...}}``):

- ``nan_loglik``   ``{"iter": i, "restart": r, "times": n}`` -- the EM
  loop's loglik becomes NaN at iteration ``i`` (1-based; the initial
  E-step is iteration 0). For the jitted EM loops the plan is consumed at
  TRACE time and the injection is compiled into that executable, so a
  same-executable retry re-observes the fault while a rebuilt (escalated)
  model traces clean -- ``times`` therefore counts *traced executables*,
  i.e. the escalation rung that finally runs clean. The host-driven
  streaming loop consumes at runtime per EM run. ``restart`` (optional)
  targets ONE lane of the batched restart loop (the drop-one-keep-
  survivors rehearsal, models/restarts.py); a plan with ``restart`` set
  never fires in an EM loop that has no restart axis.
- ``singular_cov`` ``{"cluster": c, "times": n}`` -- the seeded state's
  cluster ``c`` gets a singular covariance (R zeroed) with the poisoned
  inverse (Rinv +inf) a real inversion of it would produce; consumed per
  seeded fit.
- ``poison_block`` ``{"block": j, "times": n}`` -- the streaming path's
  host->device block ``j`` arrives as all-NaN (a torn read / bad DMA);
  consumed per delivery, so the recovery retry streams clean data.
- ``read_slow`` ``{"ms": m, "block": j, "times": n}`` -- the pipelined
  ingestion worker (io/pipeline.py) sleeps ``m`` milliseconds before
  reading block ``j`` (any block when omitted): deterministic slow-disk
  injection for the bounded-queue backpressure path; consumed per read,
  host side, so results stay bit-identical -- only the prefetch wait
  moves.
- ``checkpoint_eio`` ``{"step": s, "times": n}`` -- the checkpoint write
  for sweep step ``s`` (any step when omitted) raises ``OSError(EIO)``;
  consumed per raise, so the bounded retry's n+1-th attempt succeeds.
- ``preempt`` ``{"iter": i, "block": j, "times": n}`` -- the run
  supervisor's poll treats EM iteration ``i`` (optionally: streaming
  block ``j`` of pass ``i``; segment-boundary polls match ``block: -1``)
  as if SIGTERM had just arrived: deterministic stand-in for a real
  preemption signal, driving the emergency-checkpoint + exit-75 path
  (supervisor.py; consumed at the poll, host side).
- ``rank_hang`` ``{"rank": r, "iter": i, "times": n}`` -- process ``r``
  of a multi-controller run stops heartbeating and wedges at its next
  supervisor poll (optionally at EM iteration ``i``), simulating a dead
  or stuck host so the PEER's liveness watchdog (``PeerLostError`` +
  emergency checkpoint) can be rehearsed. The wedged process never
  returns; the test harness kills it.
- ``rank_lost`` ``{"rank": r, "iter": i, "block": j, "where": w,
  "times": n}`` -- the run supervisor's poll behaves as if the liveness
  watchdog had just declared peer ``r`` dead (stale heartbeat), emitting
  ``peer_lost`` and tripping the stop flag, WITHOUT any process actually
  dying: the deterministic single-process driver for the elastic
  shrink-and-continue path (``--elastic``) and its exit-75 fallback.
  ``iter``/``block`` target one EM iteration / streaming block exactly
  like ``preempt`` (segment-boundary polls match ``block: -1``);
  ``where`` targets one poll site (e.g. ``sweep`` for between-K).
  Consumed at the poll, host side.
- ``collective_timeout`` ``{"name": b, "rank": r, "times": k}`` -- the
  named filesystem-rendezvous barrier (``parallel.distributed.barrier``;
  any barrier when ``name`` is omitted) raises the same
  :class:`PeerLostError` a real timeout would, with ``rank`` as the
  blamed peer, before any waiting happens -- so the collective-loss leg
  of elastic recovery is rehearsable on one process.
- ``serve_nan`` ``{"model": name, "times": n}`` -- the serving loop's
  coalesced dispatch for ``model`` (any model when omitted) returns
  all-NaN scores, standing in for a poisoned registry artifact so the
  post-dispatch non-finite check and the per-route circuit breaker
  (serving/server.py, serving/breaker.py) can be rehearsed; consumed
  per dispatch, so a breaker's half-open probe after ``times``
  dispatches observes the model healthy again.
- ``serve_slow`` ``{"ms": m, "model": name, "times": n}`` -- the serving
  dispatch sleeps ``m`` milliseconds before the executor call
  (optionally only for ``model``): deterministic latency injection for
  the deadline/coalescing paths; consumed per dispatch.
- ``worker_crash`` ``{"worker": w, "gen": g, "model": name,
  "exitcode": c, "times": n}`` -- the serving dispatch hard-kills its
  own process (``os._exit``, default code 9 -- indistinguishable from a
  SIGKILL'd worker) just before the executor call, optionally only in
  pool worker ``w`` and/or respawn generation ``g`` (matched against the
  ``GMM_SERVE_WORKER`` / ``GMM_SERVE_WORKER_GEN`` env the pool stamps on
  each child; generation 0 is the first launch) or for ``model``. The
  deterministic driver for the worker pool's containment arc
  (serving/pool.py): sibling retry of the dead worker's in-flight
  requests, jittered-doubling respawn, crash-loop quarantine. A
  respawned worker is a FRESH process that re-reads GMM_FAULTS, so pin
  ``gen: 0`` to crash once and observe the respawn serve clean, or omit
  ``gen`` to crash every generation and drive the quarantine path.
- ``registry_torn`` ``{"name": n, "version": v, "times": k}`` -- the
  registry's version load raises :class:`RegistryError` as if the
  artifact were torn on disk (optionally only for one name/version);
  consumed per load attempt, so walk-back and breaker-recovery
  rehearsals observe the next attempt succeed.
- ``retrain_fail`` ``{"model": name, "times": n}`` -- the lifecycle
  controller's shadow minibatch-EM refit (lifecycle/controller.py)
  raises before fitting (optionally only for ``model``), driving the
  jittered-doubling retry ladder and, at exhaustion, the
  quarantine-the-attempt path; consumed per attempt, so the n+1-th
  retry fits clean. The serving path never observes the failure.
- ``canary_regression`` ``{"model": name, "shift": s, "times": n}`` --
  the canary gate evaluation scores the CANDIDATE as if its mean
  holdout score had regressed by ``s`` (default: far past the gate's
  tolerance), so the mean-regression gate rejects it; consumed per gate
  evaluation. Client-visible responses stay byte-identical -- only the
  shadow scores are poisoned.
- ``promote_torn`` ``{"name": n, "version": v, "times": k}`` -- the
  registry's promote raises between the manifest stage-flip and the
  candidate-marker removal, simulating a crash mid-promotion: the
  candidate stays invisible to enumeration/poll and the flip stays
  retryable; consumed per promote attempt.

Activation: ``faults.use({...})`` (context manager, in-process tests) or
the ``GMM_FAULTS`` env var holding the JSON spec (subprocess workers; read
once, at the first hook that fires). No plan installed = every hook returns
None immediately.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

ENV_VAR = "GMM_FAULTS"

KNOWN_KINDS = ("nan_loglik", "singular_cov", "poison_block", "read_slow",
               "checkpoint_eio", "preempt", "rank_hang", "rank_lost",
               "collective_timeout", "serve_nan", "serve_slow",
               "worker_crash", "registry_torn", "retrain_fail",
               "canary_regression", "promote_torn")


def _values_match(spec_val: Any, val: Any) -> bool:
    """Spec-vs-call match: integer kinds compare as ints (the original
    contract); non-numeric params (serve_nan's model NAME) as strings."""
    try:
        return int(spec_val) == int(val)
    except (TypeError, ValueError):
        return str(spec_val) == str(val)


class FaultPlan:
    """A mutable injection plan: per-kind params plus a firing budget."""

    def __init__(self, spec: Dict[str, Dict[str, Any]]):
        for kind in spec:
            if kind not in KNOWN_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (expected one of "
                    f"{KNOWN_KINDS})")
        self._lock = threading.Lock()
        self._spec = {
            kind: dict(cfg, _remaining=int(cfg.get("times", 1)))
            for kind, cfg in spec.items()
        }
        self.fired: Dict[str, int] = {k: 0 for k in self._spec}

    def peek(self, kind: str) -> Optional[Dict[str, Any]]:
        """The kind's params if it still has budget (no consumption)."""
        cfg = self._spec.get(kind)
        if cfg is None or cfg["_remaining"] <= 0:
            return None
        return cfg

    def take(self, kind: str, **match) -> Optional[Dict[str, Any]]:
        """Consume one firing of ``kind`` if armed and every ``match``
        key equals the plan's value (plan keys absent from the spec match
        anything -- e.g. ``checkpoint_eio`` with no ``step`` fires on any
        step). Returns the params dict or None."""
        with self._lock:
            cfg = self._spec.get(kind)
            if cfg is None or cfg["_remaining"] <= 0:
                return None
            for key, val in match.items():
                if key in cfg and not _values_match(cfg[key], val):
                    return None
            cfg["_remaining"] -= 1
            self.fired[kind] = self.fired.get(kind, 0) + 1
            return cfg


_installed: Optional[FaultPlan] = None
_env_checked = False
_env_lock = threading.Lock()


def install(spec: Optional[Dict[str, Dict[str, Any]]]) -> Optional[FaultPlan]:
    """Install (or, with None, clear) the process-wide fault plan."""
    global _installed, _env_checked
    _installed = FaultPlan(spec) if spec is not None else None
    _env_checked = True  # explicit install/clear overrides the env plan
    return _installed


def clear() -> None:
    install(None)


class use:
    """Context manager: install a plan for the enclosed block, then clear.

    The plan object is the as-target value, so tests can assert on
    ``plan.fired`` after the block.
    """

    def __init__(self, spec: Dict[str, Dict[str, Any]]):
        self._spec = spec

    def __enter__(self) -> FaultPlan:
        return install(self._spec)

    def __exit__(self, *exc) -> None:
        clear()


def active() -> Optional[FaultPlan]:
    """The current plan: an installed one, else GMM_FAULTS (parsed once)."""
    global _installed, _env_checked
    if _installed is not None:
        return _installed
    if not _env_checked:
        with _env_lock:
            if not _env_checked:
                raw = os.environ.get(ENV_VAR)
                if raw:
                    _installed = FaultPlan(json.loads(raw))
                _env_checked = True
    return _installed


def take(kind: str, **match) -> Optional[Dict[str, Any]]:
    """Module-level shortcut: consume from the active plan (None = no-op)."""
    plan = active()
    return plan.take(kind, **match) if plan is not None else None


def peek(kind: str) -> Optional[Dict[str, Any]]:
    plan = active()
    return plan.peek(kind) if plan is not None else None


def raise_io_error(kind: str, **match) -> None:
    """Raise an injected OSError(EIO) when ``kind`` is armed and matches."""
    cfg = take(kind, **match)
    if cfg is not None:
        import errno

        raise OSError(errno.EIO, f"injected {kind} fault", str(cfg))


def maybe_poison_state(state):
    """Apply an armed ``singular_cov`` fault to a freshly seeded state.

    Zeroes cluster ``c``'s covariance and sets its inverse to +inf -- the
    poisoned pair a real inversion of a singular R produces -- so the first
    E-step's densities go non-finite and the health bitmask must catch it
    (``nonfinite_params`` + ``nonfinite_loglik``).
    """
    cfg = take("singular_cov")
    if cfg is None:
        return state
    import jax.numpy as jnp

    c = int(cfg.get("cluster", 0))
    return state.replace(
        R=state.R.at[c].set(0.0),
        Rinv=state.Rinv.at[c].set(jnp.inf),
    )


def maybe_poison_block(chunk, wts, block: int):
    """Apply an armed ``poison_block`` fault to one streamed host block."""
    cfg = take("poison_block", block=block)
    if cfg is None:
        return chunk, wts
    import numpy as np

    bad = np.full_like(np.asarray(chunk), np.nan)
    return bad, wts
