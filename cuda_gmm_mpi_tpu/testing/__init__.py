"""Test-support subpackage: deterministic fault injection (testing.faults).

Shipped inside the package (not under tests/) because the injection points
live in production modules -- the EM loop, the streaming block feeder, the
checkpointer -- and those modules must be able to consult the active fault
plan without importing the test tree. With no plan installed every hook is
a near-free no-op (one module-attribute check).
"""

from . import faults

__all__ = ["faults"]
