"""Preemption-safe execution: the run-scoped shutdown supervisor.

The reference has no shutdown story at all: a batch-scheduler SIGTERM (or a
wall-clock limit) kills ``gaussianMPI`` with every byte of sweep state still
in host RAM (``gaussian.cu:262-275`` holds the best model until the final
write), and a dead rank leaves the survivors blocked forever in the next
``MPI_Allreduce``. On preemptible TPU slices -- the north-star deployment --
eviction-with-grace-period is the COMMON case, so this module turns kills,
deadlines, and peer loss into clean, resumable exits:

- :class:`RunSupervisor` installs SIGTERM/SIGINT handlers and an optional
  wall-clock deadline (``GMMConfig.max_runtime_s`` / ``--max-runtime``) and
  exposes a cooperative stop flag. Signal handlers only SET the flag -- all
  real work happens at the next poll point on the main thread, never in
  signal context.
- The host-driven sweep, the streaming block loop, the segmented EM
  driver (``GMMModel.run_em_resumable``), and the serving tick loop
  (``serving/server.py`` -- which drains instead of checkpointing: flush
  the queue, shed late arrivals, exit 75) poll the flag between device
  dispatches. On stop they write an *emergency checkpoint* -- the intra-K
  sub-step of :class:`~cuda_gmm_mpi_tpu.utils.checkpoint.SweepCheckpointer`
  carrying the mid-EM state, iteration count, loglik trajectory, and (for
  streaming) the partially reduced block accumulator -- then raise
  :class:`PreemptedError`, which the CLI maps to exit code 75
  (``EX_TEMPFAIL``: preempted, resumable). ``--resume auto`` restores the
  sub-step and restarts INSIDE the interrupted fit.
- :class:`LivenessWatchdog` (multi-controller runs) exchanges rank
  heartbeats through the shared checkpoint filesystem
  (``parallel.distributed`` heartbeat primitives -- multi-host runs already
  require one, docs/DISTRIBUTED.md) on the telemetry heartbeat cadence. A
  peer whose heartbeat goes stale beyond ``peer_timeout_s`` produces a loud
  :class:`PeerLostError` plus a local emergency checkpoint instead of an
  indefinite collective hang; ``distributed.barrier`` becomes
  timeout-bounded while a watchdog is active.

Activation mirrors telemetry's ambient pattern: the CLI (or a library
caller) wraps a fit in ``with supervisor.use(RunSupervisor(...)):`` and the
instrumented layers find it via :func:`current`; the default ambient
supervisor is inert. Telemetry events ``preempt`` / ``shutdown`` /
``peer_lost`` document the lifecycle (docs/OBSERVABILITY.md); the full state
diagram lives in docs/ROBUSTNESS.md ("Run lifecycle").

Multi-host semantics: each rank polls its OWN signals/deadline (batch
schedulers deliver SIGTERM to every rank of a preempted job; clocks may skew
a deadline by seconds across hosts). The emergency sub-step write itself is
process-0-only (the replicated sweep state is identical everywhere), and a
rank that stops while its peers are wedged in a collective is exactly what
the watchdog timeout exists to unblock.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# sysexits.h codes -- the CLI exit-code contract (docs/API.md):
EX_SOFTWARE = 70   # NumericalFaultError after recovery exhaustion
EX_IOERR = 74      # unreadable/torn input or checkpoint IO failure
EX_TEMPFAIL = 75   # preempted (signal/deadline/peer loss), resumable


class PreemptedError(RuntimeError):
    """The run was stopped cooperatively (signal or deadline) and, when a
    checkpoint directory was configured, its intra-K state is durable on
    disk. Maps to exit 75 (EX_TEMPFAIL): rerun with the same
    ``--checkpoint-dir`` (and ``--resume auto``, the default) to continue
    inside the interrupted fit."""

    def __init__(self, message: str, *, reason: str = "signal",
                 step: Optional[int] = None, em_iter: Optional[int] = None,
                 checkpointed: bool = False):
        super().__init__(message)
        self.reason = reason
        self.step = step
        self.em_iter = em_iter
        self.checkpointed = checkpointed


class PeerLostError(RuntimeError):
    """A peer rank of a multi-controller run stopped participating (no
    heartbeat within ``peer_timeout_s``, or a collective barrier timed
    out). The local rank checkpoints and exits 75 instead of blocking
    forever in the next collective -- restart the whole job to resume."""

    def __init__(self, message: str, *, rank: Optional[int] = None,
                 age_s: Optional[float] = None,
                 timeout_s: Optional[float] = None):
        super().__init__(message)
        self.rank = rank
        self.age_s = age_s
        self.timeout_s = timeout_s


class RunSupervisor:
    """Cooperative stop flag + signal handlers + deadline + watchdog.

    ``max_runtime_s``: optional wall-clock budget measured from
    :meth:`install` (the CLI's ``--max-runtime``); the deadline trips the
    same stop flag a SIGTERM does, so a scheduler's hard kill limit can be
    front-run with a clean checkpointed exit. ``install_signals=False``
    supports library use from non-main threads (``signal.signal`` is
    main-thread-only) and tests.
    """

    _HANDLED = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, max_runtime_s: Optional[float] = None,
                 install_signals: bool = True):
        self.max_runtime_s = max_runtime_s
        self._install_signals = install_signals
        self._stop = threading.Event()
        self._reason: Optional[str] = None
        self._lost_peer: Optional[Dict[str, Any]] = None
        self._deadline: Optional[float] = None
        self._old_handlers: Dict[int, Any] = {}
        self._watchdog: Optional["LivenessWatchdog"] = None
        self._preempt_emitted = False
        self._stop_consumed = threading.Event()
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    @property
    def active(self) -> bool:
        return True

    def install(self) -> "RunSupervisor":
        """Arm the deadline and (main thread only) the signal handlers."""
        if self.max_runtime_s is not None:
            self._deadline = time.monotonic() + float(self.max_runtime_s)
        if self._install_signals:
            try:
                for sig in self._HANDLED:
                    self._old_handlers[sig] = signal.signal(
                        sig, self._on_signal)
            except ValueError:
                # Not the main thread: cooperative stop still works via
                # deadline/watchdog/request_stop; signals stay default.
                self._old_handlers.clear()
        return self

    def uninstall(self) -> None:
        """Restore prior signal handlers and stop the watchdog."""
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_handlers.clear()
        self.stop_watchdog()

    def _on_signal(self, signum, frame) -> None:
        # Signal context: set the flag and nothing else (no locks, no IO).
        # A second delivery falls through to the ORIGINAL handler so an
        # operator's double Ctrl-C still kills a wedged run the hard way.
        if self._stop.is_set():
            old = self._old_handlers.get(signum)
            if callable(old):
                old(signum, frame)
            elif old == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)
            return
        self._reason = ("sigterm" if signum == signal.SIGTERM else "sigint")
        self._stop.set()

    # -- the stop flag -----------------------------------------------------

    def request_stop(self, reason: str) -> None:
        """Trip the stop flag programmatically (watchdog, tests)."""
        if not self._stop.is_set():
            self._reason = reason
            self._stop.set()

    @property
    def stop_requested(self) -> bool:
        self._check_deadline()
        return self._stop.is_set()

    @property
    def stop_reason(self) -> Optional[str]:
        return self._reason

    @property
    def lost_peer(self) -> Optional[Dict[str, Any]]:
        """``{rank, age_s, timeout_s}`` once the watchdog flagged a peer."""
        return self._lost_peer

    def _check_deadline(self) -> None:
        if (self._deadline is not None and not self._stop.is_set()
                and time.monotonic() >= self._deadline):
            self._reason = "deadline"
            self._stop.set()

    def poll(self, *, where: str, k: Optional[int] = None,
             em_iter: Optional[int] = None) -> bool:
        """The cooperative intervention point (main thread, between device
        dispatches). Returns True when the run must stop now. Consults, in
        order: the ``rank_hang`` fault injection (testing only -- wedges
        THIS rank so a peer's watchdog can be rehearsed), the ``preempt``
        injection (a deterministic stand-in for SIGTERM at a specific EM
        iteration / streaming block), the signal flag, and the deadline.
        Emits one ``preempt`` telemetry record on the first observation.
        """
        from .testing import faults

        if faults.peek("rank_hang") is not None:
            self._maybe_hang(where=where, em_iter=em_iter)
        if not self._stop.is_set():
            self._maybe_rank_lost(where=where, em_iter=em_iter, block=-1)
        if not self._stop.is_set() and em_iter is not None:
            # block=-1: a spec targeting a specific streaming block must
            # only fire from poll_block, never at a segment boundary.
            if faults.take("preempt", iter=em_iter, block=-1) is not None:
                self._reason = "preempt_injected"
                self._stop.set()
        self._check_deadline()
        if not self._stop.is_set():
            return False
        self._emit_preempt(where=where, k=k, em_iter=em_iter)
        return True

    def poll_block(self, *, k: Optional[int], em_iter: int,
                   block: int) -> bool:
        """Streaming-block-granularity poll: like :meth:`poll` but the
        ``preempt`` injection can target a specific block of a specific
        pass (``{"iter": i, "block": j}``)."""
        from .testing import faults

        if faults.peek("rank_hang") is not None:
            self._maybe_hang(where="stream_block", em_iter=em_iter)
        if not self._stop.is_set():
            self._maybe_rank_lost(where="stream_block", em_iter=em_iter,
                                  block=block)
        if not self._stop.is_set():
            if faults.take("preempt", iter=em_iter, block=block) is not None:
                self._reason = "preempt_injected"
                self._stop.set()
        self._check_deadline()
        if not self._stop.is_set():
            return False
        self._emit_preempt(where="stream_block", k=k, em_iter=em_iter)
        return True

    def _maybe_hang(self, *, where: str, em_iter: Optional[int]) -> None:
        """Honor an armed ``rank_hang`` injection: stop heartbeating and
        wedge this rank right here (simulating a host stuck in a collective
        or a swap death), so the PEER's watchdog path can be tested. The
        process never returns from this; the test harness kills it."""
        from .testing import faults

        cfg = faults.peek("rank_hang")
        if cfg is not None and "iter" in cfg and em_iter is None:
            return  # iter-targeted spec: only EM-iteration polls match
        try:
            import jax

            rank = int(jax.process_index())
        except Exception:
            rank = 0
        match: Dict[str, Any] = {"rank": rank}
        if em_iter is not None:
            match["iter"] = em_iter
        if faults.take("rank_hang", **match) is None:
            return
        if self._watchdog is not None:
            self._watchdog.stop_writing()
        from .utils.logging_ import get_logger

        get_logger().warning(
            "rank_hang fault injected at %s (rank %d): wedging this "
            "process", where, rank)
        while True:  # pragma: no cover - killed externally
            time.sleep(3600.0)

    def _maybe_rank_lost(self, *, where: str, em_iter: Optional[int],
                         block: int) -> None:
        """Honor an armed ``rank_lost`` injection: behave exactly as if
        the liveness watchdog had just declared the spec's peer dead --
        WITHOUT any process dying -- so the elastic shrink path (and the
        exit-75 fallback when ``--elastic`` is off) is rehearsable
        deterministically on a single process. Gating mirrors ``preempt``:
        an ``iter``/``block``-targeted spec never fires at a between-K
        poll, and ``where`` (optional) pins one poll site."""
        from .testing import faults

        cfg = faults.peek("rank_lost")
        if cfg is None:
            return
        if em_iter is None:
            # Between-K (sweep/fleet) poll: only an untargeted spec --
            # or one pinned to this site via ``where`` -- may fire here.
            if "iter" in cfg or "block" in cfg:
                return
            cfg = faults.take("rank_lost", where=where)
        else:
            cfg = faults.take("rank_lost", where=where, iter=em_iter,
                              block=block)
        if cfg is None:
            return
        self._synthesize_peer_loss(
            rank=int(cfg.get("rank", 1)),
            timeout_s=float(cfg.get("timeout_s",
                                    self.collective_timeout_s or 0.0)))

    def _synthesize_peer_loss(self, *, rank: int,
                              timeout_s: float = 0.0,
                              age_s: Optional[float] = None) -> None:
        """The watchdog's declare-dead sequence, minus the forced-exit
        escalation thread: the poll that invokes this returns True
        immediately, so the main thread is by construction not wedged."""
        self._lost_peer = {"rank": int(rank),
                           "age_s": round(float(age_s if age_s is not None
                                                else timeout_s), 3),
                           "timeout_s": float(timeout_s)}
        from . import telemetry
        from .utils.logging_ import get_logger

        get_logger().error(
            "peer rank %d declared lost (injected rank_lost fault)", rank)
        rec = telemetry.current()
        if rec.active:
            rec.emit("peer_lost", rank=int(rank),
                     timeout_s=float(timeout_s),
                     age_s=self._lost_peer["age_s"])
            rec.metrics.count("peer_losses")
        if self._watchdog is not None:
            self.stop_watchdog()
        self.request_stop("peer_lost")

    def reset_for_retry(self) -> None:
        """Re-arm the supervisor for an elastic refit: drop the consumed
        stop (and the peer it blamed) so the surviving world's next fit
        polls clean. Signal handlers and the wall-clock deadline persist
        -- the runtime budget spans the whole run, shrinks included."""
        self.stop_watchdog()
        self._stop = threading.Event()
        self._stop_consumed = threading.Event()
        self._reason = None
        self._lost_peer = None
        self._preempt_emitted = False

    def _emit_preempt(self, *, where: str, k=None, em_iter=None) -> None:
        with self._lock:
            if self._preempt_emitted:
                return
            self._preempt_emitted = True
        from . import telemetry

        rec = telemetry.current()
        if rec.active:
            fields: Dict[str, Any] = {"reason": self._reason, "where": where}
            if k is not None:
                fields["k"] = int(k)
            if em_iter is not None:
                fields["em_iter"] = int(em_iter)
            if self._lost_peer is not None:
                fields["peer"] = self._lost_peer
            rec.emit("preempt", **fields)
            rec.metrics.count("preempts")

    # -- watchdog ----------------------------------------------------------

    def start_watchdog(self, directory: str, *, rank: int, nproc: int,
                       timeout_s: float,
                       interval_s: Optional[float] = None,
                       peers: Optional[List[int]] = None) -> None:
        """Start (idempotently) the cross-host liveness watchdog. Runs
        until :meth:`uninstall`; a stale peer trips the stop flag with
        reason ``peer_lost`` and the next poll raises
        :class:`PeerLostError` after the emergency checkpoint. ``peers``
        (original rank ids) overrides the default everyone-but-me set --
        an elastic refit watches only the sealed membership's survivors,
        never the rank it just shrank away."""
        if self._watchdog is not None:
            return

        def on_lost(peer_rank: int, age_s: float) -> None:
            self._lost_peer = {"rank": int(peer_rank),
                               "age_s": round(float(age_s), 3),
                               "timeout_s": float(timeout_s)}
            from . import telemetry
            from .utils.logging_ import get_logger

            get_logger().error(
                "peer rank %d heartbeat stale for %.1fs (timeout %.1fs): "
                "stopping with an emergency checkpoint", peer_rank, age_s,
                timeout_s)
            rec = telemetry.current()
            if rec.active:
                rec.emit("peer_lost", rank=int(peer_rank),
                         timeout_s=float(timeout_s),
                         age_s=round(float(age_s), 3))
                rec.metrics.count("peer_losses")
            self.request_stop("peer_lost")
            # Escalation: if the main thread never reaches raise_stop --
            # it is wedged INSIDE a compute collective waiting on the very
            # peer that died, so no poll point will ever run -- the
            # cooperative stop cannot work. After a grace window, exit
            # hard with the preemption code: the completed-K checkpoints
            # on disk are the emergency state (a mid-collective EM carry
            # is not host-observable), and a loud exit 75 beats an
            # indefinite hang (the reference's dead-rank behavior).
            grace = min(float(timeout_s), 30.0)

            def _force_exit():
                if self._stop_consumed.wait(grace):
                    return
                get_logger().error(
                    "main thread did not observe peer loss within %.1fs "
                    "(wedged in a collective?): forcing exit %d",
                    grace, EX_TEMPFAIL)
                try:
                    rec2 = telemetry.current()
                    if rec2.active:
                        rec2.emit("shutdown", reason="peer_lost",
                                  checkpointed=False, forced=True)
                except Exception:
                    pass
                os._exit(EX_TEMPFAIL)

            threading.Thread(target=_force_exit,
                             name="gmm-peer-lost-exit",
                             daemon=True).start()

        self._watchdog = LivenessWatchdog(
            directory, rank=rank, nproc=nproc, timeout_s=timeout_s,
            interval_s=interval_s, on_peer_lost=on_lost, peers=peers)
        self._watchdog.start()

    def stop_watchdog(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None

    @property
    def collective_timeout_s(self) -> Optional[float]:
        """Barrier timeout while the watchdog runs (None = unbounded).
        ``distributed.barrier`` consults this so a filesystem-rendezvous
        barrier cannot outlive a dead peer by more than the timeout."""
        if self._watchdog is None:
            return None
        return float(self._watchdog.timeout_s)

    def raise_stop(self, *, step: Optional[int] = None,
                   em_iter: Optional[int] = None,
                   checkpointed: bool = False) -> None:
        """Raise the stop as the right exception type (peer loss vs
        preemption) after the caller finished its emergency checkpoint."""
        self._stop_consumed.set()
        if self._reason == "peer_lost" and self._lost_peer is not None:
            p = self._lost_peer
            raise PeerLostError(
                f"peer rank {p['rank']} lost (heartbeat stale "
                f"{p['age_s']:.1f}s > timeout {p['timeout_s']:.1f}s); "
                "emergency checkpoint "
                + ("written" if checkpointed else "unavailable "
                   "(no --checkpoint-dir)"),
                rank=p["rank"], age_s=p["age_s"], timeout_s=p["timeout_s"])
        raise PreemptedError(
            f"run preempted ({self._reason}); "
            + (f"resumable from step {step}"
               + (f" iteration {em_iter}" if em_iter is not None else "")
               if checkpointed else
               "NOT resumable (no --checkpoint-dir)"),
            reason=self._reason or "unknown", step=step, em_iter=em_iter,
            checkpointed=checkpointed)

    def __enter__(self) -> "RunSupervisor":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class _NullSupervisor(RunSupervisor):
    """Inert ambient default: every poll is a cheap False."""

    def __init__(self):
        super().__init__(install_signals=False)

    @property
    def active(self) -> bool:
        return False

    def poll(self, **kw) -> bool:  # noqa: D102 - inert fast path
        return False

    def poll_block(self, **kw) -> bool:
        return False


class LivenessWatchdog(threading.Thread):
    """Background heartbeat writer + peer staleness checker.

    Each rank writes ``<dir>/rank<i>.hb`` every ``interval_s`` (default:
    a quarter of the timeout, capped at the telemetry heartbeat floor of
    5 s) and checks every peer's file age against ``timeout_s``. The
    exchange medium is the shared checkpoint filesystem multi-host runs
    already require (GCS/NFS on pods) -- deliberately NOT a device
    collective: a collective heartbeat from a background thread would
    interleave with the main thread's compute collectives, and a hung
    peer is precisely the case where collectives stop returning.

    Staleness is READER-LOCAL: a peer's age is this watchdog's monotonic
    time since it last OBSERVED that peer's heartbeat mtime change --
    never a cross-host wall-clock difference. A peer whose clock is
    skewed hours into the past (or future) keeps producing mtime
    *changes* at the heartbeat cadence and is therefore never falsely
    declared dead; only a genuinely frozen file ages out.
    """

    def __init__(self, directory: str, *, rank: int, nproc: int,
                 timeout_s: float, interval_s: Optional[float] = None,
                 on_peer_lost: Optional[Callable[[int, float], None]] = None,
                 peers: Optional[List[int]] = None):
        super().__init__(name="gmm-liveness-watchdog", daemon=True)
        self.directory = directory
        self.rank = int(rank)
        self.nproc = int(nproc)
        self.peers = (tuple(int(p) for p in peers if int(p) != int(rank))
                      if peers is not None
                      else tuple(p for p in range(self.nproc)
                                 if p != self.rank))
        self.timeout_s = float(timeout_s)
        self.interval_s = float(interval_s if interval_s is not None
                                else min(max(self.timeout_s / 4.0, 0.2), 5.0))
        self._on_peer_lost = on_peer_lost
        self._stopped = threading.Event()
        self._writing = True
        self._started_mono = time.monotonic()
        # peer -> (last observed mtime, monotonic instant of that
        # observation): the reader-local staleness clock.
        self._seen: Dict[int, tuple] = {}

    def stop(self) -> None:
        self._stopped.set()

    def stop_writing(self) -> None:
        """Keep the thread alive but stop heartbeating (``rank_hang``)."""
        self._writing = False
        self._stopped.set()

    def run(self) -> None:  # pragma: no cover - exercised via subprocesses
        from .parallel import distributed

        while not self._stopped.is_set():
            if self._writing:
                try:
                    distributed.write_rank_heartbeat(
                        self.directory, self.rank)
                except OSError:
                    pass  # transient FS hiccup; next beat retries
            lost = self.check_peers()
            if lost is not None:
                rank, age = lost
                if self._on_peer_lost is not None:
                    self._on_peer_lost(rank, age)
                return
            self._stopped.wait(self.interval_s)

    def check_peers(self):
        """(rank, age_s) of the stalest over-timeout peer, else None. A
        peer that never wrote yet ages from this watchdog's start (ranks
        come up seconds apart; the timeout doubles as the grace window).

        Ages are reader-local monotonic deltas since the last observed
        mtime CHANGE -- mtime values are only compared for equality,
        never against this host's clock, so cross-host clock skew (or an
        NTP step on the peer) cannot fake a stale heartbeat."""
        from .parallel import distributed

        now = time.monotonic()
        worst = None
        for peer in self.peers:
            mtime = distributed.read_rank_heartbeat(self.directory, peer)
            seen = self._seen.get(peer)
            if seen is None or seen[0] != mtime:
                # First sight, or the file changed since last check:
                # restart this peer's staleness clock at now. A missing
                # file keeps the watchdog-start epoch as its baseline.
                base = (self._started_mono if mtime is None else now)
                self._seen[peer] = (mtime, base)
                seen = self._seen[peer]
            age = now - seen[1]
            if age > self.timeout_s and (worst is None or age > worst[1]):
                worst = (peer, age)
        return worst


class ElasticRecovery:
    """Bounded shrink-and-continue driver for :class:`PeerLostError`.

    The drivers (``fit_gmm``, the fleet loop) wrap their fit in::

        while True:
            try:
                return _fit(...)
            except PeerLostError as e:
                recovery = recovery or ElasticRecovery.maybe(config)
                if recovery is None:
                    raise                       # exit 75, as today
                config = recovery.recover(e, config)

    Each recovery attempt backs off (``elastic_backoff_s`` doubling),
    rendezvouses the survivors on the checkpoint filesystem
    (``parallel.elastic``), adopts the sealed membership as the world
    overlay, re-arms the supervisor, and returns a config with
    ``resume="auto"`` so the refit restores the newest checkpoint.
    After ``elastic_max_retries`` exhausted attempts -- or a shrink
    below ``min_hosts`` -- the original error propagates and the run
    exits 75 exactly as a non-elastic peer loss would.
    """

    def __init__(self):
        self.attempt = 0

    @staticmethod
    def maybe(config) -> Optional["ElasticRecovery"]:
        """An ElasticRecovery when the config opted in (``--elastic``
        plus a checkpoint dir -- the rendezvous medium), else None."""
        if getattr(config, "elastic", False) \
                and getattr(config, "checkpoint_dir", None):
            return ElasticRecovery()
        return None

    def recover(self, exc: PeerLostError, config):
        """One shrink: rendezvous the survivors, adopt the new world,
        return the refit config. Re-raises ``exc`` when recovery is out
        of budget, the lost rank is unidentifiable, or the world would
        shrink below ``min_hosts``."""
        from .telemetry import spans as tl_spans

        # The recovery phase gets its own trace span (rev v2.1): under
        # --metrics-port a shrink-and-resume shows up in the fit's span
        # tree with its measured cost, not just as shrink/resume events.
        with tl_spans.span("elastic_recovery", attempt=self.attempt + 1):
            return self._recover(exc, config)

    def _recover(self, exc: PeerLostError, config):
        import dataclasses

        from . import telemetry
        from .parallel import elastic
        from .utils.logging_ import get_logger

        log = get_logger()
        self.attempt += 1
        max_retries = int(getattr(config, "elastic_max_retries", 2))
        if self.attempt > max_retries:
            log.error("elastic recovery budget exhausted (%d attempts); "
                      "giving up", max_retries)
            raise exc
        if exc.rank is None:
            log.error("peer loss without an identifiable rank; cannot "
                      "shrink -- giving up")
            raise exc
        backoff = (float(getattr(config, "elastic_backoff_s", 0.5))
                   * (2.0 ** (self.attempt - 1)))
        if backoff > 0:
            time.sleep(backoff)

        mdir = elastic.membership_dir(config.checkpoint_dir)
        prev = elastic.read_membership(mdir)
        my_rank = elastic.original_rank()
        if prev is None:
            _, nproc0 = elastic.world()
            prev = elastic.Membership(generation=0,
                                      ranks=tuple(range(nproc0)),
                                      world_size0=nproc0)
        window = min(max(float(getattr(config, "peer_timeout_s", 60.0)),
                         1.0), 30.0)
        sealed = elastic.rendezvous(mdir, my_rank=my_rank, prev=prev,
                                    lost=(int(exc.rank),),
                                    window_s=window)
        min_hosts = int(getattr(config, "min_hosts", 1))
        if sealed.world_size < min_hosts:
            log.error("elastic shrink to %d host(s) is below --min-hosts "
                      "%d; giving up", sealed.world_size, min_hosts)
            raise exc
        elastic.set_world_overlay(sealed, my_rank)
        elastic.note_shrink()
        current().reset_for_retry()
        log.warning(
            "elastic recovery: generation %d sealed with %d/%d host(s) "
            "%s (lost rank %d, attempt %d/%d); resuming from checkpoint",
            sealed.generation, sealed.world_size, prev.world_size,
            list(sealed.ranks), int(exc.rank), self.attempt, max_retries)
        rec = telemetry.current()
        if rec.active:
            rec.emit("elastic_shrink", generation=int(sealed.generation),
                     survivors=[int(r) for r in sealed.ranks],
                     world_size=int(sealed.world_size),
                     lost_ranks=[int(exc.rank)], attempt=int(self.attempt),
                     min_hosts=min_hosts)
            rec.metrics.count("elastic_shrinks")
        elastic.note_resume()
        if rec.active:
            rec.emit("elastic_resume", generation=int(sealed.generation),
                     attempt=int(self.attempt),
                     world_size=int(sealed.world_size))
        return dataclasses.replace(config, resume="auto")


_NULL = _NullSupervisor()
_stack: List[RunSupervisor] = []


def current() -> RunSupervisor:
    """The ambient supervisor (inert unless a run activated one)."""
    return _stack[-1] if _stack else _NULL


@contextlib.contextmanager
def use(sup: RunSupervisor):
    """Make ``sup`` the ambient supervisor for the enclosed run (installs
    handlers/deadline on entry, restores on exit)."""
    _stack.append(sup)
    sup.install()
    try:
        yield sup
    finally:
        _stack.pop()
        sup.uninstall()
