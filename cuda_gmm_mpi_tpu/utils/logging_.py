"""Structured logging: the runtime replacement for the reference's printf
macro levels (``DEBUG``/``PRINT``/``EMUPRINT``, ``gaussian.h:44-60``).

The reference's three compile-time verbosity tiers map to standard logging
levels selected at runtime from GMMConfig:

  ENABLE_DEBUG (gaussian.h:31) -> logging.DEBUG
  ENABLE_PRINT (gaussian.h:35) -> logging.INFO
  default (both off)           -> logging.WARNING

``metrics_line`` emits machine-readable one-line JSON records (loglik,
rissanen, iteration timing) -- the structured upgrade over the reference's
ad-hoc printf telemetry (SURVEY.md SS5.5). It is now a thin adapter over
the telemetry subsystem's line writer (``telemetry.write_line``); the
full run-scoped event stream lives in ``cuda_gmm_mpi_tpu.telemetry``.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any, Dict

from ..telemetry import write_line

_LOGGER_NAME = "cuda_gmm_mpi_tpu"


def get_logger(config=None) -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"
        ))
        logger.addHandler(h)
        logger.propagate = False
    if config is not None:
        if getattr(config, "enable_debug", False):
            logger.setLevel(logging.DEBUG)
        elif getattr(config, "enable_print", False):
            logger.setLevel(logging.INFO)
        else:
            logger.setLevel(logging.WARNING)
    return logger


def metrics_line(event: str, stream=None, **fields: Any) -> Dict[str, Any]:
    """Emit one JSON metrics record to stderr; returns the record.

    Legacy stderr surface, byte-compatible with its pre-telemetry output
    (no schema/run-id stamping); the run-scoped JSONL stream is the
    RunRecorder's job and the two never double-write the same sink.
    """
    rec = {"event": event, "ts": round(time.time(), 3)}
    rec.update(fields)
    write_line(rec, stream=stream or sys.stderr)
    return rec
