"""Structured logging: the runtime replacement for the reference's printf
macro levels (``DEBUG``/``PRINT``/``EMUPRINT``, ``gaussian.h:44-60``).

The reference's three compile-time verbosity tiers map to standard logging
levels selected at runtime from GMMConfig:

  ENABLE_DEBUG (gaussian.h:31) -> logging.DEBUG
  ENABLE_PRINT (gaussian.h:35) -> logging.INFO
  default (both off)           -> logging.WARNING

``metrics_line`` emits machine-readable one-line JSON records (loglik,
rissanen, iteration timing) -- the structured upgrade over the reference's
ad-hoc printf telemetry (SURVEY.md SS5.5).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict

_LOGGER_NAME = "cuda_gmm_mpi_tpu"


def get_logger(config=None) -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"
        ))
        logger.addHandler(h)
        logger.propagate = False
    if config is not None:
        if getattr(config, "enable_debug", False):
            logger.setLevel(logging.DEBUG)
        elif getattr(config, "enable_print", False):
            logger.setLevel(logging.INFO)
        else:
            logger.setLevel(logging.WARNING)
    return logger


def metrics_line(event: str, stream=None, **fields: Any) -> Dict[str, Any]:
    """Emit one JSON metrics record; returns the record."""
    rec = {"event": event, "ts": round(time.time(), 3)}
    rec.update(fields)
    print(json.dumps(rec), file=stream or sys.stderr)
    return rec
