"""Version-bridging runtime helpers (JAX API drift).

The supported JAX range spells some knobs differently; every call site
that needs one goes through here so the bridge lives in exactly one
place (the shard_map check_vma/check_rep bridge lives with its single
call site in ``parallel.sharded_em``).
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int, pin_platform: bool = True) -> None:
    """Pin this process to the CPU platform with ``n`` virtual devices.

    Newer JAX has the ``jax_num_cpu_devices`` config option; older
    releases only honor the ``--xla_force_host_platform_device_count``
    XLA flag, which is read when the CPU backend initializes -- so this
    must run before ANY device use (jax may already be imported; a
    preloading sitecustomize hook does exactly that on some images).
    """
    import jax

    if pin_platform:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
