"""Auxiliary subsystems (SURVEY SS5): profiling, logging, checkpointing."""
