"""Auxiliary subsystems (SURVEY SS5): profiling, logging, checkpointing."""

from .logging_ import get_logger, metrics_line
from .profiling import CATEGORIES, PhaseTimer, trace

__all__ = ["get_logger", "metrics_line", "CATEGORIES", "PhaseTimer", "trace"]
