"""Checkpoint/resume for the model-order sweep (capability upgrade).

The reference has NO persistence: the best model lives in host RAM across the
entire K-sweep (100 iterations x up to 512 K values) and is written to disk
only at the very end (``saved_clusters``, ``gaussian.cu:262-275, 839-851``;
SURVEY.md SS5.4 calls out checkpointing as a required upgrade). Here each
completed K saves an orbax checkpoint of the sweep position, so a killed run
resumes at the next K instead of restarting the whole search.

Layout: ``<dir>/sweep/<step>/`` orbax PyTree checkpoints, where step counts
completed EM runs. The stored tree carries the current (possibly merged)
state, the best-so-far state, and the sweep scalars.

Two write paths share that layout:

- **Collective (orbax)** -- the host-driven sweep: every rank calls
  ``save``, orbax coordinates (primary host writes), with a cross-process
  barrier. Safe only from the MAIN thread: the barrier executes a device
  collective.
- **Callback-safe (``<step>.npz``)** -- the fused sweep: ``save`` is
  invoked from inside an ordered ``io_callback`` while the device is
  mid-program and BLOCKED on the callback's completion, so it must never
  dispatch device work (an orbax barrier here deadlocks the whole job:
  the barrier's collective waits for the sweep, the sweep waits for the
  callback, the callback waits for the barrier). Process 0 alone writes a
  flat ``np.savez`` atomically (tmp + ``os.replace``); no barrier is
  needed because the emitted payload is identical on every rank
  (replicated state, cluster shards pre-gathered).

``restore`` reads either format; mixing them in one directory resolves to
the newest step.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..state import GMMState


def _to_tree(state: GMMState) -> Dict[str, Any]:
    return {
        "N": state.N, "pi": state.pi, "constant": state.constant,
        "avgvar": state.avgvar, "means": state.means, "R": state.R,
        "Rinv": state.Rinv, "active": state.active,
    }


def _from_tree(t: Dict[str, Any]) -> GMMState:
    import jax.numpy as jnp

    return GMMState(**{k: jnp.asarray(v) for k, v in t.items()})


class SweepCheckpointer:
    """Orbax-backed persistence of the order-search sweep.

    ``keep`` bounds retained steps (default 2: the newest for resume plus
    one fallback in case the newest is torn -- restore() walks back). A
    K=512 sweep would otherwise leave ~500 dead steps (~17 MB each at the
    reference envelope) on the checkpoint filesystem.
    """

    def __init__(self, directory: str, keep: int = 2):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(os.path.join(directory, "sweep"))
        os.makedirs(self._dir, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self._keep = max(1, keep)

    def _prune(self, newest_step: int) -> None:
        """Drop steps older than the retention window. Called by the save
        paths AFTER step ``newest_step`` is durable; only process 0 removes
        (other ranks never write). Best-effort: a prune failure must never
        break the run that just checkpointed successfully."""
        import shutil

        cutoff = newest_step - self._keep + 1
        try:
            for s in self._all_steps():
                if s >= cutoff:
                    continue
                try:
                    npz = os.path.join(self._dir, f"{s}.npz")
                    if os.path.exists(npz):
                        os.remove(npz)
                    d = os.path.join(self._dir, str(s))
                    if os.path.isdir(d):
                        shutil.rmtree(d)
                except OSError:
                    pass
            # Orphaned tmp files from crashed save_local calls (killed
            # between mkstemp and replace) match neither pattern above;
            # they are dead the moment this process is saving again (one
            # writer, serialized saves), so sweep them too.
            for f in os.listdir(self._dir):
                if f.endswith(".tmp.npz"):
                    try:
                        os.remove(os.path.join(self._dir, f))
                    except OSError:
                        pass
        except OSError:
            # Best-effort end to end: a transient listdir failure (ESTALE/
            # EIO on network filesystems) must never kill the run that
            # just checkpointed successfully.
            pass

    def save(self, step: int, payload: Dict[str, Any]) -> None:
        """payload: state, best_state (GMMState), plus plain scalars."""
        tree = dict(payload)
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        path = os.path.join(self._dir, str(step))
        self._ckpt.save(path, tree, force=True)
        self._ckpt.wait_until_finished()
        import jax

        if jax.process_index() == 0:
            self._prune(step)

    def save_local(self, step: int, payload: Dict[str, Any]) -> None:
        """Callback-safe save: no device work, no cross-process barrier.

        Process 0 writes ``<step>.npz`` atomically; other ranks return
        immediately (every rank holds the identical replicated payload, so
        one durable copy on the shared checkpoint FS is the whole story).
        Safe to call from inside an ordered ``io_callback`` -- the ONLY
        save path that is (see module docstring for the deadlock).
        """
        import jax

        if jax.process_index() != 0:
            return
        tree = dict(payload)
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        flat = {}
        for key, val in tree.items():
            if isinstance(val, dict):
                for leaf, arr in val.items():
                    flat[f"{key}.{leaf}"] = np.asarray(arr)
            else:
                flat[key] = np.asarray(val)
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp.npz")
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
            # The durability contract ("checkpoint s on disk before step
            # s+1 computes", fused_sweep.py) must survive a HOST crash, not
            # just a process kill: flush+fsync the data before the atomic
            # rename, then fsync the directory so the rename itself is
            # durable. The tmp name is mkstemp-unique so concurrent savers
            # (racing callback threads) can never interleave writes into
            # one file.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, f"{step}.npz"))
        dir_fd = os.open(self._dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._prune(step)  # already process-0-only here

    def _all_steps(self) -> list:
        if not os.path.isdir(self._dir):
            return []
        steps = [int(d) for d in os.listdir(self._dir) if d.isdigit()]
        steps += [int(f[:-4]) for f in os.listdir(self._dir)
                  if f.endswith(".npz") and f[:-4].isdigit()]
        return steps

    def latest_step(self) -> Optional[int]:
        steps = self._all_steps()
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Load the requested (default: newest) step. With no explicit
        ``step``, an unreadable newest checkpoint (e.g. torn by a crash on
        a filesystem without rename atomicity) falls back to the next
        older one instead of wedging resume -- losing one step beats
        losing the run."""
        if step is not None:
            return self._restore_step(step)
        steps = self._all_steps()
        for s in sorted(steps, reverse=True):
            try:
                return self._restore_step(s)
            except Exception as e:
                # Loud fallback: a systematic failure (permissions, numpy
                # version skew) would otherwise masquerade as a clean
                # resume from a much older step.
                import warnings

                warnings.warn(
                    f"checkpoint step {s} unreadable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous step", RuntimeWarning)
                if s == min(steps):
                    raise
        return None

    def _restore_step(self, step: int) -> Dict[str, Any]:
        npz = os.path.join(self._dir, f"{step}.npz")
        if os.path.exists(npz):
            with np.load(npz) as z:
                tree: Dict[str, Any] = {}
                for key in z.files:
                    if "." in key:
                        group, leaf = key.split(".", 1)
                        tree.setdefault(group, {})[leaf] = z[key]
                    else:
                        tree[key] = z[key]
        else:
            tree = self._ckpt.restore(os.path.join(self._dir, str(step)))
        tree["state"] = _from_tree(tree["state"])
        tree["best_state"] = _from_tree(tree["best_state"])
        tree["step"] = step
        return tree
