"""Checkpoint/resume for the model-order sweep (capability upgrade).

The reference has NO persistence: the best model lives in host RAM across the
entire K-sweep (100 iterations x up to 512 K values) and is written to disk
only at the very end (``saved_clusters``, ``gaussian.cu:262-275, 839-851``;
SURVEY.md SS5.4 calls out checkpointing as a required upgrade). Here each
completed K saves an orbax checkpoint of the sweep position, so a killed run
resumes at the next K instead of restarting the whole search.

Layout: ``<dir>/sweep/<step>/`` orbax PyTree checkpoints, where step counts
completed EM runs. The stored tree carries the current (possibly merged)
state, the best-so-far state, and the sweep scalars.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..state import GMMState


def _to_tree(state: GMMState) -> Dict[str, Any]:
    return {
        "N": state.N, "pi": state.pi, "constant": state.constant,
        "avgvar": state.avgvar, "means": state.means, "R": state.R,
        "Rinv": state.Rinv, "active": state.active,
    }


def _from_tree(t: Dict[str, Any]) -> GMMState:
    import jax.numpy as jnp

    return GMMState(**{k: jnp.asarray(v) for k, v in t.items()})


class SweepCheckpointer:
    """Orbax-backed persistence of the order-search sweep."""

    def __init__(self, directory: str):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(os.path.join(directory, "sweep"))
        os.makedirs(self._dir, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()

    def save(self, step: int, payload: Dict[str, Any]) -> None:
        """payload: state, best_state (GMMState), plus plain scalars."""
        tree = dict(payload)
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        path = os.path.join(self._dir, str(step))
        self._ckpt.save(path, tree, force=True)
        self._ckpt.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        if not os.path.isdir(self._dir):
            return None
        steps = [int(d) for d in os.listdir(self._dir) if d.isdigit()]
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        tree = self._ckpt.restore(os.path.join(self._dir, str(step)))
        tree["state"] = _from_tree(tree["state"])
        tree["best_state"] = _from_tree(tree["best_state"])
        tree["step"] = step
        return tree
