"""Checkpoint/resume for the model-order sweep (capability upgrade).

The reference has NO persistence: the best model lives in host RAM across the
entire K-sweep (100 iterations x up to 512 K values) and is written to disk
only at the very end (``saved_clusters``, ``gaussian.cu:262-275, 839-851``;
SURVEY.md SS5.4 calls out checkpointing as a required upgrade). Here each
completed K saves an orbax checkpoint of the sweep position, so a killed run
resumes at the next K instead of restarting the whole search.

Layout: ``<dir>/sweep/<step>/`` orbax PyTree checkpoints, where step counts
completed EM runs. The stored tree carries the current (possibly merged)
state, the best-so-far state, and the sweep scalars.

Two write paths share that layout:

- **Collective (orbax)** -- the host-driven sweep: every rank calls
  ``save``, orbax coordinates (primary host writes), with a cross-process
  barrier. Safe only from the MAIN thread: the barrier executes a device
  collective.
- **Callback-safe (``<step>.npz``)** -- the fused sweep: ``save`` is
  invoked from inside an ordered ``io_callback`` while the device is
  mid-program and BLOCKED on the callback's completion, so it must never
  dispatch device work (an orbax barrier here deadlocks the whole job:
  the barrier's collective waits for the sweep, the sweep waits for the
  callback, the callback waits for the barrier). Process 0 alone writes a
  flat ``np.savez`` atomically (tmp + ``os.replace``); no barrier is
  needed because the emitted payload is identical on every rank
  (replicated state, cluster shards pre-gathered).

``restore`` reads either format; mixing them in one directory resolves to
the newest step.
"""

from __future__ import annotations

import os
import random
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..state import GMMState
from ..testing import faults

# First-retry backoff; doubles per attempt with +-25% deterministic jitter
# (seeded per (step, attempt), so concurrent rank-0 writers across a fleet
# desynchronize without making tests flaky).
RETRY_BASE_S = 0.05


def _to_tree(state: GMMState) -> Dict[str, Any]:
    return {
        "N": state.N, "pi": state.pi, "constant": state.constant,
        "avgvar": state.avgvar, "means": state.means, "R": state.R,
        "Rinv": state.Rinv, "active": state.active,
    }


def _from_tree(t: Dict[str, Any]) -> GMMState:
    import jax.numpy as jnp

    return GMMState(**{k: jnp.asarray(v) for k, v in t.items()})


class SweepCheckpointer:
    """Orbax-backed persistence of the order-search sweep.

    ``keep`` bounds retained steps (default 2: the newest for resume plus
    one fallback in case the newest is torn -- restore() walks back). A
    K=512 sweep would otherwise leave ~500 dead steps (~17 MB each at the
    reference envelope) on the checkpoint filesystem.
    """

    def __init__(self, directory: str, keep: int = 2, retries: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(os.path.join(directory, "sweep"))
        os.makedirs(self._dir, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self._keep = max(1, keep)
        self._retries = max(0, retries)
        # Transient-failure retries observed so far (run_summary.health).
        self.io_retries = 0

    def _write_with_retries(self, op: str, step: int,
                            write: Callable[[], None]) -> bool:
        """Run ``write`` with bounded, jittered exponential backoff.

        A transient ``OSError`` (EIO/ESTALE on a network checkpoint
        filesystem) must not kill an hours-long sweep -- least of all from
        inside the fused sweep's ordered ``io_callback``, where an
        exception aborts the device program. Each failure emits an
        ``io_retry`` telemetry record; exhaustion logs loudly and SKIPS
        the save (a missing checkpoint degrades resume granularity, a
        crashed run loses everything). Returns True when durable.
        """
        from .. import telemetry

        delay = RETRY_BASE_S
        for attempt in range(self._retries + 1):
            try:
                # Deterministic injection point (testing.faults:
                # checkpoint_eio), budget-bounded so the retry observes
                # the fault gone -- the transient-EIO shape.
                faults.raise_io_error("checkpoint_eio", step=step)
                write()
                return True
            except OSError as e:
                gave_up = attempt == self._retries
                rec = telemetry.current()
                if rec.active:
                    rec.emit("io_retry", op=op, step=int(step),
                             attempt=attempt + 1, error=str(e),
                             delay_s=(0.0 if gave_up else round(delay, 4)),
                             gave_up=gave_up)
                    rec.metrics.count("io_retries")
                if gave_up:
                    from .logging_ import get_logger

                    get_logger().error(
                        "checkpoint %s for step %d failed after %d "
                        "attempt(s): %s -- continuing WITHOUT this "
                        "checkpoint", op, step, attempt + 1, e)
                    return False
                self.io_retries += 1
                # +-25% deterministic jitter around the exponential term.
                jitter = 0.75 + 0.5 * random.Random(
                    (int(step) << 8) | attempt).random()
                time.sleep(delay * jitter)
                delay *= 2.0

    def _prune(self, newest_step: int) -> None:
        """Drop steps older than the retention window. Called by the save
        paths AFTER step ``newest_step`` is durable; only process 0 removes
        (other ranks never write). Best-effort: a prune failure must never
        break the run that just checkpointed successfully."""
        import shutil

        cutoff = newest_step - self._keep + 1
        try:
            for s in self._all_steps():
                if s >= cutoff:
                    continue
                try:
                    npz = os.path.join(self._dir, f"{s}.npz")
                    if os.path.exists(npz):
                        os.remove(npz)
                    d = os.path.join(self._dir, str(s))
                    if os.path.isdir(d):
                        shutil.rmtree(d)
                except OSError:
                    pass
            # Orphaned tmp files from crashed save_local calls (killed
            # between mkstemp and replace) match neither pattern above;
            # they are dead the moment this process is saving again (one
            # writer, serialized saves), so sweep them too.
            for f in os.listdir(self._dir):
                if f.endswith(".tmp.npz"):
                    try:
                        os.remove(os.path.join(self._dir, f))
                    except OSError:
                        pass
        except OSError:
            # Best-effort end to end: a transient listdir failure (ESTALE/
            # EIO on network filesystems) must never kill the run that
            # just checkpointed successfully.
            pass

    def save(self, step: int, payload: Dict[str, Any]) -> None:
        """payload: state, best_state (GMMState), plus plain scalars.

        Write failures retry with jittered backoff (``retries``); see
        ``_write_with_retries``. Multi-host: every rank runs the same
        bounded retry schedule, so the orbax collective stays aligned
        across ranks whether an attempt fails or succeeds (injected
        faults fire identically everywhere by construction).
        """
        tree = dict(payload)
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        path = os.path.join(self._dir, str(step))

        def write():
            self._ckpt.save(path, tree, force=True)
            self._ckpt.wait_until_finished()

        if not self._write_with_retries("save", step, write):
            return
        import jax

        if jax.process_index() == 0:
            self._prune(step)

    def save_local(self, step: int, payload: Dict[str, Any]) -> None:
        """Callback-safe save: no device work, no cross-process barrier.

        Process 0 writes ``<step>.npz`` atomically; other ranks return
        immediately (every rank holds the identical replicated payload, so
        one durable copy on the shared checkpoint FS is the whole story).
        Safe to call from inside an ordered ``io_callback`` -- the ONLY
        save path that is (see module docstring for the deadlock).
        """
        import jax

        if jax.process_index() != 0:
            return
        tree = dict(payload)
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        flat = {}
        for key, val in tree.items():
            if isinstance(val, dict):
                for leaf, arr in val.items():
                    flat[f"{key}.{leaf}"] = np.asarray(arr)
            else:
                flat[key] = np.asarray(val)

        def write():
            import tempfile

            fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp.npz")
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **flat)
                # The durability contract ("checkpoint s on disk before
                # step s+1 computes", fused_sweep.py) must survive a HOST
                # crash, not just a process kill: flush+fsync the data
                # before the atomic rename, then fsync the directory so
                # the rename itself is durable. The tmp name is
                # mkstemp-unique so concurrent savers (racing callback
                # threads) can never interleave writes into one file.
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self._dir, f"{step}.npz"))
            dir_fd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

        # Bounded retry: this runs inside the ordered io_callback while
        # the device program is blocked on it -- an escaped exception here
        # would abort the whole job for a transient filesystem hiccup.
        if self._write_with_retries("save_local", step, write):
            self._prune(step)  # already process-0-only here

    def _all_steps(self) -> list:
        if not os.path.isdir(self._dir):
            return []
        steps = [int(d) for d in os.listdir(self._dir) if d.isdigit()]
        steps += [int(f[:-4]) for f in os.listdir(self._dir)
                  if f.endswith(".npz") and f[:-4].isdigit()]
        return steps

    def latest_step(self) -> Optional[int]:
        steps = self._all_steps()
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Load the requested (default: newest) step. With no explicit
        ``step``, an unreadable newest checkpoint (e.g. torn by a crash on
        a filesystem without rename atomicity) falls back to the next
        older one instead of wedging resume -- losing one step beats
        losing the run."""
        if step is not None:
            return self._restore_step(step)
        steps = self._all_steps()
        for s in sorted(steps, reverse=True):
            try:
                return self._restore_step(s)
            except Exception as e:
                # Loud fallback: a systematic failure (permissions, numpy
                # version skew) would otherwise masquerade as a clean
                # resume from a much older step.
                import warnings

                warnings.warn(
                    f"checkpoint step {s} unreadable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous step", RuntimeWarning)
                if s == min(steps):
                    raise
        return None

    def _restore_step(self, step: int) -> Dict[str, Any]:
        npz = os.path.join(self._dir, f"{step}.npz")
        if os.path.exists(npz):
            with np.load(npz) as z:
                tree: Dict[str, Any] = {}
                for key in z.files:
                    if "." in key:
                        group, leaf = key.split(".", 1)
                        tree.setdefault(group, {})[leaf] = z[key]
                    else:
                        tree[key] = z[key]
        else:
            tree = self._ckpt.restore(os.path.join(self._dir, str(step)))
        tree["state"] = _from_tree(tree["state"])
        tree["best_state"] = _from_tree(tree["best_state"])
        tree["step"] = step
        return tree
