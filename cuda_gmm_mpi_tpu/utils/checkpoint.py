"""Checkpoint/resume for the model-order sweep (capability upgrade).

The reference has NO persistence: the best model lives in host RAM across the
entire K-sweep (100 iterations x up to 512 K values) and is written to disk
only at the very end (``saved_clusters``, ``gaussian.cu:262-275, 839-851``;
SURVEY.md SS5.4 calls out checkpointing as a required upgrade). Here each
completed K saves an orbax checkpoint of the sweep position, so a killed run
resumes at the next K instead of restarting the whole search.

Layout: ``<dir>/sweep/<step>/`` orbax PyTree checkpoints, where step counts
completed EM runs. The stored tree carries the current (possibly merged)
state, the best-so-far state, and the sweep scalars.

Two write paths share that layout:

- **Collective (orbax)** -- the host-driven sweep: every rank calls
  ``save``, orbax coordinates (primary host writes), with a cross-process
  barrier. Safe only from the MAIN thread: the barrier executes a device
  collective.
- **Callback-safe (``<step>.npz``)** -- the fused sweep: ``save`` is
  invoked from inside an ordered ``io_callback`` while the device is
  mid-program and BLOCKED on the callback's completion, so it must never
  dispatch device work (an orbax barrier here deadlocks the whole job:
  the barrier's collective waits for the sweep, the sweep waits for the
  callback, the callback waits for the barrier). Process 0 alone writes a
  flat ``np.savez`` atomically (tmp + ``os.replace``); no barrier is
  needed because the emitted payload is identical on every rank
  (replicated state, cluster shards pre-gathered).

``restore`` reads either format; mixing them in one directory resolves to
the newest step.

**Intra-K sub-steps** (preemption-safe execution, docs/ROBUSTNESS.md "Run
lifecycle"): ``save_substep`` writes ``<step>.iter<i>.npz`` -- the emergency
checkpoint of an EM fit interrupted mid-K at iteration ``i``, carrying the
mid-EM state, the loglik trajectory so far, and (streaming) the partially
reduced block accumulator. A sub-step is strictly newer than every full
step below it; ``restore_substep`` finds the newest one so ``--resume
auto`` restarts INSIDE the interrupted fit instead of at its beginning.
Sub-steps use the callback-safe write path (process 0, atomic npz) because
emergency saves must never start a cross-process collective: the peers may
already be dead -- that can be WHY we are saving.
"""

from __future__ import annotations

import os
import random
import re
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..state import GMMState
from ..testing import faults


class CheckpointRestoreError(OSError):
    """Every checkpoint step in the directory was unreadable.

    Aggregates the per-step failures (``.errors``: newest step first) so
    the usually-most-informative newest-step error is never shadowed by
    the oldest one the walk-back happened to end on; the newest failure is
    also chained as ``__cause__``. The CLI maps this to exit 74
    (EX_IOERR).
    """

    def __init__(self, message: str,
                 errors: List[Tuple[int, BaseException]]):
        self.errors = errors
        lines = [message]
        for step, err in errors:
            lines.append(f"  step {step}: {type(err).__name__}: {err}")
        super().__init__("\n".join(lines))


_SUBSTEP_RE = re.compile(r"^(\d+)\.iter(\d+)\.npz$")

# First-retry backoff; doubles per attempt with +-25% deterministic jitter
# (seeded per (step, attempt), so concurrent rank-0 writers across a fleet
# desynchronize without making tests flaky).
RETRY_BASE_S = 0.05


def _to_tree(state: GMMState) -> Dict[str, Any]:
    return {
        "N": state.N, "pi": state.pi, "constant": state.constant,
        "avgvar": state.avgvar, "means": state.means, "R": state.R,
        "Rinv": state.Rinv, "active": state.active,
    }


def _from_tree(t: Dict[str, Any]) -> GMMState:
    import jax.numpy as jnp

    return GMMState(**{k: jnp.asarray(v) for k, v in t.items()})


def flatten_tree(payload: Dict[str, Any]) -> Dict[str, Any]:
    """One-level flatten of a checkpoint payload into npz-ready keys.

    ``GMMState`` values are expanded to their leaf arrays and every
    nested dict to ``group.leaf`` keys; scalars/arrays pass through as
    ``np.asarray``. The shared serializer behind the sweep checkpoints
    AND the serving model registry (serving/registry.py) -- one artifact
    format, one flattening rule.
    """
    flat: Dict[str, Any] = {}
    for key, val in payload.items():
        if isinstance(val, GMMState):
            val = _to_tree(val)
        if isinstance(val, dict):
            for leaf, arr in val.items():
                flat[f"{key}.{leaf}"] = np.asarray(arr)
        else:
            flat[key] = np.asarray(val)
    return flat


def write_npz_atomic(directory: str, target: str,
                     flat: Dict[str, Any]) -> None:
    """Durable atomic npz write: tmp + fsync + ``os.replace`` + dir fsync.

    The write path every callback-safe checkpoint and registry artifact
    shares: the payload must survive a HOST crash, not just a process
    kill, so the data is fsynced before the atomic rename and the
    directory entry after it -- without the directory fsync a crash can
    lose the RENAME and the restore walk-back would see its "newest"
    step vanish. The directory fsync is POSIX-gated: Windows cannot
    ``os.open`` a directory (rename durability is the filesystem's
    business there), and crashing on the gate would un-durably fail a
    write that already succeeded. The tmp name is mkstemp-unique so
    concurrent savers can never interleave writes into one file.
    """
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.npz")
    with os.fdopen(fd, "wb") as f:
        np.savez(f, **flat)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, target)
    _fsync_dir(directory)


def write_json_atomic(path: str, obj: Any) -> None:
    """``write_npz_atomic``'s sibling for JSON artifacts (tuning DB,
    manifests): tmp + fsync + ``os.replace`` + dir fsync, same crash
    contract. Keys are sorted so two writers producing the same logical
    content produce the same bytes (diff-able artifacts)."""
    import json
    import tempfile

    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.json")
    with os.fdopen(fd, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(directory)


def _fsync_dir(directory: str) -> None:
    """POSIX-only durability fsync of a directory entry after a rename."""
    if os.name != "posix":
        return
    dir_fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def load_npz_tree(path: str,
                  state_keys: Tuple[str, ...] = ("state", "best_state"),
                  ) -> Dict[str, Any]:
    """Un-flatten one npz artifact written via :func:`flatten_tree`.

    ``group.leaf`` keys regroup into dicts; groups named in
    ``state_keys`` (when present) are rebuilt as :class:`GMMState`.
    """
    with np.load(path) as z:
        tree: Dict[str, Any] = {}
        for key in z.files:
            if "." in key:
                group, leaf = key.split(".", 1)
                tree.setdefault(group, {})[leaf] = z[key]
            else:
                tree[key] = z[key]
    for key in state_keys:
        if key in tree:
            tree[key] = _from_tree(tree[key])
    return tree


class SweepCheckpointer:
    """Orbax-backed persistence of the order-search sweep.

    ``keep`` bounds retained steps (default 2: the newest for resume plus
    one fallback in case the newest is torn -- restore() walks back). A
    K=512 sweep would otherwise leave ~500 dead steps (~17 MB each at the
    reference envelope) on the checkpoint filesystem.
    """

    def __init__(self, directory: str, keep: int = 2, retries: int = 3,
                 allow_world_change: bool = False):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(os.path.join(directory, "sweep"))
        os.makedirs(self._dir, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self._keep = max(1, keep)
        self._retries = max(0, retries)
        # Elastic runs restore checkpoints written at a DIFFERENT world
        # size by design (the sweep state is replicated, so any world can
        # restore it); non-elastic runs treat that as the misconfiguration
        # it is and fail the walk-back with an explicit mismatch message.
        self._allow_world_change = bool(allow_world_change)
        # Transient-failure retries observed so far (run_summary.health).
        self.io_retries = 0

    @staticmethod
    def _world_meta() -> Dict[str, Any]:
        """World-size/generation stamp every save carries, so restore can
        DIAGNOSE a world mismatch instead of surfacing it later as a
        shape-mismatch traceback from deep inside npz loading."""
        from ..parallel import elastic

        return {"ckpt_world_size": np.asarray(elastic.world()[1],
                                              np.int64),
                "ckpt_generation": np.asarray(elastic.generation(),
                                              np.int64)}

    def _validate_meta(self, tree: Dict[str, Any], step: int) -> None:
        """Raise an informative error when a stamped checkpoint was
        written at a different world size and this run did not opt into
        elastic world changes. Legacy checkpoints (no stamp) skip the
        check. Runs inside the restore walk-back, so the message lands in
        the aggregated :class:`CheckpointRestoreError`."""
        if "ckpt_world_size" not in tree:
            return
        from ..parallel import elastic

        saved_world = int(np.asarray(tree["ckpt_world_size"]))
        saved_gen = int(np.asarray(tree.get("ckpt_generation", 0)))
        here = int(elastic.world()[1])
        if saved_world != here and not self._allow_world_change:
            raise ValueError(
                f"checkpoint step {step} was written at world size "
                f"{saved_world} (membership generation {saved_gen}) but "
                f"this run has {here} host(s); resume at the original "
                "world size, or pass --elastic to accept a shrunken "
                "world (docs/DISTRIBUTED.md 'Elastic recovery')")

    def _write_with_retries(self, op: str, step: int,
                            write: Callable[[], None]) -> bool:
        """Run ``write`` with bounded, jittered exponential backoff.

        A transient ``OSError`` (EIO/ESTALE on a network checkpoint
        filesystem) must not kill an hours-long sweep -- least of all from
        inside the fused sweep's ordered ``io_callback``, where an
        exception aborts the device program. Each failure emits an
        ``io_retry`` telemetry record; exhaustion logs loudly and SKIPS
        the save (a missing checkpoint degrades resume granularity, a
        crashed run loses everything). Returns True when durable.
        """
        from .. import telemetry

        delay = RETRY_BASE_S
        for attempt in range(self._retries + 1):
            try:
                # Deterministic injection point (testing.faults:
                # checkpoint_eio), budget-bounded so the retry observes
                # the fault gone -- the transient-EIO shape.
                faults.raise_io_error("checkpoint_eio", step=step)
                write()
                return True
            except OSError as e:
                gave_up = attempt == self._retries
                rec = telemetry.current()
                if rec.active:
                    rec.emit("io_retry", op=op, step=int(step),
                             attempt=attempt + 1, error=str(e),
                             delay_s=(0.0 if gave_up else round(delay, 4)),
                             gave_up=gave_up)
                    rec.metrics.count("io_retries")
                if gave_up:
                    from .logging_ import get_logger

                    get_logger().error(
                        "checkpoint %s for step %d failed after %d "
                        "attempt(s): %s -- continuing WITHOUT this "
                        "checkpoint", op, step, attempt + 1, e)
                    return False
                self.io_retries += 1
                # +-25% deterministic jitter around the exponential term.
                jitter = 0.75 + 0.5 * random.Random(
                    (int(step) << 8) | attempt).random()
                time.sleep(delay * jitter)
                delay *= 2.0

    def _prune(self, newest_step: int) -> None:
        """Drop steps older than the retention window. Called by the save
        paths AFTER step ``newest_step`` is durable; only process 0 removes
        (other ranks never write). Best-effort: a prune failure must never
        break the run that just checkpointed successfully."""
        import shutil

        cutoff = newest_step - self._keep + 1
        try:
            for s in self._all_steps():
                if s >= cutoff:
                    continue
                try:
                    npz = os.path.join(self._dir, f"{s}.npz")
                    if os.path.exists(npz):
                        os.remove(npz)
                    d = os.path.join(self._dir, str(s))
                    if os.path.isdir(d):
                        shutil.rmtree(d)
                except OSError:
                    pass
            # Intra-K sub-steps at or below the newest COMPLETED step are
            # superseded (their K finished after the emergency save).
            for s, i in self._substeps():
                if s <= newest_step:
                    try:
                        os.remove(os.path.join(self._dir,
                                               f"{s}.iter{i}.npz"))
                    except OSError:
                        pass
            # Orphaned tmp files from crashed save_local calls (killed
            # between mkstemp and replace) match neither pattern above;
            # they are dead the moment this process is saving again (one
            # writer, serialized saves), so sweep them too.
            for f in os.listdir(self._dir):
                if f.endswith(".tmp.npz"):
                    try:
                        os.remove(os.path.join(self._dir, f))
                    except OSError:
                        pass
        except OSError:
            # Best-effort end to end: a transient listdir failure (ESTALE/
            # EIO on network filesystems) must never kill the run that
            # just checkpointed successfully.
            pass

    def save(self, step: int, payload: Dict[str, Any]) -> None:
        """payload: state, best_state (GMMState), plus plain scalars.

        Write failures retry with jittered backoff (``retries``); see
        ``_write_with_retries``. Multi-host: every rank runs the same
        bounded retry schedule, so the orbax collective stays aligned
        across ranks whether an attempt fails or succeeds (injected
        faults fire identically everywhere by construction).
        """
        tree = dict(payload, **self._world_meta())
        tree["state"] = _to_tree(payload["state"])
        tree["best_state"] = _to_tree(payload["best_state"])
        path = os.path.join(self._dir, str(step))

        def write():
            self._ckpt.save(path, tree, force=True)
            self._ckpt.wait_until_finished()

        if not self._write_with_retries("save", step, write):
            return
        import jax

        if jax.process_index() == 0:
            self._prune(step)

    def save_local(self, step: int, payload: Dict[str, Any]) -> None:
        """Callback-safe save: no device work, no cross-process barrier.

        Process 0 writes ``<step>.npz`` atomically; other ranks return
        immediately (every rank holds the identical replicated payload, so
        one durable copy on the shared checkpoint FS is the whole story).
        Safe to call from inside an ordered ``io_callback`` -- the ONLY
        save path that is (see module docstring for the deadlock).
        """
        import jax

        if jax.process_index() != 0:
            return
        flat = self._flatten(dict(payload, **self._world_meta()))
        target = os.path.join(self._dir, f"{step}.npz")

        # Bounded retry: this runs inside the ordered io_callback while
        # the device program is blocked on it -- an escaped exception here
        # would abort the whole job for a transient filesystem hiccup.
        if self._write_with_retries(
                "save_local", step,
                lambda: self._write_npz_atomic(target, flat)):
            self._prune(step)  # already process-0-only here

    def save_substep(self, step: int, em_iter: int,
                     payload: Dict[str, Any]) -> bool:
        """Emergency intra-K checkpoint: ``<step>.iter<em_iter>.npz``.

        The preemption path's save (supervisor.py): the payload carries the
        MID-EM state of the K being fitted at sweep step ``step``, the
        iteration count and loglik trajectory so far (``em_iter`` /
        ``em_lls``), and -- for the streaming path -- the partially reduced
        block accumulator, so ``--resume auto`` restarts inside the
        interrupted fit. Process 0 only, atomic npz, NO collective: the
        peers may already be dead (peer-loss emergency saves), and a
        stopping run must never block on one. A sub-step outranks every
        full step below it at restore time (``restore_substep``); it is
        pruned the moment its K completes. Returns True when durable.
        """
        import jax

        if jax.process_index() != 0:
            return True
        flat = self._flatten(dict(payload, em_iter=np.int64(em_iter),
                                  **self._world_meta()))
        target = os.path.join(self._dir, f"{step}.iter{em_iter}.npz")
        ok = self._write_with_retries(
            "save_substep", step,
            lambda: self._write_npz_atomic(target, flat))
        if ok:
            # Older sub-steps of the same K are superseded (best-effort).
            for s, i in self._substeps():
                if s == step and i < em_iter:
                    try:
                        os.remove(os.path.join(self._dir,
                                               f"{s}.iter{i}.npz"))
                    except OSError:
                        pass
        return ok

    def discard_substeps(self, step: int) -> None:
        """Drop intra-K sub-steps at or below ``step``: that K completed,
        so its emergency mid-EM state is superseded. The save paths prune
        these as a side effect, but the sweep's FINAL K has no full-step
        save -- the resumed fit calls this directly so a finished run
        never leaves a live-looking sub-step behind. Process 0 only,
        best-effort."""
        import jax

        if jax.process_index() != 0:
            return
        for s, i in self._substeps():
            if s <= step:
                try:
                    os.remove(os.path.join(self._dir, f"{s}.iter{i}.npz"))
                except OSError:
                    pass

    def _flatten(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One-level flatten of the payload (GMMStates expanded to leaf
        arrays) into npz-ready ``group.leaf`` keys (flatten_tree)."""
        return flatten_tree(payload)

    def _write_npz_atomic(self, target: str, flat: Dict[str, Any]) -> None:
        # The durability contract ("checkpoint s on disk before step s+1
        # computes", fused_sweep.py): see write_npz_atomic.
        write_npz_atomic(self._dir, target, flat)

    def _all_steps(self) -> list:
        if not os.path.isdir(self._dir):
            return []
        steps = [int(d) for d in os.listdir(self._dir) if d.isdigit()]
        steps += [int(f[:-4]) for f in os.listdir(self._dir)
                  if f.endswith(".npz") and f[:-4].isdigit()]
        return steps

    def _substeps(self) -> List[Tuple[int, int]]:
        """(step, em_iter) of every intra-K sub-step file on disk."""
        if not os.path.isdir(self._dir):
            return []
        out = []
        for f in os.listdir(self._dir):
            m = _SUBSTEP_RE.match(f)
            if m:
                out.append((int(m.group(1)), int(m.group(2))))
        return out

    def latest_step(self) -> Optional[int]:
        steps = self._all_steps()
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Load the requested (default: newest) step. With no explicit
        ``step``, an unreadable newest checkpoint (e.g. torn by a crash on
        a filesystem without rename atomicity) falls back to the next
        older one instead of wedging resume -- losing one step beats
        losing the run. When EVERY step is unreadable the failures are
        aggregated into one :class:`CheckpointRestoreError` (newest first,
        newest chained as ``__cause__``) -- the newest step's error is
        usually the informative one and must not be shadowed by whichever
        ancient step the walk-back died on."""
        if step is not None:
            return self._restore_step(step)
        failures: List[Tuple[int, BaseException]] = []
        for s in sorted(self._all_steps(), reverse=True):
            try:
                return self._restore_step(s)
            except Exception as e:
                # Loud fallback: a systematic failure (permissions, numpy
                # version skew) would otherwise masquerade as a clean
                # resume from a much older step.
                import warnings

                failures.append((s, e))
                warnings.warn(
                    f"checkpoint step {s} unreadable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous step", RuntimeWarning)
        if failures:
            raise CheckpointRestoreError(
                f"all {len(failures)} checkpoint step(s) under "
                f"{self._dir} are unreadable", failures) from failures[0][1]
        return None

    def restore_substep(self) -> Optional[Dict[str, Any]]:
        """The newest LIVE intra-K sub-step's payload (with ``step`` and
        ``em_iter`` set), or None.

        A sub-step at or below the newest full step is stale -- its K
        completed after the emergency save -- and is ignored (the next
        durable full save prunes it). An unreadable sub-step (torn by a
        crash during the emergency write) warns and falls back to older
        live sub-steps, then to None: resume then restarts that K from
        its beginning via the full-step walk-back, which is the correct
        degraded behavior, not an error.
        """
        latest_full = self.latest_step()
        for s, i in sorted(self._substeps(), reverse=True):
            if latest_full is not None and s <= latest_full:
                break  # stale: that K completed after this emergency save
            path = os.path.join(self._dir, f"{s}.iter{i}.npz")
            try:
                tree = _load_npz_tree(path)
                self._validate_meta(tree, s)
            except Exception as e:
                import warnings

                warnings.warn(
                    f"intra-K sub-step {s}.iter{i} unreadable "
                    f"({type(e).__name__}: {e}); resuming that K from its "
                    "beginning instead", RuntimeWarning)
                continue
            tree["step"] = s
            tree["em_iter"] = i
            return tree
        return None

    def _restore_step(self, step: int) -> Dict[str, Any]:
        npz = os.path.join(self._dir, f"{step}.npz")
        if os.path.exists(npz):
            tree = _load_npz_tree(npz)
        else:
            tree = self._ckpt.restore(os.path.join(self._dir, str(step)))
            tree["state"] = _from_tree(tree["state"])
            tree["best_state"] = _from_tree(tree["best_state"])
        self._validate_meta(tree, step)
        tree["step"] = step
        return tree


def _load_npz_tree(path: str) -> Dict[str, Any]:
    """Un-flatten one npz checkpoint (load_npz_tree; the two GMMState
    groups are required here -- a sweep checkpoint always has both)."""
    tree = load_npz_tree(path)
    for key in ("state", "best_state"):
        if not isinstance(tree.get(key), GMMState):
            raise ValueError(f"checkpoint {path!r} is missing the "
                             f"{key!r} group")
    return tree
