"""Per-phase profiler with the reference's 7-category taxonomy.

The reference instruments every phase with cudaEvent timers grouped in a
``profile_t`` struct -- categories ``e_step, m_step, constants, reduce,
memcpy, cpu, mpi`` (``gaussian.cu:76-84``) -- and prints totals plus
per-iteration averages at the end (``gaussian.cu:967``). This module keeps the
same taxonomy so baselines compare 1:1, with the TPU-native mapping:

  e_step    fused E-step + sufficient-stats pass (estep1+estep2+mstep sums --
            fused on TPU, so the reference's separate m_step kernel time is
            largely folded in here)
  m_step    parameter update from stats (division/guards, gaussian.cu:611-686)
  constants Cholesky Rinv/log-det/pi (constants_kernel)
  reduce    model-order reduction: empty elimination + pair scan + merge
            (the reference's "Order Reduce" timer, gaussian.cu:858-953)
  memcpy    host<->device transfers (device_put/device_get)
  cpu       host-side work: parsing, chunking, seeding, output assembly
  mpi       cross-host collective setup (inside jit on TPU; ~0 single-host)

Two usage modes:
  - coarse (always available): wrap phases via ``timer.phase(name)`` context
    managers around the jitted calls;
  - deep-dive: ``jax.profiler`` trace capture via ``trace(log_dir)``.

Since the telemetry subsystem landed, PhaseTimer is a thin adapter over it:
the report table renders through ``telemetry.report.render_phase_table``
(one formatter for the live ``--profile`` print and the offline
``gmm report``), every measured span is forwarded into the active
RunRecorder's metrics registry as a ``phase.<name>`` histogram, and
``snapshot()`` is the shape ``run_summary.phase_profile`` carries.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from ..telemetry import current as _current_recorder
from ..telemetry import render_phase_table

CATEGORIES = ("e_step", "m_step", "constants", "reduce", "memcpy", "cpu", "mpi")


class PhaseTimer:
    """Accumulating wall-clock timers, one slot per reference category."""

    def __init__(self):
        self.seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.counts: Dict[str, int] = {c: 0 for c in CATEGORIES}

    @contextlib.contextmanager
    def phase(self, name: str):
        if name not in self.seconds:  # allow ad-hoc categories too
            self.seconds[name] = 0.0
            self.counts[name] = 0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float, count: int = 1) -> None:
        self.seconds[name] = self.seconds.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + count
        rec = _current_recorder()
        if rec.active:
            rec.metrics.observe(f"phase.{name}", seconds)

    def report(self) -> str:
        """Total + per-call average per category (gaussian.cu:967's layout)."""
        return render_phase_table(self.seconds, self.counts)

    def as_dict(self) -> Dict[str, float]:
        return dict(self.seconds)

    def snapshot(self) -> Dict[str, dict]:
        """``run_summary.phase_profile`` payload: seconds + call counts."""
        return {"seconds": dict(self.seconds), "counts": dict(self.counts)}


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """jax.profiler trace capture (TensorBoard-viewable), no-op when None."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
