"""Output writers: the reference's ``.summary`` and ``.results`` formats.

Format provenance (README.txt:79-84, gaussian.cu:998-1061, 1180-1201):

``<outfile>.summary`` -- per saved cluster:
    Cluster #<c>
    Probability: <pi %f>
    N: <N %f>
    Means: <%.3f per dim, space-separated, trailing space>

    R Matrix:
    <%.3f per entry, space-separated rows, trailing space>
    <blank><blank>

``<outfile>.results`` -- per event:
    <data CSV %f> \t <membership CSV %f>

A native C++ writer for .results exists (io.native) because formatting
N x (D + K) floats through printf is itself a bottleneck at 1M+ events.
"""

from __future__ import annotations

from typing import IO

import numpy as np


def _fmt(x: float) -> str:
    return f"{float(x):f}"  # C printf %f: 6 decimal places


def write_cluster(f: IO[str], pi: float, n: float, means: np.ndarray,
                  R: np.ndarray) -> None:
    """One cluster block (writeCluster, gaussian.cu:1180-1197)."""
    f.write(f"Probability: {_fmt(pi)}\n")
    f.write(f"N: {_fmt(n)}\n")
    f.write("Means: " + "".join(f"{m:.3f} " for m in means) + "\n")
    f.write("\nR Matrix:\n")
    for row in R:
        f.write("".join(f"{v:.3f} " for v in row) + "\n")


def write_summary(path: str, result, enable_output: bool = True) -> None:
    """``<outfile>.summary`` (gaussian.cu:1014-1040).

    The file is created unconditionally (as the reference does); cluster blocks
    are written when ``enable_output`` (the runtime ENABLE_OUTPUT).
    """
    means = result.means
    state = result.state
    with open(path, "w") as f:
        if not enable_output:
            return
        for c in range(result.ideal_num_clusters):
            f.write(f"Cluster #{c}\n")
            write_cluster(
                f,
                float(np.asarray(state.pi)[c]),
                float(np.asarray(state.N)[c]),
                means[c],
                np.asarray(state.R)[c],
            )
            f.write("\n\n")


def write_results(path: str, data: np.ndarray, memberships: np.ndarray,
                  use_native: str = "auto") -> None:
    """``<outfile>.results`` (gaussian.cu:1042-1059): data CSV, tab,
    per-cluster membership CSV, one line per event."""
    stream_results(path, [(data, memberships)], use_native=use_native)


def _append_text(f: IO[str], data: np.ndarray, memberships: np.ndarray) -> None:
    for i in range(data.shape[0]):
        f.write(",".join(_fmt(v) for v in data[i]))
        f.write("\t")
        f.write(",".join(_fmt(v) for v in memberships[i]))
        f.write("\n")


def stream_results(path: str, chunk_iter, use_native: str = "auto") -> int:
    """Streaming ``.results`` writer: bounded memory at any N.

    ``chunk_iter`` yields ``(data_block [B, D], memberships_block [B, K])``
    pairs (original data coordinates); blocks are formatted and appended as
    they arrive, so the full N x K posterior matrix never exists in host RAM
    (at the 10M x 128 benchmark scale it would be ~5 GB -- the reference
    gathers exactly that through MPI, gaussian.cu:783-823). Returns the
    number of events written. Byte-identical output to ``write_results``.
    """
    written = 0
    if use_native != "never":
        from . import native

        if native.available():
            with native.ResultsWriter(path) as w:
                for block, memb in chunk_iter:
                    w.append(block, memb)
                    written += block.shape[0]
            return written
        if use_native == "always":
            raise RuntimeError("native gmm_io library unavailable "
                               "(use_native='always')")
    with open(path, "w") as f:
        for block, memb in chunk_iter:
            _append_text(f, block, memb)
            written += block.shape[0]
    return written
