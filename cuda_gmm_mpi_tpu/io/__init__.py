"""Data I/O layer (SURVEY L0): readers, writers, native fast paths."""

from .readers import read_bin, read_csv, read_data, write_bin
from .writers import write_results, write_summary

__all__ = [
    "read_bin", "read_csv", "read_data", "write_bin",
    "write_results", "write_summary",
]
