"""Data I/O layer (SURVEY L0): readers, writers, native fast paths."""

from .pipeline import PipelinedBlockSource, streamed_moments
from .readers import (
    FileSource, data_shape, read_bin, read_csv, read_data, read_rows,
    write_bin,
)
from .writers import write_results, write_summary

__all__ = [
    "FileSource", "PipelinedBlockSource", "data_shape", "read_bin",
    "read_csv", "read_data", "read_rows", "streamed_moments", "write_bin",
    "write_results", "write_summary",
]
