"""Data I/O layer (SURVEY L0): readers, writers, native fast paths."""

from .readers import (
    FileSource, data_shape, read_bin, read_csv, read_data, read_rows,
    write_bin,
)
from .writers import write_results, write_summary

__all__ = [
    "FileSource", "data_shape", "read_bin", "read_csv", "read_data",
    "read_rows", "write_bin", "write_results", "write_summary",
]
