"""Out-of-core pipelined ingestion: bounded-queue background block reads.

The streaming model (models/streaming.py) already overlaps the host->device
copy of block j+1 with the device compute of block j -- but only from a
HOST-RESIDENT chunk array, so peak host memory is still O(N) per host, the
same shape as the reference's broadcast-a-full-replica ingest
(``gaussian.cu:191-201``). This module extends the overlap pipeline one
stage back to disk: a :class:`PipelinedBlockSource` wraps a
:class:`~cuda_gmm_mpi_tpu.io.readers.FileSource` and serves the streaming
loop per-block ``[S, B, D]`` slices that a background worker thread reads
(byte-range ``read_range`` -- the io/readers.py metadata cache makes each
one O(slice)), decodes, casts, and centers WHILE the device computes the
previous block. A bounded queue (``GMMConfig.ingest_queue_depth``) caps the
prefetch distance, so peak host memory is O(queue_depth x block), never
O(N).

Bit-identity contract: block j holds local shard d's chunk ``d * blocks +
j`` -- the exact block-major layout ``StreamingGMMModel.prepare`` gives the
resident path -- and each chunk's rows are cast and centered with the same
elementwise recipe ``_prepare_fit`` applies to the resident slice, so the
streamed statistics (and therefore the fit) match the host-resident path
bit for bit, single-device and data-mesh alike. Per-rank sharding composes
the same way: each host's source covers only its own ``host_chunk_bounds``
row range, so no host ever holds (or reads) more than its slice.

:func:`streamed_moments` is the matching out-of-core replacement for the
load -> ``validate_finite`` -> ``global_moments`` prologue: one pass of
per-chunk range reads builds the identical per-chunk partials matrix
(``parallel.distributed.moment_part``) and accumulates the non-finite-row
scan, then makes ONE collectively agreed validation decision -- the same
collective shape as the resident path, so multi-controller ranks can never
diverge on a raise.

Determinism: one worker thread reads blocks strictly in ascending order per
pass and the consumer requests them in the same order, so delivery order is
deterministic by construction (asserted under ``-p no:randomly`` in
tests/test_ingest.py); ``faults`` ``read_slow`` injection only moves the
prefetch wait, never the data.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np

from ..testing import faults


class PipelinedBlockSource:
    """Lazy block source: the streaming loop's chunk-array stand-in.

    Implements the minimal surface ``StreamingGMMModel`` consumes
    (``shape``, ``dtype``, ``get_block``) plus ingestion telemetry
    counters. ``get_block(j)`` returns ``([B, D], [B])`` when
    ``local_data_size == 1`` and ``([S, B, D], [S, B])`` block-major
    otherwise -- already cast to the compute dtype and centered, i.e.
    exactly what ``_put_block`` would have sliced out of a prepared
    resident array.

    ``num_chunks`` must be this host's chunk-slot count from
    ``host_chunk_bounds`` (always a multiple of the local data-axis
    extent), ``start``/``stop`` its row range. Chunk slots past
    ``stop - start`` rows are zero-filled with zero weights, the same
    padding contract as ``chunk_events``.
    """

    def __init__(self, source, *, start: int, stop: int, chunk_size: int,
                 num_chunks: int, local_data_size: int = 1,
                 shift: Optional[np.ndarray] = None, dtype=np.float64,
                 queue_depth: int = 4):
        if num_chunks % max(local_data_size, 1):
            raise ValueError(
                f"num_chunks {num_chunks} not divisible by the local "
                f"data-axis extent {local_data_size}; derive slices with "
                "parallel.distributed.host_chunk_bounds")
        self.source = source
        self.start, self.stop = int(start), int(stop)
        self.chunk_size = int(chunk_size)
        self.num_chunks = int(num_chunks)
        self.local_data_size = max(int(local_data_size), 1)
        self.num_blocks = self.num_chunks // self.local_data_size
        self._shift = None if shift is None else np.asarray(shift)
        self._dtype = np.dtype(dtype)
        self.queue_depth = max(int(queue_depth), 1)
        self._n_dims = int(source.shape[1])
        # -- ingestion telemetry (read by ingest_summary / tests) --
        self.last_wait_s = 0.0     # consumer wait for the latest block
        self.prefetch_wait_s = 0.0  # cumulative consumer wait
        self.blocks_read = 0
        self.bytes_read = 0
        self.peak_resident = 0     # max blocks ever resident in the queue
        self.delivered_order: list = []  # capped; seeded-order assertion
        self._summary_emitted = False
        # -- worker state --
        self._gen = 0
        self._next = 0             # block index the live worker serves next
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()

    # -- array-facade surface consumed by StreamingGMMModel ---------------

    @property
    def shape(self):
        return (self.num_chunks, self.chunk_size, self._n_dims)

    @property
    def dtype(self):
        return self._dtype

    @property
    def total_weight(self) -> float:
        """This host's real (un-padded) event count == its weight sum."""
        return float(self.stop - self.start)

    # -- block production --------------------------------------------------

    def _read_chunk(self, c: int, out_x: np.ndarray, out_w: np.ndarray):
        """Fill one chunk slot: range-read, cast, center, pad (the same
        elementwise recipe the resident path applies to its whole slice)."""
        m = self.stop - self.start
        a = min(c * self.chunk_size, m)
        b = min((c + 1) * self.chunk_size, m)
        if b > a:
            raw = self.source.read_range(self.start + a, self.start + b)
            self.bytes_read += int(raw.nbytes)
            rows = raw.astype(self._dtype, copy=False)
            if self._shift is not None:
                rows = rows - self._shift[None, :]
            out_x[:b - a] = rows
            out_w[:b - a] = 1.0

    def _read_block(self, j: int):
        """One block's ([S, B, D], [S, B]) (squeezed to 2-D/1-D when
        S == 1), read on the worker thread."""
        cfg = faults.take("read_slow", block=j)
        if cfg is not None:
            time.sleep(float(cfg.get("ms", 0)) / 1e3)
        S, B = self.local_data_size, self.chunk_size
        x = np.zeros((S, B, self._n_dims), self._dtype)
        w = np.zeros((S, B), self._dtype)
        for d in range(S):
            self._read_chunk(d * self.num_blocks + j, x[d], w[d])
        if S == 1:
            return x[0], w[0]
        return x, w

    def _run(self, gen: int, q: queue.Queue, start_block: int):
        """Worker loop: read blocks ``start_block..num_blocks-1`` in order
        into the bounded queue; exits when the pass ends, the generation
        is superseded (a seek restarted the stream), or the source closes.
        Read errors are delivered in-band so the consumer re-raises them
        on its thread."""
        for j in range(start_block, self.num_blocks):
            try:
                payload = (j, self._read_block(j), None)
            except BaseException as e:  # delivered, not swallowed
                payload = (j, None, e)
            while True:
                if self._gen != gen or self._closed or self._queue is not q:
                    return
                try:
                    q.put(payload, timeout=0.1)
                    break
                except queue.Full:
                    continue
            with self._lock:
                self.peak_resident = max(self.peak_resident, q.qsize())
            if payload[2] is not None:
                return

    def _restart(self, start_block: int):
        with self._lock:
            self._gen += 1
            gen = self._gen
            q = queue.Queue(maxsize=self.queue_depth)
            self._queue = q
            self._next = start_block
            self._thread = threading.Thread(
                target=self._run, args=(gen, q, start_block),
                name=f"gmm-ingest-{id(self) & 0xffff:x}", daemon=True)
        self._thread.start()

    def get_block(self, j: int):
        """Block j's (chunks, weights), blocking only when the prefetcher
        has not gotten to it yet (``last_wait_s`` records that wait)."""
        if self._closed:
            raise RuntimeError("PipelinedBlockSource is closed")
        if not 0 <= j < self.num_blocks:
            raise IndexError(
                f"block {j} out of range [0, {self.num_blocks})")
        if self._queue is None or self._next != j:
            # Cold start, new pass (wrap to 0), or an out-of-order seek
            # (mid-pass resume): restart the prefetcher at j.
            self._restart(j)
        q, gen = self._queue, self._gen
        t0 = time.perf_counter()
        while True:
            try:
                jj, data, err = q.get(timeout=0.1)
            except queue.Empty:
                if self._closed:
                    raise RuntimeError("PipelinedBlockSource closed "
                                       "while waiting for a block")
                if self._gen != gen:   # superseded mid-wait; re-request
                    return self.get_block(j)
                continue
            break
        if jj != j:
            # One worker reads in ascending order and one consumer pops in
            # the same order, so this is unreachable by construction.
            raise RuntimeError(f"prefetch order violated: got block {jj}, "
                               f"expected {j}")
        self.last_wait_s = time.perf_counter() - t0
        self.prefetch_wait_s += self.last_wait_s
        if err is not None:
            raise err
        self._next = j + 1
        self.blocks_read += 1
        if len(self.delivered_order) < 65536:
            self.delivered_order.append(j)
        return data

    def reseek(self, *, start: int, stop: int, num_chunks: int,
               local_data_size: Optional[int] = None) -> None:
        """Rebind this source to a NEW ``host_chunk_bounds`` row range --
        the elastic-recovery re-shard: after the world shrinks, each
        survivor's slice of the event range changes, and re-seeking the
        live source beats reopening the file (the readers' metadata cache
        and the source handle survive). Supersedes any in-flight prefetch
        generation; the next ``get_block(0)`` starts a fresh pass over the
        new range. Telemetry counters continue to accumulate -- one
        ``ingest_summary`` still describes the whole source lifetime."""
        if self._closed:
            raise RuntimeError("PipelinedBlockSource is closed")
        S = int(local_data_size if local_data_size is not None
                else self.local_data_size)
        if int(num_chunks) % max(S, 1):
            raise ValueError(
                f"num_chunks {num_chunks} not divisible by the local "
                f"data-axis extent {S}; derive slices with "
                "parallel.distributed.host_chunk_bounds")
        with self._lock:
            self._gen += 1          # supersede any in-flight worker
            self._queue = None      # next get_block cold-starts at j
            self._next = 0
            self.start, self.stop = int(start), int(stop)
            self.num_chunks = int(num_chunks)
            self.local_data_size = max(S, 1)
            self.num_blocks = self.num_chunks // self.local_data_size

    def close(self):
        """Stop the worker and emit ``ingest_summary`` once (idempotent)."""
        if self._closed:
            return
        self._closed = True
        with self._lock:
            self._gen += 1
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._emit_summary()

    def __del__(self):
        # Safety net for fits aborted by an exception (preemption,
        # validation raise): without it a worker blocked on a full queue
        # would spin at its put-retry cadence until process exit.
        try:
            self.close()
        except Exception:
            pass

    def emit_start(self, rec, *, em_mode: str = "full") -> None:
        """One ``ingest_start`` record on ``rec`` (no-op when inactive)."""
        if not getattr(rec, "active", False):
            return
        rec.emit(
            "ingest_start",
            source=str(getattr(self.source, "path", "<source>")),
            rows=int(self.stop - self.start),
            queue_depth=int(self.queue_depth),
            row_start=int(self.start), row_stop=int(self.stop),
            blocks=int(self.num_blocks),
            chunk_size=int(self.chunk_size),
            mode=str(em_mode),
        )

    def _emit_summary(self) -> None:
        if self._summary_emitted:
            return
        from ..telemetry import current as current_recorder

        rec = current_recorder()
        if not rec.active:
            return
        self._summary_emitted = True
        rec.emit(
            "ingest_summary",
            blocks_read=int(self.blocks_read),
            peak_resident_blocks=int(self.peak_resident),
            prefetch_wait_s=round(float(self.prefetch_wait_s), 6),
            bytes=int(self.bytes_read),
            queue_depth=int(self.queue_depth),
        )


def streamed_moments(source, start: int, stop: int, chunk_size: int,
                     num_chunks: int, *, validate: bool = True,
                     collective: bool = False, dtype=None):
    """(mean[D], var[D]) float64 + input validation in ONE out-of-core pass.

    Builds the exact per-chunk partials matrix ``global_moments`` builds
    from a resident slice (``moment_part`` per chunk, same chunk grid, same
    reduction), accumulating the non-finite-row scan alongside, then makes
    the single (optionally collective) raise/continue decision
    ``validate_finite`` would have made -- so the pipelined prologue is
    bit-identical to the resident one without ever materializing the slice.
    """
    from ..parallel.distributed import moment_part, reduce_moment_parts
    from ..validation import finite_row_stats, raise_if_nonfinite

    d = int(source.shape[1])
    parts = np.zeros((num_chunks, 1 + 2 * d), np.float64)
    n_bad, first_bad = 0, -1
    m = stop - start
    for j in range(num_chunks):
        a, b = min(j * chunk_size, m), min((j + 1) * chunk_size, m)
        if b <= a:
            continue
        block = np.ascontiguousarray(source.read_range(start + a, start + b))
        if validate:
            nb, fb = finite_row_stats(block, start + a, dtype=dtype)
            if nb:
                n_bad += nb
                if first_bad < 0:
                    first_bad = fb
        parts[j] = moment_part(block)
    if validate:
        raise_if_nonfinite(n_bad, first_bad, collective=collective)
    return reduce_moment_parts(parts)
