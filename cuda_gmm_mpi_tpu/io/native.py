"""ctypes bindings to the native C++ I/O library (native/gmm_io.cpp).

The reference's data path is native C++ (readData.cpp); this module keeps that
property for the TPU build: a small C++ shared library does the hot text
parsing/formatting, loaded via ctypes (no pybind11 in this image). Falls back
gracefully -- callers check ``available()`` and use the NumPy paths otherwise.

The library is built on demand by ``ensure_built()`` using the repo's
``native/Makefile``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libgmm_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def ensure_built(force: bool = False) -> bool:
    """Build libgmm_io.so via make if missing or stale. Returns True on
    success (make itself is a no-op when the .so is up to date)."""
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if os.path.exists(_LIB_PATH) and not force:
        try:
            lib_mtime = os.path.getmtime(_LIB_PATH)
            srcs = [makefile, os.path.join(_NATIVE_DIR, "gmm_io.cpp")]
            if all(os.path.getmtime(s) <= lib_mtime
                   for s in srcs if os.path.exists(s)):
                return True
        except OSError:
            return True  # can't stat sources; use the existing library
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "libgmm_io.so"],
            check=True, capture_output=True, timeout=120,
        )
    except Exception:
        return False
    return os.path.exists(_LIB_PATH)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not ensure_built():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.gmm_read_data.restype = ctypes.c_int
        lib.gmm_read_data.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ]
        lib.gmm_data_shape.restype = ctypes.c_int
        lib.gmm_data_shape.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.gmm_read_range.restype = ctypes.c_int
        lib.gmm_read_range.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ]
        lib.gmm_free.restype = None
        lib.gmm_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.gmm_write_results.restype = ctypes.c_int
        lib.gmm_write_results.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gmm_results_open.restype = ctypes.c_void_p
        lib.gmm_results_open.argtypes = [ctypes.c_char_p]
        lib.gmm_results_append.restype = ctypes.c_int
        lib.gmm_results_append.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gmm_results_close.restype = ctypes.c_int
        lib.gmm_results_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def read_data(path: str) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native gmm_io library unavailable")
    n = ctypes.c_int64()
    d = ctypes.c_int64()
    buf = ctypes.POINTER(ctypes.c_float)()
    rc = lib.gmm_read_data(path.encode(), ctypes.byref(n), ctypes.byref(d),
                           ctypes.byref(buf))
    if rc != 0:
        raise ValueError(f"native reader failed on {path!r} (rc={rc})")
    try:
        arr = np.ctypeslib.as_array(buf, shape=(n.value, d.value)).copy()
    finally:
        lib.gmm_free(buf)
    return arr


def data_shape(path: str):
    """(num_events, num_dims) without loading the payload (BIN: header only;
    CSV: one streaming pass, O(1) memory)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native gmm_io library unavailable")
    n = ctypes.c_int64()
    d = ctypes.c_int64()
    rc = lib.gmm_data_shape(path.encode(), ctypes.byref(n), ctypes.byref(d))
    if rc != 0:
        raise ValueError(f"native shape probe failed on {path!r} (rc={rc})")
    return n.value, d.value


def read_range(path: str, start: int, stop=None) -> np.ndarray:
    """Rows [start, stop) as float32 [rows, D]; peak memory O(slice).
    ``stop=None`` reads to the end of the file in a single pass."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native gmm_io library unavailable")
    n = ctypes.c_int64()
    d = ctypes.c_int64()
    buf = ctypes.POINTER(ctypes.c_float)()
    rc = lib.gmm_read_range(path.encode(), start,
                            -1 if stop is None else stop,
                            ctypes.byref(n), ctypes.byref(d),
                            ctypes.byref(buf))
    if rc != 0:
        raise ValueError(
            f"native range read failed on {path!r}[{start}:{stop}] (rc={rc})"
        )
    try:
        arr = np.ctypeslib.as_array(buf, shape=(n.value, d.value)).copy()
    finally:
        lib.gmm_free(buf)
    return arr


class ResultsWriter:
    """Streaming .results writer: append event blocks, bounded memory.

    Context manager over the native handle API (gmm_results_open/append/
    close); the full N x K posterior matrix never has to exist.
    """

    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native gmm_io library unavailable")
        self._lib = lib
        self._h = lib.gmm_results_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path!r} for writing")
        self._path = path

    def append(self, data: np.ndarray, memberships: np.ndarray) -> None:
        data = np.ascontiguousarray(data, np.float32)
        memberships = np.ascontiguousarray(memberships, np.float32)
        n, d = data.shape
        k = memberships.shape[1]
        if memberships.shape[0] != n:
            raise ValueError("data/membership row mismatch")
        rc = self._lib.gmm_results_append(
            self._h,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            memberships.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, d, k,
        )
        if rc != 0:
            raise IOError(f"native append failed on {self._path!r} (rc={rc})")

    def close(self) -> None:
        if self._h:
            rc = self._lib.gmm_results_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError(f"close failed on {self._path!r} (rc={rc})")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # An exception is already propagating (e.g. append() failed);
            # a failing close() must not mask it.
            try:
                self.close()
            except IOError:
                pass
            return False
        self.close()


def write_results(path: str, data: np.ndarray, memberships: np.ndarray) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native gmm_io library unavailable")
    data = np.ascontiguousarray(data, np.float32)
    memberships = np.ascontiguousarray(memberships, np.float32)
    n, d = data.shape
    k = memberships.shape[1]
    rc = lib.gmm_write_results(
        path.encode(),
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        memberships.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, d, k,
    )
    if rc != 0:
        raise IOError(f"native writer failed on {path!r} (rc={rc})")
