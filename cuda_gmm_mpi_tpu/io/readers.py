"""Input data readers: CSV (with header drop) and BIN.

Python/NumPy implementation of the reference's ``readData.cpp`` semantics, with
an optional native C++ fast path (see ``cuda_gmm_mpi_tpu.io.native``) that this
module transparently prefers when the shared library is available.

Reference semantics reproduced exactly:
- dispatch on filename: names ending in "bin" -> binary, else CSV
  (readData.cpp:25-33 -- the reference compares the last 3 chars)
- BIN layout: int32 num_events, int32 num_dimensions, then
  num_events*num_dimensions float32 row-major (readData.cpp:35-47)
- CSV: comma-delimited; dimension count taken from the first line; the FIRST
  LINE IS DROPPED as a header (readData.cpp:84); blank lines skipped
  (readData.cpp:61); ragged rows -> error (readData.cpp:104-107); fields parsed
  with atof semantics (invalid text parses as 0.0)
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def read_data(path: str, use_native: str = "auto") -> np.ndarray:
    """Read events as a float32 [num_events, num_dimensions] array.

    ``use_native``: 'auto' tries the C++ reader and falls back to Python;
    'always' requires it; 'never' forces the Python path.
    """
    if use_native != "never":
        from . import native

        if native.available():
            return native.read_data(path)
        if use_native == "always":
            raise RuntimeError("native gmm_io library unavailable "
                               "(use_native='always')")
    if path.endswith("bin"):
        return read_bin(path)
    return read_csv(path)


def read_bin(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        header = np.fromfile(f, dtype=np.int32, count=2)
        if header.size != 2:
            raise ValueError(f"{path}: truncated BIN header")
        num_events, num_dims = int(header[0]), int(header[1])
        data = np.fromfile(f, dtype=np.float32, count=num_events * num_dims)
    if data.size != num_events * num_dims:
        raise ValueError(f"{path}: truncated BIN payload")
    return data.reshape(num_events, num_dims)


def _atof(s: str) -> float:
    """C atof semantics: parse a leading float, else 0.0 (readData.cpp:108)."""
    s = s.strip()
    try:
        return float(s)
    except ValueError:
        # atof parses the longest valid prefix; approximate cheaply
        for end in range(len(s), 0, -1):
            try:
                return float(s[:end])
            except ValueError:
                continue
        return 0.0


def read_csv(path: str) -> np.ndarray:
    with open(path, "r") as f:
        lines = [ln for ln in (raw.strip("\r\n") for raw in f) if ln != ""]
    if not lines:
        raise ValueError(f"{path}: empty input file")

    num_dims = len(lines[0].split(","))
    body = lines[1:]  # first line dropped as header (readData.cpp:84)
    num_events = len(body)
    if num_events == 0:
        raise ValueError(f"{path}: no data rows after header")

    # Fast path: try numpy's parser; fall back to atof semantics row-by-row.
    try:
        data = np.genfromtxt(body, delimiter=",", dtype=np.float32)
        data = np.atleast_2d(data)
        if data.shape[1] != num_dims or np.isnan(data).any():
            raise ValueError
    except Exception:
        data = np.empty((num_events, num_dims), np.float32)
        for i, ln in enumerate(body):
            fields = ln.split(",")
            if len(fields) != num_dims:
                raise ValueError(
                    f"{path}: row {i + 2} has {len(fields)} fields, "
                    f"expected {num_dims}"
                )
            data[i] = [_atof(fields[j]) for j in range(num_dims)]
    return data


def write_bin(path: str, data: np.ndarray) -> None:
    """Writer for the BIN format (test fixtures / dataset prep)."""
    data = np.ascontiguousarray(data, dtype=np.float32)
    with open(path, "wb") as f:
        np.asarray([data.shape[0], data.shape[1]], np.int32).tofile(f)
        data.tofile(f)
