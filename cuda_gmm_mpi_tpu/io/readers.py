"""Input data readers: CSV (with header drop) and BIN, with range support.

Python/NumPy implementation of the reference's ``readData.cpp`` semantics, with
an optional native C++ fast path (see ``cuda_gmm_mpi_tpu.io.native``) that this
module transparently prefers when the shared library is available.

Reference semantics reproduced exactly:
- dispatch on filename: names ending in "bin" -> binary, else CSV
  (readData.cpp:25-33 -- the reference compares the last 3 chars)
- BIN layout: int32 num_events, int32 num_dimensions, then
  num_events*num_dimensions float32 row-major (readData.cpp:35-47)
- CSV: comma-delimited; dimension count taken from the first line; the FIRST
  LINE IS DROPPED as a header (readData.cpp:84); blank lines skipped
  (readData.cpp:61); ragged rows -> error (readData.cpp:104-107); fields parsed
  with atof semantics (invalid text parses as 0.0)

Beyond the reference, every reader takes an optional ``[start, stop)`` row
range and streams: peak memory is O(slice), never O(file). This is what makes
the anti-``MPI_Bcast`` design real -- the reference broadcasts the ENTIRE
dataset to every node (gaussian.cu:191-201); here each host of a
multi-controller run reads only its contiguous slice (BIN seeks it directly,
CSV single-pass-scans with a bounded buffer).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

import numpy as np

# --- per-path file metadata cache -------------------------------------------
# Per-block range reads used to re-scan the file prefix on EVERY block of
# every pass: read_bin re-read the 8-byte header, data_shape re-counted the
# whole CSV, and read_csv line-scanned from byte 0 up to ``start`` each call
# -- O(passes x blocks x N) line parsing for the pipelined ingestion loop.
# The cache below memoizes what those scans learn, keyed by
# (abspath, mtime_ns, size) so a rewritten file can never serve stale
# metadata. CSV entries additionally accumulate ``marks``: data-row ->
# byte-offset checkpoints recorded as reads complete, so a sequential
# per-block read seeks straight to its range instead of re-parsing the
# prefix. Entries are tiny (a shape tuple + one int per block boundary).

_META_LOCK = threading.Lock()
_META_CACHE: dict = {}


def _file_meta(path: str) -> dict:
    """The mutable metadata dict for ``path`` at its current (mtime, size).

    Stale entries for the same path (the file was rewritten) are dropped;
    the returned dict is shared across callers and threads -- all mutations
    are single dict-item writes (GIL-atomic)."""
    st = os.stat(path)
    key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
    with _META_LOCK:
        meta = _META_CACHE.get(key)
        if meta is None:
            for k in [k for k in _META_CACHE if k[0] == key[0]]:
                del _META_CACHE[k]
            meta = {}
            _META_CACHE[key] = meta
        return meta


class TruncatedInputError(ValueError):
    """The input file is torn: a BIN header/payload shorter than its own
    declared size (a partial copy, a crashed writer). A ValueError
    subclass so existing parse-error handling still applies, but distinct
    so the CLI can map unreadable/torn input to exit 74 (EX_IOERR) while
    malformed CONTENT keeps the reference's exit 1."""


def read_data(path: str, start: int = 0, stop: Optional[int] = None,
              use_native: str = "auto", screen: str = "off",
              screen_dtype=None) -> np.ndarray:
    """Read events [start, stop) as a float32 [rows, num_dimensions] array.

    Default range is the whole file. ``use_native``: 'auto' tries the C++
    reader and falls back to Python; 'always' requires it; 'never' forces the
    Python path.

    ``screen`` is the ingest-time integrity gate: ``'reject'`` raises
    :class:`~cuda_gmm_mpi_tpu.validation.InvalidInputError` on any NaN/Inf
    row with a per-file, per-row message -- bad cytometry input fails HERE,
    not as an EM health flag 40 iterations later; ``'quarantine'`` (the
    CLI's ``--allow-nonfinite``) counts and DROPS the bad rows with a
    warning; ``'off'`` (default) admits everything, matching the
    reference's atof semantics. ``screen_dtype``: also treat values that
    would overflow this (compute) dtype as non-finite, mirroring
    ``validation.validate_finite``. Applies to BIN, CSV, and native reads
    alike (the screen runs on the parsed rows).
    """
    _check_range(path, start, stop)
    data = None
    if use_native != "never":
        from . import native

        if native.available():
            try:
                if start == 0 and stop is None:
                    data = native.read_data(path)
                else:
                    data = native.read_range(path, start, stop)
            except ValueError:
                if not path.endswith("bin"):
                    raise
                # Re-diagnose BIN failures through the Python reader: a
                # torn header/payload must surface as TruncatedInputError
                # (CLI exit 74, EX_IOERR), not the native reader's generic
                # parse failure; a file the native path wrongly rejected
                # still loads.
                data = None
        elif use_native == "always":
            raise RuntimeError("native gmm_io library unavailable "
                               "(use_native='always')")
    if data is None:
        data = (read_bin(path, start, stop) if path.endswith("bin")
                else read_csv(path, start, stop))
    if screen != "off":
        data, _ = screen_nonfinite(data, path, mode=screen,
                                   dtype=screen_dtype, start=start)
    return data


def screen_nonfinite(data: np.ndarray, path: str, *, mode: str = "reject",
                     dtype=None, start: int = 0):
    """Input-integrity screen: reject or quarantine NaN/Inf event rows.

    Returns ``(data, n_dropped)``. ``mode='reject'`` raises
    ``InvalidInputError`` naming the file and the first offending rows;
    ``mode='quarantine'`` drops them (logged loudly) and returns the clean
    remainder. ``dtype`` additionally treats magnitudes that overflow the
    compute dtype (e.g. 1e39 under float32) as non-finite, so quarantined
    data passes the fit-time validator too. Row numbers are 0-based data
    rows (after the CSV header), offset by ``start`` for range reads.
    """
    if mode not in ("reject", "quarantine"):
        raise ValueError(f"unknown screen mode: {mode!r}")
    finite = np.isfinite(data)
    if dtype is not None and np.dtype(dtype).itemsize < data.dtype.itemsize:
        finite &= np.abs(data) <= np.finfo(dtype).max
    row_ok = finite.all(axis=1)
    bad = np.flatnonzero(~row_ok)
    if bad.size == 0:
        return data, 0
    shown = ", ".join(str(start + int(b)) for b in bad[:5])
    if mode == "reject":
        from ..validation import InvalidInputError

        raise InvalidInputError(
            f"{path}: {bad.size} non-finite event row(s) at ingest "
            f"(data rows {shown}{', ...' if bad.size > 5 else ''}); "
            "NaN/Inf events poison every downstream statistic -- clean "
            "the file, or quarantine with --allow-nonfinite")
    from ..utils.logging_ import get_logger

    get_logger().warning(
        "%s: quarantined %d non-finite event row(s) at ingest (data rows "
        "%s%s) -- they are EXCLUDED from the fit", path, bad.size, shown,
        ", ..." if bad.size > 5 else "")
    return np.ascontiguousarray(data[row_ok]), int(bad.size)


def _check_range(path: str, start: int, stop: Optional[int]) -> None:
    """Uniform sign/order validation so every backend (native C, Python BIN,
    Python CSV) rejects the same inputs -- a negative stop must never reach
    the native layer, where it would be read as the to-end sentinel."""
    if start < 0 or (stop is not None and stop < start):
        raise ValueError(f"{path}: invalid row range [{start}, {stop})")


def data_shape(path: str, use_native: str = "auto") -> Tuple[int, int]:
    """(num_events, num_dimensions) without loading the payload.

    BIN reads the 8-byte header; CSV makes one streaming pass counting
    non-blank lines (minus the header) -- O(1) memory either way. The
    result is cached per (path, mtime, size), so per-block range readers
    probing the shape every block pay the scan once per file, not once
    per block.
    """
    meta = _file_meta(path)
    cached = meta.get("shape")
    if cached is not None:
        return cached
    if use_native != "never":
        from . import native

        if native.available():
            meta["shape"] = native.data_shape(path)
            return meta["shape"]
        if use_native == "always":
            raise RuntimeError("native gmm_io library unavailable "
                               "(use_native='always')")
    if path.endswith("bin"):
        with open(path, "rb") as f:
            header = np.fromfile(f, dtype=np.int32, count=2)
        if header.size != 2:
            raise TruncatedInputError(f"{path}: truncated BIN header")
        if header[0] <= 0 or header[1] <= 0:  # same contract as bin_shape()
            raise ValueError(f"{path}: malformed BIN header {header.tolist()}")
        meta["bin_header"] = (int(header[0]), int(header[1]))
        meta["shape"] = meta["bin_header"]
        return meta["shape"]
    num_dims = None
    count = 0
    for _, line in _iter_csv_lines(path):
        if num_dims is None:
            num_dims = line.count(",") + 1
        count += 1
    if num_dims is None or count < 2:
        raise ValueError(f"{path}: no data rows after header")
    meta["shape"] = (count - 1, num_dims)
    return meta["shape"]


def read_bin(path: str, start: int = 0,
             stop: Optional[int] = None) -> np.ndarray:
    """BIN rows [start, stop): header + one fseek + one bounded fromfile
    (readData.cpp:35-47 layout; trivially seekable, SURVEY.md SS2.4). The
    header dims are cached per (path, mtime, size), so per-block range
    reads skip the header re-read after the first block."""
    _check_range(path, start, stop)
    meta = _file_meta(path)
    with open(path, "rb") as f:
        hdr = meta.get("bin_header")
        if hdr is None:
            header = np.fromfile(f, dtype=np.int32, count=2)
            if header.size != 2:
                raise TruncatedInputError(f"{path}: truncated BIN header")
            hdr = meta["bin_header"] = (int(header[0]), int(header[1]))
        num_events, num_dims = hdr
        if stop is None:
            stop = num_events
        if not (0 <= start <= stop <= num_events):
            raise ValueError(
                f"{path}: range [{start}, {stop}) out of bounds for "
                f"{num_events} events"
            )
        f.seek(8 + start * num_dims * 4)
        rows = stop - start
        data = np.fromfile(f, dtype=np.float32, count=rows * num_dims)
    if data.size != rows * num_dims:
        raise TruncatedInputError(f"{path}: truncated BIN payload")
    return data.reshape(rows, num_dims)


def _atof(s: str) -> float:
    """C atof semantics: parse a leading float, else 0.0 (readData.cpp:108)."""
    s = s.strip()
    try:
        return float(s)
    except ValueError:
        # atof parses the longest valid prefix; approximate cheaply
        for end in range(len(s), 0, -1):
            try:
                return float(s[:end])
            except ValueError:
                continue
        return 0.0


def _iter_csv_lines(path: str):
    """Yield (line_index, stripped_line) for non-blank lines; index 0 is the
    header. Streams the file -- never holds more than one line."""
    idx = 0
    with open(path, "r") as f:
        for raw in f:
            line = raw.strip("\r\n")
            if line == "":
                continue  # blank lines skipped (readData.cpp:61)
            yield idx, line
            idx += 1


def _parse_fields(fields, out_row):
    try:
        for j, s in enumerate(fields):
            out_row[j] = float(s)
    except ValueError:
        for j, s in enumerate(fields):
            out_row[j] = _atof(s)


def read_csv(path: str, start: int = 0,
             stop: Optional[int] = None) -> np.ndarray:
    """CSV rows [start, stop), streaming: one pass, O(slice) peak memory.

    The first non-blank line is dropped as a header (readData.cpp:84) and sets
    the dimension count; ragged rows among those read raise (readData.cpp:
    104-107). With a bounded ``stop`` the scan exits early at the range end.

    Range reads leave row -> byte-offset checkpoints in the per-path
    metadata cache (one per visited range boundary), and later reads seek
    to the closest checkpoint at or before ``start`` instead of re-parsing
    the prefix -- the sequential per-block reads of the pipelined ingestion
    loop each scan exactly their own byte range after the first pass.
    """
    _check_range(path, start, stop)
    meta = _file_meta(path)
    marks = meta.setdefault("csv_marks", {})
    num_dims = meta.get("csv_dims")
    resume_row, resume_off = -1, 0
    if num_dims is not None:
        for r, off in list(marks.items()):
            if resume_row < r <= start:
                resume_row, resume_off = r, off
    data = None
    seen = 0
    total_rows = max(resume_row, 0)
    with open(path, "rb") as f:
        header_done = resume_row >= 0
        row = max(resume_row, 0)
        if header_done:
            f.seek(resume_off)
        while True:
            pos = f.tell()
            raw = f.readline()
            if not raw:
                break
            line = raw.decode("utf-8").strip("\r\n")
            if line == "":
                continue  # blank lines skipped (readData.cpp:61)
            if not header_done:
                num_dims = meta["csv_dims"] = line.count(",") + 1
                header_done = True
                marks.setdefault(0, f.tell())
                continue
            total_rows = row + 1
            if row < start:
                row += 1
                continue
            if stop is not None and row >= stop:
                marks.setdefault(row, pos)
                break
            if row == start:
                marks.setdefault(row, pos)
            fields = line.split(",")
            if len(fields) != num_dims:
                raise ValueError(
                    f"{path}: row {row + 2} has {len(fields)} fields, "
                    f"expected {num_dims}"
                )
            if data is None:
                # Bounded initial allocation: rows arrive from the scan, so
                # an absurd stop errors at EOF instead of OOMing up front.
                grow = min(stop - start, 65536) if stop is not None else 4096
                data = np.empty((max(grow, 1), num_dims), np.float32)
            elif seen == data.shape[0]:  # amortized doubling
                add = data.shape[0]
                if stop is not None:
                    add = min(add, (stop - start) - data.shape[0])
                data = np.concatenate(
                    [data, np.empty((max(add, 1), num_dims), np.float32)]
                )
            _parse_fields(fields, data[seen])
            seen += 1
            row += 1
    if num_dims is None:
        raise ValueError(f"{path}: empty input file")
    want = None if stop is None else stop - start
    if seen == 0 and start == 0 and want is None:
        raise ValueError(f"{path}: no data rows after header")
    if want is not None and seen != want:
        raise ValueError(
            f"{path}: range [{start}, {stop}) out of bounds "
            f"({seen} rows available in range)"
        )
    if want is None and start > total_rows:
        # Same contract as the BIN/native paths: a start past EOF is an
        # error, not an empty shard (it would hide a sharding bug upstream).
        raise ValueError(
            f"{path}: range start {start} out of bounds for {total_rows} rows"
        )
    if data is None:
        return np.zeros((0, num_dims), np.float32)
    return data[:seen]


def read_rows(path: str, indices, use_native: str = "auto") -> np.ndarray:
    """Gather specific rows by index (order preserved, duplicates allowed).

    The seeding primitive for per-host loading: evenly-spaced seed rows
    (gaussian.cu:110-121) can be fetched without reading the dataset. BIN
    seeks each unique row; CSV makes one streaming pass collecting the wanted
    rows -- O(len(indices)) memory either way. The gather itself always runs
    in Python (it is seek-bound, not parse-bound); ``use_native='always'``
    still asserts the native library is present for deployment consistency.
    """
    if use_native == "always":
        from . import native

        if not native.available():
            raise RuntimeError("native gmm_io library unavailable "
                               "(use_native='always')")
    indices = np.asarray(indices, np.int64)
    if indices.size == 0:
        n, d = data_shape(path, use_native=use_native)
        return np.zeros((0, d), np.float32)
    uniq = np.unique(indices)
    if path.endswith("bin"):
        with open(path, "rb") as f:
            header = np.fromfile(f, dtype=np.int32, count=2)
            if header.size != 2:
                raise TruncatedInputError(f"{path}: truncated BIN header")
            num_events, num_dims = int(header[0]), int(header[1])
            if uniq[0] < 0 or uniq[-1] >= num_events:
                raise ValueError(f"{path}: row index out of bounds")
            rows = {}
            for i in uniq:
                f.seek(8 + int(i) * num_dims * 4)
                r = np.fromfile(f, dtype=np.float32, count=num_dims)
                if r.size != num_dims:
                    raise TruncatedInputError(f"{path}: truncated BIN payload")
                rows[int(i)] = r
    else:
        want = set(int(i) for i in uniq)
        rows = {}
        num_dims = None
        for idx, line in _iter_csv_lines(path):
            if idx == 0:
                num_dims = line.count(",") + 1
                continue
            row = idx - 1
            if row not in want:
                continue
            fields = line.split(",")
            if len(fields) != num_dims:
                raise ValueError(
                    f"{path}: row {idx + 1} has {len(fields)} fields, "
                    f"expected {num_dims}"
                )
            out = np.empty((num_dims,), np.float32)
            _parse_fields(fields, out)
            rows[row] = out
            if len(rows) == len(want):
                break
        if len(rows) != len(want):
            raise ValueError(f"{path}: row index out of bounds")
    return np.stack([rows[int(i)] for i in indices])


class FileSource:
    """A dataset file as a random-access row source.

    The loading interface consumed by the multi-host fit path: ``shape`` probes
    cheaply, ``read_range``/``read_rows`` pull only what the caller needs, so a
    host's resident footprint is its slice -- the turnkey replacement for the
    ``read_my_rows`` recipe in docs/DISTRIBUTED.md.
    """

    def __init__(self, path: str, use_native: str = "auto"):
        self.path = path
        self.use_native = use_native
        self._shape: Optional[Tuple[int, int]] = None

    @property
    def shape(self) -> Tuple[int, int]:
        if self._shape is None:
            self._shape = data_shape(self.path, use_native=self.use_native)
        return self._shape

    def read_range(self, start: int, stop: int) -> np.ndarray:
        return read_data(self.path, start, stop, use_native=self.use_native)

    def read_rows(self, indices) -> np.ndarray:
        return read_rows(self.path, indices, use_native=self.use_native)

    def read_all(self) -> np.ndarray:
        return read_data(self.path, use_native=self.use_native)

    def __getitem__(self, key) -> np.ndarray:
        # Contiguous row slices only: lets array-shaped consumers
        # (iter_memberships' block loop) walk a file source without
        # materializing it -- each slice is one bounded range read.
        if isinstance(key, slice) and key.step in (None, 1):
            start, stop, _ = key.indices(self.shape[0])
            return self.read_range(start, stop)
        raise TypeError("FileSource supports contiguous row slices only")


def write_bin(path: str, data: np.ndarray) -> None:
    """Writer for the BIN format (test fixtures / dataset prep)."""
    data = np.ascontiguousarray(data, dtype=np.float32)
    with open(path, "wb") as f:
        np.asarray([data.shape[0], data.shape[1]], np.int32).tofile(f)
        data.tofile(f)


def read_summary(path: str) -> dict:
    """Parse a ``.summary`` model file back into arrays.

    Inverse of ``writers.write_summary`` and format-compatible with the
    reference's own output (writeCluster, gaussian.cu:1180-1197) -- the
    reference never reads these back; this reader makes the format a
    round-trippable model interchange (``GaussianMixture.from_summary``).
    Means and R carry the format's 3-decimal precision; Probability/N carry
    printf %f's 6 decimals.

    Returns ``{"pi": [K], "N": [K], "means": [K, D], "R": [K, D, D]}``.
    """
    pis, ns, means, Rs = [], [], [], []
    cur_R = None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("Cluster #"):
                cur_R = None
            elif line.startswith("Probability: "):
                pis.append(float(line.split(": ", 1)[1]))
            elif line.startswith("N: "):
                ns.append(float(line.split(": ", 1)[1]))
            elif line.startswith("Means: "):
                means.append([float(v) for v in line.split()[1:]])
            elif line.startswith("R Matrix:"):
                cur_R = []
                Rs.append(cur_R)
            elif cur_R is not None and line.strip():
                cur_R.append([float(v) for v in line.split()])
    if not pis or not (len(pis) == len(ns) == len(means) == len(Rs)):
        raise ValueError(f"{path}: not a well-formed .summary file")
    d = len(means[0])
    R = np.asarray(Rs, np.float64)
    if R.shape != (len(pis), d, d):
        raise ValueError(
            f"{path}: R blocks have shape {R.shape}, expected "
            f"({len(pis)}, {d}, {d})")
    return {
        "pi": np.asarray(pis, np.float64),
        "N": np.asarray(ns, np.float64),
        "means": np.asarray(means, np.float64),
        "R": R,
    }
