"""Out-of-core pipelined ingestion + stepwise minibatch EM (round 13).

Contracts under test (io/pipeline.py, models/streaming.py minibatch
driver, the CLI --ingest/--em-mode surface):

  * pipelined ingestion is a TRANSPORT change, not a math change: fits
    are bit-identical to the host-resident path, single-device and data
    mesh, full and diag covariance;
  * the bounded queue really bounds residency (read_slow backpressure
    moves the prefetch wait, never the data), and delivery order is
    deterministic by construction;
  * stepwise minibatch EM converges within the health-check tolerance of
    full EM while touching one minibatch per step;
  * preemption mid-pass (pipelined) and mid-step (minibatch) checkpoints
    the carry state, exits 75 at the CLI, and --resume auto reproduces
    the uninterrupted run byte-for-byte;
  * peak host RSS stays O(queue_depth x block) for a fit whose dataset
    never fits the budgeted host slice (slow test).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, supervisor
from cuda_gmm_mpi_tpu.io import FileSource, PipelinedBlockSource, write_bin
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.supervisor import PreemptedError, RunSupervisor
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import communicate_or_kill, worker_env

CLI = [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli"]


def _blob_file(tmp_path, rng, n=2048, d=3, k=3, name="events.bin"):
    centers = rng.normal(scale=9.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float32)
    path = str(tmp_path / name)
    write_bin(path, data)
    return path


def _substeps(ck):
    d = os.path.join(ck, "sweep")
    if not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d)
                  if ".iter" in f and f.endswith(".npz"))


def _sup():
    return RunSupervisor(install_signals=False)


# ---------------------------------------------------------------------------
# bit-identity: pipelined == resident
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh,diag", [
    (None, False), ((8, 1), False), (None, True), ((8, 1), True),
])
def test_pipelined_bit_identical_to_resident(tmp_path, rng, mesh, diag):
    """The tentpole contract: ingest='pipelined' changes WHERE blocks come
    from (per-block byte ranges off disk vs a resident host slice), not a
    single bit of the fit -- across the K sweep, on a data mesh, and for
    both covariance families."""
    path = _blob_file(tmp_path, rng)
    kw = dict(min_iters=4, max_iters=4, chunk_size=256, dtype="float64",
              stream_events=True, diag_only=diag,
              mesh_shape=mesh, seed=7)
    r_res = fit_gmm(FileSource(path), 4, 2, config=GMMConfig(**kw))
    r_pipe = fit_gmm(FileSource(path), 4, 2,
                     config=GMMConfig(ingest="pipelined", **kw))
    assert r_pipe.ideal_num_clusters == r_res.ideal_num_clusters
    assert r_pipe.final_loglik == r_res.final_loglik
    assert r_pipe.min_rissanen == r_res.min_rissanen
    np.testing.assert_array_equal(np.asarray(r_pipe.means),
                                  np.asarray(r_res.means))
    np.testing.assert_array_equal(np.asarray(r_pipe.covariances),
                                  np.asarray(r_res.covariances))
    for (k1, ll1, *_), (k2, ll2, *_) in zip(r_pipe.sweep_log,
                                            r_res.sweep_log):
        assert k1 == k2 and ll1 == ll2


def test_pipelined_csv_bit_identical(tmp_path, rng):
    """CSV sources pipeline too: the byte-range reader serves the same
    decoded rows either way, so the fits agree exactly."""
    centers = rng.normal(scale=9.0, size=(3, 4))
    x = (centers[rng.integers(0, 3, 1500)]
         + rng.normal(size=(1500, 4))).astype(np.float32)
    csv = tmp_path / "ev.csv"
    csv.write_text("a,b,c,d\n" + "\n".join(
        ",".join(f"{v:.6f}" for v in r) for r in x))
    kw = dict(min_iters=4, max_iters=4, chunk_size=128, dtype="float64",
              stream_events=True, seed=5)
    r_res = fit_gmm(FileSource(str(csv)), 3, 3, config=GMMConfig(**kw))
    r_pipe = fit_gmm(FileSource(str(csv)), 3, 3,
                     config=GMMConfig(ingest="pipelined", **kw))
    assert r_pipe.final_loglik == r_res.final_loglik
    np.testing.assert_array_equal(np.asarray(r_pipe.means),
                                  np.asarray(r_res.means))


# ---------------------------------------------------------------------------
# guards
# ---------------------------------------------------------------------------


def test_ingest_config_guards(blobs):
    data, _ = blobs
    with pytest.raises(ValueError, match="unknown ingest"):
        GMMConfig(ingest="mmap")
    with pytest.raises(ValueError, match="streaming block loop"):
        GMMConfig(ingest="pipelined")  # needs stream_events
    with pytest.raises(ValueError, match="ingest_queue_depth"):
        GMMConfig(stream_events=True, ingest="pipelined",
                  ingest_queue_depth=0)
    with pytest.raises(ValueError, match="unknown em_mode"):
        GMMConfig(em_mode="sgd")
    with pytest.raises(ValueError, match="stepwise driver"):
        GMMConfig(em_mode="minibatch")  # needs stream_events
    with pytest.raises(ValueError, match="minibatch_alpha"):
        GMMConfig(stream_events=True, em_mode="minibatch",
                  minibatch_alpha=0.5)
    with pytest.raises(ValueError, match="minibatch_t0"):
        GMMConfig(stream_events=True, em_mode="minibatch",
                  minibatch_t0=-1.0)
    with pytest.raises(ValueError, match="minibatch_size"):
        GMMConfig(stream_events=True, em_mode="minibatch",
                  minibatch_size=-5)
    # pipelined ingestion needs a file source: an in-memory array is
    # already resident, so the config is a contradiction.
    cfg = GMMConfig(stream_events=True, ingest="pipelined",
                    min_iters=2, max_iters=2, chunk_size=128)
    with pytest.raises(ValueError, match="FileSource"):
        fit_gmm(data, 3, 3, config=cfg)


# ---------------------------------------------------------------------------
# bounded queue: backpressure, determinism, telemetry
# ---------------------------------------------------------------------------


def test_backpressure_read_slow(tmp_path, rng):
    """A slow disk (read_slow injection on one block) shifts the prefetch
    wait, bounds residency at queue_depth, and changes NOTHING about the
    delivered data or its order."""
    n, d, chunk = 2048, 3, 256
    path = _blob_file(tmp_path, rng, n=n, d=d)
    src = FileSource(path)
    expect = [src.read_range(j * chunk, (j + 1) * chunk).astype(np.float64)
              for j in range(n // chunk)]

    with faults.use({"read_slow": {"ms": 40, "block": 1, "times": 2}}) \
            as plan:
        pbs = PipelinedBlockSource(src, start=0, stop=n, chunk_size=chunk,
                                   num_chunks=n // chunk, queue_depth=2)
        try:
            for _pass in range(2):
                for j in range(pbs.num_blocks):
                    x, w = pbs.get_block(j)
                    np.testing.assert_array_equal(x, expect[j])
                    np.testing.assert_array_equal(w, np.ones(chunk))
        finally:
            pbs.close()
    assert plan.fired["read_slow"] == 2
    assert pbs.prefetch_wait_s > 0.0  # the consumer DID wait on block 1
    assert 1 <= pbs.peak_resident <= 2  # the queue bound held
    assert pbs.delivered_order == list(range(pbs.num_blocks)) * 2
    assert pbs.blocks_read == 2 * pbs.num_blocks


def test_prefetch_order_deterministic_and_seek(tmp_path, rng):
    """One worker reads ascending, one consumer pops ascending: delivery
    order is the block sequence itself, and an out-of-order request (a
    mid-pass resume seek) restarts the prefetcher at the requested
    block."""
    n, chunk = 1024, 128
    path = _blob_file(tmp_path, rng, n=n)
    src = FileSource(path)
    pbs = PipelinedBlockSource(src, start=0, stop=n, chunk_size=chunk,
                               num_chunks=n // chunk, queue_depth=3)
    try:
        for j in range(pbs.num_blocks):
            pbs.get_block(j)
        # mid-pass seek: resume replays from block 5
        expect = src.read_range(5 * chunk, 6 * chunk).astype(np.float64)
        x, _ = pbs.get_block(5)
        np.testing.assert_array_equal(x, expect)
        for j in range(6, pbs.num_blocks):
            pbs.get_block(j)
        with pytest.raises(IndexError):
            pbs.get_block(pbs.num_blocks)
    finally:
        pbs.close()
    assert pbs.delivered_order == (list(range(pbs.num_blocks))
                                   + list(range(5, pbs.num_blocks)))
    with pytest.raises(RuntimeError, match="closed"):
        pbs.get_block(0)


def test_pipelined_telemetry_stream(tmp_path, rng):
    """A pipelined fit's metrics stream validates against the schema and
    carries the round-13 ingestion story: one ingest_start, one
    ingest_summary whose peak residency respects the queue bound, and
    chunk_flush records split into prefetch_wait_s / compute_s."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
    from cuda_gmm_mpi_tpu.telemetry.report import render_report

    path = _blob_file(tmp_path, rng)
    mf = tmp_path / "m.jsonl"
    cfg = GMMConfig(min_iters=3, max_iters=3, chunk_size=256,
                    dtype="float64", stream_events=True, ingest="pipelined",
                    ingest_queue_depth=2, metrics_file=str(mf), seed=7)
    fit_gmm(FileSource(path), 3, 3, config=cfg)

    records = read_stream(str(mf))
    assert validate_stream(records) == []
    starts = [r for r in records if r["event"] == "ingest_start"]
    summaries = [r for r in records if r["event"] == "ingest_summary"]
    assert len(starts) == 1 and len(summaries) == 1
    assert starts[0]["mode"] == "full"
    assert starts[0]["rows"] == 2048
    assert starts[0]["queue_depth"] == 2
    s = summaries[0]
    assert 1 <= s["peak_resident_blocks"] <= 2
    assert s["blocks_read"] >= starts[0]["blocks"]  # >= one full pass
    assert s["bytes"] > 0 and s["prefetch_wait_s"] >= 0.0
    flushes = [r for r in records if r["event"] == "chunk_flush"]
    assert flushes
    for r in flushes:
        assert r["prefetch_wait_s"] >= 0.0 and r["compute_s"] >= 0.0
    rep = render_report(records)
    assert "ingest:" in rep and "ingest summary:" in rep
    assert "prefetch wait" in rep


# ---------------------------------------------------------------------------
# stepwise minibatch EM
# ---------------------------------------------------------------------------


def test_minibatch_within_health_tolerance_of_full(tmp_path, rng):
    """The acceptance bound: a gamma-sum-matched stepwise run lands within
    health_regression_scale x convergence_epsilon of full EM's loglik --
    the same tolerance the health layer treats as 'no regression' -- while
    each step touches one minibatch instead of the full pass."""
    n, d, k = 4096, 3, 4
    path = _blob_file(tmp_path, rng, n=n, d=d, k=k)
    kw = dict(chunk_size=256, dtype="float64", stream_events=True, seed=3)
    full = fit_gmm(FileSource(path), k, k,
                   config=GMMConfig(min_iters=12, max_iters=12, **kw))
    mb = fit_gmm(FileSource(path), k, k,
                 config=GMMConfig(min_iters=340, max_iters=340,
                                  em_mode="minibatch", minibatch_size=1024,
                                  ingest="pipelined", **kw))
    tol = 10.0 * convergence_epsilon(n, d)  # health_regression_scale x eps
    assert abs(mb.final_loglik - full.final_loglik) <= tol


def test_minibatch_resident_matches_pipelined(tmp_path, rng):
    """em_mode='minibatch' composes with BOTH ingestion modes and the step
    sequence is deterministic, so resident and pipelined stepwise fits are
    bit-identical to each other."""
    path = _blob_file(tmp_path, rng)
    kw = dict(min_iters=20, max_iters=20, chunk_size=256, dtype="float64",
              stream_events=True, em_mode="minibatch", minibatch_size=512,
              seed=9)
    r_res = fit_gmm(FileSource(path), 3, 3, config=GMMConfig(**kw))
    r_pipe = fit_gmm(FileSource(path), 3, 3,
                     config=GMMConfig(ingest="pipelined", **kw))
    assert r_pipe.final_loglik == r_res.final_loglik
    np.testing.assert_array_equal(np.asarray(r_pipe.means),
                                  np.asarray(r_res.means))


# ---------------------------------------------------------------------------
# preemption + resume (in-process, deterministic injection)
# ---------------------------------------------------------------------------


def test_injected_preempt_pipelined_mid_pass_resume(tmp_path, rng):
    """Mid-pass preemption under pipelined ingestion: the sub-step saves
    the partial stream accumulator, and the resumed run -- which seeks the
    prefetcher to the first unprocessed block -- is bit-identical to the
    uninterrupted fit."""
    path = _blob_file(tmp_path, rng, n=3072)
    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")
    kw = dict(min_iters=5, max_iters=5, chunk_size=256, dtype="float64",
              stream_events=True, ingest="pipelined",
              preempt_poll_iters=2, seed=7)

    with supervisor.use(_sup()):
        ref = fit_gmm(FileSource(path), 4, 4,
                      config=GMMConfig(checkpoint_dir=ck_ref, **kw))

    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 2, "block": 3}}):
            with supervisor.use(_sup()):
                fit_gmm(FileSource(path), 4, 4,
                        config=GMMConfig(checkpoint_dir=ck, **kw))
    assert ei.value.checkpointed
    subs = _substeps(ck)
    assert len(subs) == 1
    with np.load(os.path.join(ck, "sweep", subs[0])) as z:
        assert {"stream_pass", "stream_block", "stream_acc.Nk"} <= \
            set(z.files)
        assert int(z["stream_pass"]) == 2 and int(z["stream_block"]) == 4

    with supervisor.use(_sup()):
        res = fit_gmm(FileSource(path), 4, 4,
                      config=GMMConfig(checkpoint_dir=ck, **kw))
    assert res.final_loglik == ref.final_loglik
    assert res.min_rissanen == ref.min_rissanen
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))


def test_injected_preempt_minibatch_resume(tmp_path, rng):
    """Mid-run preemption under stepwise EM: the sub-step saves the decay
    state (mb_step / mb_cursor / mb_acc), and the resumed run replays the
    exact remaining step sequence -- bit-identical final model."""
    path = _blob_file(tmp_path, rng, n=3072)
    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")
    kw = dict(min_iters=10, max_iters=10, chunk_size=256, dtype="float64",
              stream_events=True, ingest="pipelined", em_mode="minibatch",
              minibatch_size=512, preempt_poll_iters=2, seed=7)

    with supervisor.use(_sup()):
        ref = fit_gmm(FileSource(path), 4, 4,
                      config=GMMConfig(checkpoint_dir=ck_ref, **kw))

    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 3}}) as plan:
            with supervisor.use(_sup()):
                fit_gmm(FileSource(path), 4, 4,
                        config=GMMConfig(checkpoint_dir=ck, **kw))
    assert plan.fired["preempt"] == 1
    assert ei.value.checkpointed
    subs = _substeps(ck)
    assert len(subs) == 1
    with np.load(os.path.join(ck, "sweep", subs[0])) as z:
        keys = set(z.files)
        assert {"mb_step", "mb_cursor", "mb_acc.Nk", "mb_acc.M1",
                "mb_acc.M2"} <= keys
        assert int(z["mb_step"]) == 3

    with supervisor.use(_sup()):
        res = fit_gmm(FileSource(path), 4, 4,
                      config=GMMConfig(checkpoint_dir=ck, **kw))
    assert res.final_loglik == ref.final_loglik
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))
    assert _substeps(ck) == []  # consumed + pruned


# ---------------------------------------------------------------------------
# CLI: exit 75 + byte-identical resume
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode,spec,extra", [
    ("pipelined", {"preempt": {"iter": 2, "block": 2}}, []),
    ("minibatch", {"preempt": {"iter": 3}},
     ["--em-mode=minibatch", "--minibatch-size=1024"]),
])
def test_cli_preempt_exit75_then_byte_identical_resume(
        tmp_path, rng, mode, spec, extra):
    """The CLI acceptance path, deterministic via GMM_FAULTS: an injected
    preemption (the SIGTERM stand-in) mid-fit exits 75 with a durable
    sub-step; rerunning the same command resumes and produces output
    files byte-identical to an uninterrupted run's."""
    infile = _blob_file(tmp_path, rng, n=3000, d=3, k=4)
    ck = str(tmp_path / "ck")

    def args(out, ckdir):
        return ["4", infile, str(out), "4", "--device=cpu",
                "--dtype=float64", "--min-iters=6", "--max-iters=6",
                "--sweep-k-buckets=off", "--preempt-poll-iters=2",
                "--chunk-size=256", "--stream-events", "--ingest=pipelined",
                f"--checkpoint-dir={ckdir}", *extra]

    def run(out, ckdir, fault_spec=None):
        env = worker_env()
        if fault_spec is not None:
            env["GMM_FAULTS"] = json.dumps(fault_spec)
        p = subprocess.Popen(CLI + args(out, ckdir),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, env=env, text=True)
        out_, err_ = communicate_or_kill(p, timeout=600)
        return p.returncode, out_, err_

    rc, o, e = run(tmp_path / "int", ck, fault_spec=spec)
    assert rc == 75, f"expected EX_TEMPFAIL:\n{o}\n{e[-3000:]}"
    assert "Preempted" in e
    assert len(_substeps(ck)) == 1

    rc2, o2, e2 = run(tmp_path / "resumed", ck)
    assert rc2 == 0, f"resume failed:\n{o2}\n{e2[-3000:]}"
    assert _substeps(ck) == []

    rc3, o3, e3 = run(tmp_path / "ref", str(tmp_path / "ck_ref"))
    assert rc3 == 0, f"reference failed:\n{o3}\n{e3[-3000:]}"

    assert (tmp_path / "resumed.summary").read_bytes() == \
        (tmp_path / "ref.summary").read_bytes()
    assert (tmp_path / "resumed.results").read_bytes() == \
        (tmp_path / "ref.results").read_bytes()


# ---------------------------------------------------------------------------
# peak host RSS is bounded by the queue, not the file
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_pipelined_rss_bounded_by_queue(sized_tmp_path):
    """Fit a 128 MB on-disk dataset both ways, each in its own process
    (ru_maxrss is a process-lifetime high-water mark): the resident fit's
    RSS growth carries a materialized copy of the data, the pipelined one
    must not -- its residency is O(queue_depth x block), independent of
    the file size. An absolute bound would measure the XLA CPU runtime's
    ~160 MB of fit-time allocations, which both modes pay identically, so
    the contract is the A/B difference."""
    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, resource, sys
from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.io import FileSource
from cuda_gmm_mpi_tpu.models import fit_gmm

path, mode = sys.argv[1], sys.argv[2]
jax.devices()
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=4096,
                stream_events=True, ingest=mode)
r = fit_gmm(FileSource(path), 2, 2, config=cfg)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print("GROWTH_KB", int(peak - base), "LL", float(r.final_loglik))
"""
    path = str(sized_tmp_path / "big.bin")
    n, d, step = 4_000_000, 8, 1 << 16
    rng = np.random.default_rng(0)
    # Written in bounded slices so the WRITER (this pytest process) never
    # holds the dataset either.
    with open(path, "wb") as f:
        np.asarray([n, d], np.int32).tofile(f)
        for lo in range(0, n, step):
            m = min(step, n - lo)
            f.write(rng.normal(size=(m, d)).astype(np.float32).tobytes())
    data_mb = n * d * 4 / 1024 / 1024  # 128 MB on disk

    growth, ll = {}, {}
    for mode in ("resident", "pipelined"):
        r = subprocess.run([sys.executable, "-c", code, path, mode],
                           capture_output=True, text=True, env=worker_env(),
                           timeout=600)
        assert r.returncode == 0, f"{mode}:\n{r.stdout}\n{r.stderr[-3000:]}"
        parts = r.stdout.split()
        growth[mode] = int(parts[parts.index("GROWTH_KB") + 1]) / 1024.0
        ll[mode] = float(parts[parts.index("LL") + 1])
    assert ll["pipelined"] == ll["resident"]  # same fit, bit for bit
    # The resident fit held at least one full copy of the data ...
    assert growth["resident"] >= data_mb, growth
    # ... the pipelined fit held none of it (only the shared runtime
    # allocations plus O(queue x block) buffers).
    assert growth["pipelined"] <= 0.6 * growth["resident"], growth
    assert growth["pipelined"] <= growth["resident"] - 0.7 * data_mb, growth
