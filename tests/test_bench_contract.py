"""Lock bench.py's driver contract.

The build driver's only interface to this repo's performance story is
``python bench.py``: ONE JSON line on stdout (metric/value/unit/
vs_baseline, BENCH_r{N}.json is recorded verbatim from it) plus an exit
code — 0 measured on the intended platform, 2 bad usage, 3 no
accelerator (``accelerator_unavailable`` set so a dead tunnel can never
masquerade as a perf regression, the round-3 lesson where a CPU fallback
was recorded as 0.9x baseline). These tests pin that contract from the
outside, as a subprocess, exactly the way the driver calls it.

No test here touches the TPU tunnel: the fast-fail test kills the probe
subprocess in ~10 ms (before the child can even start importing jax),
and the measured runs force GMM_BENCH_CPU=1.
"""

import json
import os
import subprocess
import sys

import pytest

from .conftest import worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, args=(), timeout=600):
    # worker_env scrubs the harness's 8-device forcing and pins CPU for
    # subprocesses; bench.py owns its platform selection beyond that.
    env = worker_env()
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, BENCH, *args],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


def _json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got {stdout!r}"
    return json.loads(lines[0])


def test_require_accel_fast_fails_with_unavailable_artifact():
    """GMM_BENCH_REQUIRE_ACCEL=1 + failed probe => immediate rc 3 and an
    artifact that cannot be mistaken for a measurement (no CPU fallback
    measurement is run — for unattended accelerator sessions)."""
    r = _run({
        "GMM_BENCH_REQUIRE_ACCEL": "1",
        "GMM_BENCH_PROBE_ATTEMPTS": "1",
        "GMM_BENCH_PROBE_TIMEOUT_S": "0.01",  # killed before jax imports
    }, timeout=120)
    assert r.returncode == 3, r.stderr
    j = _json_line(r.stdout)
    assert j["accelerator_unavailable"] is True
    assert j["value"] == 0.0 and j["vs_baseline"] == 0.0
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in j


def test_deliberate_cpu_wins_over_require_accel():
    """GMM_BENCH_CPU=1 skips the probe entirely, so REQUIRE_ACCEL (meant
    for unattended accelerator sessions, exported by hw_session.sh) must
    not turn a deliberate CPU run into an rc-3 abort -- e.g. when a
    harness inherits both knobs from a measurement session's environment."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_REQUIRE_ACCEL": "1",
        "GMM_BENCH_PROBE_ATTEMPTS": "1",
        "GMM_BENCH_PROBE_TIMEOUT_S": "0.01",
    }, ["--config=1"], timeout=300)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["value"] > 0
    assert j["accelerator_unavailable"] is False


def test_unknown_config_is_usage_error():
    r = _run({"GMM_BENCH_CPU": "1"}, ["--config=nope"], timeout=120)
    assert r.returncode == 2
    assert "unknown --config" in r.stderr


def test_bad_env_knobs_are_usage_errors():
    """Nonpositive GMM_BENCH_MAX_N / GMM_BENCH_CHUNK must fail loudly with
    rc 2 (not crash deep in setup with an opaque shape error)."""
    r = _run({"GMM_BENCH_CPU": "1", "GMM_BENCH_MAX_N": "0"}, timeout=300)
    assert r.returncode == 2
    assert "GMM_BENCH_MAX_N" in r.stderr
    r = _run({"GMM_BENCH_CPU": "1", "GMM_BENCH_CHUNK": "-3"}, timeout=300)
    assert r.returncode == 2
    assert "GMM_BENCH_CHUNK" in r.stderr


@pytest.mark.slow
def test_deliberate_cpu_run_measures_with_rc0():
    """GMM_BENCH_CPU=1 is the deliberate-CPU contract: rc 0, a real
    measurement, and accelerator_unavailable explicitly false."""
    r = _run({"GMM_BENCH_CPU": "1"}, ["--config=1"])
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "iters/sec"
    assert j["value"] > 0 and j["vs_baseline"] > 0
    assert j["accelerator_unavailable"] is False
    assert "cpu" in j["metric"]
