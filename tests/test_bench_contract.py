"""Lock bench.py's driver contract.

The build driver's only interface to this repo's performance story is
``python bench.py``: ONE JSON line on stdout (metric/value/unit/
vs_baseline, BENCH_r{N}.json is recorded verbatim from it) plus an exit
code — 0 measured on the intended platform, 2 bad usage, 3 no
accelerator (``accelerator_unavailable`` set so a dead tunnel can never
masquerade as a perf regression, the round-3 lesson where a CPU fallback
was recorded as 0.9x baseline). These tests pin that contract from the
outside, as a subprocess, exactly the way the driver calls it.

No test here touches the TPU tunnel: the fast-fail test kills the probe
subprocess in ~10 ms (before the child can even start importing jax),
and the measured runs force GMM_BENCH_CPU=1. One exception to the
subprocess framing: the baseline-parity test loads bench.py in-process
(importlib; no top-level side effects) to certify its NumPy iterations
against the framework's under conftest's CPU/x64 setup.
"""

import json
import os
import subprocess
import sys

import pytest

from .conftest import worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, args=(), timeout=600):
    # worker_env scrubs the harness's 8-device forcing and pins CPU for
    # subprocesses; bench.py owns its platform selection beyond that.
    env = worker_env()
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, BENCH, *args],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


def _json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got {stdout!r}"
    return json.loads(lines[0])


def test_require_accel_fast_fails_with_unavailable_artifact():
    """GMM_BENCH_REQUIRE_ACCEL=1 + failed probe => immediate rc 3 and an
    artifact that cannot be mistaken for a measurement (no CPU fallback
    measurement is run — for unattended accelerator sessions)."""
    r = _run({
        "GMM_BENCH_REQUIRE_ACCEL": "1",
        "GMM_BENCH_PROBE_ATTEMPTS": "1",
        "GMM_BENCH_PROBE_TIMEOUT_S": "0.01",  # killed before jax imports
    }, timeout=120)
    assert r.returncode == 3, r.stderr
    j = _json_line(r.stdout)
    assert j["accelerator_unavailable"] is True
    assert j["value"] == 0.0 and j["vs_baseline"] == 0.0
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in j


def test_deliberate_cpu_wins_over_require_accel():
    """GMM_BENCH_CPU=1 skips the probe entirely, so REQUIRE_ACCEL (meant
    for unattended accelerator sessions, exported by hw_session.sh) must
    not turn a deliberate CPU run into an rc-3 abort -- e.g. when a
    harness inherits both knobs from a measurement session's environment."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_REQUIRE_ACCEL": "1",
        "GMM_BENCH_PROBE_ATTEMPTS": "1",
        "GMM_BENCH_PROBE_TIMEOUT_S": "0.01",
    }, ["--config=1"], timeout=300)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["value"] > 0
    assert j["accelerator_unavailable"] is False


def test_unknown_config_is_usage_error():
    r = _run({"GMM_BENCH_CPU": "1"}, ["--config=nope"], timeout=120)
    assert r.returncode == 2
    assert "unknown --config" in r.stderr


def test_bad_env_knobs_are_usage_errors():
    """Nonpositive GMM_BENCH_MAX_N / GMM_BENCH_CHUNK must fail loudly with
    rc 2 (not crash deep in setup with an opaque shape error)."""
    r = _run({"GMM_BENCH_CPU": "1", "GMM_BENCH_MAX_N": "0"}, timeout=300)
    assert r.returncode == 2
    assert "GMM_BENCH_MAX_N" in r.stderr
    r = _run({"GMM_BENCH_CPU": "1", "GMM_BENCH_CHUNK": "-3"}, timeout=300)
    assert r.returncode == 2
    assert "GMM_BENCH_CHUNK" in r.stderr


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_session_band_matches_perf_doc():
    """bench.SESSION_BAND_MS_PER_ITER and docs/PERF.md's documented
    session-variance band are maintained by hand in two places ("update
    BOTH together" -- bench.py:50); this drift test makes forgetting one
    side a test failure instead of a silently self-contradicting artifact."""
    import re

    text = open(os.path.join(REPO, "docs", "PERF.md"),
                encoding="utf-8").read()
    m = re.search(
        r"session_band_ms_per_iter:\s*\[\s*([0-9.]+)\s*,\s*([0-9.]+)\s*\]",
        text)
    assert m, "docs/PERF.md no longer documents session_band_ms_per_iter"
    doc_band = [float(m.group(1)), float(m.group(2))]
    assert doc_band == _load_bench().SESSION_BAND_MS_PER_ITER


@pytest.mark.parametrize("diag", [False, True])
def test_numpy_baseline_matches_framework_iteration(diag):
    """vs_baseline is only honest if bench.py's NumPy iteration computes
    the SAME iteration the framework runs: one EM step from the same seed
    state on the same data must produce the same loglik and parameters
    (float64, well-populated clusters so no degeneracy guard fires)."""
    import jax.numpy as jnp
    import numpy as np

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    bench = _load_bench()
    rng = np.random.default_rng(11)
    k, d, n = 5, 4, 4000
    centers = rng.normal(scale=10.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float64)

    cfg = GMMConfig(min_iters=1, max_iters=1, chunk_size=1000,
                    dtype="float64", diag_only=diag)
    model = GMMModel(cfg)
    state = seed_clusters_host(data, k, dtype=np.float64)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    s1, ll1, iters = model.run_em(state, jnp.asarray(chunks),
                                  jnp.asarray(wts),
                                  convergence_epsilon(n, d))
    assert int(iters) == 1

    p0 = bench.baseline_params(state, k, dtype=np.float64)
    if diag:
        x2 = data * data
        cpu_iteration = bench.numpy_em_iteration_diag
    else:
        x2 = (data[:, :, None] * data[:, None, :]).reshape(n, -1)
        cpu_iteration = bench.numpy_em_iteration
    # em_while_loop returns the loglik of the UPDATED params (its body is
    # M-step then E-step), so parity needs two NumPy calls: the first
    # yields the updated params p1, the second's loglik is evaluated at p1.
    p1, _ = cpu_iteration(data, x2, p0)
    _, ll_np = cpu_iteration(data, x2, p1)

    np.testing.assert_allclose(float(ll1), ll_np, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(s1.means)[:k], p1["means"],
                               rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(np.asarray(s1.pi)[:k], p1["pi"],
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(s1.constant)[:k], p1["constant"],
                               rtol=1e-9)


def test_restart_ab_mode_contract():
    """--restarts (GMM_BENCH_RESTARTS) emits ONE JSON record carrying
    both walls AND winner parity in the same run -- the same contract
    style as the --sweep mode. Tiny shape so the A/B stays tier-1-fast."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_RESTARTS": "2",
        "GMM_BENCH_RESTART_N": "2000",
        "GMM_BENCH_RESTART_D": "4",
        "GMM_BENCH_RESTART_K": "4",
        "GMM_BENCH_RESTART_ITERS": "2",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    ab = j["restarts"]
    assert ab["n_init"] == 2
    assert ab["batched"]["wall_s"] > 0 and ab["sequential"]["wall_s"] > 0
    # winner parity asserted in the SAME record as the walls
    assert ab["winner_equal"] is True
    assert ab["ideal_k_equal"] is True
    assert ab["rel_score_diff"] < 1e-6
    assert j["vs_baseline"] == ab["speedup"]
    for side in ("batched", "sequential"):
        assert ab[side]["winner_init"] is not None


def test_envelope_mode_contract():
    """--envelope (GMM_BENCH_ENVELOPE=1) emits ONE JSON record with the
    fused-vs-jnp walls AND parity for BOTH covariance families of the
    K=512/D=32 reference envelope shape (CPU-shrunk here), the resolved
    backend, and the accelerator_unavailable passthrough -- the same
    contract style as --sweep/--restarts."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_ENVELOPE": "1",
        "GMM_BENCH_ENVELOPE_N": "2048",
        "GMM_BENCH_ENVELOPE_K": "8",
        "GMM_BENCH_ENVELOPE_D": "4",
        "GMM_BENCH_ENVELOPE_ITERS": "2",
        "GMM_BENCH_ENVELOPE_BLOCK": "128",
        "GMM_BENCH_CHUNK": "1024",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    env = j["envelope"]
    for fam in ("full", "diag"):
        side = env[fam]
        assert side["fused"]["wall_s"] > 0 and side["jnp"]["wall_s"] > 0
        # off-TPU the kernel MUST report the interpret backend -- a CPU
        # record can never masquerade as a Mosaic measurement
        assert side["fused"]["backend"] == "pallas-interpret"
        assert side["jnp"]["backend"] == "jnp"
        # walls + parity in the SAME record
        assert side["parity_ok"] is True
        assert "bit_identical" in side
    assert j["vs_baseline"] == env["full"]["speedup"]


def test_serve_mode_contract():
    """--serve (GMM_BENCH_SERVE=1) emits ONE JSON record with the cold
    first-request wall AND the warm steady-state percentiles, plus the
    zero-recompile proof bit — the acceptance contract: after one
    warm-up per (model, N-bucket), varying-N traffic performs no new
    traces/compiles and warm p50 < the cold first-request wall."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_SERVE": "1",
        "GMM_BENCH_SERVE_N": "2000",
        "GMM_BENCH_SERVE_D": "3",
        "GMM_BENCH_SERVE_K": "4",
        "GMM_BENCH_SERVE_REQUESTS": "100",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    s = j["serve"]
    assert s["requests"] >= 100
    assert s["cold_first_request_s"] > 0
    warm = s["warm"]
    assert warm["p50_s"] > 0 and warm["p99_s"] >= warm["p50_s"]
    assert warm["qps"] > 0
    # cold/warm in the SAME record, with the acceptance bits asserted
    assert s["warm_p50_lt_cold"] is True
    assert warm["p50_s"] < s["cold_first_request_s"]
    assert s["zero_recompile_after_warm"] is True
    assert s["new_compiles_after_warm"] == 0
    # resilience counters (rev v1.7) ride the record so soak runs
    # surface degradation; a clean A/B reports all-zero
    res = s["resilience"]
    assert res["shed"] == 0
    assert res["deadline_expired"] == 0
    assert res["reloads"] == 0
    assert res["breaker"]["trips"] == 0
    assert res["breaker"]["fastfails"] == 0
    assert res["breaker"]["open_routes"] == 0
    # vs_baseline is the cold/warm ratio (record fields are rounded
    # independently, so compare with slack)
    ratio = s["cold_first_request_s"] / warm["p50_s"]
    assert abs(j["vs_baseline"] - ratio) <= 0.01 * ratio + 0.01


def test_drift_mode_contract():
    """--drift (GMM_BENCH_DRIFT=1) emits ONE JSON record proving the rev
    v2.4 drift plane end to end: the envelope landed in the registry,
    in-distribution traffic sits under the PSI threshold without an
    alarm, deliberately shifted traffic sits over it AND raised the
    drift_alarm, sketching performed zero new compiles on the warmed
    path, and value/vs_baseline is the drift-on/off wall ratio."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_DRIFT": "1",
        "GMM_BENCH_DRIFT_N": "2000",
        "GMM_BENCH_DRIFT_D": "3",
        "GMM_BENCH_DRIFT_K": "4",
        "GMM_BENCH_DRIFT_REQUESTS": "40",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "x" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    d = j["drift"]
    assert d["envelope_in_registry"] is True
    # the detection contract, both directions, in the SAME record
    assert d["psi_in"] < d["threshold"] < d["psi_shifted"]
    assert d["alarm_in"] is False and d["alarm_fired"] is True
    assert d["detected"] is True
    # sketching rides the answered host block: no new executor work
    assert d["new_compiles"] == 0 and d["zero_recompile"] is True
    assert d["wall_on_s"] > 0 and d["wall_off_s"] > 0
    assert j["vs_baseline"] == d["overhead"]
    stats = d["drift_stats"]
    # three flushed windows (discarded warm-up + in-dist + shifted),
    # exactly one alarm -- from the shifted phase
    assert stats["windows"] == 3 and stats["alarms"] == 1
    assert stats["last"]["bench@1"]["alarm"] is True


def test_lifecycle_mode_contract():
    """--lifecycle (GMM_BENCH_LIFECYCLE=1) emits ONE JSON record
    driving the rev v2.6 closed loop end to end: injected drift fires
    the alarm, the shadow retrain publishes and canaries a candidate
    (gate values in the record), promotion flips it live, the injected
    post-promotion regression auto-rolls back, and the restored version
    scores bit-identically to the pre-promotion server. Per-phase walls
    are all measured; value/vs_baseline is the lifecycle-on/off steady
    serve ratio on identical warmed traffic."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_LIFECYCLE": "1",
        "GMM_BENCH_LIFECYCLE_N": "2000",
        "GMM_BENCH_LIFECYCLE_D": "3",
        "GMM_BENCH_LIFECYCLE_K": "4",
        "GMM_BENCH_LIFECYCLE_REQUESTS": "20",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "x" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    lc = j["lifecycle"]
    # the whole arc, in ONE record
    assert lc["alarm_fired"] is True
    assert lc["counts"] == {"retrains": 1, "canaries": 1, "promotes": 1,
                            "rollbacks": 1, "quarantines": 1}
    assert lc["closed_loop"] is True
    # per-phase walls all measured
    for phase in ("drift_detect_s", "retrain_s", "canary_promote_s",
                  "rollback_s"):
        assert lc["phases"][phase] > 0, phase
    # canary gate values ride the record (regression negative = the
    # candidate scored the drifted holdout better than the incumbent)
    g = lc["gates"]
    assert g["psi"] is not None and g["ks"] is not None
    assert g["regression"] <= g["tolerance"]
    assert g["shadow_rows"] > 0
    # promotion flipped v2 live, the rollback re-published v1 as v3 and
    # quarantined v2 -- and the restored npz + a fixed probe's scores
    # match the pre-promotion server exactly
    assert lc["promoted_version"] == 2
    assert lc["restored_version"] == 3
    assert lc["live_versions"] == [1, 3]
    assert lc["rollback_reason"] in ("score_regression", "drift_alarm",
                                     "breaker_trip")
    assert lc["rollback_restored_bit_identical"] is True
    assert j["vs_baseline"] == lc["overhead"] > 0


def test_http_mode_contract():
    """--http (GMM_BENCH_HTTP=1) emits ONE JSON record proving the rev
    v2.7 network tier end to end: a real `gmm serve --http --workers 2`
    subprocess tree driven closed-loop over TCP, a worker SIGKILLed
    mid-load with ZERO failed client requests (the acceptance bit), the
    supervised respawn's recovery wall measured, SIGTERM still draining
    to exit 75, and the server's own serve_summary.http rollup riding
    the record. value/vs_baseline is TCP p50 over in-process p50 --
    what the tier costs per request."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_HTTP": "1",
        "GMM_BENCH_HTTP_N": "2000",
        "GMM_BENCH_HTTP_D": "3",
        "GMM_BENCH_HTTP_K": "4",
        "GMM_BENCH_HTTP_REQUESTS": "40",
        "GMM_BENCH_HTTP_WORKERS": "2",
        "GMM_BENCH_HTTP_CLIENTS": "2",
        "GMM_BENCH_HTTP_AB_N": "2000",
        "GMM_BENCH_HTTP_AB_D": "8",
        "GMM_BENCH_HTTP_AB_ROWS": "64",
        "GMM_BENCH_HTTP_AB_REQUESTS": "30",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    h = j["http"]
    assert h["workers"] == 2 and h["requests"] == 40
    assert h["startup_s"] > 0
    assert h["p50_s"] > 0 and h["p99_s"] >= h["p50_s"]
    assert h["qps"] > 0
    # the acceptance bits: the mid-load SIGKILL happened, cost zero
    # failed requests, and the slot came back under supervision
    assert h["worker_killed"] is True
    assert h["failed_requests"] == 0
    assert h["zero_failed_requests"] is True
    assert h["kill_recovery_s"] is not None and h["kill_recovery_s"] > 0
    # SIGTERM over TCP keeps the preemption exit-code contract
    assert h["drain_exit_code"] == 75
    assert h["clean_drain_exit_75"] is True
    # the server's own rollup rode the record: the crash was counted,
    # nothing 5xx'd, nothing exhausted the sibling retry
    roll = h["rollup"]
    assert roll["worker_crashes"] >= 1 and roll["worker_respawns"] >= 1
    assert roll["errors_5xx"] == 0 and roll["retries_exhausted"] == 0
    # vs_baseline is the TCP/in-process p50 ratio (independently
    # rounded fields, so compare with slack)
    ratio = h["p50_s"] / h["inproc_p50_s"]
    assert abs(j["vs_baseline"] - ratio) <= 0.01 * ratio + 0.01
    assert j["vs_baseline"] > 0
    # the rev v2.8 payload-format x window-policy A/B rode the record:
    # both arms answered bit-identically to the same probe rows
    # (parity is ASSERTED inside bench.py -- reaching here proves it),
    # warm traffic never host-staged or recompiled on either arm, and
    # the p50 ratio was measured (the 0.7x target bit is hardware-
    # dependent, so the contract checks presence, not the bit's value)
    ab = h["ab"]
    assert ab["parity"] is True
    for arm in ("json_fixed", "binary_adaptive"):
        assert ab[arm]["p50_s"] > 0
        assert ab[arm]["host_staging"] == 0
        assert ab[arm]["zero_recompile_after_warm"] is True
    assert ab["json_fixed"]["encoding"] == "json"
    assert ab["binary_adaptive"]["encoding"] == "binary"
    assert ab["p50_ratio"] > 0
    assert isinstance(ab["meets_target"], bool)
    # the adaptive arm's controller actually adapted and stayed bounded
    assert ab["binary_adaptive"]["window_adaptations"] >= 0


def test_probe_budget_fails_over_after_one_hang():
    """Default probe budget: ONE attempt -- a hung probe fails over to
    CPU immediately instead of burning the old 5 x 90s retry ladder
    (BENCH_r05's ~7.5 wasted minutes). GMM_BENCH_PROBE_RETRIES adds
    retries back, opt-in."""
    import time

    bench = _load_bench()
    env_keys = ("GMM_BENCH_PROBE_ATTEMPTS", "GMM_BENCH_PROBE_RETRIES",
                "GMM_BENCH_PROBE_WAIT", "GMM_BENCH_PROBE_WAIT_S",
                "GMM_BENCH_PROBE_TIMEOUT_S")
    saved = {k: os.environ.pop(k, None) for k in env_keys}
    try:
        os.environ["GMM_BENCH_PROBE_TIMEOUT_S"] = "0.01"
        t0 = time.monotonic()
        assert bench.probe_default_platform() is False
        # one 10ms probe, no retry waits: far under the old ~450s floor
        assert time.monotonic() - t0 < 30.0
        # retries are opt-in and configurable
        os.environ["GMM_BENCH_PROBE_RETRIES"] = "2"
        os.environ["GMM_BENCH_PROBE_WAIT"] = "0.05"
        t0 = time.monotonic()
        assert bench.probe_default_platform() is False
        assert time.monotonic() - t0 < 30.0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.slow
def test_deliberate_cpu_run_measures_with_rc0():
    """GMM_BENCH_CPU=1 is the deliberate-CPU contract: rc 0, a real
    measurement, and accelerator_unavailable explicitly false."""
    r = _run({"GMM_BENCH_CPU": "1"}, ["--config=1"])
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "iters/sec"
    assert j["value"] > 0 and j["vs_baseline"] > 0
    assert j["accelerator_unavailable"] is False
    assert "cpu" in j["metric"]


def test_tenancy_ab_mode_contract():
    """--tenancy (GMM_BENCH_TENANCY=1) emits ONE JSON record carrying
    the fleet AND sequential walls plus per-tenant parity bits -- the
    same contract style as --restarts. Tiny shapes, pow2 K so the
    bit-parity contract applies (docs/TENANCY.md)."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_TENANCY": "1",
        "GMM_BENCH_TENANTS": "3",
        "GMM_BENCH_TENANCY_N": "1500",
        "GMM_BENCH_TENANCY_D": "3",
        "GMM_BENCH_TENANCY_K": "4",
        "GMM_BENCH_TENANCY_ITERS": "2",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    ab = j["tenancy"]
    assert ab["tenants"] == 3
    assert ab["fleet_wall_s"] > 0 and ab["sequential_wall_s"] > 0
    # walls + parity in the SAME record
    assert ab["all_parity_ok"] is True
    assert ab["all_bit_identical"] is True
    assert len(ab["per_tenant"]) == 3
    for t in ab["per_tenant"]:
        assert t["ideal_k_equal"] is True
        assert t["loglik_bit_identical"] is True
    assert ab["dropped"] == 0
    assert j["vs_baseline"] == ab["speedup"]
    assert ab["mode"] in ("scan", "vmap")


def test_ingest_ab_mode_contract():
    """--ingest (GMM_BENCH_INGEST=1) emits ONE JSON record carrying the
    resident AND pipelined AND minibatch walls, per-mode peak-RSS growth,
    and the bit-identical-loglik parity bit in the same run. The RSS
    *ratio* is NOT asserted: at contract-test shapes the jax runtime's
    allocations dominate both sides; the memory headline is a
    measurement claim (BENCH artifact), not a structural invariant."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_INGEST": "1",
        "GMM_BENCH_INGEST_N": "20000",
        "GMM_BENCH_INGEST_D": "4",
        "GMM_BENCH_INGEST_K": "4",
        "GMM_BENCH_INGEST_BLOCK": "2048",
        "GMM_BENCH_INGEST_ITERS": "15",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "x" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    ab = j["ingest"]
    for mode in ("resident", "pipelined", "minibatch"):
        side = ab[mode]
        assert side["mode"] == mode
        assert side["wall_s"] > 0
        assert side["rss_peak_kb"] >= side["rss_base_kb"] > 0
        assert side["rss_growth_kb"] == (side["rss_peak_kb"]
                                         - side["rss_base_kb"])
    # The acceptance BIT: resident and pipelined logliks exactly equal
    # (out-of-core ingestion is a transport change, not a math change).
    assert ab["loglik_parity"] is True
    assert ab["resident"]["loglik"] == ab["pipelined"]["loglik"]
    # Minibatch is approximate by design; the record must carry its
    # error AND the acceptance bound (health_regression_scale x
    # convergence_epsilon) it is judged against. The gamma-sum-matched
    # step budget exists precisely so the bound holds even at tiny
    # contract shapes.
    assert ab["minibatch_rel_err"] >= 0
    assert ab["minibatch_tolerance"] > 0
    assert ab["minibatch_steps"] >= ab["minibatch"]["em_steps"] > 0
    assert ab["minibatch_regression"] >= 0
    assert ab["minibatch_regression"] <= ab["minibatch_abs_err"] + 1e-9
    assert ab["minibatch_regression"] <= ab["minibatch_tolerance"]
    assert ab["minibatch_within_tolerance"] is True
    assert j["vs_baseline"] == ab["rss_growth_ratio"]


def test_obs_mode_contract():
    """--obs (GMM_BENCH_OBS=1) emits ONE JSON record carrying all three
    walls (off / stream / live) plus both overhead ratios, live-scrape
    health, and the bit-identity bit. `within_bound` must be PRESENT and
    boolean but its truth is not asserted: at contract-test shapes the
    fixed per-fit costs dominate and the ratio is noise -- the bound is
    a measurement claim for bench shapes (docs/OBSERVABILITY.md), not a
    structural invariant."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_OBS": "1",
        "GMM_BENCH_OBS_N": "4000",
        "GMM_BENCH_OBS_D": "4",
        "GMM_BENCH_OBS_K": "4",
        # Enough iterations that the (warm) live fit outlives several
        # scraper polls and sampler ticks -- the scrape-health bits
        # below must not race a millisecond fit window.
        "GMM_BENCH_OBS_ITERS": "60",
        "GMM_SAMPLER_INTERVAL_S": "0.05",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "x" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    ab = j["obs"]
    assert ab["n"] == 4000 and ab["k"] == 4 and ab["em_iters"] == 60
    # all three walls in the SAME record, ratios consistent with them
    for wall in ("off_wall_s", "stream_wall_s", "live_wall_s"):
        assert ab[wall] > 0
    assert ab["stream_overhead"] > 0 and ab["live_overhead"] > 0
    assert j["value"] == ab["live_overhead"] == j["vs_baseline"]
    assert ab["documented_bound"] > 1.0
    assert isinstance(ab["within_bound"], bool)
    # live-plane health: the endpoint was scraped DURING the fit and the
    # last scrape parsed as OpenMetrics; the live stream carries spans
    # and sampler heartbeats.
    assert ab["scrapes"] >= 1
    assert ab["scrape_parse_ok"] is True
    assert ab["span_records"] > 0
    assert ab["sampler_heartbeats"] >= 1
    # Instrumentation must not change the arithmetic.
    assert ab["loglik_bit_identical"] is True


def test_profile_mode_contract():
    """--profile (GMM_BENCH_PROFILE=1) emits ONE JSON record asserting
    the rev v2.2 compile-introspection contract: the run_summary.profile
    block has the documented shape (site compiles <= XLA compiles,
    per-site counts summing to the total), and two back-to-back
    identical runs `gmm diff` CLEAN (diff_exit 0, vs_baseline 1.0)."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_PROFILE": "1",
        "GMM_BENCH_PROFILE_N": "4000",
        "GMM_BENCH_PROFILE_D": "4",
        "GMM_BENCH_PROFILE_K": "4",
        "GMM_BENCH_PROFILE_ITERS": "3",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    p = j["profile"]
    assert p["n"] == 4000 and p["k"] == 4 and p["em_iters"] == 3
    # the profile block's shape held (the in-bench assertions passed)
    assert p["profile_shape_ok"] is True
    assert p["compiles"] >= 1
    assert p["compiles"] <= p["xla_compiles"]
    assert p["compile_seconds"] > 0
    assert sum(p["sites"].values()) == p["compiles"]
    assert "em" in p["sites"]
    # CPU provides cost analysis: the envelope numbers rode along
    assert p["cost_flops"] and p["cost_flops"] > 0
    assert p["cost_bytes_accessed"] and p["cost_bytes_accessed"] > 0
    # BOTH runs carried a profile, and the identical pair diffed clean
    assert p["second_run_has_profile"] is True
    assert p["diff_exit"] == 0
    assert j["vs_baseline"] == 1.0
    assert p["fingerprint"]


def test_timeline_mode_contract():
    """--timeline (GMM_BENCH_TIMELINE=1) emits ONE JSON record asserting
    the rev v2.3 trace-export contract: a live-plane fit's stream exports
    to a Chrome/Perfetto trace that passes the --validate structural
    oracle, with clock (not estimated) alignment and real slice/counter
    content (vs_baseline 1.0 = clean)."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_TIMELINE": "1",
        "GMM_BENCH_TIMELINE_N": "4000",
        "GMM_BENCH_TIMELINE_D": "4",
        "GMM_BENCH_TIMELINE_K": "4",
        "GMM_BENCH_TIMELINE_ITERS": "3",
        # fast sampler so heartbeats (and their clock anchors) land even
        # in a short fit
        "GMM_SAMPLER_INTERVAL_S": "0.05",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    t = j["timeline"]
    assert t["n"] == 4000 and t["k"] == 4 and t["em_iters"] == 3
    # the emitted document passed its own structural oracle
    assert t["validate_ok"] is True
    assert t["validate_errors"] == 0
    # a v2.3 recorder anchors its own stream: never "estimated"
    assert t["alignment"] == "clock"
    # real content: span/em slices, counter samples, >0 bytes on disk
    assert t["slices"] > 0
    assert t["counters"] > 0
    assert t["events"] >= t["slices"] + t["counters"]
    assert t["tracks"] >= 1
    assert t["trace_bytes"] > 0
    assert j["vs_baseline"] == 1.0


def test_tune_ab_mode_contract():
    """--tune (GMM_BENCH_TUNE=1) emits ONE JSON record carrying the
    probe sweep's decisions, BOTH walls (default geometry vs tuned), and
    parity in the same run -- vs_baseline is the default/tuned ratio.
    Tiny shape + 1 probe iteration so the full ladder stays
    tier-1-fast."""
    r = _run({
        "GMM_BENCH_CPU": "1",
        "GMM_BENCH_TUNE": "1",
        "GMM_BENCH_TUNE_N": "4000",
        "GMM_BENCH_TUNE_D": "4",
        "GMM_BENCH_TUNE_K": "4",
        "GMM_BENCH_TUNE_ITERS": "2",
        "GMM_BENCH_TUNE_PROBE_ITERS": "1",
    }, timeout=600)
    assert r.returncode == 0, r.stderr
    j = _json_line(r.stdout)
    assert j["unit"] == "s" and j["value"] > 0
    assert j["accelerator_unavailable"] is False
    t = j["tune"]
    assert t["n"] == 4000 and t["k"] == 4 and t["em_iters"] == 2
    # the probe's own wall is reported separately, never inside a side
    assert t["probe_wall_s"] > 0
    assert t["default"]["wall_s"] > 0 and t["tuned"]["wall_s"] > 0
    assert j["vs_baseline"] == t["speedup"]
    # chunk_size came from the measured sweep (the DB it just wrote)
    by_knob = {d["knob"]: d for d in t["decisions"]}
    assert by_knob["chunk_size"]["source"] == "db"
    assert len(by_knob["chunk_size"]["candidates"]) >= 2
    assert t["tuned"]["chunk_size"] == int(by_knob["chunk_size"]["chosen"])
    # numerical parity asserted in the SAME record as the walls
    assert t["parity_ok"] is True
    assert t["ideal_k_equal"] is True
    if t["bit_parity_expected"]:
        assert t["rel_loglik_diff"] == 0.0
