"""Seeding semantics vs the reference's two-stage init (net effective state)."""

import jax.numpy as jnp
import numpy as np

from cuda_gmm_mpi_tpu.ops.constants import LOG_2PI
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters, seed_means_indices


def test_seed_indices_match_reference_float_math():
    # (int)(c * seed), seed = (N-1)/(K-1)  -- gaussian.cu:110-120
    n, k = 1000, 7
    idx = np.asarray(seed_means_indices(n, k))
    seed = (n - 1.0) / (k - 1.0)
    expected = [int(np.float32(c) * np.float32(seed)) for c in range(k)]
    np.testing.assert_array_equal(idx, expected)
    assert idx[0] == 0 and idx[-1] == n - 1


def test_seed_single_cluster():
    idx = np.asarray(seed_means_indices(100, 1))
    np.testing.assert_array_equal(idx, [0])


def test_seed_state_fields(rng):
    n, d, k = 500, 4, 5
    data = rng.normal(scale=2.0, size=(n, d))
    state = seed_clusters(jnp.asarray(data), k, covariance_dynamic_range=1e3)

    np.testing.assert_allclose(np.asarray(state.N), n / k)          # :324
    np.testing.assert_allclose(np.asarray(state.pi), 1.0 / k)       # :323
    np.testing.assert_allclose(np.asarray(state.R),
                               np.stack([np.eye(d)] * k))           # :316-320
    np.testing.assert_allclose(np.asarray(state.Rinv),
                               np.stack([np.eye(d)] * k))
    # constant on R=I: -D/2 ln 2pi
    np.testing.assert_allclose(np.asarray(state.constant),
                               -d * 0.5 * LOG_2PI, rtol=1e-12)
    # avgvar = mean_d(E[x^2]-E[x]^2)/1e3  (gaussian_kernel.cu:79-99,325)
    var = (data ** 2).mean(0) - data.mean(0) ** 2
    np.testing.assert_allclose(np.asarray(state.avgvar), var.mean() / 1e3,
                               rtol=1e-10)
    # means: evenly spaced events from the FULL data (host override,
    # gaussian.cu:108-123)
    idx = np.asarray(seed_means_indices(n, k))
    np.testing.assert_allclose(np.asarray(state.means), data[idx])
    assert bool(jnp.all(state.active))


def test_seed_padded(rng):
    n, d, k, kp = 200, 3, 4, 8
    data = rng.normal(size=(n, d))
    state = seed_clusters(jnp.asarray(data), k, num_clusters_padded=kp)
    assert state.num_clusters_padded == kp
    np.testing.assert_array_equal(np.asarray(state.active),
                                  [True] * k + [False] * (kp - k))
    assert np.all(np.asarray(state.N)[k:] == 0)
