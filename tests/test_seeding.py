"""Seeding semantics vs the reference's two-stage init (net effective state)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.ops.constants import LOG_2PI
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters, seed_means_indices

from .conftest import make_blobs


def test_seed_indices_match_reference_float_math():
    # (int)(c * seed), seed = (N-1)/(K-1)  -- gaussian.cu:110-120
    n, k = 1000, 7
    idx = np.asarray(seed_means_indices(n, k))
    seed = (n - 1.0) / (k - 1.0)
    expected = [int(np.float32(c) * np.float32(seed)) for c in range(k)]
    np.testing.assert_array_equal(idx, expected)
    assert idx[0] == 0 and idx[-1] == n - 1


def test_seed_single_cluster():
    idx = np.asarray(seed_means_indices(100, 1))
    np.testing.assert_array_equal(idx, [0])


def test_seed_state_fields(rng):
    n, d, k = 500, 4, 5
    data = rng.normal(scale=2.0, size=(n, d))
    state = seed_clusters(jnp.asarray(data), k, covariance_dynamic_range=1e3)

    np.testing.assert_allclose(np.asarray(state.N), n / k)          # :324
    np.testing.assert_allclose(np.asarray(state.pi), 1.0 / k)       # :323
    np.testing.assert_allclose(np.asarray(state.R),
                               np.stack([np.eye(d)] * k))           # :316-320
    np.testing.assert_allclose(np.asarray(state.Rinv),
                               np.stack([np.eye(d)] * k))
    # constant on R=I: -D/2 ln 2pi
    np.testing.assert_allclose(np.asarray(state.constant),
                               -d * 0.5 * LOG_2PI, rtol=1e-12)
    # avgvar = mean_d(E[x^2]-E[x]^2)/1e3  (gaussian_kernel.cu:79-99,325)
    var = (data ** 2).mean(0) - data.mean(0) ** 2
    np.testing.assert_allclose(np.asarray(state.avgvar), var.mean() / 1e3,
                               rtol=1e-10)
    # means: evenly spaced events from the FULL data (host override,
    # gaussian.cu:108-123)
    idx = np.asarray(seed_means_indices(n, k))
    np.testing.assert_allclose(np.asarray(state.means), data[idx])
    assert bool(jnp.all(state.active))


def test_seed_padded(rng):
    n, d, k, kp = 200, 3, 4, 8
    data = rng.normal(size=(n, d))
    state = seed_clusters(jnp.asarray(data), k, num_clusters_padded=kp)
    assert state.num_clusters_padded == kp
    np.testing.assert_array_equal(np.asarray(state.active),
                                  [True] * k + [False] * (kp - k))
    assert np.all(np.asarray(state.N)[k:] == 0)


def test_kmeanspp_indices_deterministic_and_spread(rng):
    from cuda_gmm_mpi_tpu.ops.seeding import kmeanspp_indices

    data, centers = make_blobs(rng, n=2000, d=3, k=4)
    i1 = kmeanspp_indices(data, 4, seed=5)
    i2 = kmeanspp_indices(data, 4, seed=5)
    np.testing.assert_array_equal(i1, i2)  # deterministic given seed
    assert len(set(i1.tolist())) == 4
    # D^2 weighting should land one seed near each well-separated blob
    picked = data[i1]
    d = np.linalg.norm(picked[:, None, :] - centers[None], axis=-1).min(0)
    assert (d < 4.0).all(), d


def test_kmeanspp_subsample_path():
    from cuda_gmm_mpi_tpu.ops.seeding import kmeanspp_indices

    r = np.random.default_rng(0)
    data = r.normal(size=(5000, 2))
    idx = kmeanspp_indices(data, 8, seed=1, max_sample=1000)
    assert len(idx) == 8 and (idx < 5000).all() and (idx >= 0).all()


def test_kmeanspp_more_clusters_than_points():
    from cuda_gmm_mpi_tpu.ops.seeding import kmeanspp_indices

    data = np.zeros((3, 2))  # all-identical points: d2 collapses to 0
    idx = kmeanspp_indices(data, 5, seed=0)
    assert len(idx) == 5


def test_seed_method_kmeanspp_end_to_end(rng):
    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models import fit_gmm

    data, centers = make_blobs(rng, n=1200, d=3, k=4)
    cfg = GMMConfig(min_iters=8, max_iters=8, chunk_size=256, dtype="float64",
                    seed_method="kmeans++", seed=3)
    r = fit_gmm(data, 4, 4, config=cfg)
    assert np.isfinite(r.final_loglik)
    d = np.linalg.norm(r.means[:, None, :] - centers[None], axis=-1).min(0)
    assert (d < 1.0).all(), d


def test_seed_method_validation():
    from cuda_gmm_mpi_tpu.config import GMMConfig

    with pytest.raises(ValueError):
        GMMConfig(seed_method="random")
