"""Serving subsystem: registry, AOT executor cache, micro-batched server.

Covers the PR's contracts (docs/SERVING.md):
- registry save/load round-trips score BIT-identically to the in-memory
  estimator (full + diag), versioning is monotonic, manifest/shape
  mismatches fail loudly (RegistryError), torn newest versions walk back;
- the executable cache hits/misses/evicts per the pow2 bucket policy and
  NEVER recompiles on the warm path (varying N inside warmed buckets);
- the sklearn-surface estimator routes inference through the executor,
  so repeated predict/score calls with varying N stay zero-retrace
  (the compile-count regression the pre-serving code failed);
- micro-batched dispatch is bit-identical to the per-request loop;
- the `gmm serve` CLI speaks the JSONL protocol end to end and its
  telemetry stream validates against schema rev v1.6;
- `gmm export` from a sweep checkpoint selects the BEST-scoring K and
  records the criterion.
"""

import json
import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, GaussianMixture, fit_gmm
from cuda_gmm_mpi_tpu.serving import (GMMServer, ModelRegistry,
                                      RegistryError, ScoringExecutor,
                                      pow2_bucket)

from .conftest import make_blobs


def fitted(rng, *, diag=False, k=3, d=4, n=600, dtype="float32"):
    data, _ = make_blobs(rng, n=n, d=d, k=k, dtype=np.float64)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=4, max_iters=4, chunk_size=256,
                         dtype=dtype, diag_only=diag))
    gm.fit(data.astype(np.dtype(dtype)))
    return gm, data.astype(np.dtype(dtype))


# ---------------------------------------------------------------- registry


@pytest.mark.parametrize("diag", [False, True])
def test_registry_roundtrip_bit_identical(rng, tmp_path, diag):
    """Save -> load -> score must be BIT-identical to the in-memory
    estimator (both covariance families): the registry stores the exact
    state leaves, unlike the 3-decimal .summary format."""
    gm, data = fitted(rng, diag=diag)
    v = gm.to_registry(str(tmp_path), "m")
    assert v == 1
    gm2 = GaussianMixture.from_registry(str(tmp_path), "m")
    X = data[:173]
    assert np.array_equal(gm.score_samples(X), gm2.score_samples(X))
    assert np.array_equal(gm.predict_proba(X), gm2.predict_proba(X))
    assert np.array_equal(gm.predict(X), gm2.predict(X))
    assert gm2.n_components_ == gm.n_components_
    m = ModelRegistry(str(tmp_path)).load("m").manifest
    assert m["covariance_type"] == gm.config.covariance_type
    assert m["dtype"] == "float32"
    assert m["k"] == gm.n_components_ and m["d"] == 4


def test_registry_versioning_and_latest(rng, tmp_path):
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    assert gm.to_registry(reg, "m") == 1
    assert gm.to_registry(reg, "m") == 2
    assert reg.versions("m") == [1, 2]
    assert reg.load("m").version == 2          # latest by default
    assert reg.load("m", 1).version == 1       # explicit pin
    assert reg.models() == ["m"]
    with pytest.raises(RegistryError, match="immutable"):
        gm.to_registry(reg, "m", version=1)
    with pytest.raises(RegistryError, match="no version"):
        reg.load("m", 7)
    with pytest.raises(RegistryError, match="unknown model"):
        reg.load("ghost")


def test_registry_manifest_mismatch_is_loud(rng, tmp_path):
    """A manifest whose K disagrees with the stored arrays must raise
    RegistryError at load, never serve under the wrong densities."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    man = tmp_path / "m" / "1" / "manifest.json"
    doc = json.loads(man.read_text())
    doc["k"] = doc["k"] + 3
    man.write_text(json.dumps(doc))
    with pytest.raises(RegistryError, match="manifest says K="):
        reg.load("m", 1)


def test_registry_torn_newest_walks_back(rng, tmp_path):
    """Default resolution falls back over a torn newest version with a
    warning (checkpoint walk-back semantics); an explicitly pinned torn
    version fails loudly; all-torn raises the aggregate."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "m")
    (tmp_path / "m" / "2" / "model.npz").write_bytes(b"torn")
    with pytest.warns(RuntimeWarning, match="version 2 unreadable"):
        assert reg.load("m").version == 1
    with pytest.raises(RegistryError, match="unreadable model artifact"):
        reg.load("m", 2)
    (tmp_path / "m" / "1" / "model.npz").write_bytes(b"torn")
    with pytest.warns(RuntimeWarning):
        with pytest.raises(RegistryError, match="every version"):
            reg.load("m")


def test_registry_rejects_bad_names(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    for bad in ("", "../x", "a/b", ".hidden"):
        with pytest.raises(RegistryError, match="invalid model name"):
            reg._check_name(bad)


# ---------------------------------------------------------------- executor


def test_pow2_bucket_policy():
    assert [pow2_bucket(n) for n in (1, 2, 3, 5, 16, 17)] == \
        [1, 2, 4, 8, 16, 32]
    assert pow2_bucket(3, lo=256) == 256
    assert pow2_bucket(100_000, lo=256, hi=4096) == 4096


def test_executor_cache_hit_miss_and_lru_eviction(rng):
    """The LRU bound: with room for 2 executables, a third bucket evicts
    the least-recently-used one, and revisiting it recompiles (counted --
    an undersized cache is observable, not silent)."""
    gm, data = fitted(rng)
    state = gm.result_.state
    ex = ScoringExecutor(min_block=32, max_block=256, max_executables=2)
    ex.infer(state, data[:20])       # block 32: compile 1
    ex.infer(state, data[:60])       # block 64: compile 2
    assert (ex.misses, ex.compiles, ex.evictions) == (2, 2, 0)
    ex.infer(state, data[:20])       # block 32 again: hit
    assert ex.hits == 1
    ex.infer(state, data[:120])      # block 128: compile 3, evicts 64
    assert ex.evictions == 1 and ex.cache_size == 2
    c = ex.compiles
    ex.infer(state, data[:60])       # evicted bucket: recompile
    assert ex.compiles == c + 1


def test_executor_warm_path_zero_recompile(rng):
    """The acceptance contract: after one warm-up per N-bucket, 100
    requests with VARYING N perform no new traces or compiles."""
    gm, data = fitted(rng)
    state = gm.result_.state
    ex = ScoringExecutor(min_block=32, max_block=256)
    for n in (32, 64, 128, 256):     # warm one request per bucket
        ex.infer(state, data[:n])
    c0 = ex.compile_count
    lens = rng.integers(1, 257, size=100)
    for n in lens:
        ex.infer(state, data[:int(n)])
    assert ex.compile_count == c0, "warm path traced/compiled"
    assert ex.hits >= 100


def test_executor_split_and_parity_vs_estimator(rng):
    """Requests beyond max_block split into block slices; results equal
    the unsplit computation row-for-row (padding rows are inert)."""
    gm, data = fitted(rng)
    state = gm.result_.state
    big = ScoringExecutor(min_block=32, max_block=1024)
    small = ScoringExecutor(min_block=32, max_block=64)
    X = data[:300] - gm.result_.data_shift[None, :].astype(data.dtype)
    wb, zb = big.infer(state, X)
    ws, zs = small.infer(state, X)
    assert np.array_equal(zb, zs) and np.array_equal(wb, ws)
    assert small.padded_rows(300) == 64 * 4 + 64  # 4 full + bucketed tail


def test_executor_shares_across_models_same_family(rng, tmp_path):
    """Two same-(K-bucket, D) models share every executable: the cache is
    keyed by shapes, not by model identity."""
    gm1, data = fitted(rng, k=3)
    gm2, _ = fitted(np.random.default_rng(7), k=4)  # pow2 bucket = 4 both
    ex = ScoringExecutor(min_block=64, max_block=64)
    ex.infer(gm1.result_.state, data[:10])
    c0 = ex.compile_count
    ex.infer(gm2.result_.state, data[:10])
    assert ex.compile_count == c0


def test_estimator_varying_n_hits_one_executable_per_bucket(rng):
    """The satellite regression: GaussianMixture.predict/score_samples
    used to retrace for every distinct input length (jit keys on exact
    shapes); routed through the N-bucketed executor they must compile at
    most once per pow2 bucket and reuse it for every later N."""
    from cuda_gmm_mpi_tpu.serving.executor import executor_for_config

    gm, data = fitted(rng)
    ex = executor_for_config(gm.config)
    gm.score_samples(data[:256])     # warm the min_block bucket
    c0 = ex.compile_count
    for n in (3, 17, 40, 99, 150, 201, 256):
        gm.predict(data[:n])
        gm.score_samples(data[:n])
        gm.predict_proba(data[:n])
    assert ex.compile_count == c0, (
        "estimator inference recompiled on a varying-N warm path")


# -------------------------------------------------------------- server


def serve_requests(data, k=3):
    return [
        {"id": 0, "model": "m", "op": "score", "x": data[:7].tolist()},
        {"id": 1, "model": "m", "op": "predict", "x": data[7:19].tolist()},
        {"id": 2, "model": "m", "op": "predict_proba",
         "x": data[19:22].tolist()},
        {"id": 3, "model": "m", "op": "score_samples",
         "x": data[22:41].tolist()},
        {"id": 4, "model": "m", "op": "score", "x": data[41:44].tolist()},
    ]


def test_microbatch_coalescing_parity(rng, tmp_path):
    """Batched dispatch == per-request loop, bit for bit: coalescing may
    change latency, never results."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = serve_requests(data)
    batched = server.handle_requests(reqs, coalesce=True)
    solo = server.handle_requests(reqs, coalesce=False)
    assert len(batched) == len(solo) == len(reqs)
    for a, b in zip(batched, solo):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    assert all(r["ok"] for r in batched)


def test_server_parity_vs_estimator(rng, tmp_path):
    """A served score_samples response equals the estimator's own
    scoring of the same rows (the whole serving stack changes latency,
    not numbers)."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    X = data[:31]
    resp = server.handle_requests(
        [{"id": 0, "model": "m", "op": "score_samples",
          "x": X.tolist()}])[0]
    assert resp["ok"]
    np.testing.assert_array_equal(
        np.asarray(resp["result"], np.float32), gm.score_samples(X))


def test_server_error_paths(rng, tmp_path):
    """Malformed requests answer ok=false on their id; the loop and the
    other requests in the same batch are unaffected."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = [
        {"id": 0, "model": "ghost", "op": "score", "x": data[:2].tolist()},
        {"id": 1, "model": "m", "op": "transmogrify",
         "x": data[:2].tolist()},
        {"id": 2, "model": "m", "op": "score", "x": [[1.0, 2.0]]},  # bad D
        {"id": 3, "model": "m", "op": "score",
         "x": [[float("nan")] * 4]},
        {"id": 4, "model": "m", "op": "score", "x": data[:2].tolist()},
    ]
    resps = {r["id"]: r for r in server.handle_requests(reqs)}
    assert not resps[0]["ok"] and "unknown model" in resps[0]["error"]
    assert not resps[1]["ok"] and "unknown op" in resps[1]["error"]
    assert not resps[2]["ok"] and "D=4" in resps[2]["error"]
    assert not resps[3]["ok"] and "NaN" in resps[3]["error"]
    assert resps[4]["ok"]


def test_server_version_routing(rng, tmp_path):
    """Requests may pin a version; default routes to the newest at first
    use. Distinct versions really serve distinct parameters."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")                      # v1
    gm2 = GaussianMixture.from_registry(reg, "m")
    gm2.result_.state = gm2.result_.state.replace(
        means=gm2.result_.state.means + 1.0)      # visibly different v2
    reg.save("m", gm2.result_, config=gm2.config)
    server = GMMServer(reg)
    X = data[:5].tolist()
    r_latest = server.handle_requests(
        [{"model": "m", "op": "score", "x": X}])[0]
    r_v1 = server.handle_requests(
        [{"model": "m", "version": 1, "op": "score", "x": X}])[0]
    assert r_latest["version"] == 2 and r_v1["version"] == 1
    assert r_latest["result"] != r_v1["result"]


# ----------------------------------------------------------- CLI + schema


def _write_requests(path, data, n=6):
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "id": i, "model": "m",
                "op": ("score" if i % 2 else "predict"),
                "x": data[i * 5:(i + 1) * 5 + i].tolist()}) + "\n")


def test_serve_cli_smoke_jsonl_protocol(rng, tmp_path):
    """`gmm serve` end to end over the JSONL protocol: every request gets
    a response line on its id, and the telemetry stream validates against
    schema rev v1.6 with serve_request/serve_batch/serve_summary."""
    from cuda_gmm_mpi_tpu.cli import main
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    reqs = tmp_path / "req.jsonl"
    resp_path = tmp_path / "resp.jsonl"
    metrics = tmp_path / "serve_metrics.jsonl"
    _write_requests(reqs, data)
    rc = main(["serve", "--registry", str(tmp_path / "reg"),
               "--input", str(reqs), "--output", str(resp_path),
               "--metrics-file", str(metrics)])
    assert rc == 0
    resps = [json.loads(ln) for ln in resp_path.read_text().splitlines()]
    assert sorted(r["id"] for r in resps) == list(range(6))
    assert all(r["ok"] for r in resps)
    for r in resps:
        assert r["model"] == "m" and r["version"] == 1
        assert (isinstance(r["result"], float)
                or len(r["result"]) == r["n"])

    records = read_stream(str(metrics))
    assert validate_stream(records) == []
    events = [r["event"] for r in records]
    assert events.count("serve_request") == 6
    assert "serve_batch" in events
    summary = [r for r in records if r["event"] == "serve_summary"][-1]
    assert summary["requests"] == 6 and summary["qps"] > 0
    assert summary["latency_ms"]["p50"] > 0
    assert summary["metrics"]["counters"]["serve_requests"] == 6
    # warmed at startup: no dispatch-time AOT compiles on any batch
    assert all(r.get("compiled", 0) == 0 for r in records
               if r["event"] == "serve_batch")


def test_serve_report_renders_serving_section(rng, tmp_path, capsys):
    """`gmm report` renders the v1.6 serving section from the stream."""
    from cuda_gmm_mpi_tpu.cli import main

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    reqs = tmp_path / "req.jsonl"
    metrics = tmp_path / "m.jsonl"
    _write_requests(reqs, data, n=3)
    assert main(["serve", "--registry", str(tmp_path / "reg"),
                 "--input", str(reqs), "--output", str(tmp_path / "o"),
                 "--metrics-file", str(metrics)]) == 0
    assert main(["report", str(metrics), "--validate"]) == 0
    out = capsys.readouterr().out
    assert "Serving (rev v1.6" in out
    assert "micro-batches" in out and "QPS" in out


def test_serve_unix_socket(rng, tmp_path):
    """The UNIX-socket front end speaks the same protocol; concurrent
    clients share the micro-batch queue."""
    import socket
    import threading

    from cuda_gmm_mpi_tpu.serving.server import serve_main

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    sock_path = str(tmp_path / "gmm.sock")
    t = threading.Thread(target=serve_main, args=(
        ["--registry", str(tmp_path / "reg"), "--socket", sock_path,
         "--max-requests", "3"],), daemon=True)
    t.start()
    deadline = 30.0
    import time as _t
    t0 = _t.monotonic()
    while not os.path.exists(sock_path):
        assert _t.monotonic() - t0 < deadline, "socket never appeared"
        _t.sleep(0.02)
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rw")
    for i in range(3):
        f.write(json.dumps({"id": i, "model": "m", "op": "score",
                            "x": data[:4].tolist()}) + "\n")
    f.flush()
    got = [json.loads(f.readline()) for _ in range(3)]
    c.close()
    t.join(timeout=deadline)
    assert not t.is_alive()
    assert sorted(r["id"] for r in got) == [0, 1, 2]
    assert all(r["ok"] for r in got)


# ------------------------------------------------------------------ export


def test_export_checkpoint_selects_best_k_not_last_step(rng, tmp_path):
    """The satellite contract: a sweep checkpoint's in-flight state is
    the LAST fitted K; export must pick best_state (the best-criterion
    configuration) and record the criterion + score in the manifest."""
    data, _ = make_blobs(rng, n=600, d=3, k=3, dtype=np.float32)
    ck = str(tmp_path / "ck")
    res = fit_gmm(data, 6, 0,
                  config=GMMConfig(min_iters=3, max_iters=3,
                                   chunk_size=256, checkpoint_dir=ck))
    assert res.ideal_num_clusters < 6  # the sweep really merged
    reg = ModelRegistry(str(tmp_path / "reg"))
    v = reg.export_checkpoint(ck, "swept")
    m = reg.load("swept", v)
    assert m.manifest["criterion"] == "rissanen"
    assert m.manifest["source"] == "checkpoint"
    assert np.isclose(m.manifest["score"], res.min_rissanen)
    # best-scoring K, not the last step's in-flight K (which is < ideal
    # by the end of the sweep)
    assert m.k == res.ideal_num_clusters
    np.testing.assert_array_equal(m.data_shift,
                                  np.asarray(res.data_shift, np.float64))
    gm = GaussianMixture.from_registry(reg, "swept")
    # identical best parameters => identical scores on fresh rows
    ref = GaussianMixture(6, config=GMMConfig(min_iters=3, max_iters=3,
                                              chunk_size=256))
    ref.result_, ref._model = res, None
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel

    ref._model = GMMModel(ref.config)
    assert np.array_equal(gm.score_samples(data[:50]),
                          ref.score_samples(data[:50]))


def test_export_cli_checkpoint_and_summary(rng, tmp_path, capsys):
    from cuda_gmm_mpi_tpu.cli import main
    from cuda_gmm_mpi_tpu.io import write_summary

    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    ck = str(tmp_path / "ck")
    res = fit_gmm(data, 5, 0,
                  config=GMMConfig(min_iters=2, max_iters=2,
                                   chunk_size=256, checkpoint_dir=ck))
    reg_dir = str(tmp_path / "reg")
    assert main(["export", "--registry", reg_dir, "--name", "a",
                 "--checkpoint", ck]) == 0
    out = capsys.readouterr().out
    assert "exported 'a' version 1" in out and "rissanen=" in out

    summary = str(tmp_path / "model.summary")
    write_summary(summary, res)
    assert main(["export", "--registry", reg_dir, "--name", "b",
                 "--summary", summary]) == 0
    reg = ModelRegistry(reg_dir)
    assert reg.load("b").manifest["source"] == "summary"
    # bad source fails loudly with rc 1, not a traceback
    assert main(["export", "--registry", reg_dir, "--name", "c",
                 "--checkpoint", str(tmp_path / "nothing")]) == 1


def test_export_empty_checkpoint_is_loud(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RegistryError, match="no sweep checkpoints"):
        reg.export_checkpoint(str(tmp_path / "missing"), "x")
