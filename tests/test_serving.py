"""Serving subsystem: registry, AOT executor cache, micro-batched server.

Covers the PR's contracts (docs/SERVING.md):
- registry save/load round-trips score BIT-identically to the in-memory
  estimator (full + diag), versioning is monotonic, manifest/shape
  mismatches fail loudly (RegistryError), torn newest versions walk back;
- the executable cache hits/misses/evicts per the pow2 bucket policy and
  NEVER recompiles on the warm path (varying N inside warmed buckets);
- the sklearn-surface estimator routes inference through the executor,
  so repeated predict/score calls with varying N stay zero-retrace
  (the compile-count regression the pre-serving code failed);
- micro-batched dispatch is bit-identical to the per-request loop;
- the `gmm serve` CLI speaks the JSONL protocol end to end and its
  telemetry stream validates against schema rev v1.6;
- `gmm export` from a sweep checkpoint selects the BEST-scoring K and
  records the criterion.
"""

import json
import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, GaussianMixture, fit_gmm
from cuda_gmm_mpi_tpu.serving import (GMMServer, ModelRegistry,
                                      RegistryError, ScoringExecutor,
                                      pow2_bucket)

from .conftest import make_blobs


def fitted(rng, *, diag=False, k=3, d=4, n=600, dtype="float32"):
    data, _ = make_blobs(rng, n=n, d=d, k=k, dtype=np.float64)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=4, max_iters=4, chunk_size=256,
                         dtype=dtype, diag_only=diag))
    gm.fit(data.astype(np.dtype(dtype)))
    return gm, data.astype(np.dtype(dtype))


# ---------------------------------------------------------------- registry


@pytest.mark.parametrize("diag", [False, True])
def test_registry_roundtrip_bit_identical(rng, tmp_path, diag):
    """Save -> load -> score must be BIT-identical to the in-memory
    estimator (both covariance families): the registry stores the exact
    state leaves, unlike the 3-decimal .summary format."""
    gm, data = fitted(rng, diag=diag)
    v = gm.to_registry(str(tmp_path), "m")
    assert v == 1
    gm2 = GaussianMixture.from_registry(str(tmp_path), "m")
    X = data[:173]
    assert np.array_equal(gm.score_samples(X), gm2.score_samples(X))
    assert np.array_equal(gm.predict_proba(X), gm2.predict_proba(X))
    assert np.array_equal(gm.predict(X), gm2.predict(X))
    assert gm2.n_components_ == gm.n_components_
    m = ModelRegistry(str(tmp_path)).load("m").manifest
    assert m["covariance_type"] == gm.config.covariance_type
    assert m["dtype"] == "float32"
    assert m["k"] == gm.n_components_ and m["d"] == 4


def test_registry_versioning_and_latest(rng, tmp_path):
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    assert gm.to_registry(reg, "m") == 1
    assert gm.to_registry(reg, "m") == 2
    assert reg.versions("m") == [1, 2]
    assert reg.load("m").version == 2          # latest by default
    assert reg.load("m", 1).version == 1       # explicit pin
    assert reg.models() == ["m"]
    with pytest.raises(RegistryError, match="immutable"):
        gm.to_registry(reg, "m", version=1)
    with pytest.raises(RegistryError, match="no version"):
        reg.load("m", 7)
    with pytest.raises(RegistryError, match="unknown model"):
        reg.load("ghost")


def test_registry_manifest_mismatch_is_loud(rng, tmp_path):
    """A manifest whose K disagrees with the stored arrays must raise
    RegistryError at load, never serve under the wrong densities."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    man = tmp_path / "m" / "1" / "manifest.json"
    doc = json.loads(man.read_text())
    doc["k"] = doc["k"] + 3
    man.write_text(json.dumps(doc))
    with pytest.raises(RegistryError, match="manifest says K="):
        reg.load("m", 1)


def test_registry_torn_newest_walks_back(rng, tmp_path):
    """Default resolution falls back over a torn newest version with a
    warning (checkpoint walk-back semantics); an explicitly pinned torn
    version fails loudly; all-torn raises the aggregate."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "m")
    (tmp_path / "m" / "2" / "model.npz").write_bytes(b"torn")
    with pytest.warns(RuntimeWarning, match="version 2 unreadable"):
        assert reg.load("m").version == 1
    with pytest.raises(RegistryError, match="unreadable model artifact"):
        reg.load("m", 2)
    (tmp_path / "m" / "1" / "model.npz").write_bytes(b"torn")
    with pytest.warns(RuntimeWarning):
        with pytest.raises(RegistryError, match="every version"):
            reg.load("m")


def test_registry_rejects_bad_names(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    for bad in ("", "../x", "a/b", ".hidden"):
        with pytest.raises(RegistryError, match="invalid model name"):
            reg._check_name(bad)


def test_registry_torn_walkback_is_a_counted_event(rng, tmp_path):
    """Rev v2.6: the torn-newest walk-back is OBSERVABLE, not just a
    Python warning -- one schema-valid ``registry_torn`` event naming
    the skipped version plus the ``registry_torn`` counter (rendered as
    ``gmm_registry_torn_total`` by the /metrics exporter)."""
    from cuda_gmm_mpi_tpu import telemetry
    from cuda_gmm_mpi_tpu.telemetry.exporter import render_openmetrics
    from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "m")
    (tmp_path / "m" / "2" / "model.npz").write_bytes(b"torn")

    stream = []

    class _Sink:
        def write(self, line):
            stream.append(json.loads(line))

        def flush(self):
            pass

    rec = telemetry.RunRecorder(stream=_Sink())
    with telemetry.use(rec), rec:
        with pytest.warns(RuntimeWarning, match="version 2 unreadable"):
            assert reg.load("m").version == 1
        snapshot = rec.metrics.snapshot()
    assert validate_stream(stream) == []
    torn = [r for r in stream if r["event"] == "registry_torn"]
    assert len(torn) == 1
    assert torn[0]["model"] == "m" and torn[0]["version"] == 2
    assert "error" in torn[0]
    assert snapshot["counters"]["registry_torn"] == 1
    text = render_openmetrics(snapshot)
    assert "gmm_registry_torn_total 1" in text


def test_registry_disappearance_never_crashes_serving(rng, tmp_path):
    """Lifecycle hard case: the registry being DELETED out from under a
    live server (rsync flip, operator error) must degrade, not crash --
    ``latest_fingerprint``/``poll``/``maybe_reload`` go quiet and every
    already-resolved route keeps answering from its prepared state."""
    import shutil

    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "m")
    server = GMMServer(reg, warm=False)
    x = data[:16].tolist()

    def ask():
        resp = server.handle_requests(
            [{"id": 0, "model": "m", "op": "score_samples", "x": x}])[0]
        assert resp["ok"], resp
        return resp

    before = ask()               # pins the default route at v2
    assert before["version"] == 2

    # 1) newest version dir vanishes: the poll sees a change and the
    # reload walks BACK to the newest readable version.
    shutil.rmtree(tmp_path / "m" / "2")
    swaps = server.maybe_reload()
    assert [s["to_version"] for s in swaps] == [1]
    assert ask()["version"] == 1

    # 2) the whole model dir vanishes: no fingerprint, no swap, the
    # prepared route keeps serving.
    shutil.rmtree(tmp_path / "m")
    assert reg.latest_fingerprint("m") is None
    assert server.maybe_reload() == []
    assert ask()["version"] == 1

    # 3) the entire registry root vanishes: enumeration and the poll
    # degrade to empty, reload stays a no-op, routes still answer.
    shutil.rmtree(tmp_path)
    assert reg.models() == []
    assert reg.versions("m") == []
    assert reg.poll({"m": (1, "x")}) == {}
    assert server.maybe_reload() == []
    assert ask()["version"] == 1

    # 4) a NEVER-resolved model is a per-request error (breaker path),
    # not a server crash.
    resp = server.handle_requests(
        [{"id": 1, "model": "ghost", "op": "score_samples", "x": x}])[0]
    assert not resp["ok"] and "unknown model" in resp["error"]


# ---------------------------------------------------------------- executor


def test_pow2_bucket_policy():
    assert [pow2_bucket(n) for n in (1, 2, 3, 5, 16, 17)] == \
        [1, 2, 4, 8, 16, 32]
    assert pow2_bucket(3, lo=256) == 256
    assert pow2_bucket(100_000, lo=256, hi=4096) == 4096


def test_executor_cache_hit_miss_and_lru_eviction(rng):
    """The LRU bound: with room for 2 executables, a third bucket evicts
    the least-recently-used one, and revisiting it recompiles (counted --
    an undersized cache is observable, not silent)."""
    gm, data = fitted(rng)
    state = gm.result_.state
    ex = ScoringExecutor(min_block=32, max_block=256, max_executables=2)
    ex.infer(state, data[:20])       # block 32: compile 1
    ex.infer(state, data[:60])       # block 64: compile 2
    assert (ex.misses, ex.compiles, ex.evictions) == (2, 2, 0)
    ex.infer(state, data[:20])       # block 32 again: hit
    assert ex.hits == 1
    ex.infer(state, data[:120])      # block 128: compile 3, evicts 64
    assert ex.evictions == 1 and ex.cache_size == 2
    c = ex.compiles
    ex.infer(state, data[:60])       # evicted bucket: recompile
    assert ex.compiles == c + 1


def test_executor_warm_path_zero_recompile(rng):
    """The acceptance contract: after one warm-up per N-bucket, 100
    requests with VARYING N perform no new traces or compiles."""
    gm, data = fitted(rng)
    state = gm.result_.state
    ex = ScoringExecutor(min_block=32, max_block=256)
    for n in (32, 64, 128, 256):     # warm one request per bucket
        ex.infer(state, data[:n])
    c0 = ex.compile_count
    lens = rng.integers(1, 257, size=100)
    for n in lens:
        ex.infer(state, data[:int(n)])
    assert ex.compile_count == c0, "warm path traced/compiled"
    assert ex.hits >= 100


def test_executor_split_and_parity_vs_estimator(rng):
    """Requests beyond max_block split into block slices; results equal
    the unsplit computation row-for-row (padding rows are inert)."""
    gm, data = fitted(rng)
    state = gm.result_.state
    big = ScoringExecutor(min_block=32, max_block=1024)
    small = ScoringExecutor(min_block=32, max_block=64)
    X = data[:300] - gm.result_.data_shift[None, :].astype(data.dtype)
    wb, zb = big.infer(state, X)
    ws, zs = small.infer(state, X)
    assert np.array_equal(zb, zs) and np.array_equal(wb, ws)
    assert small.padded_rows(300) == 64 * 4 + 64  # 4 full + bucketed tail


def test_executor_shares_across_models_same_family(rng, tmp_path):
    """Two same-(K-bucket, D) models share every executable: the cache is
    keyed by shapes, not by model identity."""
    gm1, data = fitted(rng, k=3)
    gm2, _ = fitted(np.random.default_rng(7), k=4)  # pow2 bucket = 4 both
    ex = ScoringExecutor(min_block=64, max_block=64)
    ex.infer(gm1.result_.state, data[:10])
    c0 = ex.compile_count
    ex.infer(gm2.result_.state, data[:10])
    assert ex.compile_count == c0


def test_estimator_varying_n_hits_one_executable_per_bucket(rng):
    """The satellite regression: GaussianMixture.predict/score_samples
    used to retrace for every distinct input length (jit keys on exact
    shapes); routed through the N-bucketed executor they must compile at
    most once per pow2 bucket and reuse it for every later N."""
    from cuda_gmm_mpi_tpu.serving.executor import executor_for_config

    gm, data = fitted(rng)
    ex = executor_for_config(gm.config)
    gm.score_samples(data[:256])     # warm the min_block bucket
    c0 = ex.compile_count
    for n in (3, 17, 40, 99, 150, 201, 256):
        gm.predict(data[:n])
        gm.score_samples(data[:n])
        gm.predict_proba(data[:n])
    assert ex.compile_count == c0, (
        "estimator inference recompiled on a varying-N warm path")


# -------------------------------------------------------------- server


def serve_requests(data, k=3):
    return [
        {"id": 0, "model": "m", "op": "score", "x": data[:7].tolist()},
        {"id": 1, "model": "m", "op": "predict", "x": data[7:19].tolist()},
        {"id": 2, "model": "m", "op": "predict_proba",
         "x": data[19:22].tolist()},
        {"id": 3, "model": "m", "op": "score_samples",
         "x": data[22:41].tolist()},
        {"id": 4, "model": "m", "op": "score", "x": data[41:44].tolist()},
    ]


def test_microbatch_coalescing_parity(rng, tmp_path):
    """Batched dispatch == per-request loop, bit for bit: coalescing may
    change latency, never results."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = serve_requests(data)
    batched = server.handle_requests(reqs, coalesce=True)
    solo = server.handle_requests(reqs, coalesce=False)
    assert len(batched) == len(solo) == len(reqs)
    for a, b in zip(batched, solo):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    assert all(r["ok"] for r in batched)


def test_server_parity_vs_estimator(rng, tmp_path):
    """A served score_samples response equals the estimator's own
    scoring of the same rows (the whole serving stack changes latency,
    not numbers)."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    X = data[:31]
    resp = server.handle_requests(
        [{"id": 0, "model": "m", "op": "score_samples",
          "x": X.tolist()}])[0]
    assert resp["ok"]
    np.testing.assert_array_equal(
        np.asarray(resp["result"], np.float32), gm.score_samples(X))


def test_server_error_paths(rng, tmp_path):
    """Malformed requests answer ok=false on their id; the loop and the
    other requests in the same batch are unaffected."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = [
        {"id": 0, "model": "ghost", "op": "score", "x": data[:2].tolist()},
        {"id": 1, "model": "m", "op": "transmogrify",
         "x": data[:2].tolist()},
        {"id": 2, "model": "m", "op": "score", "x": [[1.0, 2.0]]},  # bad D
        {"id": 3, "model": "m", "op": "score",
         "x": [[float("nan")] * 4]},
        {"id": 4, "model": "m", "op": "score", "x": data[:2].tolist()},
    ]
    resps = {r["id"]: r for r in server.handle_requests(reqs)}
    assert not resps[0]["ok"] and "unknown model" in resps[0]["error"]
    assert not resps[1]["ok"] and "unknown op" in resps[1]["error"]
    assert not resps[2]["ok"] and "D=4" in resps[2]["error"]
    assert not resps[3]["ok"] and "NaN" in resps[3]["error"]
    assert resps[4]["ok"]


def test_server_version_routing(rng, tmp_path):
    """Requests may pin a version; default routes to the newest at first
    use. Distinct versions really serve distinct parameters."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")                      # v1
    gm2 = GaussianMixture.from_registry(reg, "m")
    gm2.result_.state = gm2.result_.state.replace(
        means=gm2.result_.state.means + 1.0)      # visibly different v2
    reg.save("m", gm2.result_, config=gm2.config)
    server = GMMServer(reg)
    X = data[:5].tolist()
    r_latest = server.handle_requests(
        [{"model": "m", "op": "score", "x": X}])[0]
    r_v1 = server.handle_requests(
        [{"model": "m", "version": 1, "op": "score", "x": X}])[0]
    assert r_latest["version"] == 2 and r_v1["version"] == 1
    assert r_latest["result"] != r_v1["result"]


# ----------------------------------------------------------- CLI + schema


def _write_requests(path, data, n=6):
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "id": i, "model": "m",
                "op": ("score" if i % 2 else "predict"),
                "x": data[i * 5:(i + 1) * 5 + i].tolist()}) + "\n")


def test_serve_cli_smoke_jsonl_protocol(rng, tmp_path):
    """`gmm serve` end to end over the JSONL protocol: every request gets
    a response line on its id, and the telemetry stream validates against
    schema rev v1.6 with serve_request/serve_batch/serve_summary."""
    from cuda_gmm_mpi_tpu.cli import main
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    reqs = tmp_path / "req.jsonl"
    resp_path = tmp_path / "resp.jsonl"
    metrics = tmp_path / "serve_metrics.jsonl"
    _write_requests(reqs, data)
    rc = main(["serve", "--registry", str(tmp_path / "reg"),
               "--input", str(reqs), "--output", str(resp_path),
               "--metrics-file", str(metrics)])
    assert rc == 0
    resps = [json.loads(ln) for ln in resp_path.read_text().splitlines()]
    assert sorted(r["id"] for r in resps) == list(range(6))
    assert all(r["ok"] for r in resps)
    for r in resps:
        assert r["model"] == "m" and r["version"] == 1
        assert (isinstance(r["result"], float)
                or len(r["result"]) == r["n"])

    records = read_stream(str(metrics))
    assert validate_stream(records) == []
    events = [r["event"] for r in records]
    assert events.count("serve_request") == 6
    assert "serve_batch" in events
    summary = [r for r in records if r["event"] == "serve_summary"][-1]
    assert summary["requests"] == 6 and summary["qps"] > 0
    assert summary["latency_ms"]["p50"] > 0
    assert summary["metrics"]["counters"]["serve_requests"] == 6
    # warmed at startup: no dispatch-time AOT compiles on any batch
    assert all(r.get("compiled", 0) == 0 for r in records
               if r["event"] == "serve_batch")


def test_serve_report_renders_serving_section(rng, tmp_path, capsys):
    """`gmm report` renders the v1.6 serving section from the stream."""
    from cuda_gmm_mpi_tpu.cli import main

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    reqs = tmp_path / "req.jsonl"
    metrics = tmp_path / "m.jsonl"
    _write_requests(reqs, data, n=3)
    assert main(["serve", "--registry", str(tmp_path / "reg"),
                 "--input", str(reqs), "--output", str(tmp_path / "o"),
                 "--metrics-file", str(metrics)]) == 0
    assert main(["report", str(metrics), "--validate"]) == 0
    out = capsys.readouterr().out
    assert "Serving (rev v1.6" in out
    assert "micro-batches" in out and "QPS" in out


def test_serve_unix_socket(rng, tmp_path):
    """The UNIX-socket front end speaks the same protocol; concurrent
    clients share the micro-batch queue."""
    import socket
    import threading

    from cuda_gmm_mpi_tpu.serving.server import serve_main

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    sock_path = str(tmp_path / "gmm.sock")
    t = threading.Thread(target=serve_main, args=(
        ["--registry", str(tmp_path / "reg"), "--socket", sock_path,
         "--max-requests", "3"],), daemon=True)
    t.start()
    deadline = 30.0
    import time as _t
    t0 = _t.monotonic()
    while not os.path.exists(sock_path):
        assert _t.monotonic() - t0 < deadline, "socket never appeared"
        _t.sleep(0.02)
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rw")
    for i in range(3):
        f.write(json.dumps({"id": i, "model": "m", "op": "score",
                            "x": data[:4].tolist()}) + "\n")
    f.flush()
    got = [json.loads(f.readline()) for _ in range(3)]
    c.close()
    t.join(timeout=deadline)
    assert not t.is_alive()
    assert sorted(r["id"] for r in got) == [0, 1, 2]
    assert all(r["ok"] for r in got)


def _socket_serve_thread(tmp_path, extra_args, max_requests):
    """A serve_main --socket thread + the path, for hardening tests."""
    import threading

    from cuda_gmm_mpi_tpu.serving.server import serve_main

    sock_path = str(tmp_path / "gmm.sock")
    t = threading.Thread(target=serve_main, args=(
        ["--registry", str(tmp_path / "reg"), "--socket", sock_path,
         "--max-requests", str(max_requests)] + extra_args,), daemon=True)
    t.start()
    import time as _t
    t0 = _t.monotonic()
    while not os.path.exists(sock_path):
        assert _t.monotonic() - t0 < 30.0, "socket never appeared"
        _t.sleep(0.02)
    return t, sock_path


def test_serve_socket_read_deadline_frees_stalled_reader(rng, tmp_path):
    """Rev v2.7 reader containment: a client that connects and sends
    NOTHING (slowloris) is disconnected at --read-timeout-s instead of
    parking its reader thread forever; a healthy client on the same
    server is served throughout."""
    import socket

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    t, sock_path = _socket_serve_thread(
        tmp_path, ["--read-timeout-s", "0.3"], max_requests=1)
    staller = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    staller.connect(sock_path)
    staller.settimeout(30.0)
    healthy = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    healthy.connect(sock_path)
    f = healthy.makefile("rw")
    f.write(json.dumps({"id": 0, "model": "m", "op": "score",
                        "x": data[:4].tolist()}) + "\n")
    f.flush()
    assert json.loads(f.readline())["ok"]  # stall never blocks service
    # the stalled connection is CLOSED server-side at the deadline
    assert staller.recv(1) == b""
    staller.close()
    healthy.close()
    t.join(timeout=60)
    assert not t.is_alive()


def test_serve_socket_oversized_line_is_rejected_not_buffered(rng,
                                                              tmp_path):
    """Rev v2.7 reader containment: a request line past --max-body-bytes
    is answered ``line_too_long`` and the connection closed -- the line
    never reaches the parser or the batching queue."""
    import socket

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    t, sock_path = _socket_serve_thread(
        tmp_path, ["--max-body-bytes", "4096"], max_requests=1)
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rw")
    f.write(json.dumps({"id": 9, "model": "m", "op": "score",
                        "x": data[:500].tolist()}) + "\n")
    f.flush()
    resp = json.loads(f.readline())
    assert not resp["ok"] and resp["error"] == "line_too_long"
    assert f.readline() == ""              # connection closed after it
    c.close()
    # a bounded request on a fresh connection still serves
    c2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c2.connect(sock_path)
    f2 = c2.makefile("rw")
    f2.write(json.dumps({"id": 1, "model": "m", "op": "score",
                         "x": data[:4].tolist()}) + "\n")
    f2.flush()
    assert json.loads(f2.readline())["ok"]
    c2.close()
    t.join(timeout=60)
    assert not t.is_alive()


# ------------------------------------------------------------------ export


def test_export_checkpoint_selects_best_k_not_last_step(rng, tmp_path):
    """The satellite contract: a sweep checkpoint's in-flight state is
    the LAST fitted K; export must pick best_state (the best-criterion
    configuration) and record the criterion + score in the manifest."""
    data, _ = make_blobs(rng, n=600, d=3, k=3, dtype=np.float32)
    ck = str(tmp_path / "ck")
    res = fit_gmm(data, 6, 0,
                  config=GMMConfig(min_iters=3, max_iters=3,
                                   chunk_size=256, checkpoint_dir=ck))
    assert res.ideal_num_clusters < 6  # the sweep really merged
    reg = ModelRegistry(str(tmp_path / "reg"))
    v = reg.export_checkpoint(ck, "swept")
    m = reg.load("swept", v)
    assert m.manifest["criterion"] == "rissanen"
    assert m.manifest["source"] == "checkpoint"
    assert np.isclose(m.manifest["score"], res.min_rissanen)
    # best-scoring K, not the last step's in-flight K (which is < ideal
    # by the end of the sweep)
    assert m.k == res.ideal_num_clusters
    np.testing.assert_array_equal(m.data_shift,
                                  np.asarray(res.data_shift, np.float64))
    gm = GaussianMixture.from_registry(reg, "swept")
    # identical best parameters => identical scores on fresh rows
    ref = GaussianMixture(6, config=GMMConfig(min_iters=3, max_iters=3,
                                              chunk_size=256))
    ref.result_, ref._model = res, None
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel

    ref._model = GMMModel(ref.config)
    assert np.array_equal(gm.score_samples(data[:50]),
                          ref.score_samples(data[:50]))


def test_export_cli_checkpoint_and_summary(rng, tmp_path, capsys):
    from cuda_gmm_mpi_tpu.cli import main
    from cuda_gmm_mpi_tpu.io import write_summary

    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    ck = str(tmp_path / "ck")
    res = fit_gmm(data, 5, 0,
                  config=GMMConfig(min_iters=2, max_iters=2,
                                   chunk_size=256, checkpoint_dir=ck))
    reg_dir = str(tmp_path / "reg")
    assert main(["export", "--registry", reg_dir, "--name", "a",
                 "--checkpoint", ck]) == 0
    out = capsys.readouterr().out
    assert "exported 'a' version 1" in out and "rissanen=" in out

    summary = str(tmp_path / "model.summary")
    write_summary(summary, res)
    assert main(["export", "--registry", reg_dir, "--name", "b",
                 "--summary", summary]) == 0
    reg = ModelRegistry(reg_dir)
    assert reg.load("b").manifest["source"] == "summary"
    # bad source fails loudly with rc 1, not a traceback
    assert main(["export", "--registry", reg_dir, "--name", "c",
                 "--checkpoint", str(tmp_path / "nothing")]) == 1


def test_export_empty_checkpoint_is_loud(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RegistryError, match="no sweep checkpoints"):
        reg.export_checkpoint(str(tmp_path / "missing"), "x")


# ------------------------------------------------- resilience (rev v1.7)
#
# The serving resilience layer (docs/ROBUSTNESS.md "Serving"): graceful
# drain under the run supervisor, bounded-queue load shedding, request
# deadlines, registry hot-reload, and per-route circuit breakers -- each
# rehearsed deterministically via the serve-path fault injections.

import threading
import time

from cuda_gmm_mpi_tpu import supervisor as supervisor_mod
from cuda_gmm_mpi_tpu import telemetry
from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream
from cuda_gmm_mpi_tpu.testing import faults


def _collecting_reply(bucket):
    def reply(resp):
        bucket.append(resp)
    return reply


def _req(i, data, n=4, model="m", **extra):
    return {"id": i, "model": model, "op": "score_samples",
            "x": data[i * n:(i + 1) * n].tolist(), **extra}


def test_socket_mode_conflicts_with_input_output_loudly(tmp_path):
    """The satellite contract: --socket with --input/--output used to be
    silently ignored; now it is an argparse error (exit 2)."""
    from cuda_gmm_mpi_tpu.serving.server import serve_main

    for extra in (["--input", "r.jsonl"], ["--output", "o.jsonl"]):
        with pytest.raises(SystemExit) as exc:
            serve_main(["--registry", str(tmp_path / "reg"),
                        "--socket", str(tmp_path / "s.sock")] + extra)
        assert exc.value.code == 2


def test_drain_flushes_queue_and_sheds_late_arrivals(rng, tmp_path):
    """The graceful-drain contract: a supervisor stop observed by the
    tick loop flushes every ADMITTED request (real responses), returns
    reason 'preempted', and post-drain arrivals answer shutting_down
    without being queued."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    got = []
    for i in range(3):
        server.submit_line(json.dumps(_req(i, data)),
                           _collecting_reply(got))
    sup = supervisor_mod.RunSupervisor(install_signals=False)
    sup.request_stop("sigterm")
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), supervisor_mod.use(sup):
        reason = server.run_loop()
    assert reason == "preempted"
    assert server.draining and server.drain_reason == "sigterm"
    # every admitted request was flushed with its real answer
    assert sorted(r["id"] for r in got) == [0, 1, 2]
    assert all(r["ok"] for r in got)
    np.testing.assert_array_equal(
        np.asarray(got[0]["result"], np.float32),
        gm.score_samples(data[0:4]))
    # a post-drain arrival is shed, never queued
    late = []
    server.submit_line(json.dumps(_req(9, data)),
                       _collecting_reply(late))
    assert late and not late[0]["ok"]
    assert late[0]["error"] == "shutting_down"
    # the supervisor's preempt event rode the stream from the poll site
    events = [r["event"] for r in stream]
    assert "preempt" in events
    preempt = next(r for r in stream if r["event"] == "preempt")
    assert preempt["where"] == "serve" and preempt["reason"] == "sigterm"


class _StreamSink:
    """Minimal text-stream sink decoding records into a list."""

    def __init__(self, records):
        self._records = records

    def write(self, line):
        self._records.append(json.loads(line))

    def flush(self):
        pass


def test_overload_sheds_and_survivors_are_unharmed(rng, tmp_path):
    """Bounded admission: arrivals past --max-queue-rows shed with
    'overloaded' on the reader thread; already-queued requests still get
    their exact results; a shed is a serve_shed record."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    from cuda_gmm_mpi_tpu.serving.server import _Pending

    server = GMMServer(ModelRegistry(str(tmp_path)), max_queue_rows=8)
    got, shed = [], []
    for i in range(2):   # 2 x 4 rows fill the bound exactly
        assert server.submit(_Pending(_req(i, data),
                                      _collecting_reply(got)))
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        for i in (2, 3):  # queue full: these must shed immediately
            server.submit_line(json.dumps(_req(i, data)),
                               _collecting_reply(shed))
        assert [r["error"] for r in shed] == ["overloaded"] * 2
        reason = server.run_loop(idle_timeout_s=0.4)
    assert reason == "idle"
    assert server.shed == 2
    assert sorted(r["id"] for r in got) == [0, 1] and all(
        r["ok"] for r in got)
    np.testing.assert_array_equal(
        np.asarray(got[0]["result"], np.float32),
        gm.score_samples(data[0:4]))
    sheds = [r for r in stream if r["event"] == "serve_shed"]
    assert len(sheds) == 2
    assert sheds[0]["reason"] == "overloaded"
    assert sheds[0]["max_queue_rows"] == 8
    assert validate_stream(stream) == []


def test_oversized_request_admitted_only_against_empty_queue(rng,
                                                             tmp_path):
    """A request wider than the whole bound must not be rejected forever:
    it is admitted when the queue is empty (it can never fit better)."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)), max_queue_rows=4)
    got = []
    from cuda_gmm_mpi_tpu.serving.server import _Pending

    big = {"id": 0, "model": "m", "op": "score",
           "x": data[:32].tolist()}
    assert server.submit(_Pending(big, _collecting_reply(got)))
    # queue now holds 32 rows > bound: the next request sheds
    assert not server.submit(_Pending(_req(1, data),
                                      _collecting_reply(got)))
    assert server.run_loop(idle_timeout_s=0.3) == "idle"
    ok = [r for r in got if r.get("ok")]
    assert len(ok) == 1 and ok[0]["id"] == 0


def test_deadline_expired_rejected_before_dispatch(rng, tmp_path):
    """A request whose budget ran out while queued answers
    deadline_expired BEFORE dispatch (no executor call, batches
    counter unmoved); an unexpired sibling in the same tick serves."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    got = []
    server.submit_line(json.dumps(_req(0, data, deadline_ms=1)),
                       _collecting_reply(got))
    server.submit_line(json.dumps(_req(1, data, deadline_ms=60_000)),
                       _collecting_reply(got))
    time.sleep(0.05)  # let request 0's budget lapse in the queue
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    batches_before = server.batches
    with telemetry.use(rec):
        assert server.run_loop(idle_timeout_s=0.3) == "idle"
    by_id = {r["id"]: r for r in got}
    assert not by_id[0]["ok"] and by_id[0]["error"] == "deadline_expired"
    assert by_id[1]["ok"]
    assert server.deadline_expired == 1
    assert server.batches == batches_before + 1  # only the survivor ran
    dl = [r for r in stream if r["event"] == "serve_deadline"]
    assert len(dl) == 1 and dl[0]["waited_ms"] >= dl[0]["deadline_ms"]
    assert validate_stream(stream) == []
    # bad deadline type is a loud per-request error
    bad = []
    server.submit_line(json.dumps(_req(2, data, deadline_ms="soon")),
                       _collecting_reply(bad))
    server.run_loop(idle_timeout_s=0.2)
    assert bad and "deadline_ms" in bad[0]["error"]


def test_coalesced_tick_parity_under_serve_slow(rng, tmp_path):
    """Injected dispatch latency (serve_slow) changes walls, never
    results: the coalesced batch equals the per-request loop bit for
    bit, and the injection really fired."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = serve_requests(data)
    baseline = server.handle_requests(reqs, coalesce=False)
    with faults.use({"serve_slow": {"ms": 30, "times": 1}}) as plan:
        t0 = time.perf_counter()
        slow = server.handle_requests(reqs, coalesce=True)
        wall = time.perf_counter() - t0
    assert plan.fired["serve_slow"] == 1
    assert wall >= 0.03
    for a, b in zip(slow, baseline):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b


def test_circuit_breaker_open_halfopen_close_lifecycle(rng, tmp_path):
    """The breaker acceptance path: a NaN-scoring model fails requests
    (non_finite_scores), trips open at the threshold, fast-fails with
    circuit_open while OTHER models keep serving, half-opens after the
    backoff, and a healthy probe closes it -- with matching v1.7
    circuit events."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "healthy")
    server = GMMServer(reg, breaker_threshold=2,
                       breaker_backoff_s=0.05)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    X = data[:5].tolist()

    def ask(model="m"):
        return server.handle_requests(
            [{"id": 0, "model": model, "op": "score", "x": X}])[0]

    with telemetry.use(rec), faults.use(
            {"serve_nan": {"model": "m", "times": 2}}) as plan:
        r1, r2 = ask(), ask()           # two poisoned dispatches
        assert not r1["ok"] and r1["error"] == "non_finite_scores"
        assert not r2["ok"]
        assert plan.fired["serve_nan"] == 2
        assert server.breaker.state(("m", None)) == "open"
        r3 = ask()                      # fast-fail, no dispatch
        assert not r3["ok"] and r3["error"] == "circuit_open"
        assert server.breaker_fastfails == 1
        # containment: the sibling model is untouched by m's breaker
        r_other = ask("healthy")
        assert r_other["ok"]
        time.sleep(0.15)                # > 0.05 * 1.25 jitter ceiling
        r4 = ask()                      # half-open probe, now healthy
        assert r4["ok"], r4
        assert server.breaker.state(("m", None)) == "closed"
    states = [r["state"] for r in stream if r["event"] == "circuit"]
    assert states == ["open", "half_open", "closed"]
    opened = next(r for r in stream if r["event"] == "circuit")
    assert opened["model"] == "m" and opened["reason"] == "non_finite"
    assert opened["failures"] == 2 and opened["backoff_s"] > 0
    assert validate_stream(stream) == []
    assert server.breaker.stats() == {
        "trips": 1, "closes": 1, "open_routes": 0}


def test_breaker_counts_registry_failures(rng, tmp_path):
    """RegistryError at resolve is a route failure too: repeated torn
    loads open the breaker; a later good load closes it via the
    half-open probe."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    server = GMMServer(reg, breaker_threshold=2,
                       breaker_backoff_s=0.01, warm=False)
    X = data[:3].tolist()

    def ask():
        return server.handle_requests(
            [{"id": 0, "model": "m", "version": 1, "op": "score",
              "x": X}])[0]

    with faults.use({"registry_torn": {"name": "m", "times": 2}}):
        assert "registry_torn" in ask()["error"]
        assert "registry_torn" in ask()["error"]
        assert server.breaker.state(("m", 1)) == "open"
    time.sleep(0.05)
    assert ask()["ok"]  # probe resolves cleanly -> closed
    assert server.breaker.state(("m", 1)) == "closed"


def test_registry_torn_injection_walks_back(rng, tmp_path):
    """registry_torn composes with the default-resolution walk-back:
    the newest version 'tears', load(name) warns and serves the
    previous one -- the hot-reload skip path in miniature."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    gm.to_registry(reg, "m")
    with faults.use({"registry_torn": {"version": 2}}):
        with pytest.warns(RuntimeWarning, match="version 2 unreadable"):
            assert reg.load("m").version == 1
    assert reg.load("m").version == 2  # budget consumed: healthy again


def test_registry_poll_fingerprints_new_versions(rng, tmp_path):
    """ModelRegistry.poll detects a new export via the manifest
    fingerprint and reports only changed models."""
    gm, _ = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    snap = {}
    changed = reg.poll(snap)
    assert set(changed) == {"m"} and changed["m"][0] == 1
    snap.update(changed)
    assert reg.poll(snap) == {}        # stable: no spurious reloads
    gm.to_registry(reg, "m")           # v2 lands
    changed = reg.poll(snap)
    assert set(changed) == {"m"} and changed["m"][0] == 2


def test_hot_reload_swaps_default_route_bit_parity(rng, tmp_path):
    """The acceptance contract: a mid-serve export atomically re-pins
    the version=None route (new results == direct v2 scoring, bit for
    bit) while the explicitly pinned old version keeps serving its old
    bits; serve_reload telemetry + counter recorded; the old version's
    prepared executor state is released."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")          # v1
    server = GMMServer(reg)
    X = data[:9].tolist()

    def ask(**extra):
        return server.handle_requests(
            [{"id": 0, "model": "m", "op": "score_samples", "x": X,
              **extra}])[0]

    r_v1 = ask()
    assert r_v1["version"] == 1
    assert server.maybe_reload() == []  # nothing new: no-op
    # a visibly different v2 lands mid-serve (the `gmm export` analog)
    gm2 = GaussianMixture.from_registry(reg, "m")
    gm2.result_.state = gm2.result_.state.replace(
        means=gm2.result_.state.means + 0.5)
    reg.save("m", gm2.result_, config=gm2.config)
    old_model = server._models[("m", None)]
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        swaps = server.maybe_reload()
    assert swaps == [{"model": "m", "from_version": 1,
                      "to_version": 2}]
    assert server.reloads == 1
    # the replaced version's prepared state left the executor memo (a
    # later pinned-version request re-prepares it lazily)
    ex = server._executor_for(old_model)
    assert not any(v[0] is old_model.state
                   for v in ex._state_memo.values())
    r_new = ask()
    assert r_new["version"] == 2
    # bit-parity: the swapped route scores exactly as a fresh v2 load
    gm_v2 = GaussianMixture.from_registry(reg, "m", version=2)
    np.testing.assert_array_equal(
        np.asarray(r_new["result"], np.float32),
        gm_v2.score_samples(np.asarray(X, np.float32)))
    # ...and the pinned old version still serves its exact old bits
    r_pin = ask(version=1)
    assert r_pin["version"] == 1 and r_pin["result"] == r_v1["result"]
    events = [r for r in stream if r["event"] == "serve_reload"]
    assert len(events) == 1 and events[0]["to_version"] == 2
    assert validate_stream(stream) == []


def test_run_loop_hot_reloads_between_ticks(rng, tmp_path):
    """End to end through run_loop's --reload-interval-s path: an export
    while the loop idles swaps the route before the next dispatch."""
    gm, data = fitted(rng)
    reg = ModelRegistry(str(tmp_path))
    gm.to_registry(reg, "m")
    server = GMMServer(reg)
    server.resolve("m")               # pin the default route at v1
    got = []
    t = threading.Thread(
        target=lambda: server.run_loop(idle_timeout_s=2.0,
                                       reload_interval_s=0.05),
        daemon=True)
    t.start()
    try:
        gm.to_registry(reg, "m")      # v2 lands mid-serve
        deadline = time.monotonic() + 5.0
        while server.reloads == 0:
            assert time.monotonic() < deadline, "reload never happened"
            time.sleep(0.02)
        server.submit_line(json.dumps(_req(0, data)),
                           _collecting_reply(got))
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        server._stop.set()
        t.join(timeout=10)
    assert got and got[0]["ok"] and got[0]["version"] == 2


def test_serve_summary_carries_resilience_counters(rng, tmp_path):
    """serve_summary (rev v1.7) rolls up shed/deadline/breaker/reload
    counters and validates; gmm report renders the resilience line."""
    from cuda_gmm_mpi_tpu.telemetry.report import render_report

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)), max_queue_rows=4)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        server.submit_line(json.dumps(_req(0, data)),
                           _collecting_reply([]))
        server.submit_line(json.dumps(_req(1, data)),
                           _collecting_reply([]))  # sheds (queue full)
        server.run_loop(idle_timeout_s=0.3)
        server.emit_summary()
    summary = next(r for r in stream if r["event"] == "serve_summary")
    assert summary["shed"] == 1
    assert summary["deadline_expired"] == 0
    assert summary["reloads"] == 0
    assert summary["breaker"]["trips"] == 0
    assert summary["metrics"]["counters"]["serve_sheds"] == 1
    assert validate_stream(stream) == []
    text = render_report(stream)
    assert "resilience:" in text and "1 shed" in text


def test_serve_cli_sigterm_drains_and_exits_75(rng, tmp_path):
    """The PR-4 exit-code contract for `gmm serve`, with a REAL signal
    (mirror of test_preemption's SIGTERM CLI test): SIGTERM a serving
    subprocess under load -> graceful drain, exit 75, and a v1.7-valid
    stream carrying preempt(where=serve) -> serve_summary -> shutdown."""
    import signal
    import socket
    import subprocess
    import sys

    from cuda_gmm_mpi_tpu.telemetry import read_stream

    from .conftest import communicate_or_kill, worker_env

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    sock_path = str(tmp_path / "gmm.sock")
    metrics = str(tmp_path / "serve.jsonl")
    p = subprocess.Popen(
        [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "serve",
         "--registry", str(tmp_path / "reg"), "--socket", sock_path,
         "--device", "cpu", "--metrics-file", metrics],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=worker_env(), text=True)
    try:
        deadline = time.monotonic() + 120.0
        while not os.path.exists(sock_path):
            assert p.poll() is None, p.communicate()
            assert time.monotonic() < deadline, "socket never appeared"
            time.sleep(0.05)
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.connect(sock_path)
        f = c.makefile("rw")
        f.write(json.dumps(_req(0, data)) + "\n")
        f.flush()
        first = json.loads(f.readline())
        assert first["ok"]            # the loop is live and serving
        p.send_signal(signal.SIGTERM)
        out_, err_ = communicate_or_kill(p, timeout=120)
        c.close()
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=60)
    assert p.returncode == 75, f"expected EX_TEMPFAIL:\n{out_}\n{err_}"
    assert "Preempted" in err_
    records = read_stream(metrics)
    assert validate_stream(records) == []
    events = [r["event"] for r in records]
    assert "preempt" in events and "shutdown" in events
    assert "serve_summary" in events
    preempt = next(r for r in records if r["event"] == "preempt")
    assert preempt["where"] == "serve"
    assert preempt["reason"] == "sigterm"
    shutdown = next(r for r in records if r["event"] == "shutdown")
    assert shutdown["reason"] == "sigterm"
    assert shutdown["checkpointed"] is False


def test_serve_cli_startup_failure_exits_1(tmp_path):
    """Exit-code contract: an unloadable model set is a startup failure
    (rc 1), not a traceback."""
    from cuda_gmm_mpi_tpu.serving.server import serve_main

    os.makedirs(tmp_path / "reg", exist_ok=True)
    rc = serve_main(["--registry", str(tmp_path / "reg"),
                     "--models", "ghost",
                     "--input", os.devnull,
                     "--output", str(tmp_path / "o.jsonl")])
    assert rc == 1


# ------------------------------------------------- stacked cross-model


def _two_family_models(rng, tmp_path):
    """Two different models of ONE numeric family (same D/dtype/full)."""
    reg = ModelRegistry(str(tmp_path))
    gm1, data1 = fitted(rng, k=3, d=4)
    gm2, data2 = fitted(rng, k=5, d=4, n=700)
    gm1.to_registry(reg, "m1")
    gm2.to_registry(reg, "m2")
    return reg, data1, data2


def _mixed_requests(data1, data2):
    return [
        {"id": 0, "model": "m1", "op": "score_samples",
         "x": data1[:40].tolist()},
        {"id": 1, "model": "m2", "op": "predict_proba",
         "x": data2[:17].tolist()},
        {"id": 2, "model": "m1", "op": "predict",
         "x": data1[50:75].tolist()},
        {"id": 3, "model": "m2", "op": "score",
         "x": data2[20:60].tolist()},
    ]


def test_stacked_cross_model_dispatch_parity(rng, tmp_path):
    """The satellite fix for the per-(model, version)-only tick loop:
    with --stack-models, one tick's groups for DIFFERENT models of one
    family ride ONE stacked executable call -- and every response is
    BIT-identical to the per-request dispatch baseline (the PR-7
    coalescing-parity contract, extended across models). The stacked
    executable maps lanes with lax.map, so each model's arithmetic is
    the solo executable's exact HLO."""
    reg, data1, data2 = _two_family_models(rng, tmp_path)
    stacked_srv = GMMServer(reg, warm=False, stack_models=True)
    plain_srv = GMMServer(reg, warm=False)
    reqs = _mixed_requests(data1, data2)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        got = stacked_srv.handle_requests(reqs, coalesce=True)
    want = plain_srv.handle_requests(reqs, coalesce=False)
    assert stacked_srv.stacked_batches == 1
    for a, b in zip(got, want):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    batches = [r for r in stream if r["event"] == "serve_batch"]
    assert len(batches) == 2  # one record per route, same stacked call
    assert all(r.get("stacked") == 2 for r in batches)
    assert validate_stream(stream) == []


def test_stacked_dispatch_poison_isolated_per_route(rng, tmp_path):
    """A poisoned model inside a stacked family fails ONLY its own
    route: the non-finite check runs per lane, its breaker counts one
    failure, and the sibling's responses stay bit-identical."""
    reg, data1, data2 = _two_family_models(rng, tmp_path)
    server = GMMServer(reg, warm=False, stack_models=True,
                       breaker_threshold=3)
    baseline = GMMServer(reg, warm=False).handle_requests(
        _mixed_requests(data1, data2), coalesce=False)
    with faults.use({"serve_nan": {"model": "m2", "times": 1}}) as plan:
        got = server.handle_requests(_mixed_requests(data1, data2),
                                     coalesce=True)
    assert plan.fired["serve_nan"] == 1
    by_id = {r["id"]: r for r in got}
    want = {r["id"]: r for r in baseline}
    for i in (0, 2):  # m1 requests: untouched, bit-identical
        a = {k: v for k, v in by_id[i].items() if k != "latency_ms"}
        b = {k: v for k, v in want[i].items() if k != "latency_ms"}
        assert a == b
    for i in (1, 3):  # m2 requests: contained failure
        assert not by_id[i]["ok"]
        assert by_id[i]["error"] == "non_finite_scores"
    # Only m2's route breaker observed the failure.
    assert server.breaker.stats()["trips"] == 0
    out = server.handle_requests(_mixed_requests(data1, data2),
                                 coalesce=True)
    assert all(r["ok"] for r in out)


def test_stacked_falls_back_per_model_when_family_is_single(rng,
                                                            tmp_path):
    """One tick, two models of DIFFERENT D: no shared family, so the
    stacked path dispatches each per-model -- responses still match the
    per-request baseline and no stacked batch is counted."""
    reg = ModelRegistry(str(tmp_path))
    gm1, data1 = fitted(rng, k=3, d=4)
    gm2, data2 = fitted(rng, k=3, d=3)
    gm1.to_registry(reg, "m1")
    gm2.to_registry(reg, "m2")
    server = GMMServer(reg, warm=False, stack_models=True)
    reqs = [
        {"id": 0, "model": "m1", "op": "score", "x": data1[:9].tolist()},
        {"id": 1, "model": "m2", "op": "score", "x": data2[:9].tolist()},
    ]
    got = server.handle_requests(reqs, coalesce=True)
    want = GMMServer(reg, warm=False).handle_requests(reqs,
                                                      coalesce=False)
    assert server.stacked_batches == 0
    for a, b in zip(got, want):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b


# ------------------------------------------------- drift plane (rev v2.4)
#
# S3 contracts of the drift-observability PR (docs/OBSERVABILITY.md
# "Drift detection"): the plane adds ZERO compiles on a warmed serve
# path (it samples the already-answered host block), and a drift-off
# server is byte-identical to pre-v2.4 behavior -- same responses, no
# drift records in the stream, no drift gauges on /metrics.


def test_drift_plane_warm_path_zero_recompile(rng, tmp_path):
    """The PR-7 zero-recompile contract survives the drift plane: after
    per-bucket warm-up, varying-N traffic (in-distribution AND shifted)
    with drift enabled performs no new traces or compiles -- sketching
    happens on the host block the answers are sliced from."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    ex = ScoringExecutor(min_block=32, max_block=256)
    server = GMMServer(ModelRegistry(str(tmp_path)), executor=ex,
                       warm=False, drift_interval_s=3600.0,
                       drift_psi_threshold=0.2)
    for n in (32, 64, 128, 256):     # warm one request per bucket
        server.handle_requests([{"id": 0, "model": "m", "op": "score",
                                 "x": data[:n].tolist()}])
    server.flush_drift()             # discard the warm-up window
    c0 = ex.compile_count
    for i, n in enumerate(rng.integers(1, 257, size=40)):
        shift = 8.0 if i % 2 else 0.0
        x = (data[:int(n)] + np.float32(shift)).tolist()
        resp = server.handle_requests(
            [{"id": i, "model": "m", "op": "score_samples", "x": x}])[0]
        assert resp["ok"]
    rows = server.flush_drift()
    assert ex.compile_count == c0, "drift plane traced/compiled"
    assert rows and rows[0]["window_rows"] > 0


def test_drift_off_server_is_byte_identical(rng, tmp_path):
    """Plane-off contract (the PR-13 shape): without --drift-interval-s
    the responses equal a drift-on server's bit for bit, the telemetry
    stream carries NO drift/drift_alarm records, and /metrics exposes
    no drift gauges."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    reg = ModelRegistry(str(tmp_path))
    off = GMMServer(reg)
    on = GMMServer(reg, drift_interval_s=3600.0)
    reqs = serve_requests(data)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        got_off = off.handle_requests(reqs)
        off.flush_drift()
    got_on = on.handle_requests(reqs)
    on.flush_drift()
    for a, b in zip(got_off, got_on):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    kinds = {r["event"] for r in stream}
    assert "drift" not in kinds and "drift_alarm" not in kinds
    assert off.drift_stats()["windows"] == 0
    assert not any(k.startswith("gmm_drift") for k in off.live_gauges())
    assert any(k.startswith("gmm_drift") for k in on.live_gauges())


# --------------------------------- data plane (rev v2.8) ----------------
#
# Serving data-plane overhaul contracts (docs/SERVING.md "Binary
# payloads" / "Adaptive micro-batching", docs/OBSERVABILITY.md):
# malformed-x hardening at admission, device-resident pinned routes with
# the serve.host_staging audit counter, the bounded adaptive window
# controller (never outside [tick_s_min, tick_s_max], never past a
# request's deadline budget), auto-stacking hysteresis, stacked
# fallthrough reconciliation, and the binary socket frames.


def test_malformed_x_answers_bad_request(rng, tmp_path):
    """Satellite hardening: ragged or non-numeric 'x' is caught at
    ADMISSION and answers the machine token ``bad_request`` (HTTP 400
    via status_for_error) -- it never reaches the tick loop, and batch
    mates are unharmed."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    reqs = [
        {"id": 0, "model": "m", "op": "score",
         "x": [[1.0, 2.0, 3.0, 4.0], [5.0, 6.0]]},       # ragged
        {"id": 1, "model": "m", "op": "score",
         "x": [["a", "b", "c", "d"]]},                    # non-numeric
        {"id": 2, "model": "m", "op": "score", "x": {"not": "rows"}},
        {"id": 3, "model": "m", "op": "score", "x": data[:2].tolist()},
    ]
    resps = {r["id"]: r for r in server.handle_requests(reqs)}
    for i in (0, 1, 2):
        assert not resps[i]["ok"]
        assert resps[i]["error"] == "bad_request"
    assert resps[3]["ok"]
    # the reader-thread path (admit_request) answers inline, pre-queue
    got = []
    admitted = server.admit_request(
        {"id": 9, "model": "m", "op": "score", "x": [[1.0], [2.0, 3.0]]},
        _collecting_reply(got))
    assert admitted is False
    assert got and got[0]["error"] == "bad_request"
    assert server._queue.qsize() == 0


def test_warm_routes_are_pinned_and_never_host_stage(rng, tmp_path):
    """Device-resident routes: resolve pins the prepared state ONCE;
    warm traffic (varied N, all ops) performs ZERO dispatch-time host
    stagings -- the serve.host_staging counter every layer (executor
    stats, server counter, /metrics gauge, serve_summary) reads 0.
    Deliberately on the process-shared family executor: counts are
    baselined at adoption, so another surface's stagings (estimator
    ops, a sibling server, earlier tests) never leak in."""
    gm, data = fitted(rng)
    # pollute the SHARED family executor before the server adopts it
    gm.score_samples(data[:5])
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        for i, n in enumerate((7, 19, 3, 41, 11)):
            resp = server.handle_requests(
                [{"id": i, "model": "m", "op": "score_samples",
                  "x": data[:n].tolist()}])[0]
            assert resp["ok"]
        server.emit_summary()
    stats = server.executor_stats()
    assert stats["pinned_states"] >= 1
    assert stats["host_stagings"] == 0
    assert server.host_stagings == 0
    assert server.live_gauges()["gmm_serve_host_stagings"] == 0.0
    assert server.live_gauges()["gmm_executor_pinned_states"] >= 1.0
    summary = next(r for r in stream if r["event"] == "serve_summary")
    assert summary["executor"]["host_stagings"] == 0
    assert validate_stream(stream) == []


def test_release_state_unpins_and_restage_is_counted(rng):
    """The pin lifecycle mirrors release_state (hot-reload/eviction):
    releasing drops the pinned entry, and a LATER preparation of that
    state is a counted host staging -- the observable fallback."""
    gm, _ = fitted(rng)
    ex = ScoringExecutor()
    state = gm.result_.state
    ex.pin_state(state)
    assert ex.stats()["pinned_states"] == 1
    assert ex.prepared_state(state) is not None
    assert ex.stats()["host_stagings"] == 0   # pinned hit, no staging
    assert ex.release_state(state) >= 1
    assert ex.stats()["pinned_states"] == 0
    ex.prepared_state(state)
    assert ex.stats()["host_stagings"] == 1


def test_adaptive_window_never_leaves_bounds(rng, tmp_path):
    """Property: over a random mix of backlog/idle/normal windows the
    controller NEVER moves the window outside [tick_s_min, tick_s_max],
    and every serve_window record it emits carries an in-bounds
    window_ms."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    lo, hi = 0.001, 0.016
    server = GMMServer(ModelRegistry(str(tmp_path)), warm=False,
                       tick_s_min=lo, tick_s_max=hi)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        for _ in range(300):
            roll = int(rng.integers(0, 3))
            if roll == 0:  # backlog window: leave items in the queue
                server._queue.put(_Pending_dummy())
            requests = int(rng.integers(0, 5))
            server._observe_window(requests)
            assert lo <= server._tick_cur <= hi, (
                f"window {server._tick_cur} escaped [{lo}, {hi}]")
            while server._queue.qsize():
                server._queue.get_nowait()
    windows = [r for r in stream if r["event"] == "serve_window"]
    assert windows, "the random schedule never adapted once"
    reasons = {r["reason"] for r in windows}
    assert reasons <= {"backlog", "idle"}
    for r in windows:
        assert lo * 1e3 <= r["window_ms"] <= hi * 1e3
    assert validate_stream(stream) == []


def _Pending_dummy():
    from cuda_gmm_mpi_tpu.serving.server import _Pending
    return _Pending({"model": "m", "op": "score", "x": [[0.0] * 4]},
                    lambda resp: None)


def test_adaptive_window_respects_deadline_budget(rng, tmp_path):
    """A window widened PAST a request's whole deadline budget must not
    starve it: the gather loop spends at most half the remaining budget
    waiting, so the answer still lands inside the deadline instead of
    expiring at it."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)), warm=False,
                       tick_s_min=0.001, tick_s_max=5.0)
    # warm the route so dispatch cost is not compile cost
    server.handle_requests([{"id": 0, "model": "m", "op": "score",
                             "x": data[:4].tolist()}])
    server._tick_cur = 5.0  # the controller widened all the way out
    got = []
    t0 = time.perf_counter()
    server.submit_line(json.dumps(_req(0, data, deadline_ms=800.0)),
                       _collecting_reply(got))
    server.run_loop(max_requests=server.requests + 1)
    waited = time.perf_counter() - t0
    assert got and got[0]["ok"], got
    assert waited < 2.0, (
        f"a 5s window starved an 800ms-deadline request for {waited}s")


def test_adaptive_auto_stack_hysteresis(rng, tmp_path):
    """Auto-stacking: three consecutive windows with a same-family pair
    flip stacked dispatch ON (serve_window auto_stack_on), stacked
    responses stay bit-identical to the solo baseline, and sustained
    single-route windows flip it back OFF."""
    reg, data1, data2 = _two_family_models(rng, tmp_path)
    server = GMMServer(reg, warm=False, tick_s_min=0.0,
                       tick_s_max=0.002)
    baseline = GMMServer(reg, warm=False)
    reqs = _mixed_requests(data1, data2)
    want = baseline.handle_requests(reqs, coalesce=False)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        # the first window resolves the routes but cannot count toward
        # the streak -- the stackability probe is registry-IO-free, so
        # unresolved routes are invisible to it
        server.handle_requests(reqs, coalesce=True)
        assert server._auto_stack is False
        for _ in range(2):           # two counted windows build streak
            got = server.handle_requests(reqs, coalesce=True)
            assert server._auto_stack is False
            assert server.stacked_batches == 0
        # the third counted window completes the streak AND rides the
        # stacked dispatch it just enabled
        got = server.handle_requests(reqs, coalesce=True)
        assert server._auto_stack is True
        assert server.stacked_batches == 1
        for a, b in zip(got, want):
            a = {k: v for k, v in a.items() if k != "latency_ms"}
            b = {k: v for k, v in b.items() if k != "latency_ms"}
            assert a == b
        solo = [{"id": 0, "model": "m1", "op": "score",
                 "x": data1[:4].tolist()}]
        for _ in range(16):          # the OFF streak
            server.handle_requests(solo, coalesce=True)
        assert server._auto_stack is False
    flips = [r for r in stream if r["event"] == "serve_window"
             and r["reason"].startswith("auto_stack")]
    assert [r["reason"] for r in flips] == ["auto_stack_on",
                                            "auto_stack_off"]
    assert flips[0]["stacked_auto"] is True
    assert flips[1]["stacked_auto"] is False
    assert validate_stream(stream) == []


def test_stacked_fallthrough_is_counted_not_silent(rng, tmp_path):
    """Satellite fix: a same-family group whose rows exceed max_block
    cannot ride the stacked call -- it dispatches solo, its serve_batch
    carries NO `stacked` field, and serve_summary.stacked_fallthrough
    counts it so stacked_batches reconciles against dispatch counts."""
    reg = ModelRegistry(str(tmp_path))
    gm1, data1 = fitted(rng, k=3, d=4)
    gm2, data2 = fitted(rng, k=5, d=4, n=700)
    gm3, data3 = fitted(rng, k=4, d=4, n=700)
    gm1.to_registry(reg, "m1")
    gm2.to_registry(reg, "m2")
    gm3.to_registry(reg, "m3")
    ex = ScoringExecutor(min_block=8, max_block=32)
    server = GMMServer(reg, executor=ex, warm=False, stack_models=True)
    reqs = [
        {"id": 0, "model": "m1", "op": "score_samples",
         "x": data1[:10].tolist()},
        {"id": 1, "model": "m2", "op": "score_samples",
         "x": data2[:12].tolist()},
        {"id": 2, "model": "m3", "op": "score_samples",
         "x": data3[:40].tolist()},     # 40 > max_block=32: fallthrough
    ]
    baseline = GMMServer(reg, executor=ScoringExecutor(
        min_block=8, max_block=32), warm=False).handle_requests(
        reqs, coalesce=False)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        got = server.handle_requests(reqs, coalesce=True)
        server.emit_summary()
    for a, b in zip(got, baseline):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    assert server.stacked_batches == 1
    assert server.stacked_fallthrough == 1
    batches = [r for r in stream if r["event"] == "serve_batch"]
    stacked = [r for r in batches if "stacked" in r]
    plain = [r for r in batches if "stacked" not in r]
    assert len(stacked) == 2 and len(plain) == 1
    assert plain[0]["model"] == "m3"
    summary = next(r for r in stream if r["event"] == "serve_summary")
    assert summary["stacked_fallthrough"] == 1
    # reconciliation: every serve_batch is either part of a stacked
    # call or accounted as fallthrough/unstackable -- nothing silent
    assert summary["metrics"]["counters"].get(
        "serve_stacked_fallthrough") == 1
    assert validate_stream(stream) == []


def test_fixed_tick_stream_is_unchanged_and_matches_adaptive(rng,
                                                             tmp_path):
    """Opt-in contract: WITHOUT --tick-min-ms/--tick-max-ms the stream
    carries no serve_window records, no summary `window` rollup, and no
    window gauges -- while an adaptive server's responses to the same
    requests stay bit-identical (scheduling never touches math)."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    reg = ModelRegistry(str(tmp_path))
    fixed = GMMServer(reg, warm=False)
    adaptive = GMMServer(reg, warm=False, tick_s_min=0.0,
                         tick_s_max=0.004)
    reqs = serve_requests(data)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        got_fixed = fixed.handle_requests(reqs)
        fixed.emit_summary()
    got_adaptive = adaptive.handle_requests(reqs)
    for a, b in zip(got_fixed, got_adaptive):
        a = {k: v for k, v in a.items() if k != "latency_ms"}
        b = {k: v for k, v in b.items() if k != "latency_ms"}
        assert a == b
    assert not any(r["event"] == "serve_window" for r in stream)
    summary = next(r for r in stream if r["event"] == "serve_summary")
    assert "window" not in summary
    assert not any(k.startswith("gmm_serve_window")
                   for k in fixed.live_gauges())
    assert any(k.startswith("gmm_serve_window")
               for k in adaptive.live_gauges())
    assert validate_stream(stream) == []


def test_server_rejects_inverted_tick_bounds(rng, tmp_path):
    gm, _ = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    with pytest.raises(ValueError, match="tick_s_min"):
        GMMServer(ModelRegistry(str(tmp_path)), tick_s_min=0.01,
                  tick_s_max=0.001)


def test_serve_cli_rejects_inverted_tick_bounds(tmp_path):
    from cuda_gmm_mpi_tpu.serving.server import serve_main

    with pytest.raises(SystemExit) as exc:
        serve_main(["--registry", str(tmp_path / "reg"),
                    "--socket", str(tmp_path / "s.sock"),
                    "--tick-min-ms", "4", "--tick-max-ms", "1"])
    assert exc.value.code == 2


def _socket_binary_payload(req: dict, rows) -> bytes:
    from cuda_gmm_mpi_tpu.serving import wire

    frame = wire.encode_rows(np.asarray(rows, np.float64))
    head = dict(req)
    head["x_bytes"] = len(frame)
    return (json.dumps(head) + "\n").encode("utf-8") + frame


def test_serve_socket_binary_frame_bit_identical(rng, tmp_path):
    """The JSONL socket's binary binding: a header line declaring
    x_bytes followed by one x-gmm-rows frame answers byte-identically
    to the same request spelled as JSON floats."""
    import socket

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    t, sock_path = _socket_serve_thread(tmp_path, [], max_requests=2)
    rows = data[:9].tolist()
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rwb")
    f.write((json.dumps({"id": 7, "model": "m", "op": "score_samples",
                         "x": rows}) + "\n").encode("utf-8"))
    f.write(_socket_binary_payload(
        {"id": 7, "model": "m", "op": "score_samples"}, rows))
    f.flush()
    raw_json = f.readline()
    raw_bin = f.readline()
    c.close()
    t.join(timeout=60)
    assert not t.is_alive()
    a, b = json.loads(raw_json), json.loads(raw_bin)
    assert a["ok"] and b["ok"]
    a.pop("latency_ms"), b.pop("latency_ms")
    assert a == b


def test_serve_socket_bad_frames_answer_bad_frame(rng, tmp_path):
    """Binary-frame hardening on the socket: a short read answers
    ``bad_frame`` and closes; an oversized declared frame answers
    ``frame_too_large`` BEFORE buffering and closes; a malformed frame
    body answers ``bad_frame`` and the stream continues (the length
    prefix kept it aligned)."""
    import socket

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    t, sock_path = _socket_serve_thread(
        tmp_path, ["--max-body-bytes", "4096"], max_requests=1)

    # oversized declared frame: rejected pre-buffering, connection ends
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rwb")
    f.write((json.dumps({"id": 0, "model": "m", "op": "score",
                         "x_bytes": 1 << 20}) + "\n").encode())
    f.flush()
    resp = json.loads(f.readline())
    assert not resp["ok"] and resp["error"] == "frame_too_large"
    assert f.readline() == b""  # server closed the stream
    c.close()

    # corrupt frame body behind an honest length prefix: answered, and
    # the SAME connection then serves a good request
    from cuda_gmm_mpi_tpu.serving import wire
    frame = bytearray(wire.encode_rows(data[:4].astype(np.float64)))
    frame[:4] = b"NOPE"
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(sock_path)
    f = c.makefile("rwb")
    f.write((json.dumps({"id": 1, "model": "m", "op": "score",
                         "x_bytes": len(frame)}) + "\n").encode()
            + bytes(frame))
    f.write(_socket_binary_payload(
        {"id": 2, "model": "m", "op": "score"}, data[:4].tolist()))
    f.flush()
    bad = json.loads(f.readline())
    good = json.loads(f.readline())
    assert not bad["ok"] and bad["error"] == "bad_frame"
    assert good["ok"] and good["id"] == 2
    c.close()
    t.join(timeout=60)
    assert not t.is_alive()
