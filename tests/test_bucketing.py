"""Bucketed cluster-width compaction for the order-search sweep (ISSUE 2).

Acceptance contract: bucketing on vs off yields the same selected K and
per-K trajectories (within float tolerance; in practice bitwise on CPU),
a K0 -> 1 sweep compiles at most ceil(log2 K0) + 1 distinct EM widths,
donated EM buffers change no results and are never reused, and the
restart path uploads the event chunks once.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm
from cuda_gmm_mpi_tpu.state import bucket_width, compact_to, zeros_state

from .conftest import make_blobs


def cfg(**kw):
    base = dict(min_iters=3, max_iters=3, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


# ---------------------------------------------------------------- units


def test_bucket_width_pow2_sequence():
    assert [bucket_width(k, 64) for k in (64, 33, 32, 17, 16, 9, 8, 5, 4,
                                          3, 2, 1)] == \
        [64, 64, 32, 32, 16, 16, 8, 8, 4, 4, 2, 1]
    # clamped to the current padded width (buckets only shrink)
    assert bucket_width(100, 100) == 100
    assert bucket_width(65, 100) == 100
    # rounded up to the cluster-mesh multiple
    assert bucket_width(3, 64, multiple=8) == 8
    assert bucket_width(9, 64, multiple=8) == 16
    # 'off' keeps the width
    assert bucket_width(2, 64, mode="off") == 64
    # a K0 -> 1 sweep visits at most ceil(log2 K0) + 1 widths
    for k0 in (8, 32, 64, 100):
        widths = {bucket_width(k, k0) for k in range(1, k0 + 1)}
        assert len(widths) <= int(np.ceil(np.log2(k0))) + 1


def test_compact_to_preserves_active_order():
    s = zeros_state(8, 2, dtype=jnp.float64)
    active = jnp.asarray([False, True, False, True, True, False, False, True])
    s = s.replace(N=jnp.arange(8.0), active=active)
    c = compact_to(s, 4)
    # active rows 1, 3, 4, 7 land in slots 0..3 in original order
    np.testing.assert_array_equal(np.asarray(c.N), [1.0, 3.0, 4.0, 7.0])
    assert bool(np.asarray(c.active).all())
    # extra slots are filled with inactive rows, still masked off
    c6 = compact_to(s, 6)
    np.testing.assert_array_equal(np.asarray(c6.N)[:4], [1.0, 3.0, 4.0, 7.0])
    assert not np.asarray(c6.active)[4:].any()
    with pytest.raises(ValueError):
        compact_to(s, 9)  # growing is not compaction


# ----------------------------------------------------- sweep parity (tier-1)


@pytest.mark.parametrize("covariance_type", ["full", "diag"])
def test_sweep_parity_bucketing_on_vs_off(rng, covariance_type):
    """Same data, same seed: bucketing must not change the answer -- same
    selected K, per-K loglik/criterion trajectories equal within tolerance,
    for both covariance families."""
    data, _ = make_blobs(rng, n=700, d=3, k=4)
    r_on = fit_gmm(data, 12, 0, config=cfg(sweep_k_buckets="pow2",
                                           covariance_type=covariance_type))
    r_off = fit_gmm(data, 12, 0, config=cfg(sweep_k_buckets="off",
                                            covariance_type=covariance_type))
    assert r_on.ideal_num_clusters == r_off.ideal_num_clusters
    assert [r[0] for r in r_on.sweep_log] == [r[0] for r in r_off.sweep_log]
    for on, off in zip(r_on.sweep_log, r_off.sweep_log):
        np.testing.assert_allclose(on[1], off[1], rtol=1e-5)   # loglik
        np.testing.assert_allclose(on[2], off[2], rtol=1e-5)   # criterion
        assert on[3] == off[3]                                 # iters
    np.testing.assert_allclose(r_on.min_rissanen, r_off.min_rissanen,
                               rtol=1e-10)
    np.testing.assert_allclose(r_on.means, r_off.means, rtol=1e-7,
                               atol=1e-9)


def test_sweep_parity_sharded_cluster_axis(rng):
    """Bucketing on a cluster-sharded mesh: widths round up to the cluster
    axis extent and the answer matches the unbucketed mesh run."""
    data, _ = make_blobs(rng, n=512, d=3, k=3)
    r_on = fit_gmm(data, 6, 0, config=cfg(mesh_shape=(4, 2), chunk_size=64,
                                          sweep_k_buckets="pow2"))
    r_off = fit_gmm(data, 6, 0, config=cfg(mesh_shape=(4, 2), chunk_size=64,
                                           sweep_k_buckets="off"))
    assert r_on.ideal_num_clusters == r_off.ideal_num_clusters
    np.testing.assert_allclose(r_on.min_rissanen, r_off.min_rissanen,
                               rtol=1e-9)


# --------------------------------------------------------- compile count


def test_compile_count_k32_sweep(rng, tmp_path):
    """A K=32 -> 1 sweep builds at most ceil(log2 32) + 1 = 6 distinct EM
    widths (asserted from run_summary's bucket report AND the jit cache)."""
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.telemetry import read_stream

    data, _ = make_blobs(rng, n=320, d=2, k=3)
    path = str(tmp_path / "m.jsonl")
    c = cfg(min_iters=2, max_iters=2, chunk_size=128, metrics_file=path)
    model = GMMModel(c)
    fit_gmm(data, 32, 0, config=c, model=model)
    summ = [r for r in read_stream(path) if r["event"] == "run_summary"][-1]
    buckets = summ["buckets"]
    assert buckets["mode"] == "pow2"
    assert buckets["em_compiles"] <= 6
    assert buckets["em_widths"][0] == 32 and buckets["em_widths"][-1] >= 1
    assert buckets["rebuckets"] == len(buckets["em_widths"]) - 1
    # The jitted EM loop itself traced at most one shape per width (the
    # telemetry sweep runs one (trajectory, donate) variant).
    traced = [fn for fn in model._em_exec_cache.values()
              if getattr(fn, "_cache_size", None) is not None]
    assert traced and all(fn._cache_size() <= 6 for fn in traced)
    # rebucket events narrate every boundary crossing
    rebs = [r for r in read_stream(path) if r["event"] == "rebucket"]
    assert len(rebs) == buckets["rebuckets"]
    for r in rebs:
        assert r["to_width"] < r["from_width"]
        assert r["k_active"] <= r["to_width"]


# -------------------------------------------------------------- donation


def test_donation_results_unchanged_and_input_deleted(rng):
    """donate=True: identical results, and the donated input state is not
    reusable afterwards (deleted on backends that support donation --
    CPU does on this jax; the sweep never touches a donated input)."""
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float64)
    model = GMMModel(cfg())
    chunks, wts = map(jnp.asarray, chunk_events(data, 256))
    eps = convergence_epsilon(512, 3)
    seed = seed_clusters_host(data, 4)

    fresh = lambda: jax.tree_util.tree_map(
        lambda a: jnp.array(np.asarray(a)), seed)  # real copies
    s_ref, ll_ref, it_ref = model.run_em(fresh(), chunks, wts, eps)
    donated_in = fresh()
    s_don, ll_don, it_don = model.run_em(donated_in, chunks, wts, eps,
                                         donate=True)
    assert float(ll_don) == float(ll_ref) and int(it_don) == int(it_ref)
    np.testing.assert_array_equal(np.asarray(s_don.means),
                                  np.asarray(s_ref.means))
    # the donated buffers must not be live afterwards
    assert all(a.is_deleted()
               for a in jax.tree_util.tree_leaves(donated_in))
    # chunks were NOT donated: still valid for the next call
    model.run_em(fresh(), chunks, wts, eps, donate=True)


def test_full_sweep_with_donation_matches_result(rng):
    """End-to-end: the donating sweep (default path) equals a fixed run's
    known-good selection; nothing downstream reads a deleted buffer."""
    data, _ = make_blobs(rng, n=600, d=2, k=3)
    r = fit_gmm(data, 6, 0, config=cfg())
    assert r.ideal_num_clusters >= 1
    assert np.isfinite(r.final_loglik)
    # the compacted best state is fully materialized (not donated away)
    assert np.isfinite(np.asarray(r.state.means)).all()


# ----------------------------------------------- restart upload hoisting


def test_restarts_upload_chunks_once(rng, tmp_path):
    """n_init > 1: the event chunks are placed on device once; restarts
    reuse the resident arrays (h2d_bytes counts ONE upload)."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream

    data, _ = make_blobs(rng, n=400, d=3, k=3)
    single = str(tmp_path / "single.jsonl")
    fit_gmm(data, 3, 3, config=cfg(chunk_size=128, metrics_file=single))
    one_upload = [x for x in read_stream(single)
                  if x["event"] == "run_summary"][-1][
                      "metrics"]["counters"]["h2d_bytes"]
    assert one_upload > 0

    path = str(tmp_path / "multi.jsonl")
    r = fit_gmm(data, 3, 3, config=cfg(n_init=3, chunk_size=128,
                                       metrics_file=path))
    assert r.ideal_num_clusters == 3
    recs = read_stream(path)
    summ = [x for x in recs if x["event"] == "run_summary"][-1]
    # 3 inits, ONE upload (the counter accumulates across the whole stream)
    assert summ["metrics"]["counters"]["h2d_bytes"] == one_upload
    # restarts still produce their own run_start/run_summary records
    assert sum(1 for x in recs if x["event"] == "run_start") == 3


def test_restarts_same_result_as_before_hoist(rng):
    """The hoist must not change results: n_init over identical data picks
    the same best as independently seeded single fits."""
    data, _ = make_blobs(rng, n=500, d=3, k=3)
    kw = dict(min_iters=4, max_iters=4, chunk_size=128, dtype="float64")
    singles = [
        fit_gmm(data, 3, 3, config=GMMConfig(seed_method="kmeans++",
                                             seed=s, **kw))
        for s in range(2)
    ]
    multi = fit_gmm(data, 3, 3, config=GMMConfig(
        n_init=2, seed=0, seed_method="kmeans++", **kw))
    np.testing.assert_allclose(
        multi.min_rissanen, min(s.min_rissanen for s in singles),
        rtol=1e-12)


# --------------------------------------------- packed precompute (satellite)


def test_precompute_features_packed_parity(rng):
    """precompute_features composes with quad_mode='packed' and is
    bit-identical to the unhoisted packed run (per-layout contract)."""
    data, _ = make_blobs(rng, n=400, d=4, k=3)
    base = dict(min_iters=3, max_iters=3, chunk_size=128, dtype="float64",
                quad_mode="packed")
    r_hoist = fit_gmm(data, 4, 4,
                      config=GMMConfig(precompute_features=True, **base))
    r_plain = fit_gmm(data, 4, 4, config=GMMConfig(**base))
    assert r_hoist.final_loglik == r_plain.final_loglik
    np.testing.assert_array_equal(r_hoist.means, r_plain.means)
    np.testing.assert_array_equal(np.asarray(r_hoist.state.R),
                                  np.asarray(r_plain.state.R))
    # 'centered' still has nothing to hoist
    with pytest.raises(ValueError):
        GMMConfig(precompute_features=True, quad_mode="centered")


# ------------------------------------------------- bench sweep-mode contract


def test_bench_sweep_mode_emits_ab(monkeypatch):
    """bench.py --sweep emits the bucketed-vs-off A/B in its JSON."""
    import bench

    monkeypatch.setenv("GMM_BENCH_SWEEP_K", "6")
    monkeypatch.setenv("GMM_BENCH_SWEEP_N", "600")
    monkeypatch.setenv("GMM_BENCH_SWEEP_D", "3")
    monkeypatch.setenv("GMM_BENCH_CHUNK", "256")
    result = bench.run_sweep_bench("cpu", accel_unavailable=False)
    sweep = result["sweep"]
    assert set(sweep) >= {"k0", "bucketed", "off", "speedup",
                          "ideal_k_equal", "ks_equal",
                          "max_rel_loglik_diff"}
    assert sweep["ideal_k_equal"] and sweep["ks_equal"]
    assert sweep["max_rel_loglik_diff"] < 1e-5
    for side in ("bucketed", "off"):
        assert sweep[side]["wall_s"] > 0
        assert len(sweep[side]["per_k_seconds"]) == len(sweep[side]["ks"])
    assert result["unit"] == "s" and result["value"] > 0


# ----------------------------------------------------- speed (acceptance)


@pytest.mark.slow
def test_bucketed_k64_sweep_measurably_faster(rng):
    """Acceptance: a K=64 -> 1 CPU sweep with bucketing beats off on wall
    clock with identical selection and 1e-5-relative trajectories."""
    import time

    from cuda_gmm_mpi_tpu.models.gmm import GMMModel

    k0 = 64
    centers = rng.normal(scale=8.0, size=(k0, 8))
    data = (centers[rng.integers(0, k0, 20000)]
            + rng.normal(size=(20000, 8))).astype(np.float32)

    def timed(mode):
        c = GMMConfig(min_iters=3, max_iters=3, chunk_size=4096,
                      sweep_k_buckets=mode)
        model = GMMModel(c)
        warm = GMMConfig(min_iters=1, max_iters=1, chunk_size=4096,
                         sweep_k_buckets=mode)
        fit_gmm(data, k0, 0, warm, model=model)  # compile every width
        t0 = time.perf_counter()
        res = fit_gmm(data, k0, 0, c, model=model)
        return time.perf_counter() - t0, res

    t_on, r_on = timed("pow2")
    t_off, r_off = timed("off")
    assert r_on.ideal_num_clusters == r_off.ideal_num_clusters
    assert [r[0] for r in r_on.sweep_log] == [r[0] for r in r_off.sweep_log]
    for on, off in zip(r_on.sweep_log, r_off.sweep_log):
        np.testing.assert_allclose(on[1], off[1], rtol=1e-5)
    assert t_on < t_off, (t_on, t_off)
