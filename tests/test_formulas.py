"""Scalar formula parity: epsilon (gaussian.cu:458), rissanen (gaussian.cu:826)."""

import math

import pytest

from cuda_gmm_mpi_tpu.ops.formulas import (
    convergence_epsilon, free_params_per_cluster, model_score,
    n_free_params, rissanen_score,
)


def test_free_params():
    assert free_params_per_cluster(24) == 1 + 24 + 0.5 * 25 * 24


def test_epsilon():
    n, d = 10000, 24
    expected = (1 + d + 0.5 * (d + 1) * d) * math.log(n * d) * 0.01
    assert convergence_epsilon(n, d) == expected


def test_rissanen():
    ll, k, n, d = -1.23e5, 8, 10000, 16
    expected = -ll + 0.5 * (k * (1 + d + 0.5 * (d + 1) * d) - 1) * math.log(n * d)
    assert rissanen_score(ll, k, n, d) == expected


def test_model_score_criteria():
    ll, k, n, d = -1.23e5, 8, 10000, 16
    assert model_score(ll, k, n, d) == rissanen_score(ll, k, n, d)
    p = n_free_params(k, d)
    assert model_score(ll, k, n, d, "bic") == -2 * ll + p * math.log(n)
    assert model_score(ll, k, n, d, "aic") == -2 * ll + 2 * p
    # family-aware counting
    p_sph = n_free_params(k, d, covariance_type="spherical")
    assert model_score(ll, k, n, d, "bic", "spherical") == (
        -2 * ll + p_sph * math.log(n))
    # rissanen keeps the reference's full count regardless of family
    assert model_score(ll, k, n, d, "rissanen", "diag") == (
        rissanen_score(ll, k, n, d))
    aicc = model_score(ll, k, n, d, "aicc")
    # the implementation's denominator carries a +1e-12 guard: approx, not ==
    assert aicc == pytest.approx(
        -2 * ll + 2 * p + 2 * p * (p + 1) / (n - p - 1), rel=1e-12)
    assert aicc > model_score(ll, k, n, d, "aic")  # correction is positive
    with pytest.raises(ValueError, match="criterion"):
        model_score(ll, k, n, d, "mdl2")
