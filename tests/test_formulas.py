"""Scalar formula parity: epsilon (gaussian.cu:458), rissanen (gaussian.cu:826)."""

import math

from cuda_gmm_mpi_tpu.ops.formulas import (
    convergence_epsilon, free_params_per_cluster, rissanen_score,
)


def test_free_params():
    assert free_params_per_cluster(24) == 1 + 24 + 0.5 * 25 * 24


def test_epsilon():
    n, d = 10000, 24
    expected = (1 + d + 0.5 * (d + 1) * d) * math.log(n * d) * 0.01
    assert convergence_epsilon(n, d) == expected


def test_rissanen():
    ll, k, n, d = -1.23e5, 8, 10000, 16
    expected = -ll + 0.5 * (k * (1 + d + 0.5 * (d + 1) * d) - 1) * math.log(n * d)
    assert rissanen_score(ll, k, n, d) == expected
