"""Test harness: 8 fake CPU devices (SURVEY.md SS4: the TPU-native analog of
"test multi-node without a cluster") and float64 enabled for oracle comparisons.

Must run before any jax import, hence module-level env mutation in conftest.
"""

import os

# NOTE: this image preloads jax via a sitecustomize hook that registers the
# axon TPU plugin in EVERY python process; JAX_PLATFORMS in os.environ does
# NOT pin the platform even when set before interpreter start (verified
# 2026-07-31 -- a wedged tunnel hangs `env JAX_PLATFORMS=cpu python -c
# "import jax; jax.devices()"` forever). The config.update calls below are
# what actually pins this process; subprocess workers must each call
# jax.config.update("jax_platforms", "cpu") themselves (they do -- see
# multihost_worker.py and worker_env()'s docstring).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older JAX has no jax_num_cpu_devices option; the XLA_FLAGS fallback
    # above (set before the first jax import) is what pins the 8 fake
    # devices there. Without this guard the WHOLE suite fails at
    # collection on such installs.
    pass
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def worker_env():
    """Environment for subprocess test workers: scrub the 8-device forcing,
    repo on PYTHONPATH. JAX_PLATFORMS=cpu is advisory only on this image
    (see the NOTE above) -- every worker script must still pin CPU itself
    via jax.config.update("jax_platforms", "cpu") before touching devices."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def communicate_or_kill(proc, timeout):
    """proc.communicate that never leaks a still-running worker."""
    import subprocess

    try:
        return proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise


def make_blobs(rng, n=2000, d=3, k=4, spread=8.0, dtype=np.float64):
    """Well-separated synthetic mixture with known parameters."""
    centers = rng.normal(scale=spread, size=(k, d))
    chunks = []
    for c in range(k):
        a = rng.normal(size=(d, d)) * 0.3
        cov = a @ a.T + np.eye(d)
        chunks.append(rng.multivariate_normal(centers[c], cov, size=n // k))
    x = np.concatenate(chunks, axis=0)
    rng.shuffle(x)
    return x.astype(dtype), centers


@pytest.fixture
def blobs(rng):
    return make_blobs(rng)


@pytest.fixture
def sized_tmp_path(tmp_path):
    """tmp_path with a disk-usage guard: disk-heavy tests (out-of-core
    ingestion fixtures writing dataset files) opt in, and a fixture that
    grows past the cap fails the TEST instead of silently filling the CI
    disk. GMM_TEST_TMPDIR_CAP_MB overrides the default 256 MB cap."""
    cap_mb = float(os.environ.get("GMM_TEST_TMPDIR_CAP_MB") or 256)
    yield tmp_path
    total = sum(f.stat().st_size for f in tmp_path.rglob("*")
                if f.is_file())
    assert total <= cap_mb * 1024 * 1024, (
        f"test left {total / 1e6:.1f} MB in {tmp_path} "
        f"(cap {cap_mb:.0f} MB; raise GMM_TEST_TMPDIR_CAP_MB only with "
        f"a reason)")
