"""Unit tests: Cholesky inverse/log-det and the constants op vs NumPy oracles."""

import jax.numpy as jnp
import numpy as np

from cuda_gmm_mpi_tpu.ops.constants import (
    LOG_2PI, chol_inverse_logdet, chol_logdet, compute_constants,
)
from cuda_gmm_mpi_tpu.state import zeros_state


def random_spd(rng, k, d, scale=1.0):
    a = rng.normal(size=(k, d, d)) * scale
    return a @ np.swapaxes(a, 1, 2) + 0.5 * np.eye(d)


def test_inverse_logdet_matches_numpy(rng):
    R = random_spd(rng, 6, 5)
    Rinv, logdet, ok = chol_inverse_logdet(jnp.asarray(R))
    assert bool(jnp.all(ok))
    np.testing.assert_allclose(np.asarray(Rinv), np.linalg.inv(R), rtol=1e-9,
                               atol=1e-9)
    np.testing.assert_allclose(
        np.asarray(logdet), np.linalg.slogdet(R)[1], rtol=1e-10
    )


def test_diag_only_path(rng):
    d = np.abs(rng.normal(size=(4, 6))) + 0.1
    R = np.stack([np.diag(row) for row in d])
    Rinv, logdet, ok = chol_inverse_logdet(jnp.asarray(R), diag_only=True)
    assert bool(jnp.all(ok))
    np.testing.assert_allclose(
        np.asarray(Rinv), np.stack([np.diag(1.0 / row) for row in d]), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(logdet), np.log(d).sum(1), rtol=1e-12)


def test_non_pd_flagged(rng):
    R = random_spd(rng, 3, 4)
    R[1] = -np.eye(4)  # not PD
    _, _, ok = chol_inverse_logdet(jnp.asarray(R))
    assert bool(ok[0]) and not bool(ok[1]) and bool(ok[2])


def test_compute_constants_semantics(rng):
    k, d = 5, 3
    state = zeros_state(k, d, dtype=jnp.float64)
    R = random_spd(rng, k, d)
    N = np.array([100.0, 0.2, 50.0, 0.0, 10.0])
    state = state.replace(
        R=jnp.asarray(R), N=jnp.asarray(N),
        active=jnp.ones(k, bool),
    )
    out = compute_constants(state)
    # constant = -D/2 ln 2pi - 1/2 ln|R|  (gaussian_kernel.cu:241)
    expected_const = -d * 0.5 * LOG_2PI - 0.5 * np.linalg.slogdet(R)[1]
    np.testing.assert_allclose(np.asarray(out.constant), expected_const,
                               rtol=1e-9)
    # pi floor 1e-10 when N < 0.5 (gaussian_kernel.cu:184-189)
    pi = np.asarray(out.pi)
    assert pi[1] == 1e-10 and pi[3] == 1e-10
    np.testing.assert_allclose(pi[0], 100.0 / N.sum(), rtol=1e-9)


def test_non_pd_reset_to_identity(rng):
    k, d = 2, 3
    state = zeros_state(k, d, dtype=jnp.float64)
    R = random_spd(rng, k, d)
    R[0] = np.diag([1.0, -1.0, 1.0])  # indefinite
    state = state.replace(R=jnp.asarray(R), N=jnp.ones(k) * 10,
                          active=jnp.ones(k, bool))
    out = compute_constants(state)
    np.testing.assert_allclose(np.asarray(out.R[0]), np.eye(d))
    np.testing.assert_allclose(np.asarray(out.Rinv[0]), np.eye(d))
    np.testing.assert_allclose(float(out.constant[0]), -d * 0.5 * LOG_2PI)


def test_chol_logdet_matches_numpy(rng):
    """The inverse-free log-det op (merge pair scan) vs the slogdet oracle,
    both covariance modes, including the non-PD flag."""
    R = random_spd(rng, 6, 5)
    logdet, ok = chol_logdet(jnp.asarray(R))
    assert bool(jnp.all(ok))
    np.testing.assert_allclose(
        np.asarray(logdet), np.linalg.slogdet(R)[1], rtol=1e-10
    )
    # non-PD row flagged, its log_det masked to 0
    R[2] = -np.eye(5)
    logdet, ok = chol_logdet(jnp.asarray(R))
    assert not bool(ok[2]) and bool(ok[0])
    assert float(logdet[2]) == 0.0
    # diagonal mode
    d = np.abs(rng.normal(size=(4, 6))) + 0.1
    Rd = np.stack([np.diag(row) for row in d])
    logdet, ok = chol_logdet(jnp.asarray(Rd), diag_only=True)
    assert bool(jnp.all(ok))
    np.testing.assert_allclose(np.asarray(logdet), np.log(d).sum(1),
                               rtol=1e-12)
    # agreement with the inverse-bearing sibling (single source of truth)
    ld2 = chol_inverse_logdet(jnp.asarray(Rd), diag_only=True)[1]
    np.testing.assert_array_equal(np.asarray(logdet), np.asarray(ld2))


def test_chol_logdet_single_definition():
    """Guard against copy-paste drift: exactly one chol_logdet definition.

    Round-4 review found a second, byte-near-identical ``def chol_logdet``
    silently shadowing the first; this pins the module to one definition so
    the natural-log/PD semantics have a single source of truth.
    """
    import inspect
    from cuda_gmm_mpi_tpu.ops import constants as mod

    src = inspect.getsource(mod)
    assert src.count("def chol_logdet(") == 1
