"""Out-of-core streaming EM vs the in-memory path: identical trajectories.

The streaming model accumulates per-chunk statistics in the same order the
in-memory lax.scan does, so in float64 the full fit (EM + model-order sweep)
must agree to summation-order noise while the chunk data never moves to the
device as a whole.
"""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GaussianMixture, GMMConfig
from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
from cuda_gmm_mpi_tpu.models.streaming import StreamingGMMModel

from .conftest import make_blobs


def test_streaming_fit_matches_in_memory(rng):
    data, _ = make_blobs(rng, n=1100, d=3, k=3, dtype=np.float64)
    kw = dict(min_iters=5, max_iters=5, chunk_size=128, dtype="float64")
    r_mem = fit_gmm(data, 5, 2, GMMConfig(**kw))
    r_str = fit_gmm(data, 5, 2, GMMConfig(stream_events=True, **kw))
    assert r_str.ideal_num_clusters == r_mem.ideal_num_clusters
    np.testing.assert_allclose(r_str.final_loglik, r_mem.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_str.means, r_mem.means, rtol=1e-10)
    np.testing.assert_allclose(r_str.covariances, r_mem.covariances,
                               rtol=1e-9, atol=1e-12)
    # per-K trajectories agree too
    for (k1, ll1, *_), (k2, ll2, *_) in zip(r_str.sweep_log, r_mem.sweep_log):
        assert k1 == k2
        np.testing.assert_allclose(ll1, ll2, rtol=1e-12)


def test_streaming_estimator_and_weights(rng):
    """Streaming composes with the estimator surface, covariance families,
    and sample_weight (the weight row rides the host chunks)."""
    centers = rng.normal(scale=8.0, size=(2, 3))
    data = (centers[rng.integers(0, 2, 600)]
            + rng.normal(size=(600, 3))).astype(np.float64)
    w = rng.integers(1, 3, size=600).astype(np.float64)
    kw = dict(min_iters=4, max_iters=4, chunk_size=128, dtype="float64",
              covariance_type="tied", center_data=False,
              covariance_dynamic_range=1e30)
    gs = GaussianMixture(2, target_components=2, means_init=centers,
                         stream_events=True, **kw).fit(data, sample_weight=w)
    gm = GaussianMixture(2, target_components=2, means_init=centers,
                         **kw).fit(np.repeat(data, w.astype(int), axis=0))
    np.testing.assert_allclose(gs.means_, gm.means_, rtol=1e-9)
    np.testing.assert_allclose(gs.covariances_, gm.covariances_, rtol=1e-8)
    # inference path works off the streaming model
    pred = gs.predict(data)
    assert pred.shape == (600,)


def test_streaming_mesh_matches_sharded_in_memory(rng):
    """Streaming over a local data mesh keeps every device busy AND
    reproduces the in-memory sharded model's trajectory: per-shard chunk
    assignment and the final psum are identical, so the fits agree to
    float64 noise (VERDICT r3 item 5)."""
    data, _ = make_blobs(rng, n=2100, d=3, k=3, dtype=np.float64)
    kw = dict(min_iters=5, max_iters=5, chunk_size=64, dtype="float64",
              mesh_shape=(8, 1))
    r_mem = fit_gmm(data, 5, 2, GMMConfig(**kw))
    r_str = fit_gmm(data, 5, 2, GMMConfig(stream_events=True, **kw))
    assert r_str.ideal_num_clusters == r_mem.ideal_num_clusters
    np.testing.assert_allclose(r_str.final_loglik, r_mem.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_str.means, r_mem.means, rtol=1e-10)
    np.testing.assert_allclose(r_str.covariances, r_mem.covariances,
                               rtol=1e-9, atol=1e-12)
    for (k1, ll1, *_), (k2, ll2, *_) in zip(r_str.sweep_log, r_mem.sweep_log):
        assert k1 == k2
        np.testing.assert_allclose(ll1, ll2, rtol=1e-12)


def test_streaming_mesh_cli_byte_identical(tmp_path):
    """--stream-events --mesh=8 produces byte-identical .summary/.results
    to the in-memory --mesh=8 run (the CLI-level contract of item 5)."""
    from cuda_gmm_mpi_tpu.cli import main

    rng = np.random.default_rng(3)
    centers = rng.normal(scale=10.0, size=(4, 4))
    x = (centers[rng.integers(0, 4, 3000)]
         + rng.normal(size=(3000, 4))).astype(np.float32)
    csv = tmp_path / "ev.csv"
    csv.write_text("a,b,c,d\n" + "\n".join(
        ",".join(f"{v:.6f}" for v in r) for r in x))

    def run(tag, extra):
        out = tmp_path / tag
        rc = main(["6", str(csv), str(out), "4", "--mesh=8",
                   "--min-iters=6", "--max-iters=6", *extra])
        assert rc == 0
        return (out.with_suffix(".summary").read_bytes(),
                out.with_suffix(".results").read_bytes())

    s_mem, m_mem = run("mem", [])
    s_str, m_str = run("str", ["--stream-events"])
    assert s_str == s_mem
    assert m_str == m_mem


@pytest.mark.slow
def test_streaming_mesh_host_bounded_rss(tmp_path):
    """The mesh-streaming path must not materialize the device-resident
    dataset: fitting with a data array much larger than the per-block
    working set keeps the process RSS growth far below a full-device
    upload's footprint (O(blocks) transfers, O(1) residency)."""
    import subprocess
    import sys

    from .conftest import worker_env

    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices
force_cpu_devices(8)
import numpy as np, resource
from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm

rng = np.random.default_rng(0)
n, d = 2_000_000, 8
data = rng.normal(size=(n, d)).astype(np.float32)  # 64 MB host-side
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=4096,
                stream_events=True, mesh_shape=(8, 1))
r = fit_gmm(data, 2, 2, config=cfg)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
# ru_maxrss is KB on linux. Allow generous jit/runtime overhead but stay
# far under a second full copy of the dataset on device (64 MB) --
# streaming holds ~8 chunks x 4096 x 8 x 4B = 1 MB of blocks at a time.
growth_mb = (peak - base) / 1024.0
print("GROWTH_MB", growth_mb, "LL", float(r.final_loglik))
assert growth_mb < 45.0, growth_mb
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=worker_env(), timeout=600)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr[-3000:]}"
    assert "GROWTH_MB" in r.stdout


def test_streaming_mesh_sample_weight_matches_in_memory(rng):
    """sample_weight rides the host chunks through the block-major mesh
    layout: weighted mesh-streaming matches weighted mesh in-memory."""
    centers = rng.normal(scale=8.0, size=(2, 3))
    data = (centers[rng.integers(0, 2, 900)]
            + rng.normal(size=(900, 3))).astype(np.float64)
    w = rng.uniform(0.5, 3.0, size=900).astype(np.float64)
    kw = dict(min_iters=4, max_iters=4, chunk_size=64, dtype="float64",
              mesh_shape=(8, 1))
    r_mem = fit_gmm(data, 3, 2, GMMConfig(**kw), sample_weight=w)
    r_str = fit_gmm(data, 3, 2, GMMConfig(stream_events=True, **kw),
                    sample_weight=w)
    np.testing.assert_allclose(r_str.final_loglik, r_mem.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_str.means, r_mem.means, rtol=1e-10)


def test_streaming_guards(rng):
    with pytest.raises(ValueError, match="cluster mesh axis"):
        GMMConfig(stream_events=True, mesh_shape=(4, 2))
    GMMConfig(stream_events=True, mesh_shape=(8, 1))  # data-only mesh: OK
    with pytest.raises(ValueError, match="use_pallas"):
        GMMConfig(stream_events=True, use_pallas="always")
    # fused sweep falls back to the host-driven sweep (no device-resident
    # data), with identical results
    data, _ = make_blobs(rng, n=400, d=2, k=2, dtype=np.float64)
    kw = dict(min_iters=3, max_iters=3, chunk_size=128, dtype="float64",
              stream_events=True)
    r_plain = fit_gmm(data, 3, 2, GMMConfig(**kw))
    r_fused = fit_gmm(data, 3, 2, GMMConfig(fused_sweep=True, **kw))
    np.testing.assert_allclose(r_fused.final_loglik, r_plain.final_loglik,
                               rtol=1e-12)
