"""Out-of-core streaming EM vs the in-memory path: identical trajectories.

The streaming model accumulates per-chunk statistics in the same order the
in-memory lax.scan does, so in float64 the full fit (EM + model-order sweep)
must agree to summation-order noise while the chunk data never moves to the
device as a whole.
"""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GaussianMixture, GMMConfig
from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
from cuda_gmm_mpi_tpu.models.streaming import StreamingGMMModel

from .conftest import make_blobs


def test_streaming_fit_matches_in_memory(rng):
    data, _ = make_blobs(rng, n=1100, d=3, k=3, dtype=np.float64)
    kw = dict(min_iters=5, max_iters=5, chunk_size=128, dtype="float64")
    r_mem = fit_gmm(data, 5, 2, GMMConfig(**kw))
    r_str = fit_gmm(data, 5, 2, GMMConfig(stream_events=True, **kw))
    assert r_str.ideal_num_clusters == r_mem.ideal_num_clusters
    np.testing.assert_allclose(r_str.final_loglik, r_mem.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_str.means, r_mem.means, rtol=1e-10)
    np.testing.assert_allclose(r_str.covariances, r_mem.covariances,
                               rtol=1e-9, atol=1e-12)
    # per-K trajectories agree too
    for (k1, ll1, *_), (k2, ll2, *_) in zip(r_str.sweep_log, r_mem.sweep_log):
        assert k1 == k2
        np.testing.assert_allclose(ll1, ll2, rtol=1e-12)


def test_streaming_estimator_and_weights(rng):
    """Streaming composes with the estimator surface, covariance families,
    and sample_weight (the weight row rides the host chunks)."""
    centers = rng.normal(scale=8.0, size=(2, 3))
    data = (centers[rng.integers(0, 2, 600)]
            + rng.normal(size=(600, 3))).astype(np.float64)
    w = rng.integers(1, 3, size=600).astype(np.float64)
    kw = dict(min_iters=4, max_iters=4, chunk_size=128, dtype="float64",
              covariance_type="tied", center_data=False,
              covariance_dynamic_range=1e30)
    gs = GaussianMixture(2, target_components=2, means_init=centers,
                         stream_events=True, **kw).fit(data, sample_weight=w)
    gm = GaussianMixture(2, target_components=2, means_init=centers,
                         **kw).fit(np.repeat(data, w.astype(int), axis=0))
    np.testing.assert_allclose(gs.means_, gm.means_, rtol=1e-9)
    np.testing.assert_allclose(gs.covariances_, gm.covariances_, rtol=1e-8)
    # inference path works off the streaming model
    pred = gs.predict(data)
    assert pred.shape == (600,)


def test_streaming_guards(rng):
    with pytest.raises(ValueError, match="single-device"):
        GMMConfig(stream_events=True, mesh_shape=(4, 2))
    with pytest.raises(ValueError, match="use_pallas"):
        GMMConfig(stream_events=True, use_pallas="always")
    # fused sweep falls back to the host-driven sweep (no device-resident
    # data), with identical results
    data, _ = make_blobs(rng, n=400, d=2, k=2, dtype=np.float64)
    kw = dict(min_iters=3, max_iters=3, chunk_size=128, dtype="float64",
              stream_events=True)
    r_plain = fit_gmm(data, 3, 2, GMMConfig(**kw))
    r_fused = fit_gmm(data, 3, 2, GMMConfig(fused_sweep=True, **kw))
    np.testing.assert_allclose(r_fused.final_loglik, r_plain.final_loglik,
                               rtol=1e-12)
