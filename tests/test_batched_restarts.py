"""Single-dispatch batched restarts (models/restarts.py).

The contract under test: batching the n_init restarts into one vmapped EM
program changes WALL TIME, not ANSWERS -- identical winner (init index and
selected K) and bit-comparable parameters vs the sequential
``restart_batch_size=1`` degenerate case at the same seeds; one compiled
EM executable serves every restart batch of equal shape; a converged
restart freezes out (its lane stops updating) while siblings iterate; one
poisoned restart is dropped from the batch instead of rolling back its
survivors; and a mid-batch preemption checkpoints all R trajectories and
resumes bit-identically.
"""

import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, supervisor
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.supervisor import PreemptedError, RunSupervisor
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import make_blobs


def cfg(**kw):
    base = dict(min_iters=4, max_iters=4, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


# ------------------------------------------------------------- parity


def test_batched_vs_sequential_parity_plain(rng):
    """Full K0 -> 1 sweep, 3 restarts: the batched driver must select the
    identical winner as the sequential one at the same seeds, with
    bit-comparable best-model parameters."""
    data, _ = make_blobs(rng, n=900, d=3, k=4)
    kw = dict(n_init=3, seed=0, min_iters=6, max_iters=6, chunk_size=256,
              dtype="float64")
    seq = fit_gmm(data, 6, 0, config=GMMConfig(restart_batch_size=1, **kw))
    bat = fit_gmm(data, 6, 0, config=GMMConfig(restart_batch_size=3, **kw))
    assert bat.init_index == seq.init_index
    assert bat.ideal_num_clusters == seq.ideal_num_clusters
    np.testing.assert_allclose(bat.min_rissanen, seq.min_rissanen,
                               rtol=1e-10)
    np.testing.assert_allclose(bat.final_loglik, seq.final_loglik,
                               rtol=1e-10)
    np.testing.assert_allclose(bat.means, seq.means, rtol=1e-8, atol=1e-8)
    np.testing.assert_allclose(bat.covariances, seq.covariances,
                               rtol=1e-7, atol=1e-8)
    # the sweep rows of the winner agree K by K
    assert [r[0] for r in bat.sweep_log] == [r[0] for r in seq.sweep_log]
    for b, s in zip(bat.sweep_log, seq.sweep_log):
        np.testing.assert_allclose(b[1], s[1], rtol=1e-9)


def test_batched_target_k_and_uneven_batches(rng):
    """n_init=3 in batches of 2 (a full batch + a remainder batch) at a
    target K still picks the sequential winner."""
    data, _ = make_blobs(rng, n=600, d=3, k=3)
    kw = dict(n_init=3, seed=0, min_iters=5, max_iters=5, chunk_size=256,
              dtype="float64")
    seq = fit_gmm(data, 4, 3, config=GMMConfig(restart_batch_size=1, **kw))
    bat = fit_gmm(data, 4, 3, config=GMMConfig(restart_batch_size=2, **kw))
    assert bat.init_index == seq.init_index
    np.testing.assert_allclose(bat.min_rissanen, seq.min_rissanen,
                               rtol=1e-10)


@pytest.mark.parametrize("mesh", [(2, 1), (2, 2)])
def test_batched_vs_sequential_parity_sharded(rng, mesh):
    """The sharded model runs the same batched loop (restart axis
    replicated, data axis sharded, clusters optionally sharded) and must
    agree with its own sequential restarts."""
    data, _ = make_blobs(rng, n=512, d=3, k=4)
    kw = dict(n_init=2, seed=0, min_iters=4, max_iters=4, chunk_size=64,
              dtype="float64", mesh_shape=mesh)
    seq = fit_gmm(data, 4, 4, config=GMMConfig(restart_batch_size=1, **kw))
    bat = fit_gmm(data, 4, 4, config=GMMConfig(restart_batch_size=2, **kw))
    assert bat.init_index == seq.init_index
    np.testing.assert_allclose(bat.min_rissanen, seq.min_rissanen,
                               rtol=1e-9)
    np.testing.assert_allclose(bat.means, seq.means, rtol=1e-7, atol=1e-7)


# ------------------------------------------------- compile-count guard


def test_one_executable_serves_all_equal_shape_batches(rng):
    """n_init=4 in two batches of 2: the batched EM executable compiles
    ONCE and serves both batches (jit's shape-keyed cache; the batched
    sweep is fixed-width by design)."""
    data, _ = make_blobs(rng, n=400, d=3, k=3)
    c = cfg(n_init=4, seed=0, restart_batch_size=2)
    model = GMMModel(c)
    fit_gmm(data, 4, 3, config=c, model=model)
    batched_fns = {k: fn for k, fn in model._em_exec_cache.items()
                   if isinstance(k, tuple) and k and k[0] == "batched"}
    assert batched_fns, "the fit never used the batched EM executable"
    traced = [fn for fn in batched_fns.values()
              if getattr(fn, "_cache_size", None) is not None]
    assert traced and all(fn._cache_size() == 1 for fn in traced)


def _batched_trace_counts(model):
    return [fn._cache_size()
            for k, fn in model._em_exec_cache.items()
            if isinstance(k, tuple) and k and k[0] == "batched"
            and getattr(fn, "_cache_size", None) is not None]


def test_ragged_tail_batch_reuses_bucketed_executable(rng):
    """n_init=3 in batches of 2 (full batch + remainder): the R-bucket
    padding in run_em_batched makes the tail batch reuse the SAME
    compiled executable as the full batch -- one trace total, where an
    unbucketed remainder would compile a second R=1 program."""
    data, _ = make_blobs(rng, n=400, d=3, k=3)
    c = cfg(n_init=3, seed=0, restart_batch_size=2)
    model = GMMModel(c)
    fit_gmm(data, 4, 3, config=c, model=model)
    counts = _batched_trace_counts(model)
    assert counts and all(n == 1 for n in counts), counts


def test_pallas_batched_executable_compiles_once(rng):
    """The satellite's compile-count guard on the KERNEL path: two
    equal-shaped batches (plus a bucketed remainder) through
    estep_backend='pallas' trace the batched kernel executable once --
    the memoization is per (R-bucket, K, D, dtype, precision) via the
    executable cache + jit's shape keys, same contract as the jnp path.
    """
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    c = cfg(n_init=3, seed=0, restart_batch_size=2, dtype="float32",
            estep_backend="pallas", pallas_block_b=64, chunk_size=128)
    model = GMMModel(c)
    assert model.batched_stats_fn is not None
    fit_gmm(data, 4, 3, config=c, model=model)
    counts = _batched_trace_counts(model)
    assert counts and all(n == 1 for n in counts), counts


# ------------------------------------------------------------ freeze-out


def test_freeze_out_converged_restart_stops_updating(rng):
    """A restart that converges early freezes: its trajectory log has no
    entries beyond its own iteration count while a sibling keeps
    iterating, and its final params equal its solo run's (the batched
    while-loop's masked freeze-out)."""
    import jax
    import jax.numpy as jnp

    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    data, _ = make_blobs(rng, n=1200, d=3, k=3)
    c = GMMConfig(min_iters=1, max_iters=30, chunk_size=512,
                  dtype="float64")
    model = GMMModel(c)
    chunks, wts = map(jnp.asarray, chunk_events(data, c.chunk_size))
    eps = convergence_epsilon(len(data), 3)

    fresh = seed_clusters_host(data, 3, dtype=np.float64)
    # Pre-converge one lane: EM to (near) fixpoint, then reuse as a seed.
    conv, _, _ = model.run_em(fresh, chunks, wts, eps, min_iters=30,
                              max_iters=30)
    batched = jax.tree_util.tree_map(lambda a, b: jnp.stack([a, b]),
                                     conv, fresh)
    out_s, ll_s, it_s, log_s = model.run_em_batched(
        batched, chunks, wts, eps, trajectory=True)
    it_s = np.asarray(it_s)
    log_s = np.asarray(log_s)
    assert it_s[0] < it_s[1], it_s  # lane 0 froze early, lane 1 kept going
    # frozen lane's trajectory slots beyond its own count stay NaN while
    # the live lane wrote values there
    probe = int(it_s[0]) + 1
    assert np.isnan(log_s[0, probe + 1:]).all()
    assert np.isfinite(log_s[1, probe + 1:int(it_s[1]) + 1]).all()
    # the frozen lane's result equals its solo run (bit-comparable)
    solo, ll_solo, it_solo = model.run_em(conv, chunks, wts, eps)
    assert int(it_solo) == int(it_s[0])
    np.testing.assert_allclose(np.asarray(out_s.means)[0],
                               np.asarray(solo.means), rtol=1e-12)


# --------------------------------------- restart-cache fingerprint guard


def test_restart_cache_rejects_stale_data(rng):
    """Regression (PR 5 satellite): the restart cache is keyed on the
    model instance -- a model reused with DIFFERENT same-shaped data must
    not be served the previous fit's uploaded device arrays."""
    data_a, _ = make_blobs(rng, n=400, d=3, k=3)
    data_b = np.ascontiguousarray(data_a[::-1] + 3.0)  # same shape/dtype
    c = cfg()
    model = GMMModel(c)
    # A live cache spanning two fits is the library-user pattern the
    # fingerprint exists for (order_search clears its own per-fit cache).
    model._restart_cache = {}
    try:
        fit_gmm(data_a, 3, 3, config=c, model=model)
        got = fit_gmm(data_b, 3, 3, config=c, model=model)
    finally:
        model._restart_cache = None
    want = fit_gmm(data_b, 3, 3, config=c)
    np.testing.assert_allclose(got.min_rissanen, want.min_rissanen,
                               rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(got.means), np.asarray(want.means), rtol=1e-9)


# --------------------------------------------- drop-one fault containment


def test_drop_one_restart_keeps_survivors(rng, tmp_path):
    """A nan_loglik fault targeted at restart 1 of a 3-lane batch drops
    THAT lane only: the fit completes from the survivors, the winner is a
    clean lane, and the stream records the drop (tier-1 rehearsal of the
    drop-one-keep-survivors health path)."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream

    data, _ = make_blobs(rng, n=600, d=3, k=3)
    mf = str(tmp_path / "m.jsonl")
    kw = dict(n_init=3, seed=0, restart_batch_size=3, metrics_file=mf)
    with faults.use({"nan_loglik": {"iter": 2, "restart": 1}}) as plan:
        r = fit_gmm(data, 3, 3, config=cfg(**kw))
    assert plan.fired["nan_loglik"] == 1
    assert r.init_index != 1
    assert np.isfinite(r.min_rissanen)
    assert r.health["restart_drops"] == 1
    assert r.health["fatal"]  # the observed fault is recorded, not hidden

    recs = read_stream(mf)
    assert validate_stream(recs) == []
    drops = [x for x in recs if x["event"] == "recovery"
             and x.get("action") == "drop_restart"]
    assert len(drops) == 1 and drops[0]["init"] == 1
    assert drops[0]["outcome"] == "dropped"
    sel = [x for x in recs if x["event"] == "restart_select"][-1]
    assert sel["dropped"] == [1]
    assert sel["winner"] == r.init_index
    # parity with an unfaulted sequential run over the surviving seeds:
    # the survivors' results are untouched by the sibling's fault
    clean = fit_gmm(data, 3, 3, config=cfg(
        n_init=3, seed=0, restart_batch_size=1))
    if clean.init_index != 1:  # winner survived the drop -> same pick
        assert sel["winner"] == clean.init_index
        np.testing.assert_allclose(r.min_rissanen, clean.min_rissanen,
                                   rtol=1e-9)


def test_whole_batch_fatal_escalates_ladder(rng):
    """Every lane fatal (a singular seed covariance poisons lane 0 of a
    1-lane... use an untargeted nan_loglik so ALL lanes fault): the
    escalation ladder runs -- and recovers -- instead of dropping the
    whole batch."""
    data, _ = make_blobs(rng, n=600, d=3, k=3)
    with faults.use({"nan_loglik": {"iter": 2}}) as plan:
        r = fit_gmm(data, 3, 3, config=cfg(
            n_init=2, seed=0, restart_batch_size=2))
    assert plan.fired["nan_loglik"] == 1
    assert np.isfinite(r.min_rissanen)
    assert r.health["recoveries"] >= 1
    assert "restart_drops" not in r.health


# ----------------------------------------------- telemetry stream shape


def test_batched_stream_keeps_per_init_contract(rng, tmp_path):
    """The batched driver's stream is shaped like the sequential one: one
    run_start and one run_summary PER INIT (init-tagged), per-restart
    em_iter trajectories, one upload, and the closing restart_select."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
    from cuda_gmm_mpi_tpu.telemetry.report import render_report

    data, _ = make_blobs(rng, n=400, d=3, k=3)
    mf = str(tmp_path / "m.jsonl")
    r = fit_gmm(data, 3, 3, config=cfg(n_init=3, seed=0,
                                       restart_batch_size=3,
                                       metrics_file=mf))
    recs = read_stream(mf)
    assert validate_stream(recs) == []
    events = [x["event"] for x in recs]
    assert events.count("run_start") == 3
    assert events.count("run_summary") == 3
    assert sorted({x["init"] for x in recs if "init" in x}) == [0, 1, 2]
    starts = [x for x in recs if x["event"] == "run_start"]
    assert all(x["restart_batch_size"] == 3 for x in starts)
    # per-restart em_iter trajectories, tagged by init
    iters = [x for x in recs if x["event"] == "em_iter"]
    assert {x["init"] for x in iters} == {0, 1, 2}
    summ = [x for x in recs if x["event"] == "run_summary"][-1]
    assert summ["metrics"]["counters"]["restarts"] == 2
    assert summ["metrics"]["counters"]["h2d_bytes"] > 0
    sel = [x for x in recs if x["event"] == "restart_select"][-1]
    assert sel["mode"] == "batched" and sel["batch_size"] == 3
    assert sel["winner"] == r.init_index
    assert len(sel["scores"]) == 3
    rep = render_report(recs)
    assert "Restart selection" in rep and "winner init" in rep


# ------------------------------------------------ preemption + resume


def test_preempt_mid_batch_then_bit_identical_resume(rng, tmp_path):
    """A cooperative stop mid-batched-EM writes ONE emergency sub-step
    carrying all R restart trajectories, and --resume auto reproduces the
    uninterrupted batched run's model bit-identically."""
    data, _ = make_blobs(rng, n=900, d=3, k=3)
    kw = dict(n_init=2, seed=0, restart_batch_size=2, min_iters=8,
              max_iters=8, chunk_size=512, dtype="float64",
              preempt_poll_iters=2)
    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")

    def sup():
        return RunSupervisor(install_signals=False)

    with supervisor.use(sup()):
        ref = fit_gmm(data, 5, 2, config=GMMConfig(checkpoint_dir=ck_ref,
                                                   **kw))
    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 4}}) as plan:
            with supervisor.use(sup()):
                fit_gmm(data, 5, 2, config=GMMConfig(checkpoint_dir=ck,
                                                     **kw))
    assert plan.fired["preempt"] == 1
    assert ei.value.checkpointed and ei.value.em_iter == 4
    subs = [f for f in os.listdir(os.path.join(ck, "batch0", "sweep"))
            if ".iter" in f]
    assert subs == ["0.iter4.npz"]

    with supervisor.use(sup()):
        res = fit_gmm(data, 5, 2, config=GMMConfig(checkpoint_dir=ck,
                                                   **kw))
    assert res.init_index == ref.init_index
    assert res.min_rissanen == ref.min_rissanen
    assert res.final_loglik == ref.final_loglik
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))
    # supervised batched EM changes nothing vs the unsupervised batch
    plain = fit_gmm(data, 5, 2, config=GMMConfig(**kw))
    assert plain.min_rissanen == ref.min_rissanen


# --------------------------------------------------- batch-size resolve


def test_restart_batch_size_resolution(rng):
    """Env override > config > auto cap; unsupported paths fall back to
    sequential; everything clamps to [1, n_init]."""
    from cuda_gmm_mpi_tpu.models.restarts import (
        restart_batch_auto_cap, resolve_restart_batch_size,
    )

    data = np.zeros((1000, 4))
    c = cfg(n_init=4)
    model = GMMModel(c)
    assert resolve_restart_batch_size(c, model, data, 8) >= 1
    assert resolve_restart_batch_size(
        cfg(n_init=4, restart_batch_size=3), model, data, 8) == 3
    assert resolve_restart_batch_size(
        cfg(n_init=2, restart_batch_size=64), model, data, 8) == 2
    assert resolve_restart_batch_size(cfg(), model, data, 8) == 1
    # streaming / fused-sweep paths run sequentially
    assert resolve_restart_batch_size(
        cfg(n_init=4, stream_events=True, restart_batch_size=4),
        model, data, 8) == 1
    assert resolve_restart_batch_size(
        cfg(n_init=4, fused_sweep=True, restart_batch_size=4),
        model, data, 8) == 1
    # env overrides config
    os.environ["GMM_RESTART_BATCH_SIZE"] = "2"
    try:
        assert resolve_restart_batch_size(
            cfg(n_init=4, restart_batch_size=4), model, data, 8) == 2
    finally:
        del os.environ["GMM_RESTART_BATCH_SIZE"]
    # the auto cap shrinks with the memory budget
    os.environ["GMM_RESTART_MEM_BYTES"] = str(1 << 20)
    try:
        small = restart_batch_auto_cap(c, 1_000_000, 24, 100)
    finally:
        del os.environ["GMM_RESTART_MEM_BYTES"]
    assert small == 1
    big = restart_batch_auto_cap(c, 1000, 4, 8)
    assert big > small
