"""Preemption-safe execution (supervisor.py; docs/ROBUSTNESS.md "Run
lifecycle"): cooperative stop -> emergency intra-K checkpoint -> exit 75 ->
--resume auto, plus the multi-host liveness watchdog.

The reference dies on SIGTERM with every byte of sweep state in host RAM
(gaussian.cu:262-275) and a dead MPI rank hangs every survivor's next
collective. Here a SIGTERM mid-EM must exit 75 (EX_TEMPFAIL) with a durable
``<step>.iter<i>.npz`` sub-step, the resumed run must reproduce the
uninterrupted run's model BIT-identically, and a lost peer must fail loudly
within the watchdog timeout instead of blocking forever.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, supervisor
from cuda_gmm_mpi_tpu.supervisor import (PeerLostError, PreemptedError,
                                         RunSupervisor)
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import communicate_or_kill, make_blobs, worker_env


def _cfg(ck, **kw):
    base = dict(min_iters=8, max_iters=8, chunk_size=512, dtype="float64",
                checkpoint_dir=ck, preempt_poll_iters=2)
    base.update(kw)
    return GMMConfig(**base)


def _substeps(ck):
    """Intra-K emergency sub-step files (``<step>.iter<i>.npz``) on disk."""
    d = os.path.join(ck, "sweep")
    if not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d)
                  if ".iter" in f and f.endswith(".npz"))


def _full_steps(ck):
    d = os.path.join(ck, "sweep")
    if not os.path.isdir(d):
        return []
    return [f for f in os.listdir(d)
            if f.isdigit() or (f.endswith(".npz") and f[:-4].isdigit()
                               and ".iter" not in f)]


def _sup():
    return RunSupervisor(install_signals=False)


@pytest.fixture
def blobs3(rng):
    centers = rng.normal(scale=8.0, size=(3, 3))
    data = (centers[rng.integers(0, 3, 3000)]
            + rng.normal(size=(3000, 3))).astype(np.float64)
    return data


def test_injected_preempt_mid_em_then_bit_identical_resume(tmp_path, blobs3):
    """The tentpole contract, in-process and deterministic: a cooperative
    stop at EM iteration 3 writes the intra-K sub-step, raises
    PreemptedError (checkpointed, step/iter attached), and --resume auto
    reproduces the uninterrupted run's selected model bit-identically.
    Also proves the segmented supervised EM driver itself is bit-identical
    to the unsupervised single-dispatch loop."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
    from cuda_gmm_mpi_tpu.telemetry.report import render_report

    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")
    with supervisor.use(_sup()):
        ref = fit_gmm(blobs3, 6, 2, config=_cfg(ck_ref))

    # The supervised segmented EM driver changes no results: a plain
    # unsupervised run (single-dispatch loop, no checkpointing) agrees
    # bit-for-bit.
    plain = fit_gmm(blobs3, 6, 2, config=GMMConfig(
        min_iters=8, max_iters=8, chunk_size=512, dtype="float64"))
    assert plain.min_rissanen == ref.min_rissanen
    np.testing.assert_array_equal(np.asarray(plain.means),
                                  np.asarray(ref.means))

    mf = tmp_path / "m.jsonl"
    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 3}}) as plan:
            with supervisor.use(_sup()):
                fit_gmm(blobs3, 6, 2,
                        config=_cfg(ck, metrics_file=str(mf)))
    assert plan.fired["preempt"] == 1
    e = ei.value
    assert e.reason == "preempt_injected"
    assert e.checkpointed and e.step == 0 and e.em_iter == 3
    assert _substeps(ck) == ["0.iter3.npz"]

    # Lifecycle telemetry: one preempt + one shutdown record, both valid.
    records = read_stream(str(mf))
    assert validate_stream(records) == []
    pre = [r for r in records if r["event"] == "preempt"]
    shut = [r for r in records if r["event"] == "shutdown"]
    assert len(pre) == 1 and pre[0]["reason"] == "preempt_injected"
    assert pre[0]["where"] == "em" and pre[0]["em_iter"] == 3
    assert len(shut) == 1 and shut[0]["checkpointed"]
    rep = render_report(records)
    assert "preempt" in rep and "exit 75" in rep

    # --resume auto (the default) restarts INSIDE the interrupted fit.
    with supervisor.use(_sup()):
        res = fit_gmm(blobs3, 6, 2, config=_cfg(ck))
    assert res.ideal_num_clusters == ref.ideal_num_clusters
    assert res.min_rissanen == ref.min_rissanen
    assert res.final_loglik == ref.final_loglik
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))
    # The sub-step is pruned once its K completed and saved durably.
    assert _substeps(ck) == []


def test_injected_preempt_streaming_mid_block(tmp_path, rng):
    """Streaming path: a stop targeted at pass 2, block 4 checkpoints the
    partially reduced block accumulator (stream_acc/stream_pass/
    stream_block in the sub-step) and the resumed run -- which replays the
    pass from the first unprocessed block -- stays bit-identical."""
    centers = rng.normal(scale=8.0, size=(3, 3))
    data = (centers[rng.integers(0, 3, 4096)]
            + rng.normal(size=(4096, 3))).astype(np.float64)
    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")
    kw = dict(min_iters=5, max_iters=5, chunk_size=256, stream_events=True)

    with supervisor.use(_sup()):
        ref = fit_gmm(data, 5, 2, config=_cfg(ck_ref, **kw))

    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 2, "block": 4}}):
            with supervisor.use(_sup()):
                fit_gmm(data, 5, 2, config=_cfg(ck, **kw))
    assert ei.value.checkpointed
    subs = _substeps(ck)
    assert len(subs) == 1
    with np.load(os.path.join(ck, "sweep", subs[0])) as z:
        keys = set(z.files)
        assert {"stream_pass", "stream_block",
                "stream_acc.Nk", "stream_acc.M1", "stream_acc.M2"} <= keys
        assert int(z["stream_pass"]) == 2 and int(z["stream_block"]) == 5

    with supervisor.use(_sup()):
        res = fit_gmm(data, 5, 2, config=_cfg(ck, **kw))
    assert res.min_rissanen == ref.min_rissanen
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))


def test_injected_preempt_sharded_mesh(tmp_path, rng):
    """The supervised segmented driver works on a (4,2) sharded mesh too
    (ShardedGMMModel borrows run_em_resumable): mid-EM stop, intra-K
    sub-step, bit-identical resume -- health counts stay psum-exact."""
    centers = rng.normal(scale=8.0, size=(3, 3))
    data = (centers[rng.integers(0, 3, 4096)]
            + rng.normal(size=(4096, 3))).astype(np.float64)
    kw = dict(min_iters=6, max_iters=6, chunk_size=256, mesh_shape=(4, 2))
    ck_ref, ck = str(tmp_path / "ref"), str(tmp_path / "ck")
    with supervisor.use(_sup()):
        ref = fit_gmm(data, 6, 2, config=_cfg(ck_ref, **kw))
    with pytest.raises(PreemptedError) as ei:
        with faults.use({"preempt": {"iter": 3}}):
            with supervisor.use(_sup()):
                fit_gmm(data, 6, 2, config=_cfg(ck, **kw))
    assert ei.value.checkpointed and ei.value.em_iter == 3
    assert _substeps(ck) == ["0.iter3.npz"]
    with supervisor.use(_sup()):
        res = fit_gmm(data, 6, 2, config=_cfg(ck, **kw))
    assert res.min_rissanen == ref.min_rissanen
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))


def test_fused_sweep_stops_at_emission(tmp_path, blobs3):
    """The fused whole-sweep device program's only host intervention point
    is its per-K emission callback: a deadline observed there aborts the
    program with the completed K's checkpoint durable (per-K granularity,
    no sub-step) and the rerun resumes to the same answer."""
    ck = str(tmp_path / "ck")
    kw = dict(min_iters=6, max_iters=6, chunk_size=256, fused_sweep=True)
    with pytest.raises(PreemptedError) as ei:
        with supervisor.use(RunSupervisor(install_signals=False,
                                          max_runtime_s=1e-3)):
            fit_gmm(blobs3, 6, 2, config=_cfg(ck, **kw))
    assert ei.value.reason == "deadline" and ei.value.checkpointed
    assert _full_steps(ck) and not _substeps(ck)

    res = fit_gmm(blobs3, 6, 2, config=_cfg(ck, **kw))
    ref = fit_gmm(blobs3, 6, 2, config=_cfg(str(tmp_path / "ref"), **kw))
    assert res.min_rissanen == ref.min_rissanen
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))


def test_resume_never_starts_fresh(tmp_path, blobs3):
    """resume='never' ignores the interrupted run's checkpoints (sub-step
    included): the sweep restarts at the top K and re-runs every step."""
    ck = str(tmp_path / "ck")
    with pytest.raises(PreemptedError):
        with faults.use({"preempt": {"iter": 3}}):
            with supervisor.use(_sup()):
                fit_gmm(blobs3, 6, 2, config=_cfg(ck))
    assert _substeps(ck)

    r = fit_gmm(blobs3, 6, 2, config=_cfg(ck, resume="never"))
    assert r.sweep_log[0][0] == 6            # restarted at the top
    assert len(r.sweep_log) == 5             # ...and ran every K itself
    # 'never' still writes new checkpoints for the NEXT resume.
    assert _full_steps(ck)


def test_deadline_preempts_library_run(tmp_path, blobs3):
    """GMMConfig.max_runtime_s alone (library call, no ambient supervisor)
    activates a signals-free supervisor whose deadline trips the same
    cooperative stop a SIGTERM does."""
    with pytest.raises(PreemptedError) as ei:
        fit_gmm(blobs3, 6, 2, config=_cfg(
            str(tmp_path / "ck"), max_runtime_s=1e-3))
    assert ei.value.reason == "deadline"


def test_watchdog_detects_stale_peer(tmp_path):
    """LivenessWatchdog.check_peers flags the peer whose heartbeat stops
    CHANGING for longer than the timeout of reader-local monotonic time,
    and a fresh heartbeat clears it. A backdated mtime (writer clock
    skew, an NTP step) is just a changed file, never instant staleness:
    mtimes are compared only for equality, not against this host's
    clock."""
    from cuda_gmm_mpi_tpu.parallel import distributed
    from cuda_gmm_mpi_tpu.supervisor import LivenessWatchdog

    d = str(tmp_path)
    distributed.write_rank_heartbeat(d, 0)
    distributed.write_rank_heartbeat(d, 1)
    w = LivenessWatchdog(d, rank=0, nproc=2, timeout_s=0.4)
    assert w.check_peers() is None
    old = time.time() - 60.0
    os.utime(distributed.heartbeat_path(d, 1), (old, old))
    assert w.check_peers() is None  # skew-immune: changed, not stale
    deadline = time.time() + 10.0
    lost = w.check_peers()
    while lost is None and time.time() < deadline:
        time.sleep(0.05)
        lost = w.check_peers()
    assert lost is not None
    rank, age = lost
    assert rank == 1 and age > 0.4
    distributed.write_rank_heartbeat(d, 1)
    assert w.check_peers() is None


def test_watchdog_peer_loss_trips_stop_and_raises(tmp_path):
    """End-to-end in one process: a watchdog whose peer never heartbeats
    trips the stop flag with reason peer_lost within the timeout, and
    raise_stop surfaces it as PeerLostError carrying the peer diagnosis."""
    sup = _sup()
    sup.install()
    try:
        # Peer rank 1 never writes: it ages from watchdog start and the
        # timeout doubles as the startup grace window.
        sup.start_watchdog(str(tmp_path / "hb"), rank=0, nproc=2,
                           timeout_s=2.5, interval_s=0.1)
        deadline = time.time() + 20.0
        while not sup.stop_requested and time.time() < deadline:
            time.sleep(0.01)
        assert sup.stop_requested and sup.stop_reason == "peer_lost"
        assert sup.lost_peer and sup.lost_peer["rank"] == 1
        assert sup.collective_timeout_s == 2.5  # barrier bound while alive
        assert sup.poll(where="em", k=4, em_iter=2)
        with pytest.raises(PeerLostError) as ei:
            sup.raise_stop(step=1, em_iter=2, checkpointed=True)
        assert ei.value.rank == 1 and ei.value.timeout_s == 2.5
    finally:
        sup.uninstall()


def test_raise_stop_maps_reasons():
    """signal/deadline reasons raise PreemptedError; peer_lost raises
    PeerLostError -- the CLI maps both to exit 75."""
    sup = _sup()
    sup.request_stop("sigterm")
    with pytest.raises(PreemptedError) as ei:
        sup.raise_stop(step=2, em_iter=7, checkpointed=True)
    assert ei.value.reason == "sigterm" and ei.value.em_iter == 7

    sup2 = _sup()
    sup2._lost_peer = {"rank": 1, "age_s": 9.5, "timeout_s": 5.0}
    sup2.request_stop("peer_lost")
    with pytest.raises(PeerLostError):
        sup2.raise_stop(step=0, checkpointed=False)


# -- subprocess harnesses ---------------------------------------------------

CLI = [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli"]
PEER_WORKER = os.path.join(os.path.dirname(__file__), "preempt_worker.py")


def _cli_args(infile, out, ck):
    # Sized so each K's EM spans seconds on CPU (wide mid-EM window) while
    # the full sweep is only two Ks; buckets off keeps the loop free of
    # between-K recompiles, so ~all wall time is inside run_em_resumable.
    return ["4", infile, str(out), "3", "--device=cpu", "--dtype=float64",
            "--min-iters=40", "--max-iters=40", "--sweep-k-buckets=off",
            "--preempt-poll-iters=2", f"--checkpoint-dir={ck}"]


def test_sigterm_mid_em_exits_75_then_bit_identical_resume(tmp_path, rng):
    """The acceptance contract with a REAL signal: SIGTERM a running CLI
    sweep mid-EM-fit, assert exit 75 within the grace window plus a
    durable intra-K sub-step, then assert the resumed run's final model
    files are byte-identical to an uninterrupted run's."""
    from cuda_gmm_mpi_tpu.io.readers import write_bin

    centers = rng.normal(scale=9.0, size=(4, 3))
    n = 80_000
    data = (centers[rng.integers(0, 4, n)]
            + rng.normal(size=(n, 3))).astype(np.float32)
    infile = str(tmp_path / "events.bin")
    write_bin(infile, data)

    def spawn(out, ck):
        return subprocess.Popen(CLI + _cli_args(infile, out, ck),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE,
                                env=worker_env(), text=True)

    # SIGTERM lands at a random point of K=3's multi-second EM (we wait
    # for K=4's completed step 0 first), so the stop is mid-EM with high
    # probability -- but a kill in the ms-wide between-K window is legal
    # (exit 75, no sub-step), so retry the interrupted phase until the
    # sub-step materializes.
    ck = None
    for attempt in range(3):
        ck_try = str(tmp_path / f"ck{attempt}")
        p = spawn(tmp_path / f"int{attempt}", ck_try)
        deadline = time.time() + 300.0
        try:
            while time.time() < deadline:
                if _full_steps(ck_try):
                    break
                if p.poll() is not None:
                    out_, err_ = p.communicate()
                    raise AssertionError(
                        f"worker exited before SIGTERM (rc={p.returncode})"
                        f":\n{out_}\n{err_[-3000:]}")
                time.sleep(0.05)
            else:
                raise AssertionError("no checkpoint step appeared")
            time.sleep(0.4)  # well inside K=3's EM
            p.send_signal(signal.SIGTERM)
            out_, err_ = communicate_or_kill(p, timeout=120)
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=60)
        assert p.returncode == 75, (
            f"expected EX_TEMPFAIL:\n{out_}\n{err_[-3000:]}")
        assert "Preempted" in err_
        if _substeps(ck_try):
            ck = ck_try
            break
    assert ck is not None, "SIGTERM never landed mid-EM in 3 attempts"

    # Resume completes the sweep (exit 0) from inside the interrupted fit.
    out_res = tmp_path / "resumed"
    p2 = spawn(out_res, ck)
    o2, e2 = communicate_or_kill(p2, timeout=600)
    assert p2.returncode == 0, f"resume failed:\n{o2}\n{e2[-3000:]}"
    assert _substeps(ck) == []  # consumed + pruned by the completed K

    # Ground truth: uninterrupted run, fresh checkpoint dir.
    out_ref = tmp_path / "ref"
    p3 = spawn(out_ref, str(tmp_path / "ck_ref"))
    o3, e3 = communicate_or_kill(p3, timeout=600)
    assert p3.returncode == 0, f"reference failed:\n{o3}\n{e3[-3000:]}"

    assert (tmp_path / "resumed.summary").read_bytes() == \
        (tmp_path / "ref.summary").read_bytes()
    assert (tmp_path / "resumed.results").read_bytes() == \
        (tmp_path / "ref.results").read_bytes()


@pytest.mark.slow
def test_two_process_rank_hang_watchdog(tmp_path):
    """A 2-host run where rank 1 stops heartbeating and wedges mid-EM
    (rank_hang injection): rank 0's liveness watchdog must detect the
    stale peer within peer_timeout_s and exit 75 loudly -- cooperatively
    via PeerLostError if a poll point is reachable, else through the
    forced-exit escalation -- instead of blocking forever in the next
    collective (the reference's dead-MPI-rank behavior)."""
    import json
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ck = str(tmp_path / "ck")
    procs = []
    for i in range(2):
        env = worker_env()
        if i == 1:
            env["GMM_FAULTS"] = json.dumps(
                {"rank_hang": {"rank": 1, "iter": 4}})
        procs.append(subprocess.Popen(
            [sys.executable, PEER_WORKER, str(i), "2", str(port), ck],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True))
    try:
        # Rank 0 must exit 75 on its own; rank 1 is wedged by design and
        # is killed by the harness afterwards.
        out0, err0 = communicate_or_kill(procs[0], timeout=600)
        if "Multiprocess computations aren't implemented" in out0 + err0:
            pytest.skip("CPU backend lacks multi-process collectives "
                        "(same limitation as tests/test_multihost.py)")
        assert procs[0].returncode == 75, (
            f"rank 0 rc={procs[0].returncode}:\n{out0}\n{err0[-3000:]}")
        assert ("PEER_LOST" in out0 or "heartbeat stale" in err0), \
            f"no peer-loss diagnosis:\n{out0}\n{err0[-3000:]}"
        assert procs[1].poll() is None, "rank 1 was supposed to be wedged"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=60)


WATCHDOG_WORKER = r"""
import sys, time
rank, hbdir = int(sys.argv[1]), sys.argv[2]
from cuda_gmm_mpi_tpu import supervisor

sup = supervisor.RunSupervisor()
sup.install()
sup.start_watchdog(hbdir, rank=rank, nproc=2, timeout_s=4.0,
                   interval_s=0.5)
if rank == 1:
    time.sleep(2.0)       # heartbeat a few rounds...
    sup.stop_watchdog()   # ...then "die": the heartbeat goes stale
# Both ranks now simulate a main thread wedged inside a collective that
# will never return (no poll point is ever reached): only the watchdog's
# forced-exit escalation can end rank 0.
time.sleep(600)
"""


@pytest.mark.slow
def test_two_process_watchdog_forced_exit(tmp_path):
    """The watchdog's last line of defense, across real processes and a
    real shared heartbeat directory (no device collectives, so it runs on
    any backend): when the peer dies AND the main thread is wedged where
    no poll point can run, the forced-exit escalation ends rank 0 with
    exit 75 within timeout + grace instead of hanging forever."""
    hb = str(tmp_path / "hb")
    procs = [subprocess.Popen(
        [sys.executable, "-c", WATCHDOG_WORKER, str(i), hb],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=worker_env(),
        text=True) for i in range(2)]
    try:
        t0 = time.time()
        out0, err0 = communicate_or_kill(procs[0], timeout=120)
        took = time.time() - t0
        assert procs[0].returncode == 75, (
            f"rank 0 rc={procs[0].returncode}:\n{out0}\n{err0[-3000:]}")
        assert "heartbeat stale" in err0 and "forcing exit" in err0, err0
        # died at ~2s + timeout 4s + grace 4s, never anywhere near the
        # wedged sleep: detection really was timeout-bounded
        assert took < 60.0
        assert procs[1].poll() is None  # the dead peer stays wedged
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=60)
