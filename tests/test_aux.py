"""Aux subsystems: profiler taxonomy, checkpoint/resume, structured logging."""

import json

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm
from cuda_gmm_mpi_tpu.utils.logging_ import get_logger, metrics_line
from cuda_gmm_mpi_tpu.utils.profiling import CATEGORIES, PhaseTimer

from .conftest import make_blobs


def fast_cfg(**kw):
    base = dict(min_iters=3, max_iters=3, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


def test_phase_timer_categories():
    t = PhaseTimer()
    assert set(CATEGORIES) == {"e_step", "m_step", "constants", "reduce",
                               "memcpy", "cpu", "mpi"}  # gaussian.cu:76-84
    with t.phase("e_step"):
        pass
    with t.phase("custom"):
        pass
    assert t.counts["e_step"] == 1
    rep = t.report()
    for c in CATEGORIES:
        assert c in rep
    assert "custom" in rep


def test_fit_profile_populated(rng):
    data, _ = make_blobs(rng, n=400, d=2, k=2)
    result = fit_gmm(data, 3, 2, config=fast_cfg(profile=True))
    assert result.profile is not None
    assert result.profile["e_step"] > 0
    assert result.profile["reduce"] > 0  # one merge happened
    assert "e_step" in result.profile_report


def test_checkpoint_resume(rng, tmp_path):
    data, _ = make_blobs(rng, n=400, d=2, k=3)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck"))
    r1 = fit_gmm(data, 6, 2, config=cfg)
    # a second run with the same dir resumes (partially) and must agree
    r2 = fit_gmm(data, 6, 2, config=cfg)
    assert r2.ideal_num_clusters == r1.ideal_num_clusters
    np.testing.assert_allclose(r2.min_rissanen, r1.min_rissanen, rtol=1e-9)
    np.testing.assert_allclose(r2.means, r1.means, rtol=1e-7, atol=1e-8)
    # resumed run skipped the already-completed K values
    assert len(r2.sweep_log) <= len(r1.sweep_log)


def test_checkpoint_restore_skips_torn_newest(rng, tmp_path):
    """A corrupt newest npz checkpoint (torn write on a crash) must not
    wedge resume: restore falls back to the next older step."""
    from cuda_gmm_mpi_tpu.utils.checkpoint import SweepCheckpointer

    data, _ = make_blobs(rng, n=400, d=2, k=3)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck"), fused_sweep=True)
    fit_gmm(data, 6, 2, config=cfg)  # writes <step>.npz per completed K
    ck = SweepCheckpointer(str(tmp_path / "ck"))
    newest = ck.latest_step()
    assert newest is not None and newest >= 1
    path = tmp_path / "ck" / "sweep" / f"{newest}.npz"
    good = ck.restore(newest - 1)
    path.write_bytes(path.read_bytes()[: max(8, path.stat().st_size // 3)])
    restored = ck.restore()
    assert restored is not None and restored["step"] == newest - 1
    np.testing.assert_array_equal(np.asarray(restored["state"].means),
                                  np.asarray(good["state"].means))


def test_checkpoint_restore_all_torn_aggregates_errors(tmp_path):
    """When EVERY step is unreadable the walk-back must not re-raise only
    the oldest step's error (the old bug): the failures aggregate into one
    CheckpointRestoreError, newest step first, with the newest --- usually
    most informative --- failure chained as __cause__."""
    from cuda_gmm_mpi_tpu.utils.checkpoint import (CheckpointRestoreError,
                                                   SweepCheckpointer)

    ck = SweepCheckpointer(str(tmp_path / "ck"))
    sweep = tmp_path / "ck" / "sweep"
    (sweep / "0.npz").write_bytes(b"torn")  # the SOLE checkpoint is torn
    with pytest.warns(RuntimeWarning):
        with pytest.raises(CheckpointRestoreError) as ei:
            ck.restore()
    err = ei.value
    assert [s for s, _ in err.errors] == [0]
    assert err.__cause__ is err.errors[0][1]
    assert "step 0" in str(err)

    (sweep / "1.npz").write_bytes(b"also torn")
    with pytest.warns(RuntimeWarning):
        with pytest.raises(CheckpointRestoreError) as ei2:
            ck.restore()
    assert [s for s, _ in ei2.value.errors] == [1, 0]  # newest first
    assert ei2.value.__cause__ is ei2.value.errors[0][1]

    # an EMPTY directory is not an error -- just nothing to resume
    assert SweepCheckpointer(str(tmp_path / "empty")).restore() is None


def test_crash_window_prune_sweeps_orphans(rng, tmp_path):
    """Kill between a durable save_local and its _prune: the leftovers (an
    older step, a superseded intra-K sub-step, a mkstemp .tmp.npz orphan)
    must not confuse resume -- it picks the newest step -- and the NEXT
    durable save sweeps all of them."""
    import shutil

    from cuda_gmm_mpi_tpu.utils.checkpoint import SweepCheckpointer

    data, _ = make_blobs(rng, n=400, d=2, k=3)
    ck = tmp_path / "ck"
    fit_gmm(data, 6, 2,
            config=fast_cfg(checkpoint_dir=str(ck), fused_sweep=True))
    sweep = ck / "sweep"
    ckpt = SweepCheckpointer(str(ck))
    newest = ckpt.latest_step()
    assert newest is not None and newest >= 1
    # Re-create the crash window's debris as if _prune never ran:
    shutil.copy(sweep / f"{newest}.npz", sweep / "0.npz")
    shutil.copy(sweep / f"{newest}.npz", sweep / "0.iter3.npz")
    (sweep / "deadbeef.tmp.npz").write_bytes(b"torn tmp payload")

    # Resume picks the newest step; the sub-step at/below it is stale
    # (its K completed after the emergency save) and is ignored.
    assert ckpt.restore()["step"] == newest
    assert ckpt.restore_substep() is None

    payload = {k: v for k, v in ckpt.restore(newest).items() if k != "step"}
    ckpt.save_local(newest + 1, payload)
    names = {f.name for f in sweep.iterdir()}
    assert f"{newest + 1}.npz" in names
    assert "0.npz" not in names and "0.iter3.npz" not in names
    assert not any(n.endswith(".tmp.npz") for n in names)


def test_checkpoint_retention_bounds_disk(rng, tmp_path):
    """Only the retention window (default 2 steps) survives a sweep: a
    K=512 run must not leave ~500 dead checkpoints on the (possibly GCS)
    checkpoint filesystem. Applies to both write paths."""
    import os

    data, _ = make_blobs(rng, n=400, d=2, k=3)
    for sub, extra in (("host", {}), ("fused", dict(fused_sweep=True))):
        ck = tmp_path / sub
        fit_gmm(data, 8, 2, config=fast_cfg(checkpoint_dir=str(ck), **extra))
        steps = [f for f in os.listdir(ck / "sweep")
                 if f.isdigit() or (f.endswith(".npz") and f[:-4].isdigit())]
        assert len(steps) <= 2, (sub, steps)
        # ...and the survivors still resume to the same answer
        r = fit_gmm(data, 8, 2,
                    config=fast_cfg(checkpoint_dir=str(ck), **extra))
        assert r.ideal_num_clusters >= 2


def test_checkpoint_ignored_for_different_k(rng, tmp_path):
    data, _ = make_blobs(rng, n=300, d=2, k=2)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck2"))
    fit_gmm(data, 4, 2, config=cfg)
    r = fit_gmm(data, 3, 2, config=cfg)  # different starting K -> fresh sweep
    assert r.sweep_log[0][0] == 3


def test_logger_levels():
    import logging

    lg = get_logger(GMMConfig(enable_debug=True))
    assert lg.level == logging.DEBUG
    lg = get_logger(GMMConfig(enable_print=True))
    assert lg.level == logging.INFO
    lg = get_logger(GMMConfig())
    assert lg.level == logging.WARNING


def test_metrics_line(capsys):
    import io

    buf = io.StringIO()
    rec = metrics_line("em_done", stream=buf, k=5, loglik=-1.5)
    parsed = json.loads(buf.getvalue())
    assert parsed["event"] == "em_done" and parsed["k"] == 5
    assert rec["loglik"] == -1.5
