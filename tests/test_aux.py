"""Aux subsystems: profiler taxonomy, checkpoint/resume, structured logging."""

import json

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm
from cuda_gmm_mpi_tpu.utils.logging_ import get_logger, metrics_line
from cuda_gmm_mpi_tpu.utils.profiling import CATEGORIES, PhaseTimer

from .conftest import make_blobs


def fast_cfg(**kw):
    base = dict(min_iters=3, max_iters=3, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


def test_phase_timer_categories():
    t = PhaseTimer()
    assert set(CATEGORIES) == {"e_step", "m_step", "constants", "reduce",
                               "memcpy", "cpu", "mpi"}  # gaussian.cu:76-84
    with t.phase("e_step"):
        pass
    with t.phase("custom"):
        pass
    assert t.counts["e_step"] == 1
    rep = t.report()
    for c in CATEGORIES:
        assert c in rep
    assert "custom" in rep


def test_fit_profile_populated(rng):
    data, _ = make_blobs(rng, n=400, d=2, k=2)
    result = fit_gmm(data, 3, 2, config=fast_cfg(profile=True))
    assert result.profile is not None
    assert result.profile["e_step"] > 0
    assert result.profile["reduce"] > 0  # one merge happened
    assert "e_step" in result.profile_report


def test_checkpoint_resume(rng, tmp_path):
    data, _ = make_blobs(rng, n=400, d=2, k=3)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck"))
    r1 = fit_gmm(data, 6, 2, config=cfg)
    # a second run with the same dir resumes (partially) and must agree
    r2 = fit_gmm(data, 6, 2, config=cfg)
    assert r2.ideal_num_clusters == r1.ideal_num_clusters
    np.testing.assert_allclose(r2.min_rissanen, r1.min_rissanen, rtol=1e-9)
    np.testing.assert_allclose(r2.means, r1.means, rtol=1e-7, atol=1e-8)
    # resumed run skipped the already-completed K values
    assert len(r2.sweep_log) <= len(r1.sweep_log)


def test_checkpoint_restore_skips_torn_newest(rng, tmp_path):
    """A corrupt newest npz checkpoint (torn write on a crash) must not
    wedge resume: restore falls back to the next older step."""
    from cuda_gmm_mpi_tpu.utils.checkpoint import SweepCheckpointer

    data, _ = make_blobs(rng, n=400, d=2, k=3)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck"), fused_sweep=True)
    fit_gmm(data, 6, 2, config=cfg)  # writes <step>.npz per completed K
    ck = SweepCheckpointer(str(tmp_path / "ck"))
    newest = ck.latest_step()
    assert newest is not None and newest >= 1
    path = tmp_path / "ck" / "sweep" / f"{newest}.npz"
    good = ck.restore(newest - 1)
    path.write_bytes(path.read_bytes()[: max(8, path.stat().st_size // 3)])
    restored = ck.restore()
    assert restored is not None and restored["step"] == newest - 1
    np.testing.assert_array_equal(np.asarray(restored["state"].means),
                                  np.asarray(good["state"].means))


def test_checkpoint_retention_bounds_disk(rng, tmp_path):
    """Only the retention window (default 2 steps) survives a sweep: a
    K=512 run must not leave ~500 dead checkpoints on the (possibly GCS)
    checkpoint filesystem. Applies to both write paths."""
    import os

    data, _ = make_blobs(rng, n=400, d=2, k=3)
    for sub, extra in (("host", {}), ("fused", dict(fused_sweep=True))):
        ck = tmp_path / sub
        fit_gmm(data, 8, 2, config=fast_cfg(checkpoint_dir=str(ck), **extra))
        steps = [f for f in os.listdir(ck / "sweep")
                 if f.isdigit() or (f.endswith(".npz") and f[:-4].isdigit())]
        assert len(steps) <= 2, (sub, steps)
        # ...and the survivors still resume to the same answer
        r = fit_gmm(data, 8, 2,
                    config=fast_cfg(checkpoint_dir=str(ck), **extra))
        assert r.ideal_num_clusters >= 2


def test_checkpoint_ignored_for_different_k(rng, tmp_path):
    data, _ = make_blobs(rng, n=300, d=2, k=2)
    cfg = fast_cfg(checkpoint_dir=str(tmp_path / "ck2"))
    fit_gmm(data, 4, 2, config=cfg)
    r = fit_gmm(data, 3, 2, config=cfg)  # different starting K -> fresh sweep
    assert r.sweep_log[0][0] == 3


def test_logger_levels():
    import logging

    lg = get_logger(GMMConfig(enable_debug=True))
    assert lg.level == logging.DEBUG
    lg = get_logger(GMMConfig(enable_print=True))
    assert lg.level == logging.INFO
    lg = get_logger(GMMConfig())
    assert lg.level == logging.WARNING


def test_metrics_line(capsys):
    import io

    buf = io.StringIO()
    rec = metrics_line("em_done", stream=buf, k=5, loglik=-1.5)
    parsed = json.loads(buf.getvalue())
    assert parsed["event"] == "em_done" and parsed["k"] == 5
    assert rec["loglik"] == -1.5
