"""Native C++ reader/writer parity with the Python implementations."""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.io import native
from cuda_gmm_mpi_tpu.io.readers import read_bin, read_csv, write_bin
from cuda_gmm_mpi_tpu.io.writers import write_results

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native gmm_io library not built"
)


def test_native_csv_matches_python(tmp_path, rng):
    data = rng.normal(scale=100, size=(500, 7)).astype(np.float32)
    p = tmp_path / "d.csv"
    p.write_text(
        ",".join(f"h{i}" for i in range(7)) + "\n"
        + "\n".join(",".join(f"{v:.6f}" for v in row) for row in data)
    )
    a = native.read_data(str(p))
    b = read_csv(str(p))
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.float32 and a.shape == (500, 7)


def test_native_bin_matches_python(tmp_path, rng):
    data = rng.normal(size=(123, 4)).astype(np.float32)
    p = tmp_path / "d.bin"
    write_bin(str(p), data)
    np.testing.assert_array_equal(native.read_data(str(p)), read_bin(str(p)))


def test_native_csv_blank_lines_and_crlf(tmp_path):
    p = tmp_path / "d.csv"
    p.write_bytes(b"a,b\r\n\r\n1.5,2.5\r\n\r\n3.5,4.5\r\n")
    out = native.read_data(str(p))
    np.testing.assert_allclose(out, [[1.5, 2.5], [3.5, 4.5]])


def test_native_csv_ragged_errors(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,b,c\n1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        native.read_data(str(p))


def test_native_csv_atof_semantics(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,b\nhello,1.25e2\n-3.5xyz,0\n")
    out = native.read_data(str(p))
    np.testing.assert_allclose(out, [[0.0, 125.0], [-3.5, 0.0]])


def test_native_writer_matches_python(tmp_path, rng):
    data = rng.normal(scale=10, size=(200, 5)).astype(np.float32)
    memb = rng.random(size=(200, 3)).astype(np.float32)
    memb /= memb.sum(1, keepdims=True)
    p_native = tmp_path / "n.results"
    p_python = tmp_path / "p.results"
    native.write_results(str(p_native), data, memb)
    write_results(str(p_python), data, memb, use_native="never")
    a = p_native.read_text().splitlines()
    b = p_python.read_text().splitlines()
    assert len(a) == len(b) == 200
    mismatches = [
        (x, y) for x, y in zip(a, b) if x != y
    ]
    # printf %f and our fixed-point formatter may differ in the last digit on
    # ties; allow a tiny number of one-ulp formatting diffs but no structural
    # ones.
    for x, y in mismatches:
        xs = x.replace("\t", ",").split(",")
        ys = y.replace("\t", ",").split(",")
        assert len(xs) == len(ys)
        np.testing.assert_allclose(
            [float(v) for v in xs], [float(v) for v in ys], atol=2e-6
        )


def test_native_missing_file():
    with pytest.raises(ValueError):
        native.read_data("/nonexistent/file.csv")


def test_streaming_results_byte_identical(tmp_path, rng):
    """stream_results == write_results, native and text paths alike."""
    from cuda_gmm_mpi_tpu.io.writers import stream_results

    data = rng.normal(scale=10, size=(317, 4)).astype(np.float32)
    memb = rng.random(size=(317, 5)).astype(np.float32)
    memb /= memb.sum(1, keepdims=True)

    def blocks():
        for lo in range(0, 317, 64):  # uneven tail block on purpose
            yield data[lo:lo + 64], memb[lo:lo + 64]

    for mode in ["always", "never"]:  # native handle API vs text fallback
        p_mono = tmp_path / f"mono_{mode}.results"
        p_stream = tmp_path / f"stream_{mode}.results"
        write_results(str(p_mono), data, memb, use_native=mode)
        n = stream_results(str(p_stream), blocks(), use_native=mode)
        assert n == 317
        assert p_stream.read_bytes() == p_mono.read_bytes()


def test_results_writer_context_manager(tmp_path, rng):
    data = rng.normal(size=(10, 2)).astype(np.float32)
    memb = rng.random(size=(10, 3)).astype(np.float32)
    p = tmp_path / "w.results"
    with native.ResultsWriter(str(p)) as w:
        w.append(data[:6], memb[:6])
        w.append(data[6:], memb[6:])
    assert len(p.read_text().splitlines()) == 10
    with pytest.raises(ValueError):
        with native.ResultsWriter(str(tmp_path / "x.results")) as w:
            w.append(data[:4], memb[:5])
