"""Reader/writer semantics: header drop, BIN layout, output formats."""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.io.readers import read_bin, read_csv, read_data, write_bin


def test_csv_drops_header(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("colA,colB,colC\n1.0,2.0,3.0\n4.5,5.5,6.5\n")
    data = read_csv(str(p))
    assert data.shape == (2, 3)
    np.testing.assert_allclose(data, [[1.0, 2.0, 3.0], [4.5, 5.5, 6.5]])
    assert data.dtype == np.float32


def test_csv_blank_lines_skipped(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2\n\n1,2\n\n3,4\n\n")
    data = read_csv(str(p))
    assert data.shape == (2, 2)


def test_csv_ragged_row_errors(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2,h3\n1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        read_csv(str(p))


def test_bin_roundtrip(tmp_path, rng):
    p = tmp_path / "data.bin"
    data = rng.normal(size=(37, 5)).astype(np.float32)
    write_bin(str(p), data)
    out = read_bin(str(p))
    np.testing.assert_array_equal(out, data)
    # header layout: int32 nevents, int32 ndims (readData.cpp:38-39)
    raw = np.fromfile(str(p), dtype=np.int32, count=2)
    assert raw[0] == 37 and raw[1] == 5


def test_dispatch_on_extension(tmp_path, rng):
    data = rng.normal(size=(10, 3)).astype(np.float32)
    pbin = tmp_path / "x.bin"
    write_bin(str(pbin), data)
    np.testing.assert_array_equal(read_data(str(pbin), use_native="never"), data)
    pcsv = tmp_path / "x.csv"
    pcsv.write_text("a,b,c\n" + "\n".join(
        ",".join(f"{v:.6f}" for v in row) for row in data
    ))
    np.testing.assert_allclose(read_data(str(pcsv), use_native="never"), data,
                               rtol=1e-5, atol=1e-6)


def test_summary_format(tmp_path):
    from cuda_gmm_mpi_tpu.io.writers import write_cluster
    import io

    f = io.StringIO()
    means = np.array([1.25, -2.5])
    R = np.array([[1.0, 0.5], [0.5, 2.0]])
    write_cluster(f, 0.25, 100.0, means, R)
    text = f.getvalue()
    assert "Probability: 0.250000\n" in text
    assert "N: 100.000000\n" in text
    assert "Means: 1.250 -2.500 \n" in text  # %.3f with trailing space
    assert "\nR Matrix:\n1.000 0.500 \n0.500 2.000 \n" in text


def test_results_format(tmp_path):
    from cuda_gmm_mpi_tpu.io.writers import write_results

    data = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    w = np.array([[0.75, 0.25], [0.1, 0.9]], np.float32)
    p = tmp_path / "out.results"
    write_results(str(p), data, w, use_native="never")
    lines = p.read_text().splitlines()
    assert lines[0] == "1.000000,2.000000\t0.750000,0.250000"
    assert lines[1] == "3.000000,4.000000\t0.100000,0.900000"
