"""Reader/writer semantics: header drop, BIN layout, output formats."""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.io.readers import read_bin, read_csv, read_data, write_bin


def test_csv_drops_header(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("colA,colB,colC\n1.0,2.0,3.0\n4.5,5.5,6.5\n")
    data = read_csv(str(p))
    assert data.shape == (2, 3)
    np.testing.assert_allclose(data, [[1.0, 2.0, 3.0], [4.5, 5.5, 6.5]])
    assert data.dtype == np.float32


def test_csv_blank_lines_skipped(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2\n\n1,2\n\n3,4\n\n")
    data = read_csv(str(p))
    assert data.shape == (2, 2)


def test_csv_ragged_row_errors(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2,h3\n1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        read_csv(str(p))


def test_bin_roundtrip(tmp_path, rng):
    p = tmp_path / "data.bin"
    data = rng.normal(size=(37, 5)).astype(np.float32)
    write_bin(str(p), data)
    out = read_bin(str(p))
    np.testing.assert_array_equal(out, data)
    # header layout: int32 nevents, int32 ndims (readData.cpp:38-39)
    raw = np.fromfile(str(p), dtype=np.int32, count=2)
    assert raw[0] == 37 and raw[1] == 5


def test_dispatch_on_extension(tmp_path, rng):
    data = rng.normal(size=(10, 3)).astype(np.float32)
    pbin = tmp_path / "x.bin"
    write_bin(str(pbin), data)
    np.testing.assert_array_equal(read_data(str(pbin), use_native="never"), data)
    pcsv = tmp_path / "x.csv"
    pcsv.write_text("a,b,c\n" + "\n".join(
        ",".join(f"{v:.6f}" for v in row) for row in data
    ))
    np.testing.assert_allclose(read_data(str(pcsv), use_native="never"), data,
                               rtol=1e-5, atol=1e-6)


def test_summary_format(tmp_path):
    from cuda_gmm_mpi_tpu.io.writers import write_cluster
    import io

    f = io.StringIO()
    means = np.array([1.25, -2.5])
    R = np.array([[1.0, 0.5], [0.5, 2.0]])
    write_cluster(f, 0.25, 100.0, means, R)
    text = f.getvalue()
    assert "Probability: 0.250000\n" in text
    assert "N: 100.000000\n" in text
    assert "Means: 1.250 -2.500 \n" in text  # %.3f with trailing space
    assert "\nR Matrix:\n1.000 0.500 \n0.500 2.000 \n" in text


def test_results_format(tmp_path):
    from cuda_gmm_mpi_tpu.io.writers import write_results

    data = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    w = np.array([[0.75, 0.25], [0.1, 0.9]], np.float32)
    p = tmp_path / "out.results"
    write_results(str(p), data, w, use_native="never")
    lines = p.read_text().splitlines()
    assert lines[0] == "1.000000,2.000000\t0.750000,0.250000"
    assert lines[1] == "3.000000,4.000000\t0.100000,0.900000"


# ---------------------------------------------------------------------------
# Range / streaming readers (per-host sharded loading, the anti-MPI_Bcast)
# ---------------------------------------------------------------------------

def _write_csv(path, data, header="a,b,c,d"):
    with open(path, "w") as f:
        f.write(header + "\n")
        for row in data:
            f.write(",".join(f"{v:.6f}" for v in row) + "\n")


@pytest.mark.parametrize("kind", ["bin", "csv"])
@pytest.mark.parametrize("use_native", ["never", "auto"])
def test_range_read_matches_slice(tmp_path, rng, kind, use_native):
    from cuda_gmm_mpi_tpu.io.readers import data_shape

    data = rng.normal(size=(101, 4)).astype(np.float32)
    p = str(tmp_path / f"x.{kind}")
    if kind == "bin":
        write_bin(p, data)
    else:
        _write_csv(p, data)
    assert data_shape(p, use_native=use_native) == (101, 4)
    for start, stop in [(0, 101), (0, 17), (40, 63), (97, 101), (5, 5)]:
        out = read_data(p, start, stop, use_native=use_native)
        np.testing.assert_allclose(out, data[start:stop], rtol=0, atol=2e-6)
    # stop=None reads to the end
    np.testing.assert_allclose(
        read_data(p, 13, use_native=use_native), data[13:], rtol=0, atol=2e-6)


@pytest.mark.parametrize("kind", ["bin", "csv"])
def test_range_read_out_of_bounds(tmp_path, rng, kind):
    data = rng.normal(size=(10, 3)).astype(np.float32)
    p = str(tmp_path / f"x.{kind}")
    if kind == "bin":
        write_bin(p, data)
    else:
        _write_csv(p, data, header="a,b,c")
    with pytest.raises(ValueError):
        read_data(p, 5, 11, use_native="never")


@pytest.mark.parametrize("kind", ["bin", "csv"])
def test_read_rows(tmp_path, rng, kind):
    from cuda_gmm_mpi_tpu.io.readers import read_rows

    data = rng.normal(size=(50, 3)).astype(np.float32)
    p = str(tmp_path / f"x.{kind}")
    if kind == "bin":
        write_bin(p, data)
    else:
        _write_csv(p, data, header="a,b,c")
    idx = [0, 49, 7, 7, 23]  # order preserved, duplicates allowed
    np.testing.assert_allclose(read_rows(p, idx), data[idx], rtol=0, atol=2e-6)
    with pytest.raises(ValueError):
        read_rows(p, [50])


def test_file_source(tmp_path, rng):
    from cuda_gmm_mpi_tpu.io import FileSource

    data = rng.normal(size=(30, 5)).astype(np.float32)
    p = str(tmp_path / "x.bin")
    write_bin(p, data)
    src = FileSource(p)
    assert src.shape == (30, 5)
    np.testing.assert_array_equal(src.read_range(10, 20), data[10:20])
    np.testing.assert_array_equal(src.read_rows([3, 1]), data[[3, 1]])
    np.testing.assert_array_equal(src.read_all(), data)


def test_csv_streaming_no_trailing_newline(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("a,b\n1,2\n3,4")  # no trailing \n on the last row
    out = read_csv(str(p))
    np.testing.assert_allclose(out, [[1, 2], [3, 4]])
    out = read_csv(str(p), 1, 2)
    np.testing.assert_allclose(out, [[3, 4]])


@pytest.mark.slow
@pytest.mark.parametrize("use_native", ["never", "auto"])
def test_range_read_rss_stays_o_slice(tmp_path, use_native):
    """The anti-Bcast claim made measurable: reading a 1/8 slice of a ~160 MB
    BIN must not buffer the whole file (VERDICT round-1 gap #2). Measured as
    subprocess peak RSS < baseline + file_size/4 (the slice itself is 20 MB)."""
    import subprocess
    import sys

    n, d = 1_700_000, 24  # ~163 MB payload
    p = str(tmp_path / "big.bin")
    with open(p, "wb") as f:
        np.asarray([n, d], np.int32).tofile(f)
        block = np.zeros((100_000, d), np.float32)
        for i in range(n // 100_000):
            block[:] = float(i)
            block.tofile(f)
    code = f"""
import resource, sys
import numpy as np
from cuda_gmm_mpi_tpu.io.readers import read_data
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
out = read_data({p!r}, {n // 2}, {n // 2 + n // 8}, use_native={use_native!r})
assert out.shape == ({n // 8}, {d}), out.shape
assert float(out[0, 0]) == float({n // 2} // 100_000), out[0, 0]
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print("RSS", base, peak)
sys.exit(0 if (peak - base) * 1024 < {n * d * 4} // 4 else 17)
"""
    from .conftest import worker_env

    r = subprocess.run([sys.executable, "-c", code], env=worker_env(),
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)


@pytest.mark.parametrize("use_native", ["never", "auto"])
def test_csv_trailing_empty_field_is_zero(tmp_path, use_native):
    """A trailing empty field parses as 0.0 and must NOT steal the next
    line's first value (the strtof-skips-newline pitfall)."""
    p = tmp_path / "x.csv"
    p.write_text("h1,h2\n1,\n2,3\n")
    out = read_data(str(p), use_native=use_native)
    assert out.tolist() == [[1.0, 0.0], [2.0, 3.0]]
    out = read_data(str(p), 0, 2, use_native=use_native)
    assert out.tolist() == [[1.0, 0.0], [2.0, 3.0]]


@pytest.mark.parametrize("use_native", ["never", "auto"])
def test_start_past_eof_raises(tmp_path, use_native):
    p = tmp_path / "x.csv"
    p.write_text("a,b\n1,2\n3,4\n")
    with pytest.raises(ValueError):
        read_data(str(p), 10, use_native=use_native)
    # start == n is a valid empty slice (matches BIN [n:n])
    assert read_data(str(p), 2, use_native=use_native).shape == (0, 2)


@pytest.mark.parametrize("fmt", ["csv", "bin"])
def test_screen_reject_names_file_and_rows(tmp_path, fmt):
    """Ingest-time integrity screen (ISSUE 4 satellite): NaN/Inf rows fail
    AT LOAD with a per-file, per-row error -- not 40 EM iterations later as
    a health flag -- on both the CSV and BIN paths."""
    from cuda_gmm_mpi_tpu.validation import InvalidInputError

    rows = np.arange(24.0, dtype=np.float32).reshape(8, 3)
    rows[2, 1] = np.nan
    rows[5, 0] = np.inf
    if fmt == "bin":
        p = tmp_path / "x.bin"
        write_bin(str(p), rows)
    else:
        p = tmp_path / "x.csv"
        p.write_text("a,b,c\n" + "\n".join(
            ",".join(str(v) for v in r) for r in rows))
    with pytest.raises(InvalidInputError) as ei:
        read_data(str(p), screen="reject", use_native="never")
    msg = str(ei.value)
    assert p.name in msg and "2" in msg and "5" in msg
    assert "2 non-finite" in msg


def test_screen_quarantine_drops_rows(tmp_path):
    """screen='quarantine' (--allow-nonfinite) counts and DROPS the bad
    rows; with a compute dtype, values that overflow it (1e39 under
    float32) are quarantined too so the fit-time validator passes."""
    from cuda_gmm_mpi_tpu.io.readers import screen_nonfinite

    p = tmp_path / "x.csv"
    p.write_text("a,b\n1,2\nnan,4\n5,6\n1e39,8\n9,10\n")
    out = read_data(str(p), screen="quarantine", use_native="never",
                    screen_dtype=np.float32)
    # numpy reads 1e39 as inf in the reader's float32 already; both bad
    # rows are gone and the survivors are untouched
    assert out.tolist() == [[1.0, 2.0], [5.0, 6.0], [9.0, 10.0]]

    # dtype-overflow screening on already-parsed float64 data
    data = np.array([[1.0, 2.0], [1e39, 4.0], [5.0, 6.0]])
    clean, dropped = screen_nonfinite(data, "mem", mode="quarantine",
                                      dtype=np.float32)
    assert dropped == 1 and clean.tolist() == [[1.0, 2.0], [5.0, 6.0]]
    # ...but NOT without the dtype hint (finite in float64)
    clean64, dropped64 = screen_nonfinite(data, "mem", mode="quarantine")
    assert dropped64 == 0 and clean64.shape == (3, 2)

    with pytest.raises(ValueError):
        screen_nonfinite(data, "mem", mode="banish")
