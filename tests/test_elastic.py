"""Elastic multi-host recovery (parallel/elastic.py; supervisor.
ElasticRecovery; docs/DISTRIBUTED.md "Elastic recovery").

The reference is fail-stop: one dead MPI rank kills or wedges the whole
job. PR 4 upgraded the wedge to a loud exit 75; this round upgrades exit
75 to *continuing*: survivors rendezvous on the checkpoint filesystem,
seal a generation-stamped shrunken membership, adopt it as the world
overlay, and refit from the newest checkpoint. The tier-1 tests here run
the whole arc on ONE process via the simulated-membership harness (a
pre-seeded 2-host generation-0 file plus an injected ``rank_lost``
fault); the cross-process rendezvous protocol itself is exercised by the
slow-marked multi-process test at the bottom.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, supervisor
from cuda_gmm_mpi_tpu.parallel import distributed, elastic
from cuda_gmm_mpi_tpu.supervisor import (LivenessWatchdog, PeerLostError,
                                         RunSupervisor)
from cuda_gmm_mpi_tpu.testing import faults
from cuda_gmm_mpi_tpu.utils import checkpoint as ckpt_mod

from .conftest import communicate_or_kill, worker_env

CLI = [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli"]


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    """Module-level overlay/counters are process-wide; never leak them."""
    elastic.reset()
    yield
    elastic.reset()


def _sup():
    return RunSupervisor(install_signals=False)


def _cfg(ck, **kw):
    base = dict(min_iters=8, max_iters=8, chunk_size=512, dtype="float64",
                checkpoint_dir=ck, preempt_poll_iters=1, seed=3,
                elastic_backoff_s=0.0)
    base.update(kw)
    return GMMConfig(**base)


def _seed_two_hosts(ck):
    """Pre-seed a generation-0 membership naming this process rank 0 of a
    2-host world -- the single-process chaos harness's world on paper."""
    mdir = elastic.membership_dir(ck)
    elastic.write_membership(
        mdir, elastic.Membership(generation=0, ranks=(0, 1), world_size0=2))
    return mdir


@pytest.fixture
def blobs3(rng):
    centers = rng.normal(scale=8.0, size=(3, 3))
    return (centers[rng.integers(0, 3, 3000)]
            + rng.normal(size=(3000, 3))).astype(np.float64)


# ---------------------------------------------------------------------------
# membership files
# ---------------------------------------------------------------------------


def test_membership_roundtrip_and_newest_generation(tmp_path):
    d = str(tmp_path / "membership")
    for g, ranks in ((0, (0, 1, 2, 3)), (2, (0, 3)), (1, (0, 1, 3))):
        elastic.write_membership(
            d, elastic.Membership(generation=g, ranks=ranks, world_size0=4))
    newest = elastic.read_membership(d)
    assert newest.generation == 2 and newest.ranks == (0, 3)
    assert newest.world_size == 2 and newest.world_size0 == 4
    g1 = elastic.read_membership(d, generation=1)
    assert g1.ranks == (0, 1, 3)
    # Positions in the sorted tuple are the new contiguous ranks.
    assert newest.index_of(3) == 1 and newest.index_of(1) is None
    # No tmp litter from the atomic publish.
    assert not [f for f in os.listdir(d) if ".tmp" in f]


def test_membership_missing_or_torn_reads_none(tmp_path):
    d = str(tmp_path / "membership")
    assert elastic.read_membership(d) is None
    os.makedirs(d)
    with open(os.path.join(d, "gen3.json"), "w") as f:
        f.write('{"generation": 3, "ranks": [0')  # torn write
    assert elastic.read_membership(d, generation=3) is None


# ---------------------------------------------------------------------------
# rendezvous protocol (single process; real two-process variant is below)
# ---------------------------------------------------------------------------


def test_rendezvous_coordinator_seals_when_all_announced(tmp_path):
    d = str(tmp_path / "m")
    prev = elastic.Membership(generation=0, ranks=(0, 1, 2), world_size0=3)
    elastic.announce_alive(d, 1, 1)  # the other survivor is already in
    sealed = elastic.rendezvous(d, my_rank=0, prev=prev, lost=(2,),
                                window_s=5.0)
    assert sealed.generation == 1 and sealed.ranks == (0, 1)
    assert sealed.world_size0 == 3
    # Published durably: a fresh read sees the same sealed world.
    again = elastic.read_membership(d, generation=1)
    assert again == sealed


def test_rendezvous_window_close_seals_partial_survivors(tmp_path):
    """A survivor that never announces within the window is left out:
    the sealed world is the ANNOUNCED intersection, not the hoped-for
    one, so the refit cannot hang waiting on a second dead peer."""
    d = str(tmp_path / "m")
    prev = elastic.Membership(generation=0, ranks=(0, 1, 2), world_size0=3)
    t0 = time.monotonic()
    sealed = elastic.rendezvous(d, my_rank=0, prev=prev, lost=(2,),
                                window_s=0.2, poll_s=0.02)
    assert time.monotonic() - t0 < 5.0
    assert sealed.generation == 1 and sealed.ranks == (0,)


def test_rendezvous_lost_and_excluded_ranks_raise(tmp_path):
    d = str(tmp_path / "m")
    prev = elastic.Membership(generation=0, ranks=(0, 1), world_size0=2)
    with pytest.raises(PeerLostError):
        elastic.rendezvous(d, my_rank=1, prev=prev, lost=(1,))
    # Announced too late: generation already sealed without me.
    elastic.write_membership(
        d, elastic.Membership(generation=1, ranks=(0,), world_size0=2))
    with pytest.raises(PeerLostError):
        elastic.rendezvous(d, my_rank=1, prev=prev, lost=(2,), window_s=0.2)


def test_rendezvous_noncoordinator_reads_published_or_times_out(tmp_path):
    d = str(tmp_path / "m")
    prev = elastic.Membership(generation=0, ranks=(0, 1, 2), world_size0=3)
    # Published already: the non-coordinator adopts it without waiting.
    elastic.write_membership(
        d, elastic.Membership(generation=1, ranks=(0, 1), world_size0=3))
    sealed = elastic.rendezvous(d, my_rank=1, prev=prev, lost=(2,),
                                window_s=0.2)
    assert sealed.ranks == (0, 1)
    # Dead coordinator: bounded poll, then PeerLostError blaming IT.
    d2 = str(tmp_path / "m2")
    with pytest.raises(PeerLostError) as ei:
        elastic.rendezvous(d2, my_rank=1, prev=prev, lost=(2,),
                           window_s=0.2, poll_s=0.02)
    assert ei.value.rank == 0


def test_rendezvous_deterministic_for_survivor_set(tmp_path):
    """Same survivor set -> same sealed membership, independent of the
    order announcements landed (sorted ranks, single writer)."""
    prev = elastic.Membership(generation=4, ranks=(1, 3, 5, 7),
                              world_size0=8)
    sealed = []
    for trial, order in enumerate(((3, 7), (7, 3))):
        d = str(tmp_path / f"m{trial}")
        for r in order:
            elastic.announce_alive(d, 5, r)
        sealed.append(elastic.rendezvous(d, my_rank=1, prev=prev,
                                         lost=(5,), window_s=5.0))
    assert sealed[0] == sealed[1]
    assert sealed[0].generation == 5 and sealed[0].ranks == (1, 3, 7)


# ---------------------------------------------------------------------------
# the world overlay
# ---------------------------------------------------------------------------


def test_world_overlay_and_run_summary_section():
    assert elastic.current_membership() is None
    assert elastic.generation() == 0
    assert elastic.peer_ranks() is None
    assert elastic.run_summary_section() is None

    m = elastic.Membership(generation=2, ranks=(0, 3, 5), world_size0=6)
    elastic.set_world_overlay(m, 3)
    assert elastic.world() == (1, 3)  # contiguous rank over survivors
    assert elastic.original_rank() == 3
    assert elastic.peer_ranks() == [0, 5]
    assert elastic.generation() == 2
    elastic.note_shrink()
    elastic.note_resume()
    sec = elastic.run_summary_section()
    assert sec == {"generation": 2, "world_size": 3,
                   "shrinks": 1, "resumes": 1}
    with pytest.raises(ValueError):
        elastic.set_world_overlay(m, 4)  # not a member
    elastic.clear_world_overlay()
    assert elastic.current_membership() is None


# ---------------------------------------------------------------------------
# satellite: heartbeat staleness is reader-local (clock-skew regression)
# ---------------------------------------------------------------------------


def test_watchdog_staleness_is_reader_local_not_clock_skew(tmp_path):
    """A peer whose filesystem mtimes are skewed far into the past (its
    clock runs behind, or NTP stepped it) must NOT be declared stale
    while its heartbeat keeps CHANGING; a peer whose heartbeat stops
    changing must age by the reader's own monotonic clock regardless of
    what wall-clock value the last mtime carries."""
    hb = str(tmp_path / "hb")
    wd = LivenessWatchdog(hb, rank=0, nproc=2, timeout_s=0.4,
                          interval_s=60.0)
    assert wd.peers == (1,)
    distributed.write_rank_heartbeat(hb, 1)
    path = distributed.heartbeat_path(hb, 1)

    # Peer's clock is 10 minutes BEHIND: a wall-clock comparison would
    # call this file 600s stale the instant it is written.
    past = time.time() - 600.0
    os.utime(path, (past, past))
    assert wd.check_peers() is None
    time.sleep(0.15)
    assert wd.check_peers() is None  # fresh observation, not stale yet
    # The peer heartbeats again (mtime CHANGES, still in the past):
    # its staleness clock restarts.
    os.utime(path, (past + 5.0, past + 5.0))
    time.sleep(0.3)
    assert wd.check_peers() is None
    # Now the heartbeat stops changing: reader-local monotonic age grows
    # past the timeout and the peer is declared lost -- even though the
    # file is "only seconds old" by its own (future-skewed) mtime.
    future = time.time() + 600.0
    os.utime(path, (future, future))
    wd.check_peers()  # observe the change once; clock restarts here
    time.sleep(0.55)
    worst = wd.check_peers()
    assert worst is not None
    assert worst[0] == 1 and worst[1] > 0.4


def test_watchdog_peers_override_watches_survivors_only(tmp_path):
    """An elastic refit passes the sealed membership's survivor ranks:
    the watchdog must never wait on the heartbeat of the rank it just
    shrank away (that file will be stale forever by design)."""
    hb = str(tmp_path / "hb")
    wd = LivenessWatchdog(hb, rank=0, nproc=3, timeout_s=0.2,
                          interval_s=60.0, peers=[0, 2])
    assert wd.peers == (2,)  # self filtered, lost rank 1 absent
    distributed.write_rank_heartbeat(hb, 2)
    # Rank 1 never heartbeats -- irrelevant: only rank 2 is watched.
    assert wd.check_peers() is None


# ---------------------------------------------------------------------------
# satellite: directory-fsync POSIX gating
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fsync_dir", [ckpt_mod._fsync_dir,
                                       elastic._fsync_dir])
def test_fsync_dir_is_posix_gated(tmp_path, monkeypatch, fsync_dir):
    """Both durable-rename helpers fsync the directory on POSIX and skip
    -- instead of crashing on ``os.open(dir)`` -- elsewhere."""
    d = str(tmp_path)
    fsync_dir(d)  # POSIX: opens + fsyncs the dir without error

    opened = []
    monkeypatch.setattr(os, "name", "nt")
    monkeypatch.setattr(os, "open",
                        lambda *a, **k: opened.append(a) or 0)
    fsync_dir(d)
    assert opened == []  # gated out before any directory open


def test_write_npz_atomic_survives_and_fsyncs(tmp_path):
    target = str(tmp_path / "a.npz")
    ckpt_mod.write_npz_atomic(str(tmp_path), target,
                              {"x": np.arange(3.0)})
    with np.load(target) as z:
        np.testing.assert_array_equal(z["x"], np.arange(3.0))
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp.npz")]


# ---------------------------------------------------------------------------
# satellite: checkpoint world-size/generation stamping + restore validation
# ---------------------------------------------------------------------------


def test_checkpoint_world_stamp_and_mismatch_walkback(tmp_path, blobs3):
    ck = str(tmp_path / "ck")
    with supervisor.use(_sup()):
        fit_gmm(blobs3, 4, 2, config=_cfg(ck, min_iters=3, max_iters=3))

    # Every step carries the world stamp (world size 1, generation 0),
    # and the same world restores fine.
    tree = ckpt_mod.SweepCheckpointer(ck).restore()
    assert tree is not None
    assert int(np.asarray(tree["ckpt_world_size"])) == 1
    assert int(np.asarray(tree["ckpt_generation"])) == 0

    # A different world without --elastic: the walk-back aggregates an
    # INFORMATIVE mismatch error, not a shape traceback.
    elastic.set_world_overlay(
        elastic.Membership(generation=1, ranks=(0, 1), world_size0=2), 0)
    with pytest.raises(ckpt_mod.CheckpointRestoreError) as ei:
        ckpt_mod.SweepCheckpointer(ck).restore()
    msg = str(ei.value.errors[0][1])
    assert "world size 1" in msg and "2 host(s)" in msg
    assert "--elastic" in msg

    # Opting in (what an elastic run passes) accepts the world change.
    tree = ckpt_mod.SweepCheckpointer(ck, allow_world_change=True).restore()
    assert tree is not None


# ---------------------------------------------------------------------------
# rank_lost without --elastic: the exit-75 contract is unchanged
# ---------------------------------------------------------------------------


def test_rank_lost_without_elastic_raises_peer_lost(tmp_path, blobs3):
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream

    ck = str(tmp_path / "ck")
    mf = str(tmp_path / "m.jsonl")
    with pytest.raises(PeerLostError) as ei:
        with faults.use({"rank_lost": {"iter": 3, "rank": 1}}) as plan:
            with supervisor.use(_sup()):
                fit_gmm(blobs3, 6, 2, config=_cfg(ck, metrics_file=mf))
    assert plan.fired["rank_lost"] == 1
    assert ei.value.rank == 1
    # The emergency intra-K sub-step was written before the raise.
    subs = [f for f in os.listdir(os.path.join(ck, "sweep"))
            if ".iter" in f]
    assert len(subs) == 1

    records = read_stream(mf)
    assert validate_stream(records) == []
    kinds = [r["event"] for r in records]
    assert "peer_lost" in kinds
    assert "elastic_shrink" not in kinds and "elastic_resume" not in kinds
    pl = next(r for r in records if r["event"] == "peer_lost")
    assert pl["rank"] == 1


# ---------------------------------------------------------------------------
# the tentpole: shrink + resume on an injected peer loss
# ---------------------------------------------------------------------------


def test_elastic_shrink_and_resume_end_to_end(tmp_path, blobs3):
    """rank_lost mid-sweep with --elastic: ONE fit_gmm call survives the
    loss -- rendezvous seals generation 1 over rank 0, the refit restores
    the emergency checkpoint and finishes -- and the selected model is
    identical to an uninterrupted run's (same winner K, same loglik)."""
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
    from cuda_gmm_mpi_tpu.telemetry.report import render_report

    with supervisor.use(_sup()):
        ref = fit_gmm(blobs3, 6, 2, config=_cfg(str(tmp_path / "ck_ref")))

    ck = str(tmp_path / "ck")
    mdir = _seed_two_hosts(ck)
    mf = str(tmp_path / "m.jsonl")
    with faults.use({"rank_lost": {"iter": 3, "rank": 1}}) as plan:
        with supervisor.use(_sup()):
            res = fit_gmm(blobs3, 6, 2,
                          config=_cfg(ck, elastic=True, metrics_file=mf))
    assert plan.fired["rank_lost"] == 1

    # Deterministic for this survivor set: the shrunken world reproduces
    # the uninterrupted run's selection exactly (well inside the
    # health_regression_scale x convergence_epsilon acceptance bound).
    assert res.ideal_num_clusters == ref.ideal_num_clusters
    assert res.min_rissanen == ref.min_rissanen
    assert res.final_loglik == ref.final_loglik
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))

    # Generation 1 is sealed on disk with the survivor set.
    sealed = elastic.read_membership(mdir)
    assert sealed.generation == 1 and sealed.ranks == (0,)
    assert elastic.generation() == 1

    # Telemetry: schema-valid stream, shrink -> resume arc, summary
    # rollup, and the report renders the lifecycle.
    records = read_stream(mf)
    assert validate_stream(records) == []
    shrink = next(r for r in records if r["event"] == "elastic_shrink")
    assert shrink["generation"] == 1 and shrink["survivors"] == [0]
    assert shrink["world_size"] == 1 and shrink["lost_ranks"] == [1]
    resume = next(r for r in records if r["event"] == "elastic_resume")
    assert resume["generation"] == 1 and resume["attempt"] == 1
    summary = next(r for r in records if r["event"] == "run_summary")
    assert summary["elastic"] == {"generation": 1, "world_size": 1,
                                  "shrinks": 1, "resumes": 1}
    rep = render_report(records)
    assert "elastic_shrink" in rep and "elastic_resume" in rep
    assert "Elastic: generation 1" in rep


@pytest.mark.parametrize("name,spec,kw", [
    ("mid_em", {"rank_lost": {"iter": 3, "rank": 1}}, {}),
    ("between_k", {"rank_lost": {"where": "sweep", "rank": 1}}, {}),
    ("mid_stream_block", {"rank_lost": {"iter": 2, "block": 3, "rank": 1}},
     {"stream_events": True, "chunk_size": 256}),
])
def test_chaos_matrix_rank_lost_sites_resume_identically(
        tmp_path, rng, name, spec, kw):
    """The chaos matrix: a peer loss mid-EM, between K's, and mid
    stream-block all shrink and resume to the uninterrupted result."""
    centers = rng.normal(scale=8.0, size=(3, 3))
    data = (centers[rng.integers(0, 3, 4096)]
            + rng.normal(size=(4096, 3))).astype(np.float64)

    with supervisor.use(_sup()):
        ref = fit_gmm(data, 5, 2,
                      config=_cfg(str(tmp_path / "ck_ref"), **kw))

    ck = str(tmp_path / "ck")
    _seed_two_hosts(ck)
    with faults.use(spec) as plan:
        with supervisor.use(_sup()):
            res = fit_gmm(data, 5, 2, config=_cfg(ck, elastic=True, **kw))
    assert plan.fired["rank_lost"] == 1
    assert res.ideal_num_clusters == ref.ideal_num_clusters
    assert res.final_loglik == ref.final_loglik
    np.testing.assert_array_equal(np.asarray(res.means),
                                  np.asarray(ref.means))
    assert elastic.generation() == 1


def test_elastic_survivor_set_determinism_across_runs(tmp_path, blobs3):
    """Two independent recoveries over the same survivor set agree on the
    sealed membership AND on the refit model -- the acceptance criteria's
    determinism clause."""
    results = []
    for trial in range(2):
        elastic.reset()
        ck = str(tmp_path / f"ck{trial}")
        mdir = _seed_two_hosts(ck)
        with faults.use({"rank_lost": {"iter": 3, "rank": 1}}):
            with supervisor.use(_sup()):
                res = fit_gmm(blobs3, 6, 2, config=_cfg(ck, elastic=True))
        results.append((elastic.read_membership(mdir), res))
    (m0, r0), (m1, r1) = results
    assert m0 == m1
    assert r0.ideal_num_clusters == r1.ideal_num_clusters
    assert r0.final_loglik == r1.final_loglik
    np.testing.assert_array_equal(np.asarray(r0.means),
                                  np.asarray(r1.means))


def test_elastic_min_hosts_floor_gives_up(tmp_path, blobs3):
    """A shrink below --min-hosts re-raises the original PeerLostError:
    the exit-75 operator path, not a silently undersized fit."""
    ck = str(tmp_path / "ck")
    _seed_two_hosts(ck)
    with pytest.raises(PeerLostError):
        with faults.use({"rank_lost": {"iter": 3, "rank": 1}}):
            with supervisor.use(_sup()):
                fit_gmm(blobs3, 6, 2,
                        config=_cfg(ck, elastic=True, min_hosts=2))


def test_elastic_retry_budget_exhausts_to_peer_lost(tmp_path, blobs3):
    """Repeated losses beyond elastic_max_retries propagate: the between-K
    fault re-fires on every refit (times=2) and the second loss exceeds
    the 1-attempt budget."""
    ck = str(tmp_path / "ck")
    _seed_two_hosts(ck)
    with pytest.raises(PeerLostError):
        with faults.use({"rank_lost": {"where": "sweep", "rank": 1,
                                       "times": 2}}) as plan:
            with supervisor.use(_sup()):
                fit_gmm(blobs3, 6, 2,
                        config=_cfg(ck, elastic=True,
                                    elastic_max_retries=1))
    assert plan.fired["rank_lost"] == 2


def test_collective_timeout_fault_bounds_barrier(tmp_path):
    """The collective_timeout chaos kind: an armed barrier raises the
    exact PeerLostError a timed-out collective would, honoring the
    optional name pin, BEFORE the single-process early return."""
    with faults.use({"collective_timeout": {"rank": 1, "timeout_s": 7.5,
                                            "name": "output_assembly"}}):
        distributed.barrier("some_other_barrier")  # name pin: no fire
        with pytest.raises(PeerLostError) as ei:
            distributed.barrier("output_assembly")
    assert ei.value.rank == 1 and ei.value.timeout_s == 7.5
    with faults.use({"collective_timeout": {}}):  # untargeted: any barrier
        with pytest.raises(PeerLostError) as ei:
            distributed.barrier("anything")
    assert ei.value.rank is None


# ---------------------------------------------------------------------------
# CLI: --elastic / --min-hosts, and exit-75 preservation without them
# ---------------------------------------------------------------------------


def _write_blob_file(tmp_path, rng, n=3000, d=3, k=4):
    from cuda_gmm_mpi_tpu.io import write_bin

    centers = rng.normal(scale=9.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float32)
    path = str(tmp_path / "events.bin")
    write_bin(path, data)
    return path


def test_cli_elastic_requires_checkpoint_dir(tmp_path, rng):
    infile = _write_blob_file(tmp_path, rng, n=256, d=2, k=2)
    p = subprocess.Popen(
        CLI + ["2", infile, str(tmp_path / "out"), "2", "--device=cpu",
               "--elastic"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=worker_env(),
        text=True)
    out, err = communicate_or_kill(p, timeout=300)
    assert p.returncode == 1, f"rc={p.returncode}:\n{out}\n{err}"
    assert "elastic recovery requires checkpoint_dir" in err


def test_cli_rank_lost_elastic_on_exits_0_off_exits_75(tmp_path, rng):
    """The acceptance criteria, end to end through the CLI: an injected
    rank_lost mid-sweep exits 0 with --elastic (same outputs as an
    uninterrupted run) and keeps the exit-75 peer-loss contract without
    it -- byte-identical output files either way."""
    infile = _write_blob_file(tmp_path, rng)

    def run(out, ckdir, *, extra=(), fault=None):
        env = worker_env()
        if fault is not None:
            env["GMM_FAULTS"] = json.dumps(fault)
        args = ["4", infile, str(out), "4", "--device=cpu",
                "--dtype=float64", "--min-iters=6", "--max-iters=6",
                "--sweep-k-buckets=off", "--preempt-poll-iters=1",
                "--chunk-size=256", f"--checkpoint-dir={ckdir}",
                *extra]
        p = subprocess.Popen(CLI + args, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, env=env, text=True)
        out_, err_ = communicate_or_kill(p, timeout=600)
        return p.returncode, out_, err_

    fault = {"rank_lost": {"iter": 3, "rank": 1}}

    # Without --elastic: exit 75, untouched contract.
    rc, o, e = run(tmp_path / "plain", str(tmp_path / "ck_plain"),
                   fault=fault)
    assert rc == 75, f"expected EX_TEMPFAIL:\n{o}\n{e[-3000:]}"
    assert "Peer lost" in e

    # With --elastic (membership pre-seeded to a 2-host world on paper):
    # the same loss is survived in one invocation, exit 0.
    ck = str(tmp_path / "ck_el")
    _seed_two_hosts(ck)
    rc2, o2, e2 = run(tmp_path / "el", ck,
                      extra=["--elastic", "--min-hosts=1"], fault=fault)
    assert rc2 == 0, f"elastic run failed:\n{o2}\n{e2[-3000:]}"

    # Ground truth, and the byte-identity acceptance.
    rc3, o3, e3 = run(tmp_path / "ref", str(tmp_path / "ck_ref"))
    assert rc3 == 0, f"reference failed:\n{o3}\n{e3[-3000:]}"
    assert (tmp_path / "el.summary").read_bytes() == \
        (tmp_path / "ref.summary").read_bytes()
    assert (tmp_path / "el.results").read_bytes() == \
        (tmp_path / "ref.results").read_bytes()


# ---------------------------------------------------------------------------
# the real multi-process rendezvous (slow: spawns interpreters)
# ---------------------------------------------------------------------------


RENDEZVOUS_WORKER = r"""
import sys
from cuda_gmm_mpi_tpu.parallel import elastic

d, r = sys.argv[1], int(sys.argv[2])
prev = elastic.Membership(generation=0, ranks=(0, 1, 2), world_size0=3)
m = elastic.rendezvous(d, my_rank=r, prev=prev, lost=(2,), window_s=30.0)
print("SEALED", m.generation, ",".join(str(x) for x in m.ranks))
"""


@pytest.mark.slow
def test_two_process_rendezvous_agrees_on_membership(tmp_path):
    """The filesystem rendezvous across REAL processes: ranks 0 and 1 of
    a 3-host world lose rank 2 concurrently; the coordinator (0) seals
    once both announce, the poller (1) adopts the same file, and both
    report the identical generation-1 membership."""
    d = str(tmp_path / "membership")
    procs = [subprocess.Popen(
        [sys.executable, "-c", RENDEZVOUS_WORKER, d, str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=worker_env(),
        text=True) for r in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, err = communicate_or_kill(p, timeout=300)
            assert p.returncode == 0, f"rc={p.returncode}:\n{out}\n{err}"
            outs.append([ln for ln in out.splitlines()
                         if ln.startswith("SEALED")][-1])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=60)
    assert outs[0] == outs[1] == "SEALED 1 0,1"
