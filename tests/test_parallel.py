"""Distributed EM on the 8-fake-device CPU mesh: sharded == single-device.

The TPU-native analog of "test multi-node without a cluster" (SURVEY.md SS4):
event sharding (data axis), cluster sharding (cluster axis), and the 2-D
combination must all reproduce the single-device EM trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host
from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel, make_mesh

from .conftest import make_blobs


def run_single(data, k, iters, chunk=128):
    cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                    dtype="float64")
    model = GMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(*data.shape)
    s, ll, it = model.run_em(state, jnp.asarray(chunks), jnp.asarray(wts), eps)
    return jax.device_get(s), float(ll)


def run_sharded(data, k, iters, mesh_shape, chunk=128):
    cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                    dtype="float64", mesh_shape=mesh_shape)
    model = ShardedGMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size, model.data_size)
    state = seed_clusters_host(data, k)
    state, chunks, wts = model.prepare(state, chunks, wts)
    eps = convergence_epsilon(*data.shape)
    s, ll, it = model.run_em(state, chunks, wts, eps)
    return jax.device_get(s), float(ll)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_matches_single(rng, mesh_shape):
    data, _ = make_blobs(rng, n=1024, d=3, k=4)
    k = 4
    s0, ll0 = run_single(data, k, 5)
    s1, ll1 = run_sharded(data, k, 5, mesh_shape)
    np.testing.assert_allclose(ll1, ll0, rtol=1e-9)
    kp = s0.means.shape[0]
    np.testing.assert_allclose(np.asarray(s1.means)[:kp], s0.means,
                               rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(np.asarray(s1.R)[:kp], s0.R, rtol=1e-6,
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(s1.N)[:kp], s0.N, rtol=1e-8)


def test_sharded_packed_quad_mode_matches_single(rng):
    """quad_mode='packed' composes with 2-D (data x cluster) sharding: the
    packed features/Rinv are built per cluster shard."""
    data, _ = make_blobs(rng, n=1024, d=3, k=4)
    r0 = fit_gmm(data, 4, 4, config=GMMConfig(
        min_iters=4, max_iters=4, chunk_size=128, dtype="float64",
        quad_mode="packed"))
    r1 = fit_gmm(data, 4, 4, config=GMMConfig(
        min_iters=4, max_iters=4, chunk_size=128, dtype="float64",
        quad_mode="packed", mesh_shape=(4, 2)))
    np.testing.assert_allclose(r1.final_loglik, r0.final_loglik, rtol=1e-9)
    np.testing.assert_allclose(r1.means, r0.means, rtol=1e-7, atol=1e-9)


def test_cluster_padding(rng):
    """K not divisible by the cluster axis: padded slots stay inactive."""
    data, _ = make_blobs(rng, n=512, d=3, k=3)
    s0, ll0 = run_single(data, 3, 4)
    s1, ll1 = run_sharded(data, 3, 4, (2, 4))  # K=3 padded to 4
    np.testing.assert_allclose(ll1, ll0, rtol=1e-9)
    act = np.asarray(s1.active)
    assert act[:3].all() and not act[3:].any()
    np.testing.assert_allclose(np.asarray(s1.means)[:3], s0.means[:3],
                               rtol=1e-7, atol=1e-9)


def test_fit_gmm_with_mesh(rng):
    """Full sweep through fit_gmm on a 2-D mesh matches the plain fit."""
    data, _ = make_blobs(rng, n=512, d=2, k=3)
    kw = dict(min_iters=3, max_iters=3, chunk_size=128, dtype="float64")
    r0 = fit_gmm(data, 5, 3, config=GMMConfig(**kw))
    r1 = fit_gmm(data, 5, 3, config=GMMConfig(mesh_shape=(4, 2), **kw))
    assert r1.ideal_num_clusters == r0.ideal_num_clusters
    np.testing.assert_allclose(r1.min_rissanen, r0.min_rissanen, rtol=1e-8)
    np.testing.assert_allclose(r1.means, r0.means, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_output_path_matches_plain(rng, mesh_shape):
    """The output/inference pass runs on ALL local devices and reproduces
    the plain single-device posteriors (round-3 closure of the reference's
    all-GPU membership recompute, gaussian.cu:768-823)."""
    data, _ = make_blobs(rng, n=1024, d=3, k=3)
    k = 3  # not divisible by cluster axes: exercises inference-side padding
    cfg = GMMConfig(min_iters=3, max_iters=3, chunk_size=64, dtype="float64")
    model_p = GMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(*data.shape)
    s, _, _ = model_p.run_em(state, jnp.asarray(chunks), jnp.asarray(wts), eps)
    s = jax.device_get(s)

    w_plain = model_p.memberships(s, np.asarray(chunks))

    cfg_m = GMMConfig(min_iters=3, max_iters=3, chunk_size=64,
                      dtype="float64", mesh_shape=mesh_shape)
    model_s = ShardedGMMModel(cfg_m)
    w_sh = model_s.memberships(s, np.asarray(chunks))
    assert w_sh.shape == w_plain.shape  # K columns sliced back after padding
    if mesh_shape == (8, 1):
        # Pure event sharding: same per-block program as the plain path.
        np.testing.assert_array_equal(w_sh, w_plain)
    else:
        # Cluster sharding: two-stage collective LSE reassociates the sum.
        np.testing.assert_allclose(w_sh, w_plain, rtol=1e-12, atol=1e-15)

    # The dispatch really spans every local device.
    w_dev, _ = model_s.infer_posteriors(
        s, np.zeros((model_s.inference_block, 3), np.float64))
    assert len(w_dev.sharding.device_set) == 8


def test_uneven_events_across_shards(rng):
    """Event count not divisible by devices*chunk: mask padding preserved."""
    data, _ = make_blobs(rng, n=700, d=2, k=2)  # 698 events actually
    s0, ll0 = run_single(data, 2, 3, chunk=64)
    s1, ll1 = run_sharded(data, 2, 3, (8, 1), chunk=64)
    np.testing.assert_allclose(ll1, ll0, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(s1.N)[:2], s0.N, rtol=1e-9)
