"""Multi-tenancy fleet fits (cuda_gmm_mpi_tpu/tenancy/; docs/TENANCY.md).

The contracts under test:

- **solo bit-parity** -- a fleet fit of T tenants produces, for every
  tenant, a model BIT-IDENTICAL to that tenant's solo ``fit_gmm`` at the
  same seed/config (pow2 starting K; plain + sharded meshes, full +
  diag covariance). Non-pow2 K tenants (whose solo seed width has no
  shared-program equivalent) agree at reduction-order tolerance, as does
  the ``fleet_mode='vmap'`` throughput mode.
- **ragged pack/unpack round-trip** -- packing is pure layout: the
  packed grid slices back to exactly the rows that went in.
- **drop-one containment** -- a lane-targeted ``nan_loglik`` injection
  poisons ONE tenant; it is dropped with a ``drop_tenant`` recovery
  event while every survivor's model stays bit-identical to a clean
  fleet's.
- **preempt -> resume** -- an injected preemption between sweep steps
  exits through PreemptedError with a durable group checkpoint, and
  ``resume='auto'`` continues to results bit-identical to an
  uninterrupted fleet.
- **bulk export** -- one registry version per tenant; partial failure
  stays per-tenant.
- **telemetry rev v1.8** -- fleet_start / tenant_done / fleet_summary
  validate against the schema and render in ``gmm report``.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, supervisor
from cuda_gmm_mpi_tpu.supervisor import PreemptedError, RunSupervisor
from cuda_gmm_mpi_tpu.tenancy import (
    TenantSpec, fit_fleet, pack_group, plan_fleet, unpack_rows,
)
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cfg(**kw):
    base = dict(min_iters=4, max_iters=4, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


def blob(n, k, seed, d=3):
    r = np.random.default_rng(seed)
    centers = r.normal(scale=8.0, size=(k, d))
    return (centers[r.integers(0, k, n)]
            + r.normal(size=(n, d))).astype(np.float64)


def tenant_set():
    """Mixed-N tenants, pow2 starting Ks, one with a target K."""
    return [
        TenantSpec("alpha", blob(700, 4, 1), 4),
        TenantSpec("beta", blob(500, 4, 2), 4, seed=3),
        TenantSpec("gamma", blob(900, 4, 3), 4, target_num_clusters=2),
    ]


def assert_tenant_bit_identical(tr, solo, trajectory="exact"):
    """The fleet-vs-solo parity ladder (docs/TENANCY.md):

    - the fitted MODEL (state bits, scores, selected K, shift) must be
      bit-identical in both comparisons;
    - the full per-K trajectory is bit-exact vs the FIXED-WIDTH solo
      sweep (``sweep_k_buckets='off'`` -- the fleet is fixed-width by
      construction, the batched-restart trade); vs the default pow2-
      bucketing solo it is compared at near-epsilon tolerance instead,
      because the solo sweep's width can shrink below the fleet's fixed
      width mid-sweep and non-best Ks then differ in the last bits.
    """
    r = tr.result
    assert r is not None, tr.error
    assert r.ideal_num_clusters == solo.ideal_num_clusters
    assert r.min_rissanen == solo.min_rissanen
    assert r.final_loglik == solo.final_loglik
    np.testing.assert_array_equal(np.asarray(r.state.means),
                                  np.asarray(solo.state.means))
    np.testing.assert_array_equal(np.asarray(r.state.R),
                                  np.asarray(solo.state.R))
    np.testing.assert_array_equal(np.asarray(r.state.N),
                                  np.asarray(solo.state.N))
    np.testing.assert_array_equal(r.data_shift, solo.data_shift)
    assert len(r.sweep_log) == len(solo.sweep_log)
    for frow, srow in zip(r.sweep_log, solo.sweep_log):
        assert frow[0] == srow[0] and frow[3] == srow[3]
        if trajectory == "exact":
            assert frow[1:3] == srow[1:3]
        else:
            np.testing.assert_allclose(frow[1:3], srow[1:3], rtol=1e-12)


# ---------------------------------------------------------- solo parity


def test_fleet_vs_solo_bit_parity_plain(rng):
    """Every tenant of a plain-model fleet is bit-identical -- model AND
    full per-K trajectory -- to its solo fit at the same seed/config
    (full covariance). The shared config pins ``sweep_k_buckets='off'``:
    the fleet sweep is fixed-width by construction (the PR-5 batched-
    restart trade), and 'off' is the solo sweep's fixed-width program
    shape, so both sides literally run the same HLO per tenant."""
    tenants = tenant_set()
    c = cfg(sweep_k_buckets="off")
    fleet = fit_fleet(tenants, c)
    assert not fleet.dropped
    for spec in tenants:
        solo = fit_gmm(spec.data, spec.num_clusters,
                       spec.target_num_clusters,
                       dataclasses.replace(c, seed=(c.seed if spec.seed
                                                    is None else spec.seed)))
        assert_tenant_bit_identical(fleet[spec.name], solo,
                                    trajectory="exact")


def test_fleet_vs_default_bucketing_solo_tolerance(rng):
    """Against the DEFAULT config (pow2 sweep bucketing), the solo
    sweep's padded width shrinks mid-sweep below the fleet's fixed
    width, so parity is near-epsilon rather than guaranteed-bitwise:
    identical selected K, scores within 1e-12 (docs/TENANCY.md
    'Parity guarantees')."""
    tenants = tenant_set()
    c = cfg()
    fleet = fit_fleet(tenants, c)
    for spec in tenants:
        solo = fit_gmm(spec.data, spec.num_clusters,
                       spec.target_num_clusters,
                       dataclasses.replace(c, seed=(c.seed if spec.seed
                                                    is None else spec.seed)))
        r = fleet[spec.name].result
        assert r.ideal_num_clusters == solo.ideal_num_clusters
        np.testing.assert_allclose(r.min_rissanen, solo.min_rissanen,
                                   rtol=1e-12)
        np.testing.assert_allclose(r.final_loglik, solo.final_loglik,
                                   rtol=1e-12)
        np.testing.assert_allclose(np.asarray(r.state.means),
                                   np.asarray(solo.state.means),
                                   rtol=1e-9, atol=1e-9)


def test_fleet_vs_solo_bit_parity_diag(rng):
    tenants = tenant_set()[:2]
    c = cfg(covariance_type="diag", sweep_k_buckets="off")
    fleet = fit_fleet(tenants, c)
    for spec in tenants:
        solo = fit_gmm(spec.data, spec.num_clusters,
                       spec.target_num_clusters,
                       dataclasses.replace(c, seed=(c.seed if spec.seed
                                                    is None else spec.seed)))
        assert_tenant_bit_identical(fleet[spec.name], solo,
                                    trajectory="exact")


@pytest.mark.parametrize("mesh", [(2, 1), (2, 2)])
def test_fleet_vs_solo_bit_parity_sharded(rng, mesh):
    """Sharded fleet lanes replicate the tenant axis and shard each
    lane's own chunk grid over the data axis; pad chunks interleave per
    shard so the stats psum groups exactly like the solo fit's."""
    tenants = tenant_set()[:2]
    c = cfg(chunk_size=128, mesh_shape=mesh, sweep_k_buckets="off")
    fleet = fit_fleet(tenants, c)
    for spec in tenants:
        solo = fit_gmm(spec.data, spec.num_clusters,
                       spec.target_num_clusters,
                       dataclasses.replace(c, seed=(c.seed if spec.seed
                                                    is None else spec.seed)))
        assert_tenant_bit_identical(fleet[spec.name], solo,
                                    trajectory="exact")


def test_fleet_nonpow2_k_tolerance(rng):
    """A non-pow2 starting K has no shared-program width equal to its
    solo seed width (K itself), so the contract degrades to
    reduction-order tolerance -- same selected K, near-identical
    scores (docs/TENANCY.md 'Parity guarantees')."""
    spec = TenantSpec("odd", blob(600, 3, 9), 3)
    c = cfg()
    fleet = fit_fleet([spec], c)
    solo = fit_gmm(spec.data, 3, 0, c)
    r = fleet["odd"].result
    assert r.ideal_num_clusters == solo.ideal_num_clusters
    np.testing.assert_allclose(r.min_rissanen, solo.min_rissanen,
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(r.state.means),
                               np.asarray(solo.state.means),
                               rtol=1e-7, atol=1e-7)


def test_fleet_vmap_mode_tolerance(rng):
    """fleet_mode='vmap' batches the tenant matmuls (the throughput
    shape); results agree with solo fits at tolerance, same winner K."""
    tenants = tenant_set()[:2]
    c = cfg(fleet_mode="vmap")
    fleet = fit_fleet(tenants, c)
    for spec in tenants:
        solo = fit_gmm(spec.data, spec.num_clusters, 0,
                       dataclasses.replace(c, seed=(c.seed if spec.seed
                                                    is None else spec.seed)))
        r = fleet[spec.name].result
        assert r.ideal_num_clusters == solo.ideal_num_clusters
        np.testing.assert_allclose(r.min_rissanen, solo.min_rissanen,
                                   rtol=1e-8)
        np.testing.assert_allclose(np.asarray(r.state.means),
                                   np.asarray(solo.state.means),
                                   rtol=1e-7, atol=1e-7)


# ------------------------------------------------------ packing layout


def test_ragged_pack_unpack_roundtrip(rng):
    """Packing is pure layout: the grid slices back to exactly the
    centered rows that went in, for every tenant of every group."""
    tenants = [
        TenantSpec("a", blob(700, 4, 1), 4),
        TenantSpec("b", blob(500, 4, 2), 4),
        TenantSpec("c", blob(130, 2, 3), 2),
    ]
    c = cfg()
    groups = plan_fleet(tenants, c)
    seen = set()
    for g in groups:
        packed = pack_group(g, tenants, c)
        for lane, i in enumerate(g.indices):
            spec = tenants[i]
            dtype = np.dtype(c.dtype)
            want = (spec.data.astype(dtype)
                    - packed.shifts[lane].astype(dtype)[None, :])
            got = unpack_rows(packed, lane)
            np.testing.assert_array_equal(got, want)
            # Exactly N_t unit weights; every pad row weighs zero
            # (what makes the pad algebraically inert).
            w = packed.wts[lane].reshape(-1)
            n = int(packed.n_events[lane])
            assert int((w != 0).sum()) == n
            assert set(np.unique(w).tolist()) <= {0.0, 1.0}
            seen.add(spec.name)
    assert seen == {"a", "b", "c"}


def test_plan_fleet_grouping_and_caps():
    """Tenants group by (chunk-count, K-bucket) signature; the group cap
    splits oversized groups; mixed D and duplicate names are loud."""
    c = cfg(chunk_size=256)
    tenants = [
        TenantSpec("t1", blob(500, 4, 1), 4),    # bucket 512, kb 4
        TenantSpec("t2", blob(400, 3, 2), 3),    # bucket 512, kb 4
        TenantSpec("t3", blob(900, 4, 3), 4),    # bucket 1024, kb 4
        TenantSpec("t4", blob(480, 8, 4), 8),    # bucket 512, kb 8
    ]
    groups = plan_fleet(tenants, c)
    keys = sorted((g.num_chunks, g.k_bucket, len(g.indices))
                  for g in groups)
    assert keys == [(2, 4, 2), (2, 8, 1), (4, 4, 1)]
    capped = plan_fleet(tenants,
                        dataclasses.replace(c, fleet_group_size=1))
    assert all(len(g.indices) == 1 for g in capped)
    with pytest.raises(ValueError, match="dimensionality"):
        plan_fleet([TenantSpec("x", blob(100, 2, 1, d=3), 2),
                    TenantSpec("y", blob(100, 2, 1, d=4), 2)], c)
    with pytest.raises(ValueError, match="duplicate"):
        plan_fleet([TenantSpec("x", blob(100, 2, 1), 2),
                    TenantSpec("x", blob(100, 2, 2), 2)], c)


def test_fleet_rejects_unsupported_configs():
    spec = [TenantSpec("t", blob(200, 2, 1), 2)]
    for bad, match in [
        (cfg(stream_events=True), "stream_events"),
        (cfg(fused_sweep=True), "fused_sweep"),
        (cfg(n_init=3), "n_init"),
        (cfg(estep_backend="pallas"), "Pallas"),
    ]:
        with pytest.raises(ValueError, match=match):
            fit_fleet(spec, bad)
    with pytest.raises(ValueError, match="fleet_mode"):
        cfg(fleet_mode="bogus")
    with pytest.raises(ValueError, match="fleet_group_size"):
        cfg(fleet_group_size=0)


# ------------------------------------------------- fault containment


def test_drop_one_poisoned_tenant_keeps_survivors(rng):
    """A lane-targeted nan_loglik injection (the GMM_FAULTS 'restart'
    key addresses fleet lanes too) poisons ONE tenant: it drops with a
    drop_tenant recovery action; every survivor is bit-identical to the
    clean fleet's result."""
    tenants = [
        TenantSpec("a", blob(512, 4, 1), 4),
        TenantSpec("b", blob(512, 4, 2), 4),
        TenantSpec("c", blob(512, 4, 3), 4),
    ]
    c = cfg()
    clean = fit_fleet(tenants, c)
    assert not clean.dropped
    with faults.use({"nan_loglik": {"iter": 2, "restart": 1}}):
        fleet = fit_fleet(tenants, c)
    assert [t.name for t in fleet.dropped] == ["b"]
    assert "fatal numerical fault" in fleet["b"].error
    for name in ("a", "c"):
        r = fleet[name].result
        want = clean[name].result
        assert r.final_loglik == want.final_loglik
        np.testing.assert_array_equal(np.asarray(r.state.means),
                                      np.asarray(want.state.means))


def test_poisoned_tenant_with_recovery_off_raises(rng):
    from cuda_gmm_mpi_tpu.health import NumericalFaultError

    tenants = [TenantSpec("a", blob(512, 4, 1), 4),
               TenantSpec("b", blob(512, 4, 2), 4)]
    with faults.use({"nan_loglik": {"iter": 2, "restart": 0}}):
        with pytest.raises(NumericalFaultError, match=r"tenant\(s\) a "):
            fit_fleet(tenants, cfg(recovery="off"))


# ------------------------------------------------- preempt + resume


def test_fleet_preempt_then_bit_identical_resume(rng, tmp_path):
    """An injected preemption between sweep steps raises PreemptedError
    with the completed steps durable; the resumed fleet finishes to
    results bit-identical to an uninterrupted run."""
    tenants = tenant_set()[:2]
    ckdir = str(tmp_path / "ck")
    c = cfg(checkpoint_dir=ckdir)
    want = fit_fleet(tenants, cfg())  # uninterrupted reference

    with faults.use({"preempt": {"iter": 2}}):
        with supervisor.use(RunSupervisor(install_signals=False)):
            with pytest.raises(PreemptedError):
                fit_fleet(tenants, c)
    # At least one group checkpoint survived the stop.
    assert any(p.name.startswith("group")
               for p in (tmp_path / "ck").iterdir())

    resumed = fit_fleet(tenants, c)
    for spec in tenants:
        r = resumed[spec.name].result
        w = want[spec.name].result
        assert r.final_loglik == w.final_loglik
        assert r.min_rissanen == w.min_rissanen
        np.testing.assert_array_equal(np.asarray(r.state.means),
                                      np.asarray(w.state.means))
        np.testing.assert_array_equal(np.asarray(r.state.R),
                                      np.asarray(w.state.R))


# ------------------------------------------------------- bulk export


def test_fleet_registry_export_and_serving_roundtrip(rng, tmp_path):
    """Direct fleet export: one exact registry version per tenant; a
    re-hydrated model scores bit-identically to the fleet's result."""
    from cuda_gmm_mpi_tpu.serving import ModelRegistry

    tenants = tenant_set()[:2]
    c = cfg()
    fleet = fit_fleet(tenants, c)
    reg = ModelRegistry(str(tmp_path / "reg"))
    for tr in fleet.fitted:
        v = reg.save(tr.name, tr.result, config=c, source="fleet")
        assert v == 1
        m = reg.load(tr.name)
        np.testing.assert_array_equal(np.asarray(m.state.means),
                                      np.asarray(tr.result.state.means))
        assert m.manifest["source"] == "fleet"
        assert m.k == tr.result.ideal_num_clusters


def test_bulk_export_partial_failure_reported_not_fatal(tmp_path):
    """registry.export_fleet: a tenant with a torn/missing summary is
    reported in the audit and skipped; its siblings still export."""
    from cuda_gmm_mpi_tpu.serving import ModelRegistry

    out = tmp_path / "out"
    out.mkdir()
    spec = TenantSpec("good", blob(300, 2, 1), 2)
    fleet = fit_fleet([spec], cfg())
    from cuda_gmm_mpi_tpu.io import write_summary

    write_summary(str(out / "good.summary"), fleet["good"].result)
    manifest = {
        "schema": 1,
        "tenants": [
            {"name": "good", "dropped": False,
             "summary": str(out / "good.summary"),
             "covariance_type": "full", "dtype": "float64"},
            {"name": "torn", "dropped": False,
             "summary": str(out / "missing.summary"),
             "covariance_type": "full", "dtype": "float64"},
            {"name": "was-dropped", "dropped": True,
             "error": "fatal numerical fault"},
        ],
    }
    (out / "fleet.json").write_text(json.dumps(manifest))
    reg = ModelRegistry(str(tmp_path / "reg"))
    audit = reg.export_fleet(str(out))
    by_name = {row["name"]: row for row in audit}
    assert by_name["good"]["version"] == 1
    assert "error" in by_name["torn"]
    assert by_name["was-dropped"]["skipped"] == "dropped"
    assert reg.models() == ["good"]


# ------------------------------------------------- telemetry / report


def test_fleet_telemetry_stream_validates_and_renders(rng, tmp_path):
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.telemetry.report import render_report
    from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

    tenants = tenant_set()[:2]
    path = str(tmp_path / "fleet.jsonl")
    fit_fleet(tenants, cfg(metrics_file=path))
    recs = read_stream(path)
    assert validate_stream(recs) == []
    kinds = [r["event"] for r in recs]
    assert kinds.count("fleet_start") == 1
    assert kinds.count("tenant_done") == 2
    assert kinds.count("fleet_summary") == 1
    done = {r["tenant"]: r for r in recs if r["event"] == "tenant_done"}
    assert set(done) == {"alpha", "beta"}
    assert all(not r["dropped"] and r["k"] >= 1 for r in done.values())
    summary = [r for r in recs if r["event"] == "fleet_summary"][0]
    assert summary["tenants"] == 2 and summary["dropped"] == 0
    text = render_report(recs)
    assert "Fleet (rev v1.8" in text
    assert "alpha" in text and "beta" in text


# ------------------------------------------------------- CLI (subprocess)


def _write_csv(path, x):
    with open(path, "w") as f:
        f.write(",".join(f"c{i}" for i in range(x.shape[1])) + "\n")
        for row in x:
            f.write(",".join(f"{v:.8f}" for v in row) + "\n")


def test_fleet_cli_end_to_end(tmp_path):
    """gmm fleet manifest -> per-tenant summaries + fleet.json + direct
    registry export; gmm export --fleet bulk-exports from fleet.json."""
    d = tmp_path
    for i, (n, k) in enumerate([(300, 2), (260, 2)]):
        _write_csv(d / f"t{i}.csv", blob(n, k, i + 1))
    manifest = [
        {"name": "m0", "infile": str(d / "t0.csv"), "num_clusters": 2},
        {"name": "m1", "infile": str(d / "t1.csv"), "num_clusters": 2,
         "seed": 5},
    ]
    (d / "manifest.json").write_text(json.dumps(manifest))
    env = worker_env()
    r = subprocess.run(
        [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "fleet",
         str(d / "manifest.json"), "--out-dir", str(d / "out"),
         "--registry", str(d / "reg"), "--min-iters", "2",
         "--max-iters", "2", "--chunk-size", "128", "--device", "cpu"],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr
    assert (d / "out" / "m0.summary").exists()
    assert (d / "out" / "m1.summary").exists()
    fleet_json = json.loads((d / "out" / "fleet.json").read_text())
    assert {t["name"] for t in fleet_json["tenants"]} == {"m0", "m1"}
    assert all(t.get("registry_version") == 1
               for t in fleet_json["tenants"])
    # Bulk export from the fleet manifest into a second registry.
    r2 = subprocess.run(
        [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "export",
         "--registry", str(d / "reg2"), "--fleet", str(d / "out")],
        capture_output=True, text=True, env=env, timeout=600)
    assert r2.returncode == 0, r2.stderr
    assert "2/2 tenants exported" in r2.stdout
