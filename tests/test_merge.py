"""Merge machinery vs the add_clusters/cluster_distance oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.ops.merge import (
    argmin_pair, eliminate_empty, merge_pair, pairwise_merge_distances,
    reduce_order_step,
)

from .reference_impl import np_cluster_distance, np_merge
from .test_estep import make_state
from .test_mstep import as_params


def test_pairwise_distances_match_oracle(rng):
    k, d = 5, 3
    state = make_state(rng, k, d)
    dist = np.asarray(pairwise_merge_distances(state))
    params = as_params(state)
    for i in range(k):
        for j in range(k):
            if j <= i:
                assert np.isinf(dist[i, j])
            else:
                np.testing.assert_allclose(
                    dist[i, j], np_cluster_distance(params, i, j),
                    rtol=1e-8, atol=1e-8,
                )


def test_inactive_pairs_excluded(rng):
    k, d = 5, 3
    state = make_state(rng, k, d, inactive=(1,))
    dist = np.asarray(pairwise_merge_distances(state))
    assert np.all(np.isinf(dist[1, :])) and np.all(np.isinf(dist[:, 1]))


def test_merge_pair_matches_oracle(rng):
    k, d = 4, 3
    state = make_state(rng, k, d)
    params = as_params(state)
    merged = np_merge(params, 1, 3)
    out = merge_pair(state, jnp.asarray(1), jnp.asarray(3))
    np.testing.assert_allclose(float(out.N[1]), merged["N"], rtol=1e-10)
    np.testing.assert_allclose(float(out.pi[1]), merged["pi"], rtol=1e-10)
    np.testing.assert_allclose(np.asarray(out.means[1]), merged["means"],
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(out.R[1]), merged["R"], rtol=1e-9)
    np.testing.assert_allclose(float(out.constant[1]), merged["constant"],
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(out.Rinv[1]),
                               np.linalg.inv(merged["R"]), rtol=1e-7, atol=1e-9)
    assert not bool(out.active[3])
    # untouched clusters unchanged
    np.testing.assert_allclose(np.asarray(out.means[0]), params["means"][0])


def test_eliminate_empty(rng):
    k, d = 4, 3
    state = make_state(rng, k, d)
    state = state.replace(N=jnp.asarray([10.0, 0.3, 5.0, 0.49]))
    out = eliminate_empty(state)
    np.testing.assert_array_equal(np.asarray(out.active),
                                  [True, False, True, False])


def test_reduce_order_step_merges_argmin(rng):
    k, d = 5, 3
    state = make_state(rng, k, d)
    dist = np.asarray(pairwise_merge_distances(state))
    i_exp, j_exp = np.unravel_index(np.argmin(dist), dist.shape)
    out, (i, j), min_d = reduce_order_step(state)
    assert (int(i), int(j)) == (i_exp, j_exp)
    assert float(min_d) == pytest.approx(dist[i_exp, j_exp])
    assert int(out.num_active()) == k - 1


def test_reduce_order_step_no_valid_pair(rng):
    """All-inf distances leave the state untouched (degenerate-sweep guard)."""
    k, d = 3, 3
    state = make_state(rng, k, d, inactive=(0, 1, 2))
    out, _, min_d = reduce_order_step(state)
    assert not np.isfinite(float(min_d))
    np.testing.assert_array_equal(np.asarray(out.active),
                                  np.asarray(state.active))
    np.testing.assert_allclose(np.asarray(out.N), np.asarray(state.N))


def test_argmin_pair_first_tie():
    d = jnp.asarray(np.array([[np.inf, 2.0, 2.0], [np.inf, np.inf, 2.0],
                              [np.inf, np.inf, np.inf]]))
    i, j = argmin_pair(d)
    assert (int(i), int(j)) == (0, 1)  # first in row-major scan order
