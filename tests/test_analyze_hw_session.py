"""Lock examples/analyze_hw_session.py to its producers' formats.

The analyzer parses two formats written by other files -- the
"<shape> <tag> <ms> ms/iter loglik=<ll>" rows of
examples/bench_kernel_precision.py and bench.py's JSON lines as captured
by examples/hw_session.sh -- so a format change in either producer must
fail a test, not silently produce an empty decision table in the one
short tunnel window where the real logs get made.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from .conftest import worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "analyze_hw_session.py")

# The full step set of examples/hw_session.sh, shared by the end-to-end
# smoke test (exact produced-log set) and the resume/failure-path test
# (pre-marked DONE logs). One list: a step added/renamed in the session
# script must be reflected here exactly once.
SESSION_STEPS = [
    "bench_north", "bench_north_feats", "bench_north_chunk262k",
    "bench_5", "bench_5stream", "bench_6", "bench_3_diag",
    "kernel_north", "kernel_envelope_diag", "stream_overlap",
    "components_north", "components_envelope",
]


def _load():
    spec = importlib.util.spec_from_file_location("analyze_hw_session", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_logs(d):
    (d / "kernel_north.log").write_text(
        "platform: tpu\n"
        "north     xla high                       8.60 ms/iter  loglik=-39473088\n"
        "north     xla+feats high                 6.10 ms/iter  loglik=-39473090\n"
        "north     kernel high b=512              5.20 ms/iter  loglik=-99999999\n"
        "north     kernel high b=1024: FAILED MosaicError: VMEM overflow\n"
        "DONE\n")
    (d / "bench_north.log").write_text(
        json.dumps({"metric": "EM iters/sec (1000000x24, K=100, full "
                              "covariance, tpu)",
                    "value": 78.2, "unit": "iters/sec", "vs_baseline": 432.8,
                    "accelerator_unavailable": False, "precision": "high",
                    "wall_s_per_iter": 0.0128}) + "\nDONE\n")
    (d / "bench_north_feats.log").write_text(
        json.dumps({"metric": "EM iters/sec (config=north)", "value": 0.0,
                    "unit": "iters/sec", "vs_baseline": 0.0,
                    "accelerator_unavailable": True, "watchdog": True}) + "\n")
    (d / "components_north.log").write_text(
        "platform: tpu  precision=high iters=20\n"
        "north     full         8.60 ms/pass\n"
        "north     xouter       5.60 ms/pass\n"
        "DONE\n")
    (d / "stream_overlap.log").write_text(
        "platform: tpu  n=4000000 d=24 k=64 iters=10 chunk=131072 mesh=off\n"
        "in-memory                 10.00 ms/iter  loglik=-1\n"
        "streaming                 12.00 ms/iter  loglik=-1\n"
        "streaming/in-memory ratio: 1.20x\n"
        "DONE\n")


def test_parses_producer_formats_and_guards_wrong_answers(tmp_path):
    _write_logs(tmp_path)
    mod = _load()
    rows, fails = mod.parse_kernel_logs(str(tmp_path))
    assert {r["tag"] for r in rows} == {"xla high", "xla+feats high",
                                        "kernel high b=512"}
    assert fails and "MosaicError" in fails[0]["err"]
    bench = mod.parse_bench_logs(str(tmp_path))
    assert bench["bench_north"]["value"] == 78.2
    assert bench["bench_north_feats"]["accelerator_unavailable"] is True
    comps = mod.parse_component_logs(str(tmp_path))
    assert ("north", "xouter", 5.6) in comps and ("north", "full", 8.6) in comps
    ratio, drift = mod.parse_stream_overlap(str(tmp_path))
    assert ratio == 1.2 and drift == 0.0


def test_cli_decision_excludes_drifted_winner(tmp_path):
    _write_logs(tmp_path)
    r = subprocess.run([sys.executable, SCRIPT, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    # kernel b=512 is fastest but computed a wrong answer (loglik far off
    # the XLA oracle row): it must be excluded, xla+feats crowned.
    assert "xla+feats high **<- winner**" in out
    assert "kernel high b=512" in out and "ANSWER DRIFT" in out
    assert "route to **xla+feats**" in out
    # The A/B section must not fabricate a delta from the watchdog artifact.
    assert "feature hoist" not in out
    # The no-measurement artifact is labeled as such in the bench table.
    assert "NO MEASUREMENT" in out
    # Component decomposition and streaming-overlap sections rendered.
    assert "Component decomposition" in out and "| north | xouter | 5.60 |" in out
    assert "Streaming overlap" in out and "1.20x" in out
    assert "overlap holds" in out


def test_stream_overlap_answer_drift_voids_ratio(tmp_path):
    """A fast-but-wrong streaming run must be flagged, not celebrated."""
    (tmp_path / "stream_overlap.log").write_text(
        "platform: tpu  n=4000000 d=24 k=64 iters=10 chunk=131072 mesh=off\n"
        "in-memory                 10.00 ms/iter  loglik=-1000000\n"
        "streaming                  8.00 ms/iter  loglik=-990000\n"
        "streaming/in-memory ratio: 0.80x\n"
        "DONE\n")
    r = subprocess.run([sys.executable, SCRIPT, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "ANSWER DRIFT" in r.stdout
    assert "overlap holds" not in r.stdout
    # A '--mesh' run tags its reference row 'in-memory sharded'
    # (bench_streaming.py); that variant must parse and verify like the
    # plain tag (it used to fall through to "unverified" forever).
    (tmp_path / "stream_overlap.log").write_text(
        "in-memory sharded          10.00 ms/iter  loglik=-1000000\n"
        "streaming                  11.00 ms/iter  loglik=-1000000\n"
        "streaming/in-memory ratio: 1.10x\nDONE\n")
    r = subprocess.run([sys.executable, SCRIPT, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert "overlap holds" in r.stdout and "unverified" not in r.stdout
    # A ratio whose loglik pair genuinely didn't parse stays unverified,
    # not a pass.
    (tmp_path / "stream_overlap.log").write_text(
        "in-memory                  10.00 ms/iter\n"
        "streaming                   8.00 ms/iter\n"
        "streaming/in-memory ratio: 0.80x\nDONE\n")
    r = subprocess.run([sys.executable, SCRIPT, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert "unverified" in r.stdout and "overlap holds" not in r.stdout


@pytest.mark.slow
def test_smoke_session_end_to_end(tmp_path):
    """Run the REAL runbook (HW_SMOKE=1 hw_session.sh: every step, toy
    shapes, CPU) into the REAL analyzer -- the binding rehearsal that the
    round's hardware window cannot be lost to a step or format break the
    per-producer pins didn't cover (VERDICT r4 item 6)."""
    env = worker_env()
    env["HW_SMOKE"] = "1"
    env["LOGDIR"] = str(tmp_path)
    r = subprocess.run(
        ["bash", os.path.join(REPO, "examples", "hw_session.sh")],
        capture_output=True, text=True, env=env, timeout=1500, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
    assert "session complete" in r.stdout
    # Every step must have durably completed (DONE sentinel = resumability).
    # Exact set, not a count: a silently dropped/renamed step is precisely
    # the break this rehearsal exists to catch before a live window.
    logs = sorted(p.name for p in tmp_path.glob("*.log"))
    assert logs == sorted(f"{s}.log" for s in SESSION_STEPS), logs
    for p in tmp_path.glob("*.log"):
        assert "DONE" in p.read_text(), f"{p.name} did not finish"

    # The session must have written the decision artifact itself (the
    # unattended-window contract): a kernel-vs-XLA decision table with
    # routing, the bench capture table, and the one-env A/Bs.
    analysis = (tmp_path / "ANALYSIS.md").read_text()
    assert "analysis written" in r.stdout
    assert "Kernel-vs-XLA decision table" in analysis
    assert "Routing implied" in analysis
    assert "bench.py captures" in analysis
    assert "feature hoist" in analysis and "chunk tile" in analysis
    assert "Component decomposition" in analysis
    assert "Streaming overlap" in analysis


def test_session_resume_skips_done_and_fails_loud_on_broken_analysis(tmp_path):
    """Two session contracts in one fast run (no bench executes): every
    step whose log ends in DONE is skipped on resume, and an analyzer
    failure exits 4 (hw_wait_and_run.sh stops loudly instead of burning
    probe clients on deterministic re-failure)."""
    for s in SESSION_STEPS:
        # DONE so the step skips; content unparseable so the analyzer
        # finds nothing and returns nonzero.
        (tmp_path / f"{s}.log").write_text("gibberish\nDONE\n")
    env = worker_env()
    env["HW_SMOKE"] = "1"
    env["LOGDIR"] = str(tmp_path)
    r = subprocess.run(
        ["bash", os.path.join(REPO, "examples", "hw_session.sh")],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO)
    assert r.returncode == 4, (r.returncode, r.stdout[-2000:])
    assert r.stdout.count("already done, skipping") == len(SESSION_STEPS)
    assert "analyze_hw_session.py failed" in r.stdout
    assert "nothing parseable" in (tmp_path / "ANALYSIS.md").read_text()


@pytest.mark.slow
def test_live_producer_output_parses(tmp_path):
    """Run the real producer on a toy shape and parse its actual output --
    the binding check that the two files' formats cannot drift apart."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples", "bench_kernel_precision.py"),
         "north", "--blocks=256", "--n=2000", "--chunk=512", "--iters=1",
         "--device=cpu"],
        capture_output=True, text=True, env=worker_env(), timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    (tmp_path / "kernel_live.log").write_text(r.stdout)
    mod = _load()
    rows, fails = mod.parse_kernel_logs(str(tmp_path))
    # Every non-FAILED measurement line the producer printed must parse:
    # 3 precisions x (xla, xla+feats, kernel b=256), minus any kernel rows
    # that legitimately FAILED (surfaced in `fails`, still decision data).
    assert len(rows) + len(fails) == 9, r.stdout
    assert {mod.backend_of(r_["tag"]) for r_ in rows} >= {"xla", "xla+feats"}
    for prec in ("high", "highest", "default"):
        assert any(mod.precision_of(r_["tag"]) == prec for r_ in rows)
