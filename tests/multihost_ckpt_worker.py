"""Worker for the 2-process distributed checkpoint/resume test.

Each worker is one "host" of a simulated 2-host cluster running the full
fit_gmm sweep with checkpointing enabled -- the configuration the reference
actually deployed (MPI cluster, README.txt:18) where its only recovery story
was a full restart (SURVEY.md SS5.3). Checkpoints are written through the
multi-process orbax path (every rank calls save, primary writes) and a
restarted pair of workers must resume mid-sweep.

Usage: python multihost_ckpt_worker.py <pid> <nproc> <port> <ckdir> [mode]
Prints one line: RESULT {json}

``mode``: ``fused`` runs the sweep as ONE device program per rank
(--fused-sweep) with checkpoints riding the per-K ordered io_callback
emission -- the multi-controller composition VERDICT r3 item 4 requires;
``stream`` runs the sweep out-of-core (--stream-events) with each rank
streaming its host slice over its local shards (round 4).
"""

import json
import sys


def main() -> int:
    pid, nproc, port, ckdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    )
    mode = sys.argv[5] if len(sys.argv) > 5 else ""
    fused = mode == "fused"

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices

    force_cpu_devices(2)
    jax.config.update("jax_enable_x64", True)

    from cuda_gmm_mpi_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )

    import numpy as np

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models import fit_gmm

    # Deterministic dataset, identical on every host (stands in for a shared
    # input file); fit_gmm's multi-host path slices per host internally.
    rng = np.random.default_rng(77)
    centers = rng.normal(scale=9.0, size=(4, 3))
    # Fused: the callback-safe npz saves are near-instant, so the sweep
    # needs enough work that the test's SIGKILL lands mid-run (the host
    # sweep's collective orbax saves throttle it naturally).
    n, iters = (32_768, 50) if fused else (2048, 5)
    data = (centers[rng.integers(0, 4, n)]
            + rng.normal(size=(n, 3))).astype(np.float64)

    cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=64,
                    dtype="float64",
                    checkpoint_dir=ckdir, enable_print=True,
                    fused_sweep=fused,
                    stream_events=(mode == "stream"))
    r = fit_gmm(data, 10, 2, config=cfg)
    print("RESULT " + json.dumps({
        "pid": pid,
        "ideal_k": r.ideal_num_clusters,
        "min_rissanen": r.min_rissanen,
        "final_loglik": r.final_loglik,
        "means": np.asarray(r.means).tolist(),
        "sweep_ks": [int(row[0]) for row in r.sweep_log],
    }), flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
