"""Worker for the 2-process peer-loss watchdog test (tests/test_preemption.py).

Each worker is one "host" of a simulated 2-host cluster running a supervised
sweep (supervisor.use + checkpointing, so the liveness watchdog starts). The
test arms ``GMM_FAULTS={"rank_hang": {"rank": 1, "iter": N}}`` on rank 1
only: that rank stops heartbeating and wedges at its EM-iteration-N poll,
simulating a dead/stuck host. Rank 0 must NOT block forever in the next
collective (the reference's dead-MPI-rank behavior): its watchdog flags the
stale heartbeat within ``peer_timeout_s`` and the process exits 75
(EX_TEMPFAIL) -- cooperatively via PeerLostError when a poll point is
reachable, or through the supervisor's forced-exit escalation when the main
thread is wedged inside a collective.

Usage: python preempt_worker.py <pid> <nproc> <port> <ckdir>
Prints ``RESULT {json}`` on (unexpected) clean completion.
"""

import json
import sys


def main() -> int:
    pid, nproc, port, ckdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    )

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices

    force_cpu_devices(2)
    jax.config.update("jax_enable_x64", True)

    from cuda_gmm_mpi_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )

    import numpy as np

    from cuda_gmm_mpi_tpu import supervisor
    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models import fit_gmm

    rng = np.random.default_rng(77)
    centers = rng.normal(scale=9.0, size=(4, 3))
    data = (centers[rng.integers(0, 4, 4096)]
            + rng.normal(size=(4096, 3))).astype(np.float64)

    cfg = GMMConfig(min_iters=40, max_iters=40, chunk_size=64,
                    dtype="float64", checkpoint_dir=ckdir,
                    peer_timeout_s=6.0, preempt_poll_iters=2)
    try:
        with supervisor.use(supervisor.RunSupervisor()):
            r = fit_gmm(data, 10, 2, config=cfg)
    except supervisor.PeerLostError as e:
        print(f"PEER_LOST {e}", flush=True)
        return supervisor.EX_TEMPFAIL
    except supervisor.PreemptedError as e:
        print(f"PREEMPTED {e}", flush=True)
        return supervisor.EX_TEMPFAIL
    print("RESULT " + json.dumps({
        "pid": pid,
        "ideal_k": r.ideal_num_clusters,
    }), flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
