"""Network-native serving tier (round 20): HTTP front end, retrying
client, supervised worker pool, and the v2.7 http telemetry contract.

Contracts under test (docs/SERVING.md "HTTP front end",
docs/ROBUSTNESS.md "Network failure containment"):

  * routing: ``POST /v1/models/<name>[@<version>]:<op>`` parses for
    exactly the four scoring ops; everything else is a 404, and the
    error->status taxonomy maps each server-side error token to one
    unambiguous HTTP status;
  * the in-process front end answers bit-comparable scores over TCP,
    echoes ``X-GMM-Trace-Id``, honours ``X-GMM-Deadline-Ms``, serves
    /healthz /readyz /metrics, and flips /readyz to 503 (Retry-After
    set) the moment the drain starts -- before the queue flushes;
  * body bounds (413 for oversize, 411 for missing length) and the
    connection cap (503 shed + Retry-After) hold;
  * GMMClient: bounded jittered retries on 429/502/503, a token-bucket
    retry budget that fails fast under a down pool, deadline
    propagation over the wire, and hedged duplicates that win when the
    primary stalls;
  * the worker pool routes (model, version) to a stable slot with ring
    failover, skips quarantined slots, and fails fast while draining;
  * chaos: a worker killed mid-stream (fault-injected exit AND a real
    SIGKILL) costs ZERO failed client requests -- the sibling retry
    answers, the supervisor respawns the slot, SIGTERM still drains to
    exit 75 -- and the stream stays schema-valid with the v2.7 rollup
    (`errors_5xx == 0`) that `gmm diff` gates on;
  * HTTP off => the telemetry stream is byte-identical to the pre-HTTP
    shape: no http/worker events, no ``http`` rollup key.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import zlib
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry
from cuda_gmm_mpi_tpu.serving.client import GMMClient, GMMClientError
from cuda_gmm_mpi_tpu.serving.http import (HTTP_OPS, HTTPFrontEnd,
                                           InprocBackend, parse_model_path,
                                           status_for_error)
from cuda_gmm_mpi_tpu.serving import wire
from cuda_gmm_mpi_tpu.serving.pool import NO_WORKER_WAIT_S, WorkerPool, _Worker
from cuda_gmm_mpi_tpu.telemetry import read_stream
from cuda_gmm_mpi_tpu.telemetry.diff import DEFAULT_FAIL_ON, summarize_run
from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

from .conftest import communicate_or_kill, worker_env
from .test_serving import fitted


# ------------------------------------------------------------- http plumbing


def _post(port, path, body, headers=None, timeout=60.0):
    """One raw POST; returns (status, headers-dict, decoded-body|raw)."""
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        data = (body if isinstance(body, (bytes, bytearray))
                else json.dumps(body).encode("utf-8"))
        conn.request("POST", path, data,
                     {"Content-Type": "application/json", **(headers or {})})
        r = conn.getresponse()
        raw = r.read()
        hdrs = {k.lower(): v for k, v in r.getheaders()}
        try:
            return r.status, hdrs, json.loads(raw)
        except ValueError:
            return r.status, hdrs, raw
    finally:
        conn.close()


def _get(port, path, timeout=60.0):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, {k.lower(): v for k, v in r.getheaders()}, r.read()
    finally:
        conn.close()


# ---------------------------------------------------------- routing taxonomy


def test_parse_model_path_grammar():
    assert parse_model_path("/v1/models/m:predict") == ("m", None, "predict")
    assert parse_model_path("/v1/models/m@3:score_samples") == (
        "m", 3, "score_samples")
    assert parse_model_path("/v1/models/blobs-v2@12:predict_proba") == (
        "blobs-v2", 12, "predict_proba")
    assert parse_model_path("/v1/models/m:score") == ("m", None, "score")
    for op in HTTP_OPS:
        assert parse_model_path(f"/v1/models/m:{op}")[2] == op
    # everything off-grammar is a route miss, not a crash
    for bad in ("/v1/models/m:frobnicate", "/v1/models/m", "/healthz",
                "/v1/models/:predict", "/v2/models/m:predict",
                "/v1/models/m@x:predict", "/v1/models/m@:predict", ""):
        assert parse_model_path(bad) is None


def test_status_for_error_taxonomy():
    """Each server-side error token has ONE status: load-shed and drain
    are retryable (429/503), budget expiry is 504, a crashed-pool miss
    is 502, model math going non-finite is the server's fault (500),
    an unknown model is the client's (404), and an oversize payload --
    JSON line, HTTP body, or binary frame -- is 413."""
    assert status_for_error("overloaded") == 429
    assert status_for_error("shutting_down") == 503
    assert status_for_error("circuit_open") == 503
    assert status_for_error("deadline_expired") == 504
    assert status_for_error("http_timeout") == 504
    assert status_for_error("worker_unavailable") == 502
    assert status_for_error("non_finite_scores") == 500
    assert status_for_error("dispatch failed: boom") == 500
    assert status_for_error("unknown model 'ghost'") == 404
    assert status_for_error("registry: torn artifact") == 404
    assert status_for_error("line_too_long") == 413
    assert status_for_error("body_too_large") == 413
    assert status_for_error("frame_too_large") == 413
    assert status_for_error("bad_request") == 400
    assert status_for_error("bad_frame") == 400
    assert status_for_error("anything else") == 400


# -------------------------------------------------------- in-process tier


@pytest.fixture
def inproc(rng, tmp_path):
    """A live GMMServer loop + HTTP front end in this process: HTTP
    handler threads feed the same micro-batch queue the socket readers
    do, so everything downstream of routing is the already-tested
    server core."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    server = GMMServer(ModelRegistry(reg_dir))
    t = threading.Thread(target=server.run_loop, daemon=True)
    t.start()
    front = HTTPFrontEnd(InprocBackend(server)).start()
    try:
        yield front, server, gm, data
    finally:
        front.stop()
        server._stop.set()   # works even once a test began a drain
        t.join(timeout=60)
        assert not t.is_alive()


def test_http_scores_match_estimator_and_echo_trace(inproc):
    front, server, gm, data = inproc
    port = front.port
    x = data[:17]
    st, hdrs, body = _post(port, "/v1/models/m:score_samples",
                           {"x": x.tolist()},
                           headers={"X-GMM-Trace-Id": "t-abc123"})
    assert st == 200, body
    assert body["ok"] and body["model"] == "m" and body["version"] == 1
    np.testing.assert_allclose(np.asarray(body["result"]),
                               gm.score_samples(x), rtol=1e-6)
    assert hdrs.get("x-gmm-trace-id") == "t-abc123"
    # explicit version pin routes to the same (only) version
    st, _, pinned = _post(port, "/v1/models/m@1:predict", {"x": x.tolist()})
    assert st == 200 and pinned["version"] == 1
    assert pinned["result"] == gm.predict(x).tolist()
    # the GMMClient speaks the same dialect end to end
    client = GMMClient(f"127.0.0.1:{port}")
    got = client.score(model="m", x=x.tolist())
    assert np.isclose(got, float(gm.score(x)), rtol=1e-6)
    assert client.stats()["requests"] == 1
    assert front.requests >= 3 and front.errors_5xx == 0


def test_http_client_errors_map_to_statuses(inproc):
    front, server, _, data = inproc
    port = front.port
    x = data[:4].tolist()
    st, _, body = _post(port, "/v1/models/ghost:predict", {"x": x})
    assert st == 404 and not body["ok"]
    assert "unknown model" in body["error"]
    st, _, body = _post(port, "/v1/models/m:frobnicate", {"x": x})
    assert st == 404
    st, _, body = _post(port, "/v1/models/m:predict", b"{not json")
    assert st == 400 and body["error"] == "bad_json"
    st, _, body = _post(port, "/v1/models/m:predict", {"x": x},
                        headers={"X-GMM-Deadline-Ms": "banana"})
    assert st == 400 and body["error"] == "bad_deadline"
    # missing Content-Length (chunked is not part of the dialect)
    conn = HTTPConnection("127.0.0.1", port, timeout=30.0)
    try:
        conn.putrequest("POST", "/v1/models/m:predict",
                        skip_accept_encoding=True)
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        conn.send(b"0\r\n\r\n")
        assert conn.getresponse().status == 411
    finally:
        conn.close()
    assert front.errors_4xx >= 4 and front.errors_5xx == 0


def test_http_probes_and_metrics_and_drain_flip(inproc):
    front, server, _, data = inproc
    port = front.port
    assert _get(port, "/healthz")[0] == 200
    assert _get(port, "/readyz")[0] == 200
    st, _, payload = _get(port, "/metrics")
    assert st == 200
    text = payload.decode("utf-8")
    assert "gmm_http_connections" in text and "# EOF" in text
    # the drain flips /readyz BEFORE the queue flushes; /healthz stays
    # 200 (the process is alive, just not accepting new work)
    server.begin_drain("test")
    st, hdrs, _ = _get(port, "/readyz")
    assert st == 503
    assert int(hdrs["retry-after"]) >= 1
    assert _get(port, "/healthz")[0] == 200


def test_http_deadline_header_expires_to_504(inproc):
    front, server, _, data = inproc
    st, _, body = _post(front.port, "/v1/models/m:score",
                        {"x": data[:4].tolist()},
                        headers={"X-GMM-Deadline-Ms": "0.0001"})
    assert st == 504, body
    assert body["error"] in ("deadline_expired", "http_timeout")


def test_http_body_bound_and_connection_cap(rng, tmp_path):
    """A tight front end: 2 KiB bodies, ONE connection. The oversize
    body is refused 413 without reading it; the second concurrent
    connection is shed 503 + Retry-After and counted."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    server = GMMServer(ModelRegistry(reg_dir))
    t = threading.Thread(target=server.run_loop, daemon=True)
    t.start()
    front = HTTPFrontEnd(InprocBackend(server), max_body_bytes=2048,
                         max_connections=1).start()
    try:
        port = front.port
        st, hdrs, body = _post(port, "/v1/models/m:score_samples",
                               {"x": data[:400].tolist()})
        assert st == 413 and not body["ok"]
        assert hdrs.get("connection") == "close"
        # hold the single slot open with a raw idle connection...
        hog = socket.create_connection(("127.0.0.1", port), timeout=30)
        try:
            time.sleep(0.1)
            st, hdrs, _ = _get(port, "/readyz")
            assert st == 503
            assert int(hdrs["retry-after"]) >= 1
        finally:
            hog.close()
        assert front.shed_connections >= 1
        # slot released: the next request is served again. Poll the
        # POST itself — a probe GET can still hold the single slot in
        # its handler teardown when the next connection arrives.
        deadline = time.monotonic() + 30
        while True:
            st, _, body = _post(port, "/v1/models/m:score",
                                {"x": data[:4].tolist()})
            if st == 200 or time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        assert st == 200 and body["ok"]
    finally:
        front.stop()
        server._stop.set()   # works even once a test began a drain
        t.join(timeout=60)


# ------------------------------------------------------------- GMMClient


class _Script:
    """A scripted origin: pops (status, body) per request, records what
    each attempt sent (path + headers) for the propagation asserts."""

    def __init__(self, plays):
        self.plays = list(plays)
        self.seen = []
        self.lock = threading.Lock()
        self.stall_first_s = 0.0

    def next_play(self):
        with self.lock:
            return self.plays.pop(0) if len(self.plays) > 1 \
                else self.plays[0]


@pytest.fixture
def stub():
    """A stdlib HTTP origin driven by a :class:`_Script`."""
    script = _Script([(200, {"ok": True, "result": 1.0})])

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            with script.lock:
                first = not script.seen
                script.seen.append(
                    {"path": self.path,
                     "deadline": self.headers.get("X-GMM-Deadline-Ms"),
                     "trace": self.headers.get("X-GMM-Trace-Id")})
            if first and script.stall_first_s:
                time.sleep(script.stall_first_s)
            status, body = script.next_play()
            payload = json.dumps(body).encode("utf-8")
            self.send_response(status)
            if status in (429, 503):
                self.send_header("Retry-After", "0")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield script, httpd.server_address[1]
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=30)


def test_client_retries_transient_503_then_succeeds(stub):
    script, port = stub
    script.plays = [(503, {"ok": False, "error": "shutting_down"}),
                    (503, {"ok": False, "error": "shutting_down"}),
                    (200, {"ok": True, "result": [1.0, 2.0]})]
    client = GMMClient(f"127.0.0.1:{port}", retries=4,
                       backoff_base_s=0.01)
    assert client.score_samples("m", [[0.0]]) == [1.0, 2.0]
    s = client.stats()
    assert s["requests"] == 1 and s["retries"] == 2
    assert s["budget_denied"] == 0
    assert len(script.seen) == 3


def test_client_retry_budget_fails_fast_when_pool_is_down(stub):
    """The token bucket: a cold client carries 2.0 tokens, earns
    +retry_budget per success, and a retry costs 1.0 -- so a hard-down
    origin gets exactly two retries before the budget denies the third
    instead of amplifying the outage."""
    script, port = stub
    script.plays = [(503, {"ok": False, "error": "shutting_down"})]
    client = GMMClient(f"127.0.0.1:{port}", retries=10,
                       backoff_base_s=0.01, retry_budget=0.0)
    with pytest.raises(GMMClientError, match="retry budget"):
        client.request("m", "score", [[0.0]])
    s = client.stats()
    assert s["retries"] == 2 and s["budget_denied"] == 1
    assert len(script.seen) == 3           # initial + the 2 funded retries


def test_client_does_not_retry_non_retryable_status(stub):
    script, port = stub
    script.plays = [(404, {"ok": False, "error": "unknown model 'x'"})]
    client = GMMClient(f"127.0.0.1:{port}", retries=5)
    with pytest.raises(GMMClientError, match="unknown model"):
        client.predict("x", [[0.0]])
    assert client.stats()["retries"] == 0
    assert len(script.seen) == 1


def test_client_propagates_deadline_and_version_over_the_wire(stub):
    script, port = stub
    script.plays = [(200, {"ok": True, "result": [0]})]
    client = GMMClient(f"127.0.0.1:{port}")
    client.predict("m", [[0.0]], version=3, deadline_ms=5000)
    seen = script.seen[0]
    assert seen["path"] == "/v1/models/m@3:predict"
    assert 0 < float(seen["deadline"]) <= 5000


def test_client_hedge_duplicates_a_stalled_request(stub):
    """Hedging: the first attempt stalls server-side past hedge_ms, the
    duplicate answers, the client records the hedge win."""
    script, port = stub
    script.plays = [(200, {"ok": True, "result": 7.0})]
    script.stall_first_s = 1.5
    client = GMMClient(f"127.0.0.1:{port}", hedge_ms=100,
                       timeout_s=30.0)
    assert client.score("m", [[0.0]]) == 7.0
    s = client.stats()
    assert s["hedges"] == 1 and s["hedge_wins"] == 1
    assert len(script.seen) == 2


# ------------------------------------------------------------ worker pool


def test_pool_route_order_affinity_ring_and_quarantine(tmp_path,
                                                       monkeypatch):
    """Routing is a crc32 ring: (model, version) pins a home slot (the
    executor-cache affinity), siblings follow in ring order for
    failover, quarantined slots are invisible."""
    monkeypatch.setattr(_Worker, "alive", property(lambda self: True))
    pool = WorkerPool(4, str(tmp_path), lambda i, s: ["true"])
    start = zlib.crc32(b"m@None") % 4
    order = pool._route_order("m", None)
    assert [w.idx for w in order] == [(start + i) % 4 for i in range(4)]
    # stable: the same key always routes home; a different key may not
    assert pool._route_order("m", None)[0].idx == start
    start2 = zlib.crc32(b"m@2") % 4
    assert pool._route_order("m", 2)[0].idx == start2
    # a quarantined home slot disappears; the ring order is preserved
    pool._workers[start].quarantined = True
    order = pool._route_order("m", None)
    assert [w.idx for w in order] == [(start + i) % 4 for i in range(1, 4)]


def test_pool_drain_fails_fast_without_parking(tmp_path):
    """While draining, an empty routing ring must NOT park for the
    whole-pool-dead window (NO_WORKER_WAIT_S): the request 502s
    immediately and is counted as retries_exhausted."""
    pool = WorkerPool(2, str(tmp_path), lambda i, s: ["true"])
    pool._draining.set()
    t0 = time.monotonic()
    resp, meta = pool.score({"id": 1, "model": "m", "op": "score",
                             "x": [[0.0]]})
    assert time.monotonic() - t0 < NO_WORKER_WAIT_S / 2
    assert not resp["ok"] and resp["error"] == "worker_unavailable"
    assert pool.retries_exhausted == 1 and meta["retried"] is False
    assert pool.ready() is False
    g = pool.gauges()
    assert g["gmm_http_workers"] == 2.0
    assert g["gmm_http_workers_alive"] == 0.0
    assert pool.http_stats()["retries_exhausted"] == 1


# ----------------------------------------------------------- chaos, e2e


def _start_pool_serve(tmp_path, reg_dir, *, env_extra=None, workers=2):
    """Launch `gmm serve --http 0 --workers N` and wait for the bound
    port; returns (proc, port, paths)."""
    port_file = str(tmp_path / "port")
    metrics = str(tmp_path / "serve.jsonl")
    wd = str(tmp_path / "wd")
    env = worker_env()
    env.update(env_extra or {})
    p = subprocess.Popen(
        [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "serve",
         "--registry", reg_dir, "--http", "0", "--workers", str(workers),
         "--http-port-file", port_file, "--worker-dir", wd,
         "--worker-backoff-s", "0.2", "--device", "cpu",
         "--metrics-file", metrics],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    deadline = time.monotonic() + 300.0
    while not os.path.exists(port_file):
        assert p.poll() is None, p.communicate()
        assert time.monotonic() < deadline, "http port never bound"
        time.sleep(0.05)
    port = int(open(port_file).read().strip())
    return p, port, {"metrics": metrics, "wd": wd}


def _worker_pid(wd, idx, *, not_pid=None, min_gen=0, timeout=120.0):
    """The pool's published pid for slot idx (waits out a respawn)."""
    path = os.path.join(wd, f"worker{idx}.json")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            doc = json.loads(open(path).read())
            pid = int(doc["pid"])
            if pid != (not_pid or -1) and doc.get("gen", 0) >= min_gen:
                return pid, doc
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"worker{idx}.json never advanced past "
                         f"pid {not_pid} / gen {min_gen}")


def test_pool_survives_fault_injected_worker_crash(rng, tmp_path):
    """Chaos arc #1 (deterministic): the `worker_crash` fault kind kills
    the routed worker's process (os._exit) on its FIRST request. The
    client must see only answers -- sibling retry covers the crash, the
    supervisor respawns the slot (gen 1 serves clean, the fault pins
    gen 0) -- and the stream carries the whole story schema-valid."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    home = zlib.crc32(b"m@None") % 2       # the slot requests route to
    faults_env = json.dumps({"worker_crash": {
        "worker": home, "gen": 0, "times": 1, "exitcode": 9}})
    p, port, paths = _start_pool_serve(
        tmp_path, reg_dir, env_extra={"GMM_FAULTS": faults_env})
    try:
        client = GMMClient(f"127.0.0.1:{port}", timeout_s=120.0,
                           retries=3, backoff_base_s=0.05,
                           retry_budget=1.0)
        for i in range(8):
            got = client.score_samples("m", data[:5].tolist(),
                                       deadline_ms=60_000)
            assert len(got) == 5           # every request answered
        assert client.stats()["requests"] == 8
        # the crashed slot came back under a fresh generation before
        # we drain (the respawn is what the stream must carry)
        _worker_pid(paths["wd"], home, min_gen=1)
        p.send_signal(signal.SIGTERM)
        out_, err_ = communicate_or_kill(p, timeout=180)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=60)
    assert p.returncode == 75, f"expected EX_TEMPFAIL:\n{out_}\n{err_}"
    records = read_stream(paths["metrics"])
    assert validate_stream(records) == []
    exits = [r for r in records if r["event"] == "worker_exit"
             and r.get("crash")]
    assert any(r["worker"] == home and r["exitcode"] == 9 for r in exits)
    spawns = [r for r in records if r["event"] == "worker_spawn"]
    assert any(r.get("respawn") for r in spawns)
    https = [r for r in records if r["event"] == "http_request"]
    assert len(https) == 8
    assert all(r["status"] == 200 for r in https)
    assert any(r.get("retried") for r in https)  # the sibling answered
    summary = [r for r in records if r["event"] == "serve_summary"][-1]
    roll = summary["http"]
    assert roll["errors_5xx"] == 0 and roll["retries_exhausted"] == 0
    assert roll["worker_crashes"] >= 1 and roll["worker_respawns"] >= 1


def test_pool_survives_real_sigkill_with_zero_failed_requests(rng,
                                                              tmp_path):
    """Chaos arc #2 (the acceptance criterion, with a REAL signal):
    SIGKILL the routed worker mid-stream under --workers 2. ZERO client
    requests may fail; the slot respawns under a new pid; SIGTERM still
    drains the whole tier to exit 75 with a clean v2.7 rollup."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    home = zlib.crc32(b"m@None") % 2
    p, port, paths = _start_pool_serve(tmp_path, reg_dir)
    try:
        client = GMMClient(f"127.0.0.1:{port}", timeout_s=120.0,
                           retries=3, backoff_base_s=0.05,
                           retry_budget=1.0)
        assert client.readyz()
        victim, _ = _worker_pid(paths["wd"], home)
        failed = 0
        for i in range(20):
            if i == 5:
                os.kill(victim, signal.SIGKILL)
            try:
                got = client.score_samples("m", data[:5].tolist(),
                                           deadline_ms=60_000)
                assert len(got) == 5
            except GMMClientError:
                failed += 1
        assert failed == 0, f"{failed} request(s) failed across the kill"
        respawned, doc = _worker_pid(paths["wd"], home, not_pid=victim)
        assert respawned != victim and doc["gen"] >= 1
        p.send_signal(signal.SIGTERM)
        # the probe goes dark at drain start (503 while the workers
        # flush, connection-refused once the tier exits -- both False)
        deadline = time.monotonic() + 60
        while client.readyz() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not client.readyz()
        out_, err_ = communicate_or_kill(p, timeout=180)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=60)
    assert p.returncode == 75, f"expected EX_TEMPFAIL:\n{out_}\n{err_}"
    assert "Preempted" in err_
    records = read_stream(paths["metrics"])
    assert validate_stream(records) == []
    events = [r["event"] for r in records]
    assert events.count("http_request") == 20
    crashes = [r for r in records if r["event"] == "worker_exit"
               and r.get("crash")]
    assert any(r["worker"] == home and r["exitcode"] == -9
               for r in crashes)
    summary = [r for r in records if r["event"] == "serve_summary"][-1]
    roll = summary["http"]
    assert roll["requests"] == 20
    assert roll["errors_5xx"] == 0 and roll["errors_4xx"] == 0
    assert roll["retries_exhausted"] == 0
    assert roll["worker_crashes"] >= 1 and roll["worker_respawns"] >= 1
    assert roll["worker_quarantines"] == 0


def test_http_off_stream_is_byte_identical_shape(rng, tmp_path):
    """HTTP off => the stream has NO v2.7 surface at all: no
    http_request/worker_spawn/worker_exit events and no ``http`` key in
    serve_summary. The default JSONL pipeline must not pay for the
    network tier it isn't using."""
    from cuda_gmm_mpi_tpu.cli import main

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    reqs = tmp_path / "req.jsonl"
    with open(reqs, "w") as f:
        for i in range(3):
            f.write(json.dumps({"id": i, "model": "m", "op": "score",
                                "x": data[:4].tolist()}) + "\n")
    metrics = str(tmp_path / "m.jsonl")
    assert main(["serve", "--registry", str(tmp_path / "reg"),
                 "--input", str(reqs), "--output", str(tmp_path / "o"),
                 "--metrics-file", metrics]) == 0
    records = read_stream(metrics)
    events = {r["event"] for r in records}
    assert not events & {"http_request", "worker_spawn", "worker_exit"}
    summary = [r for r in records if r["event"] == "serve_summary"][-1]
    assert "http" not in summary


# ------------------------------------------------------------- diff gates


def test_diff_folds_http_rollup_with_explicit_zeros():
    """summarize_run lifts serve_summary.http into http.* metrics and
    pins the three gated counters to EXPLICIT zeros on every serve
    stream -- so a regression from 0 crashes to 1 is a visible 0->1
    transition, not a silent missing-metric skip."""
    clean = summarize_run([{
        "event": "serve_summary", "run_id": "a", "requests": 4,
        "wall_s": 1.0}])
    for key in ("http.errors_5xx", "http.worker_crashes",
                "http.retries_exhausted"):
        assert clean["metrics"][key] == 0.0
    crashed = summarize_run([{
        "event": "serve_summary", "run_id": "b", "requests": 4,
        "wall_s": 1.0,
        "http": {"requests": 4, "errors_4xx": 0, "errors_5xx": 1,
                 "shed_connections": 0, "retries": 2,
                 "retries_exhausted": 1, "worker_crashes": 1,
                 "worker_respawns": 1, "worker_quarantines": 0,
                 "workers": 2}}])
    m = crashed["metrics"]
    assert m["http.errors_5xx"] == 1.0
    assert m["http.worker_crashes"] == 1.0
    assert m["http.retries_exhausted"] == 1.0
    assert m["http.requests"] == 4.0 and m["http.retries"] == 2.0
    # a fit-only stream grows NO http keys (byte-identity discipline)
    fit_only = summarize_run([{"event": "run_summary", "run_id": "c",
                               "wall_s": 2.0, "total_iters": 3}])
    assert not any(k.startswith("http.") for k in fit_only["metrics"])


def test_diff_default_gates_cover_the_network_tier(tmp_path):
    """The three v2.7 gates ship in DEFAULT_FAIL_ON and trip on a 0->1
    regression between two serve streams."""
    from cuda_gmm_mpi_tpu.telemetry.diff import diff_main

    for gate in ("http.errors_5xx>0", "http.worker_crashes>0",
                 "http.retries_exhausted>0"):
        assert gate in DEFAULT_FAIL_ON
    base = {"event": "serve_summary", "run_id": "a", "requests": 4,
            "wall_s": 1.0,
            "http": {"requests": 4, "errors_5xx": 0, "errors_4xx": 0,
                     "worker_crashes": 0, "retries_exhausted": 0,
                     "retries": 0, "worker_respawns": 0,
                     "worker_quarantines": 0, "shed_connections": 0,
                     "workers": 2}}
    cur = json.loads(json.dumps(base))
    cur["http"]["worker_crashes"] = 1
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    open(a, "w").write(json.dumps(base) + "\n")
    open(b, "w").write(json.dumps(cur) + "\n")
    assert diff_main([a, b]) == 1          # the gate trips...
    assert diff_main([a, a]) == 0          # ...and clean stays clean


# ------------------------------------------------- data plane (rev v2.8)


def _live_front(reg_dir, **front_kw):
    server = GMMServer(ModelRegistry(reg_dir))
    t = threading.Thread(target=server.run_loop, daemon=True)
    t.start()
    front = HTTPFrontEnd(InprocBackend(server), **front_kw).start()
    return server, t, front


def test_http_binary_payloads_bit_identical_across_ops(rng, tmp_path):
    """Zero-copy contract: for every HTTP op x {full, diag} covariance,
    an x-gmm-rows body yields a response BIT-IDENTICAL to the JSON body
    carrying the same rows -- encoding is transport, never math."""
    reg_dir = str(tmp_path / "reg")
    gm_full, data_full = fitted(rng)
    gm_full.to_registry(reg_dir, "full")
    gm_diag, data_diag = fitted(rng, diag=True)
    gm_diag.to_registry(reg_dir, "diag")
    server, t, front = _live_front(reg_dir)
    try:
        client = GMMClient(f"127.0.0.1:{front.port}")
        for model, rows in (("full", data_full[:9]),
                            ("diag", data_diag[:9])):
            x = rows.astype(np.float64)
            for op in HTTP_OPS:
                a = client.request(model, op, x.tolist(),
                                   encoding="json")
                b = client.request(model, op, x, encoding="binary")
                a.pop("latency_ms", None)
                b.pop("latency_ms", None)
                assert a == b, (model, op)
    finally:
        front.stop()
        server._stop.set()
        t.join(timeout=60)


def test_http_bad_frames_rejected(inproc):
    """Every malformed x-gmm-rows body answers 400 bad_frame (never a
    500, never a silent misread): bad magic, truncation, and trailing
    bytes past the declared N*D payload."""
    front, server, _, data = inproc
    port = front.port
    hdrs = {"Content-Type": wire.CONTENT_TYPE}
    good = wire.encode_rows(data[:3].astype(np.float64))
    bad_magic = bytearray(good)
    bad_magic[:4] = b"NOPE"
    for label, frame in (("bad magic", bytes(bad_magic)),
                         ("truncated", good[:-1]),
                         ("header only", good[:wire.HEADER.size]),
                         ("trailing", good + b"\x00")):
        st, _, body = _post(port, "/v1/models/m:score", frame,
                            headers=hdrs)
        assert st == 400, (label, st, body)
        assert not body["ok"] and body["error"] == "bad_frame", label
    # the intact frame still scores on the very same connection state
    st, _, body = _post(port, "/v1/models/m:score", good, headers=hdrs)
    assert st == 200 and body["ok"]
    assert front.errors_5xx == 0


def test_http_oversized_binary_body_answers_413(rng, tmp_path):
    """A binary frame past the body bound is refused 413 like its JSON
    twin -- size policy is format-independent."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    server, t, front = _live_front(reg_dir, max_body_bytes=2048)
    try:
        frame = wire.encode_rows(
            np.zeros((200, 4), np.float64))        # 6416 bytes > 2048
        st, _, body = _post(front.port, "/v1/models/m:score", frame,
                            headers={"Content-Type": wire.CONTENT_TYPE})
        assert st == 413 and not body["ok"]
    finally:
        front.stop()
        server._stop.set()
        t.join(timeout=60)


def test_http_warm_binary_requests_never_recompile_or_stage(inproc):
    """Perf acceptance: once a route is warm at a bucket, binary
    traffic at that bucket triggers ZERO executor compiles and ZERO
    host stagings -- the zero-copy path reuses the pinned device state
    and the cached executable."""
    front, server, _, data = inproc
    client = GMMClient(f"127.0.0.1:{front.port}")
    x = data[:16].astype(np.float64)
    client.request("m", "score_samples", x.tolist(), encoding="json")
    client.request("m", "score_samples", x, encoding="binary")
    stats = server.executor_stats()
    before = stats["compiles"]
    for _ in range(5):
        client.request("m", "score_samples", x, encoding="binary")
    stats = server.executor_stats()
    assert stats["compiles"] == before
    assert stats["host_stagings"] == 0
    assert server.host_stagings == 0


@pytest.mark.slow
def test_pool_forwards_binary_frames(rng, tmp_path):
    """One binary request through the real worker pool: the front end
    re-encodes the decoded rows as a frame on the worker hop, and the
    response matches the JSON twin bit-for-bit."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    p, port, _ = _start_pool_serve(tmp_path, reg_dir, workers=1)
    try:
        client = GMMClient(f"127.0.0.1:{port}", timeout_s=120.0)
        x = data[:8].astype(np.float64)
        a = client.request("m", "score_samples", x.tolist(),
                           encoding="json")
        b = client.request("m", "score_samples", x, encoding="binary")
        a.pop("latency_ms", None)
        b.pop("latency_ms", None)
        assert a == b
    finally:
        p.send_signal(signal.SIGTERM)
        communicate_or_kill(p, 120)


def test_diff_gate_covers_host_staging(tmp_path):
    """The rev v2.8 gate: serve.host_staging ships in DEFAULT_FAIL_ON,
    folds from serve_summary.executor.host_stagings, pins an explicit
    zero on every serve stream, and trips on a 0->1 regression."""
    from cuda_gmm_mpi_tpu.telemetry.diff import diff_main

    assert "serve.host_staging>0" in DEFAULT_FAIL_ON
    clean = summarize_run([{
        "event": "serve_summary", "run_id": "a", "requests": 4,
        "wall_s": 1.0}])
    assert clean["metrics"]["serve.host_staging"] == 0.0
    staged = summarize_run([{
        "event": "serve_summary", "run_id": "b", "requests": 4,
        "wall_s": 1.0,
        "executor": {"hits": 3, "misses": 1, "compiles": 1,
                     "evictions": 0, "live_executables": 1,
                     "pinned_states": 1, "host_stagings": 2}}])
    assert staged["metrics"]["serve.host_staging"] == 2.0
    # a fit-only stream grows no serve keys (byte-identity discipline)
    fit_only = summarize_run([{"event": "run_summary", "run_id": "c",
                               "wall_s": 2.0, "total_iters": 3}])
    assert "serve.host_staging" not in fit_only["metrics"]
    base = {"event": "serve_summary", "run_id": "a", "requests": 4,
            "wall_s": 1.0,
            "executor": {"hits": 4, "misses": 0, "compiles": 0,
                         "evictions": 0, "live_executables": 1,
                         "pinned_states": 1, "host_stagings": 0}}
    cur = json.loads(json.dumps(base))
    cur["executor"]["host_stagings"] = 1
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    open(a, "w").write(json.dumps(base) + "\n")
    open(b, "w").write(json.dumps(cur) + "\n")
    assert diff_main([a, b]) == 1          # the gate trips...
    assert diff_main([a, a]) == 0          # ...and clean stays clean
