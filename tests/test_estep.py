"""E-step vs scipy/NumPy oracles: log-densities, posteriors, log-likelihood."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats
from scipy.special import logsumexp

from cuda_gmm_mpi_tpu.ops.estep import log_densities, posteriors
from cuda_gmm_mpi_tpu.state import GMMState

from .test_constants import random_spd


def make_state(rng, k, d, dtype=jnp.float64, inactive=()):
    R = random_spd(rng, k, d)
    Rinv = np.linalg.inv(R)
    means = rng.normal(scale=3.0, size=(k, d))
    N = np.abs(rng.normal(size=k)) * 100 + 1
    pi = N / N.sum()
    const = -d * 0.5 * np.log(2 * np.pi) - 0.5 * np.linalg.slogdet(R)[1]
    active = np.ones(k, bool)
    for i in inactive:
        active[i] = False
    return GMMState(
        N=jnp.asarray(N, dtype), pi=jnp.asarray(pi, dtype),
        constant=jnp.asarray(const, dtype),
        avgvar=jnp.zeros(k, dtype),
        means=jnp.asarray(means, dtype), R=jnp.asarray(R, dtype),
        Rinv=jnp.asarray(Rinv, dtype), active=jnp.asarray(active),
    )


@pytest.mark.parametrize("quad_mode", ["expanded", "packed", "centered"])
def test_log_densities_vs_scipy(rng, quad_mode):
    k, d, n = 4, 3, 50
    state = make_state(rng, k, d)
    x = rng.normal(scale=3.0, size=(n, d))
    logp = np.asarray(log_densities(state, jnp.asarray(x), quad_mode=quad_mode))
    for c in range(k):
        expected = stats.multivariate_normal.logpdf(
            x, np.asarray(state.means[c]), np.asarray(state.R[c])
        ) + np.log(np.asarray(state.pi[c]))
        np.testing.assert_allclose(logp[:, c], expected, rtol=1e-8, atol=1e-8)


def test_diag_only_vs_scipy(rng):
    k, d, n = 3, 4, 40
    state = make_state(rng, k, d)
    # diagonalize
    R = np.asarray(state.R)
    Rd = np.stack([np.diag(np.diag(R[c])) for c in range(k)])
    const = -d * 0.5 * np.log(2 * np.pi) - 0.5 * np.log(
        np.diagonal(Rd, axis1=1, axis2=2)
    ).sum(1)
    state = state.replace(
        R=jnp.asarray(Rd), Rinv=jnp.asarray(np.linalg.inv(Rd)),
        constant=jnp.asarray(const),
    )
    x = rng.normal(scale=2.0, size=(n, d))
    logp = np.asarray(log_densities(state, jnp.asarray(x), diag_only=True))
    for c in range(k):
        expected = stats.multivariate_normal.logpdf(
            x, np.asarray(state.means[c]), Rd[c]
        ) + np.log(np.asarray(state.pi[c]))
        np.testing.assert_allclose(logp[:, c], expected, rtol=1e-8, atol=1e-8)


def test_posteriors_normalized_and_loglik(rng):
    k, d, n = 5, 3, 64
    state = make_state(rng, k, d)
    x = rng.normal(scale=3.0, size=(n, d))
    w, logz = posteriors(state, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(w).sum(1), 1.0, rtol=1e-10)
    logp = np.asarray(log_densities(state, jnp.asarray(x)))
    np.testing.assert_allclose(np.asarray(logz), logsumexp(logp, axis=1),
                               rtol=1e-10)


def test_inactive_clusters_inert(rng):
    k, d, n = 4, 3, 30
    x = rng.normal(size=(n, d))
    state_masked = make_state(rng, k, d, inactive=(2,))
    logp = np.asarray(log_densities(state_masked, jnp.asarray(x)))
    assert np.all(np.isneginf(logp[:, 2]))
    w, _ = posteriors(state_masked, jnp.asarray(x))
    assert np.all(np.asarray(w)[:, 2] == 0.0)
    np.testing.assert_allclose(np.asarray(w).sum(1), 1.0, rtol=1e-10)


def test_expanded_matches_centered_float32(rng):
    """The two quadratic-form strategies must agree tightly on centered data."""
    k, d, n = 6, 8, 128
    state = make_state(rng, k, d, dtype=jnp.float32)
    x = jnp.asarray(rng.normal(scale=2.0, size=(n, d)), jnp.float32)
    a = np.asarray(log_densities(state, x, quad_mode="expanded"))
    b = np.asarray(log_densities(state, x, quad_mode="centered"))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
