"""Pure-NumPy float64 oracle implementing the reference's EM semantics.

Independent re-derivation of the algorithm from SURVEY.md SS2-3 (estep1/estep2,
mstep_*, constants_kernel, host division/guards) used to validate the JAX ops.
"""

from __future__ import annotations

import numpy as np

LOG_2PI = np.log(2.0 * np.pi)


def np_seed(data: np.ndarray, k: int, dynamic_range: float = 1e3):
    n, d = data.shape
    seed = (n - 1.0) / (k - 1.0) if k > 1 else 0.0
    idx = (np.arange(k, dtype=np.float32) * np.float32(seed)).astype(np.int32)
    means = data[np.clip(idx, 0, n - 1)].astype(np.float64)
    var = (data.astype(np.float64) ** 2).mean(0) - data.astype(np.float64).mean(0) ** 2
    avgvar = var.mean() / dynamic_range
    return dict(
        N=np.full(k, n / k, float),
        pi=np.full(k, 1.0 / k, float),
        avgvar=np.full(k, avgvar, float),
        means=means,
        R=np.stack([np.eye(d)] * k),
        Rinv=np.stack([np.eye(d)] * k),
        constant=np.full(k, -d * 0.5 * LOG_2PI, float),
    )


def np_log_densities(params, x):
    k, d = params["means"].shape
    logp = np.empty((x.shape[0], k))
    for c in range(k):
        xc = x - params["means"][c]
        q = np.einsum("ni,ij,nj->n", xc, params["Rinv"][c], xc)
        logp[:, c] = -0.5 * q + params["constant"][c] + np.log(params["pi"][c])
    return logp


def np_estep(params, x):
    logp = np_log_densities(params, x)
    m = logp.max(axis=1, keepdims=True)
    denom = np.exp(logp - m).sum(axis=1, keepdims=True)
    logz = m + np.log(denom)
    w = np.exp(logp - logz)
    return w, float(logz.sum())


def np_mstep(params, x, w, diag_only: bool = False):
    """M-step + constants with the reference's guards (single-GPU semantics)."""
    k, d = params["means"].shape
    out = {key: val.copy() for key, val in params.items()}
    Nk = w.sum(axis=0)
    out["N"] = Nk
    for c in range(k):
        if Nk[c] > 0.5:
            mu = (w[:, c : c + 1] * x).sum(0) / Nk[c]
        else:
            mu = np.zeros(d)
        out["means"][c] = mu
        xc = x - mu
        if Nk[c] >= 1.0:
            cov_sum = np.einsum("n,ni,nj->ij", w[:, c], xc, xc)
        else:
            cov_sum = np.zeros((d, d))
        if diag_only:
            cov_sum = np.diag(np.diag(cov_sum))
        cov_sum = cov_sum + params["avgvar"][c] * np.eye(d)
        if Nk[c] > 0.5:
            out["R"][c] = cov_sum / Nk[c]
        else:
            out["R"][c] = np.eye(d)
    # constants_kernel
    for c in range(k):
        if diag_only:
            diag = np.diag(out["R"][c])
            out["Rinv"][c] = np.diag(1.0 / diag)
            logdet = np.log(diag).sum()
        else:
            out["Rinv"][c] = np.linalg.inv(out["R"][c])
            _, logdet = np.linalg.slogdet(out["R"][c])
        out["constant"][c] = -d * 0.5 * LOG_2PI - 0.5 * logdet
    total = Nk.sum()
    out["pi"] = np.where(Nk < 0.5, 1e-10, Nk / total)
    return out


def np_em(data, k, iters, diag_only=False, dynamic_range=1e3):
    """Run `iters` full EM iterations; returns (params, loglik trajectory)."""
    params = np_seed(data, k, dynamic_range)
    x = data.astype(np.float64)
    w, ll = np_estep(params, x)
    lls = [ll]
    for _ in range(iters):
        params = np_mstep(params, x, w, diag_only=diag_only)
        w, ll = np_estep(params, x)
        lls.append(ll)
    return params, lls, w


def np_merge(params, c1, c2):
    """add_clusters oracle (gaussian.cu:1210-1253), natural-log constant."""
    n1, n2 = params["N"][c1], params["N"][c2]
    wt1 = n1 / (n1 + n2)
    wt2 = 1.0 - wt1
    mu = wt1 * params["means"][c1] + wt2 * params["means"][c2]
    d1 = mu - params["means"][c1]
    d2 = mu - params["means"][c2]
    R = wt1 * (params["R"][c1] + np.outer(d1, d1)) + \
        wt2 * (params["R"][c2] + np.outer(d2, d2))
    d = mu.shape[0]
    _, logdet = np.linalg.slogdet(R)
    const = -d * 0.5 * LOG_2PI - 0.5 * logdet
    return dict(N=n1 + n2, pi=params["pi"][c1] + params["pi"][c2],
                means=mu, R=R, constant=const)


def np_cluster_distance(params, c1, c2):
    merged = np_merge(params, c1, c2)
    return (params["N"][c1] * params["constant"][c1]
            + params["N"][c2] * params["constant"][c2]
            - merged["N"] * merged["constant"])
