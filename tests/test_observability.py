"""Live observability plane (rev v2.1; docs/OBSERVABILITY.md):
OpenMetrics exporter, resource sampler, trace spans, `gmm top`.

Contracts:
- schema <-> report drift: every event kind declared in
  ``schema.EVENT_FIELDS`` has a renderer in ``gmm report`` (the
  report-side counterpart of test_telemetry's emit-site drift test);
- the exporter serves parseable OpenMetrics text whose gauges CHANGE
  between scrapes of a live fit, from a plain HTTP client thread;
- span records from a sweep fit reconstruct into a single-rooted tree
  covering sweep / per-K EM / checkpoint; the serve route path nests
  prepare/dispatch/answer under serve_route, and a client's echoed
  trace_id finds the server-side records;
- with --metrics-port unset the stream is byte-identical in shape: no
  span records, no trace_id context, no sampler heartbeats;
- the --follow tailer renders a GROWING stream incrementally (file and
  per-rank directory targets) and exits on the terminal record.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, telemetry
from cuda_gmm_mpi_tpu.telemetry import (MetricsExporter, MetricsRegistry,
                                        ResourceSampler, build_span_tree,
                                        render_openmetrics)
from cuda_gmm_mpi_tpu.telemetry import exporter as tl_exporter
from cuda_gmm_mpi_tpu.telemetry import report as report_mod
from cuda_gmm_mpi_tpu.telemetry import schema
from cuda_gmm_mpi_tpu.telemetry import spans as tl_spans
from cuda_gmm_mpi_tpu.telemetry.report import (StreamTailer, follow_stream,
                                               render_follow, report_main)

from .conftest import make_blobs


# ------------------------------------------------- schema <-> report drift


def test_every_schema_event_kind_has_a_report_renderer():
    """Adding an event to EVENT_FIELDS without teaching `gmm report` to
    render it fails HERE, not in a user's unreadably silent report --
    the report-side mirror of the emit-site drift test (PR 8)."""
    import inspect

    src = inspect.getsource(report_mod)
    missing = [kind for kind in schema.EVENT_FIELDS
               if f'"{kind}"' not in src]
    assert not missing, (
        f"event kinds with no renderer in telemetry/report.py: {missing}")


# ------------------------------------------------------------ spans (unit)


def _stream_recorder():
    import io

    buf = io.StringIO()
    return telemetry.RunRecorder(stream=buf), buf


def _records(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def test_span_noop_without_active_trace():
    """span() outside a trace() emits NOTHING -- the byte-identity gate:
    instrumented code paths cost an attribute check when the plane is
    off."""
    rec, buf = _stream_recorder()
    with tl_spans.span("phase", recorder=rec):
        pass
    assert tl_spans.begin("x", recorder=rec) is None
    assert buf.getvalue() == ""


def test_span_nesting_and_error_status():
    rec, buf = _stream_recorder()
    with tl_spans.trace() as tid:
        with tl_spans.span("outer", recorder=rec):
            with tl_spans.span("inner", recorder=rec, k=3):
                pass
        with pytest.raises(RuntimeError):
            with tl_spans.span("boom", recorder=rec):
                raise RuntimeError("x")
    recs = _records(buf)
    assert [r["name"] for r in recs] == ["inner", "outer", "boom"]
    assert all(r["event"] == "span" and r["trace_id"] == tid for r in recs)
    inner, outer, boom = recs
    assert inner["parent_id"] == outer["span_id"]
    assert "parent_id" not in outer and "parent_id" not in boom
    assert inner["k"] == 3
    assert boom["status"] == "error"
    assert all(r["duration_s"] >= 0 for r in recs)
    for r in recs:
        assert not schema.validate_record(r), schema.validate_record(r)


def test_nested_trace_reuses_outer_identity():
    with tl_spans.trace() as outer_tid:
        with tl_spans.trace() as inner_tid:
            assert inner_tid == outer_tid
        assert tl_spans.current_trace_id() == outer_tid
    assert tl_spans.current_trace_id() is None


def test_begin_end_survives_abandoned_children():
    """A raise that abandons open child spans must not corrupt later
    parentage: end() pops the handle AND everything above it."""
    rec, buf = _stream_recorder()
    with tl_spans.trace():
        sweep = tl_spans.begin("sweep", recorder=rec)
        tl_spans.begin("em_k", recorder=rec)  # abandoned (never ended)
        tl_spans.end(sweep)
        with tl_spans.span("after", recorder=rec):
            pass
    recs = _records(buf)
    by_name = {r["name"]: r for r in recs}
    assert set(by_name) == {"sweep", "after"}  # abandoned span never emits
    assert "parent_id" not in by_name["after"]


def test_build_span_tree_promotes_orphans():
    recs = [
        {"event": "span", "name": "child", "span_id": "c",
         "parent_id": "never-ended", "trace_id": "t", "t0_mono_s": 2.0,
         "duration_s": 0.1},
        {"event": "span", "name": "root", "span_id": "r",
         "trace_id": "t", "t0_mono_s": 1.0, "duration_s": 5.0},
    ]
    roots = build_span_tree(recs)
    assert [n["span"]["name"] for n in roots] == ["root", "child"]


# --------------------------------------------------------- exporter (unit)


def test_render_openmetrics_exposition_format():
    reg = MetricsRegistry()
    reg.count("em_iters", 7)
    reg.gauge("active_k", 12)
    reg.observe("serve.latency_ms", 2.0)
    reg.observe("serve.latency_ms", 4.0)
    # Without bucket data (pre-v2.2 callers) the histogram renders as an
    # OpenMetrics summary -- the backward-compatible shape.
    text = render_openmetrics(reg.snapshot(), {"gmm_custom": 1.5})
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    assert "# TYPE gmm_em_iters counter" in lines
    assert "gmm_em_iters_total 7" in lines
    assert "# TYPE gmm_active_k gauge" in lines
    assert "gmm_active_k 12" in lines
    assert "# TYPE gmm_serve_latency_ms summary" in lines
    assert "gmm_serve_latency_ms_count 2" in lines
    assert "gmm_serve_latency_ms_sum 6" in lines
    assert "gmm_custom 1.5" in lines
    # Every sample line is "name value" with a float-parseable value.
    for line in lines:
        if line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            float(value)


def test_render_openmetrics_histogram_buckets():
    """rev v2.2: with fixed-bucket data the serve-latency histogram
    renders as a true OpenMetrics histogram -- cumulative ``_bucket{le=}``
    series plus ``_count/_sum`` and the extremes as separate
    ``_minimum/_maximum`` gauge families (``_min/_max`` are not valid
    histogram sample suffixes for strict parsers; the summary form keeps
    them), while ``snapshot()`` itself stays byte-stable for pre-v2.2
    consumers."""
    reg = MetricsRegistry()
    reg.observe("serve.latency_ms", 2.0)
    reg.observe("serve.latency_ms", 4.0)
    reg.observe("serve.latency_ms", 9000.0)
    snap = reg.snapshot()
    # the 4-key summary contract is untouched by bucket collection
    assert snap["histograms"]["serve.latency_ms"] == {
        "count": 3, "sum": 9006.0, "min": 2.0, "max": 9000.0}
    text = render_openmetrics(snap, None, reg.snapshot_buckets())
    lines = text.splitlines()
    assert "# TYPE gmm_serve_latency_ms histogram" in lines
    assert "# TYPE gmm_serve_latency_ms summary" not in text
    bucket_lines = [l for l in lines
                    if l.startswith("gmm_serve_latency_ms_bucket{le=")]
    assert bucket_lines, text
    # cumulative counts, ending at the +Inf catch-all == total count
    counts = [float(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert counts == sorted(counts)
    assert bucket_lines[-1] == 'gmm_serve_latency_ms_bucket{le="+Inf"} 3'
    # the le="2.5" bucket holds the 2.0 observation
    assert any('le="2.5"} 1' in l for l in bucket_lines)
    # _count/_sum survive alongside the buckets; the extremes move to
    # distinct gauge families so the histogram family stays strictly
    # parseable (no _min/_max samples under a histogram TYPE)
    assert "gmm_serve_latency_ms_count 3" in lines
    assert "gmm_serve_latency_ms_sum 9006" in lines
    assert "# TYPE gmm_serve_latency_ms_minimum gauge" in lines
    assert "gmm_serve_latency_ms_minimum 2" in lines
    assert "gmm_serve_latency_ms_maximum 9000" in lines
    assert "gmm_serve_latency_ms_min 2" not in lines
    assert "gmm_serve_latency_ms_max 9000" not in lines
    for line in lines:
        if line and not line.startswith("#"):
            float(line.rsplit(" ", 1)[1])


def test_exporter_scrape_and_derived_rate():
    reg = MetricsRegistry()
    gauges = {"gmm_run_k": 32.0}
    with MetricsExporter(lambda: reg, lambda: gauges, port=0) as ex:
        assert ex.port and ex.port > 0
        assert tl_exporter.current_exporter() is ex

        def scrape(path="/metrics"):
            url = f"http://127.0.0.1:{ex.port}{path}"
            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.status, dict(resp.headers), \
                    resp.read().decode("utf-8")

        reg.count("em_iters", 10)
        status, headers, body = scrape()
        assert status == 200
        assert headers["Content-Type"].startswith(
            "application/openmetrics-text")
        assert "gmm_em_iters_total 10" in body
        assert "gmm_run_k 32" in body
        assert body.endswith("# EOF\n")
        reg.count("em_iters", 5)
        _, _, body2 = scrape()
        assert "gmm_em_iters_total 15" in body2       # gauges changed
        assert "gmm_em_iters_per_s" in body2          # derived rate
        with pytest.raises(urllib.error.HTTPError):
            scrape("/nope")
    assert tl_exporter.current_exporter() is None


def test_resource_sampler_stamps_heartbeats():
    rec, buf = _stream_recorder()
    sampler = ResourceSampler(recorder=rec, interval_s=0.01)
    out = sampler.sample_once()
    assert out is not None and out["event"] == "heartbeat"
    assert out["sampler"] is True and out["phase"] == "sampler"
    assert not schema.validate_record(json.loads(json.dumps(out)))
    recs = _records(buf)
    assert recs and recs[0].get("rss_bytes", 1) > 0
    # Inert recorder -> no-op, never a crash.
    assert ResourceSampler(telemetry.RunRecorder()).sample_once() is None


def test_host_rss_bytes_is_positive_here():
    rss = tl_exporter.host_rss_bytes()
    assert rss is not None and rss > 0


# ------------------------------------------------------- fit e2e (plane on)


@pytest.fixture(scope="module")
def live_fit_stream(tmp_path_factory):
    """One small fit with the full plane on, scraped from a thread while
    it runs; module-scoped so the e2e assertions share the cost."""
    tmp = tmp_path_factory.mktemp("liveplane")
    path = str(tmp / "live.jsonl")
    rng = np.random.default_rng(0)
    data, _ = make_blobs(rng, n=1500, d=4, k=3)

    bodies = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            ex = tl_exporter.current_exporter()
            if ex is not None and ex.port:
                try:
                    url = f"http://127.0.0.1:{ex.port}/metrics"
                    with urllib.request.urlopen(url, timeout=2) as resp:
                        bodies.append(resp.read().decode("utf-8"))
                except Exception:
                    pass
            stop.wait(0.01)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    os.environ["GMM_SAMPLER_INTERVAL_S"] = "0.05"
    try:
        cfg = GMMConfig(min_iters=3, max_iters=3, seed=0,
                        chunk_size=512, metrics_file=path, metrics_port=0,
                        checkpoint_dir=str(tmp / "ckpt"))
        fit_gmm(data.astype(np.float32), 4, 0, cfg)
    finally:
        os.environ.pop("GMM_SAMPLER_INTERVAL_S", None)
        stop.set()
        t.join(timeout=5)
    return telemetry.read_stream(path), bodies


def test_live_fit_scrapes_parse_and_change(live_fit_stream):
    _, bodies = live_fit_stream
    assert len(bodies) >= 2, "exporter was never scraped during the fit"
    for body in bodies:
        assert body.endswith("# EOF\n")
        for line in body.splitlines():
            if line and not line.startswith("#"):
                float(line.rsplit(" ", 1)[1])  # parseable exposition
    assert len(set(bodies)) >= 2, "gauges never changed between scrapes"
    # The run counters actually made it out the endpoint.
    assert any("gmm_em_iters_total" in b for b in bodies)
    assert any("gmm_elastic_generation" in b for b in bodies)


def test_live_fit_span_tree_is_single_rooted(live_fit_stream):
    records, _ = live_fit_stream
    spans = [r for r in records if r["event"] == "span"]
    assert spans, "plane-on fit emitted no spans"
    assert not schema.validate_stream(spans)
    assert len({s["trace_id"] for s in spans}) == 1
    roots = build_span_tree(spans)
    assert len(roots) == 1 and roots[0]["span"]["name"] == "fit"
    names = {s["name"] for s in spans}
    assert {"sweep", "em_k", "checkpoint"} <= names
    sweep = [n for n in roots[0]["children"]
             if n["span"]["name"] == "sweep"]
    assert sweep, "sweep span is not a child of the fit root"
    child_names = {c["span"]["name"] for c in sweep[0]["children"]}
    assert {"em_k", "checkpoint"} <= child_names
    # Every fit record carries the trace id context while the trace ran.
    em_iters = [r for r in records if r["event"] == "em_iter"]
    assert em_iters and all(
        r.get("trace_id") == spans[0]["trace_id"] for r in em_iters)


def test_live_fit_sampler_heartbeats_on_stream(live_fit_stream):
    records, _ = live_fit_stream
    samples = [r for r in records
               if r["event"] == "heartbeat" and r.get("sampler")]
    assert samples, "resource sampler left no heartbeat records"
    assert all(r.get("rss_bytes", 0) > 0 for r in samples)


def test_live_fit_stream_validates_and_has_mono_s(live_fit_stream):
    records, _ = live_fit_stream
    assert not schema.validate_stream(records)
    assert all("mono_s" in r for r in records)
    mono = [r["mono_s"] for r in records]
    assert mono == sorted(mono), "mono_s must be monotonic within a run"


def test_plane_off_stream_has_no_live_artifacts(tmp_path):
    """--metrics-port unset: the stream carries NO spans, NO trace_id,
    NO sampler heartbeats -- shape-identical to pre-v2.1 output."""
    path = str(tmp_path / "off.jsonl")
    rng = np.random.default_rng(0)
    data, _ = make_blobs(rng, n=800, d=3, k=2)
    cfg = GMMConfig(min_iters=2, max_iters=2, seed=0, chunk_size=512,
                    metrics_file=path)
    fit_gmm(data.astype(np.float32), 2, 2, cfg)
    records = telemetry.read_stream(path)
    assert records and not schema.validate_stream(records)
    assert not any(r["event"] == "span" for r in records)
    assert not any("trace_id" in r for r in records)
    assert not any(r.get("sampler") for r in records)


def test_metrics_port_validation():
    assert GMMConfig(metrics_port=0).metrics_port == 0
    assert GMMConfig().metrics_port is None
    with pytest.raises(ValueError, match="metrics_port"):
        GMMConfig(metrics_port=-1)
    with pytest.raises(ValueError, match="metrics_port"):
        GMMConfig(metrics_port=70000)


# ----------------------------------------------------------- follow / top


def _write_lines(path, records):
    with open(path, "a", encoding="utf-8") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _mk(event, i, **fields):
    base = {"event": event, "schema": schema.SCHEMA_VERSION,
            "ts": 1000.0 + i, "mono_s": 10.0 + i, "run_id": "r1",
            "process": 0}
    base.update(fields)
    return base


def test_stream_tailer_is_incremental_and_whole_line(tmp_path):
    path = str(tmp_path / "s.jsonl")
    t = StreamTailer(path)
    assert t.poll() == []                      # not created yet
    _write_lines(path, [_mk("run_start", 0, platform="cpu",
                            num_events=10, num_dimensions=2, start_k=2)])
    assert [r["event"] for r in t.poll()] == ["run_start"]
    assert t.poll() == []                      # no growth, no records
    # A torn trailing line stays unread until its newline arrives.
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"event": "em_iter", "schema": 1, "ts": 1, ')
    assert t.poll() == []
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('"mono_s": 1, "run_id": "r1", "process": 0, "k": 2, '
                 '"iter": 0, "loglik": -1.0, "wall_s": 0.1}\n')
    assert [r["event"] for r in t.poll()] == ["em_iter"]


def test_render_follow_live_view_content():
    recs = [
        _mk("run_start", 0, platform="cpu", num_events=100,
            num_dimensions=4, start_k=4),
        _mk("em_iter", 1, k=4, iter=0, loglik=-5.0, wall_s=0.1),
        _mk("em_iter", 2, k=4, iter=1, loglik=-4.5, wall_s=0.1,
            delta=0.5),
        _mk("em_done", 3, k=4, loglik=-4.5, score=9.0, iters=2,
            seconds=0.2),
        _mk("heartbeat", 4, phase="sampler", elapsed_s=4.0, sampler=True,
            rss_bytes=123_000_000),
    ]
    screen = render_follow(recs)
    assert "gmm top" in screen
    assert "K=4" in screen and "iters/s" in screen
    assert "best K=4" in screen
    assert "host RSS 123.0 MB" in screen
    assert "last event: heartbeat" in screen
    assert render_follow([]).startswith("(gmm top: waiting")
    # mono_s drives the rate when present: 2 iters 1s apart = 1/s.
    assert "(1.0 iters/s)" in screen


def test_follow_renders_a_growing_stream(tmp_path, capsys):
    """The --follow e2e: records appended WHILE the tailer polls show up
    in later screens, and the terminal record ends the loop."""
    path = str(tmp_path / "grow.jsonl")
    _write_lines(path, [_mk("run_start", 0, platform="cpu",
                            num_events=10, num_dimensions=2, start_k=2)])

    def writer():
        for i in range(1, 4):
            time.sleep(0.08)
            _write_lines(path, [_mk("em_iter", i, k=2, iter=i,
                                    loglik=-5.0 + i, wall_s=0.1)])
        time.sleep(0.08)
        _write_lines(path, [_mk("run_summary", 9, ideal_k=2, score=1.0,
                                final_loglik=-2.0, total_iters=3,
                                wall_s=1.0)])

    t = threading.Thread(target=writer)
    t.start()
    rc = follow_stream(path, interval_s=0.03)
    t.join()
    assert rc == 0
    out = capsys.readouterr().out
    assert "gmm top" in out
    assert "stream ended" in out            # saw the terminal record
    assert "iter=3" in out                  # saw records written mid-tail
    assert out.count("--- refresh ---") >= 1


def test_follow_merges_a_multi_rank_stream_directory(tmp_path, capsys):
    d = tmp_path / "streams"
    d.mkdir()
    _write_lines(str(d / "rank0.jsonl"),
                 [_mk("run_start", 0, platform="cpu", num_events=10,
                      num_dimensions=2, start_k=2),
                  _mk("em_iter", 1, k=2, iter=0, loglik=-3.0, wall_s=0.1)])
    _write_lines(str(d / "rank1.jsonl"),
                 [_mk("run_summary", 5, ideal_k=2, score=1.0,
                      final_loglik=-2.0, total_iters=1, wall_s=0.5)])
    rc = follow_stream(str(d), interval_s=0.01, max_renders=3)
    assert rc == 0
    out = capsys.readouterr().out
    assert "EM: K=2" in out and "stream ended" in out


def test_follow_terminates_despite_trailing_span_records(tmp_path, capsys):
    """With the live plane on, the closing fit span lands AFTER
    run_summary (it closes when fit_gmm's ExitStack unwinds around the
    emitter) -- the tailer must still exit, and the trailing span must
    make the final screen."""
    path = str(tmp_path / "trail.jsonl")
    _write_lines(path, [
        _mk("run_start", 0, platform="cpu", num_events=10,
            num_dimensions=2, start_k=2),
        _mk("run_summary", 1, ideal_k=2, score=1.0, final_loglik=-2.0,
            total_iters=3, wall_s=1.0),
        _mk("span", 2, name="fit", span_id="abcd1234abcd1234",
            trace_id="t1", t0_mono_s=9.0, duration_s=1.5),
    ])
    rc = follow_stream(path, interval_s=0.01)
    assert rc == 0
    out = capsys.readouterr().out
    assert "stream ended" in out
    assert "last fit (1.500s)" in out


def test_report_main_follow_flag_and_top_alias(tmp_path, capsys):
    path = str(tmp_path / "done.jsonl")
    _write_lines(path, [
        _mk("run_start", 0, platform="cpu", num_events=10,
            num_dimensions=2, start_k=2),
        _mk("run_summary", 1, ideal_k=2, score=1.0, final_loglik=-2.0,
            total_iters=3, wall_s=1.0),
    ])
    assert report_main([path, "--follow", "--interval", "0.01"]) == 0
    assert "stream ended" in capsys.readouterr().out
    # `gmm top` routes to report --follow before argparse.
    from cuda_gmm_mpi_tpu.cli import main

    assert main(["top", path, "--interval", "0.01"]) == 0
    assert "gmm top" in capsys.readouterr().out


# ------------------------------------------------------------- serve spans


def test_serve_trace_id_echo_joins_server_records(tmp_path):
    from cuda_gmm_mpi_tpu import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry

    rng = np.random.default_rng(0)
    data, _ = make_blobs(rng, n=400, d=4, k=3)
    gm = GaussianMixture(3, target_components=3,
                         config=GMMConfig(min_iters=3, max_iters=3,
                                          chunk_size=256))
    gm.fit(data.astype(np.float32))
    gm.to_registry(str(tmp_path), "m")

    rec, buf = _stream_recorder()
    X = data[:16].astype(np.float32).tolist()
    with telemetry.use(rec):
        server = GMMServer(ModelRegistry(str(tmp_path)),
                           trace_requests=True)
        resps = server.handle_requests([
            {"id": 1, "model": "m", "op": "score", "x": X},
            {"id": 2, "model": "m", "op": "predict", "x": X},
        ])
    assert all(r["ok"] for r in resps)
    tids = [r["trace_id"] for r in resps]
    assert len(set(tids)) == 2              # one identity per request
    recs = _records(buf)
    reqs = [r for r in recs if r["event"] == "serve_request"]
    # The echoed id joins the client to ITS server-side record.
    assert sorted(r["trace_id"] for r in reqs) == sorted(tids)
    spans = [r for r in recs if r["event"] == "span"]
    roots = build_span_tree(spans)
    assert [n["span"]["name"] for n in roots] == ["serve_route"]
    hops = [c["span"]["name"] for c in roots[0]["children"]]
    assert hops == ["prepare", "dispatch", "answer"]
    # The route span joined the FIRST request's minted trace.
    assert roots[0]["span"]["trace_id"] == tids[0]
    assert not schema.validate_stream(recs)


def test_serve_responses_unchanged_without_trace_requests(tmp_path):
    from cuda_gmm_mpi_tpu import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry

    rng = np.random.default_rng(0)
    data, _ = make_blobs(rng, n=400, d=4, k=3)
    gm = GaussianMixture(3, target_components=3,
                         config=GMMConfig(min_iters=3, max_iters=3,
                                          chunk_size=256))
    gm.fit(data.astype(np.float32))
    gm.to_registry(str(tmp_path), "m")

    rec, buf = _stream_recorder()
    X = data[:8].astype(np.float32).tolist()
    with telemetry.use(rec):
        server = GMMServer(ModelRegistry(str(tmp_path)))
        resps = server.handle_requests(
            [{"id": 1, "model": "m", "op": "score", "x": X}])
    assert "trace_id" not in resps[0]
    recs = _records(buf)
    assert not any(r["event"] == "span" for r in recs)
    assert not any("trace_id" in r for r in recs)


def test_server_live_gauges_are_exporter_ready(tmp_path):
    from cuda_gmm_mpi_tpu import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry

    rng = np.random.default_rng(0)
    data, _ = make_blobs(rng, n=400, d=4, k=3)
    gm = GaussianMixture(3, target_components=3,
                         config=GMMConfig(min_iters=3, max_iters=3,
                                          chunk_size=256))
    gm.fit(data.astype(np.float32))
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)))
    X = data[:8].astype(np.float32).tolist()
    server.handle_requests([{"id": 1, "model": "m", "op": "score",
                             "x": X}])
    gauges = server.live_gauges()
    assert gauges["gmm_serve_requests"] == 1.0
    assert gauges["gmm_serve_rows"] == 8.0
    assert 0.0 <= gauges["gmm_executor_cache_hit_rate"] <= 1.0
    assert all(isinstance(v, float) for v in gauges.values())
    text = render_openmetrics({}, gauges)
    assert "gmm_serve_queue_rows 0" in text
    assert text.endswith("# EOF\n")


# --------------------------------------- late-join rank files (rev v2.3)


def test_follow_picks_up_rank_file_created_after_tailing_begins(
        tmp_path, capsys):
    """A rank file that lands in the stream directory AFTER the follow
    loop starts (elastic regrowth, slow NFS create, a serve stream
    appearing beside a fit stream) must get a tailer mid-follow -- here
    the late file carries the ONLY terminal record, so the loop can only
    exit by discovering it."""
    d = tmp_path / "streams"
    d.mkdir()
    _write_lines(str(d / "rank0.jsonl"),
                 [_mk("run_start", 0, platform="cpu", num_events=10,
                      num_dimensions=2, start_k=2),
                  _mk("em_iter", 1, k=2, iter=0, loglik=-3.0,
                      wall_s=0.1)])

    def late_writer():
        time.sleep(0.1)
        _write_lines(str(d / "rank1.jsonl"),
                     [_mk("em_iter", 2, k=2, iter=1, loglik=-2.5,
                          wall_s=0.1),
                      _mk("run_summary", 5, ideal_k=2, score=1.0,
                          final_loglik=-2.0, total_iters=2, wall_s=0.5)])

    t = threading.Thread(target=late_writer)
    t.start()
    rc = follow_stream(str(d), interval_s=0.03)
    t.join()
    assert rc == 0
    out = capsys.readouterr().out
    assert "stream ended" in out            # terminal came from rank1
    assert "iter=1" in out                  # as did its data record


def test_stream_tailer_poll_survives_path_becoming_a_directory(tmp_path):
    """A `gmm top` target that did not exist at startup can appear as a
    DIRECTORY (per-rank streams): the dir-path tailer created while the
    path was absent must keep returning [] instead of raising
    IsADirectoryError, leaving discovery to per-file tailers."""
    path = str(tmp_path / "later")
    t = StreamTailer(path)
    assert t.poll() == []                  # nothing there yet
    os.mkdir(path)
    _write_lines(os.path.join(path, "rank0.jsonl"),
                 [_mk("run_start", 0, platform="cpu", num_events=10,
                      num_dimensions=2, start_k=2)])
    assert t.poll() == []                  # a directory, not a stream


# ----------------------------------------- span profile self-time (unit)


def test_span_profile_self_time_matches_hand_computed_fixture():
    """The "Span profile" table's SELF time must equal total minus the
    sum of DIRECT children, per node, aggregated by name -- pinned
    against a hand-built tree: fit(10.0) -> sweep(8.0) -> [em_k(3.0),
    em_k(2.0), checkpoint(1.0)]."""
    recs = [
        _mk("span", 0, name="fit", span_id="f" * 16, trace_id="t1",
            t0_mono_s=0.0, duration_s=10.0),
        _mk("span", 1, name="sweep", span_id="s" * 16, trace_id="t1",
            parent_id="f" * 16, t0_mono_s=0.5, duration_s=8.0),
        _mk("span", 2, name="em_k", span_id="a" * 16, trace_id="t1",
            parent_id="s" * 16, t0_mono_s=1.0, duration_s=3.0),
        _mk("span", 3, name="em_k", span_id="b" * 16, trace_id="t1",
            parent_id="s" * 16, t0_mono_s=4.0, duration_s=2.0),
        _mk("span", 4, name="checkpoint", span_id="c" * 16,
            trace_id="t1", parent_id="s" * 16, t0_mono_s=6.0,
            duration_s=1.0),
    ]
    lines = report_mod._render_span_profile(recs)
    rows = {}
    for line in lines[2:]:
        parts = line.split()
        if len(parts) == 4 and parts[0] != "...":
            rows[parts[0]] = (float(parts[1]), float(parts[2]),
                              int(parts[3]))
    # fit: 10 total - 8 (sweep) = 2 self; sweep: 8 - (3+2+1) = 2 self;
    # leaves: self == total; counts aggregate by name.
    assert rows["fit"] == (2.0, 10.0, 1)
    assert rows["sweep"] == (2.0, 8.0, 1)
    assert rows["em_k"] == (5.0, 5.0, 2)
    assert rows["checkpoint"] == (1.0, 1.0, 1)
    # Sorted by self time descending.
    assert list(rows)[0] == "em_k"


def test_span_profile_orphans_and_overrun_children_clamp_to_zero():
    """Two edge cases the math must survive: a child whose parent never
    emitted (crash mid-phase -- orphan-promoted, counted fully), and a
    node whose direct children SUM past its own total (overlapping
    retries) -- self time clamps at 0.0, never negative."""
    recs = [
        # Orphan: parent_id points at a span that never completed.
        _mk("span", 0, name="recovery", span_id="a" * 16, trace_id="t1",
            parent_id="gone000000000000", t0_mono_s=1.0, duration_s=4.0),
        # Overrun: children total 5.0 under a 3.0 parent.
        _mk("span", 1, name="retry", span_id="b" * 16, trace_id="t1",
            parent_id="p" * 16, t0_mono_s=2.0, duration_s=2.5),
        _mk("span", 2, name="retry", span_id="c" * 16, trace_id="t1",
            parent_id="p" * 16, t0_mono_s=3.0, duration_s=2.5),
        _mk("span", 3, name="dispatch", span_id="p" * 16, trace_id="t1",
            t0_mono_s=2.0, duration_s=3.0),
    ]
    lines = report_mod._render_span_profile(recs)
    rows = {}
    for line in lines[2:]:
        parts = line.split()
        if len(parts) == 4 and parts[0] != "...":
            rows[parts[0]] = (float(parts[1]), float(parts[2]),
                              int(parts[3]))
    assert rows["recovery"] == (4.0, 4.0, 1)      # orphan counted fully
    assert rows["dispatch"] == (0.0, 3.0, 1)      # clamped, not -2.0
    assert rows["retry"] == (5.0, 5.0, 2)
    # The tree itself promoted the orphan to a root.
    roots = build_span_tree(recs)
    assert {r["span"]["name"] for r in roots} == {"recovery", "dispatch"}
