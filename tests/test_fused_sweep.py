"""Fused (whole-sweep-on-device) path == host-driven sweep, exactly."""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm
from cuda_gmm_mpi_tpu.parallel.sharded_em import SHARD_MAP_FUSED_EMIT_OK

from .conftest import make_blobs

# check_rep-era jax CHECK-aborts (uncatchably, killing the test process) on
# io_callback under shard_map, so sharded fused emission is version-gated
# off there and these composition tests cannot run; the fallback test below
# covers what that configuration does instead.
needs_sharded_fused_emit = pytest.mark.skipif(
    not SHARD_MAP_FUSED_EMIT_OK,
    reason="io_callback under shard_map unsupported on this jax; sharded "
           "fused runs wanting emission fall back to the host sweep")


def cfg(**kw):
    base = dict(min_iters=4, max_iters=4, chunk_size=256, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


@pytest.mark.parametrize("target", [0, 3])
def test_fused_matches_host_sweep(rng, target):
    data, _ = make_blobs(rng, n=900, d=3, k=4)
    r_host = fit_gmm(data, 8, target, config=cfg())
    r_fused = fit_gmm(data, 8, target, config=cfg(fused_sweep=True))

    assert r_fused.ideal_num_clusters == r_host.ideal_num_clusters
    np.testing.assert_allclose(r_fused.min_rissanen, r_host.min_rissanen,
                               rtol=1e-12)
    np.testing.assert_allclose(r_fused.final_loglik, r_host.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_fused.means, r_host.means, rtol=1e-10,
                               atol=1e-12)
    np.testing.assert_allclose(r_fused.covariances, r_host.covariances,
                               rtol=1e-9, atol=1e-12)
    # identical per-K trajectories (k, loglik, rissanen, iters)
    assert len(r_fused.sweep_log) == len(r_host.sweep_log)
    for f, h in zip(r_fused.sweep_log, r_host.sweep_log):
        assert f[0] == h[0] and f[3] == h[3]
        np.testing.assert_allclose(f[1:3], h[1:3], rtol=1e-12)


def test_fused_k1(rng):
    data, _ = make_blobs(rng, n=300, d=2, k=2)
    r = fit_gmm(data, 1, 1, config=cfg(fused_sweep=True))
    assert r.ideal_num_clusters == 1
    assert np.isfinite(r.final_loglik)


def test_fused_with_checkpoint_emits_per_k(rng, tmp_path):
    """--fused-sweep + --checkpoint-dir stays on the fused path (round 3):
    per-K checkpoints come from the ordered io_callback emission and carry
    the fused-format payload."""
    from cuda_gmm_mpi_tpu.utils.checkpoint import SweepCheckpointer

    data, _ = make_blobs(rng, n=300, d=2, k=2)
    r = fit_gmm(
        data, 4, 2,
        config=cfg(fused_sweep=True, checkpoint_dir=str(tmp_path / "ck")),
    )
    assert (tmp_path / "ck" / "sweep").is_dir()
    restored = SweepCheckpointer(str(tmp_path / "ck")).restore()
    assert restored is not None and "fused_log" in restored  # fused payload
    assert r.ideal_num_clusters >= 2
    # Per-K seconds come from real emission arrival times, not amortization.
    assert len(r.sweep_log) >= 2
    assert len({round(row[4], 9) for row in r.sweep_log}) > 1


@needs_sharded_fused_emit
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
def test_fused_with_mesh_and_checkpoint_stays_fused(rng, tmp_path, mesh_shape):
    """Sharded fused sweep + checkpointing compose (round 4): emission fires
    per device shard with the cluster axis all-gathered, the host sink
    dedupes by step, and the per-K checkpoints land in the callback-safe
    npz format with the full (unsharded) state."""
    from cuda_gmm_mpi_tpu.utils.checkpoint import SweepCheckpointer

    data, _ = make_blobs(rng, n=512, d=3, k=3)
    r = fit_gmm(
        data, 4, 2,
        config=cfg(fused_sweep=True, mesh_shape=mesh_shape,
                   checkpoint_dir=str(tmp_path / "ck")),
    )
    sweep_dir = tmp_path / "ck" / "sweep"
    assert sweep_dir.is_dir()
    assert any(f.suffix == ".npz" for f in sweep_dir.iterdir())
    restored = SweepCheckpointer(str(tmp_path / "ck")).restore()
    assert restored is not None and "fused_log" in restored  # fused payload
    # The emitted state is the FULL model (cluster shards gathered), padded
    # K rows included -- resumable on any mesh layout.
    assert restored["state"].means.shape[1] == 3
    assert restored["state"].means.shape[0] >= 4
    assert r.ideal_num_clusters >= 2
    # Resuming from the last checkpoint reproduces the uninterrupted answer.
    r2 = fit_gmm(
        data, 4, 2,
        config=cfg(fused_sweep=True, mesh_shape=mesh_shape,
                   checkpoint_dir=str(tmp_path / "ck")),
    )
    assert r2.ideal_num_clusters == r.ideal_num_clusters
    np.testing.assert_allclose(r2.min_rissanen, r.min_rissanen, rtol=1e-9)


@needs_sharded_fused_emit
def test_fused_with_mesh_and_profile_emits_per_k(rng):
    """emit_light (profiling-only) emission also rides the sharded fused
    sweep: per-K wall seconds come from real arrival times."""
    data, _ = make_blobs(rng, n=512, d=3, k=3)
    r = fit_gmm(data, 4, 2,
                config=cfg(fused_sweep=True, mesh_shape=(4, 2),
                           profile=True))
    assert r.profile is not None
    assert r.profile["e_step"] > 0.0
    assert "fused sweep" in r.profile_report


@pytest.mark.skipif(SHARD_MAP_FUSED_EMIT_OK,
                    reason="this jax supports sharded fused emission; the "
                           "composition tests above cover it")
def test_fused_mesh_emission_falls_back_to_host_sweep(rng):
    """On jax versions where sharded fused emission would CHECK-abort,
    emission-wanting fused+mesh runs must degrade to the host-driven sweep
    (warning + correct answer), never crash."""
    data, _ = make_blobs(rng, n=512, d=3, k=3)
    r = fit_gmm(data, 4, 2,
                config=cfg(fused_sweep=True, mesh_shape=(8, 1),
                           profile=True))
    assert r.profile is not None and r.profile["e_step"] > 0.0
    # host-sweep report, not the fused coarse-attribution variant
    assert "fused sweep" not in r.profile_report
    assert r.ideal_num_clusters >= 2
    assert len(r.sweep_log) == 3


def test_fused_parity_with_mass_elimination():
    """Empty-cluster elimination can drop the count BELOW the target in one
    step; host and fused sweeps must terminate identically (the fused loop
    re-checks k >= stop_number after merging, like the host loop's while)."""
    r = np.random.default_rng(9)
    data = r.normal(size=(60, 3))  # K close to N: mass near-empty clusters
    for target in (0, 15):
        c_host = cfg(min_iters=2, max_iters=2, chunk_size=32)
        c_fused = cfg(min_iters=2, max_iters=2, chunk_size=32,
                      fused_sweep=True)
        rh = fit_gmm(data, 24, target, config=c_host)
        rf = fit_gmm(data, 24, target, config=c_fused)
        assert [row[0] for row in rf.sweep_log] == \
               [row[0] for row in rh.sweep_log], (target,)
        assert rf.ideal_num_clusters == rh.ideal_num_clusters
        np.testing.assert_allclose(rf.min_rissanen, rh.min_rissanen,
                                   rtol=1e-12)


def test_fused_sharded_data_parallel_matches_host(rng):
    """Fused sweep under shard_map on an 8-device data mesh == plain host."""
    data, _ = make_blobs(rng, n=1024, d=3, k=4)
    r_host = fit_gmm(data, 6, 3, config=cfg())
    r_fused = fit_gmm(data, 6, 3,
                      config=cfg(fused_sweep=True, mesh_shape=(8, 1)))
    assert r_fused.ideal_num_clusters == r_host.ideal_num_clusters
    np.testing.assert_allclose(r_fused.min_rissanen, r_host.min_rissanen,
                               rtol=1e-9)
    np.testing.assert_allclose(r_fused.means, r_host.means, rtol=1e-7,
                               atol=1e-9)
    assert [row[0] for row in r_fused.sweep_log] == \
           [row[0] for row in r_host.sweep_log]


@pytest.fixture(scope="module")
def cluster_blob_case():
    """Shared (data, host-path result) so the baseline fit runs once."""
    rng = np.random.default_rng(1234)
    data, _ = make_blobs(rng, n=512, d=3, k=3)
    return data, fit_gmm(data, 5, 2, config=cfg())


@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 4), (1, 8)])
def test_fused_cluster_sharded_matches_host(cluster_blob_case, mesh_shape):
    """Cluster-sharded fused sweep (all-gather order reduction) == host."""
    data, r_host = cluster_blob_case
    r_fused = fit_gmm(data, 5, 2,
                      config=cfg(fused_sweep=True, mesh_shape=mesh_shape))
    assert r_fused.ideal_num_clusters == r_host.ideal_num_clusters
    np.testing.assert_allclose(r_fused.min_rissanen, r_host.min_rissanen,
                               rtol=1e-9)
    np.testing.assert_allclose(r_fused.means, r_host.means, rtol=1e-7,
                               atol=1e-9)
    assert [row[0] for row in r_fused.sweep_log] == \
           [row[0] for row in r_host.sweep_log]


def test_fused_matches_host_float32(rng):
    """Default-dtype (float32) parity: selection identical away from
    Rissanen ~1-ulp ties (the documented float32 caveat in fused_sweep.py)."""
    data, _ = make_blobs(rng, n=800, d=3, k=4, dtype=np.float32)
    c32 = dict(min_iters=4, max_iters=4, chunk_size=256, dtype="float32")
    rh = fit_gmm(data, 7, 0, config=GMMConfig(**c32))
    rf = fit_gmm(data, 7, 0, config=GMMConfig(fused_sweep=True, **c32))
    assert rf.ideal_num_clusters == rh.ideal_num_clusters
    np.testing.assert_allclose(rf.final_loglik, rh.final_loglik, rtol=1e-6)
    np.testing.assert_allclose(rf.means, rh.means, rtol=1e-4, atol=1e-5)


def test_fused_with_profile_emits_per_k(rng):
    """--fused-sweep + --profile stays on the fused path: per-K emission
    arrival times fill the e_step category (coarse whole-K attribution) and
    real per-K seconds land in the sweep log."""
    data, _ = make_blobs(rng, n=300, d=2, k=2)
    r = fit_gmm(data, 4, 2, config=cfg(fused_sweep=True, profile=True))
    assert r.profile is not None
    assert r.profile["e_step"] > 0.0
    assert "fused sweep" in r.profile_report
    assert len({round(row[4], 9) for row in r.sweep_log}) > 1
