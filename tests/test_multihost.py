"""True multi-controller test: 2 OS processes x 2 CPU devices over localhost.

The reference's multi-node story could only be validated on an MPI cluster;
here the equivalent (jax.distributed coordination service + cross-process
psum + per-host sharded loading) runs as two subprocesses on one machine --
"test multi-node without a cluster" taken one level further than the fake
8-device mesh (SURVEY.md SS4): real process boundaries, real collectives.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(nproc: int, timeout: float = 300.0, mesh_kind: str = "data"):
    from .conftest import worker_env

    port = _free_port()
    env = worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nproc), str(port), mesh_kind],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _parse(line: str):
    kv = dict(part.split("=", 1) for part in line.split()[1:])
    return (
        float(kv["ll"]),
        int(kv["iters"]),
        np.array([float(v) for v in kv["means"].split(",")]),
    )


def test_host_chunk_bounds_equal_counts():
    """Remainders never produce unequal per-host chunk counts (the failure
    mode of naive host_slice + per-host padding: 65 events / 2 hosts /
    chunk 16 gave one host 3 chunks and the other 2)."""
    from cuda_gmm_mpi_tpu.models.gmm import chunk_events
    from cuda_gmm_mpi_tpu.parallel.distributed import host_chunk_bounds

    for n, chunk, data_axis, nproc in [
        (65, 16, 2, 2), (509, 64, 4, 2), (100_000, 8192, 8, 2),
        (7, 16, 2, 2), (128, 16, 4, 4),
    ]:
        shapes, covered = [], 0
        for pid in range(nproc):
            start, stop, nc = host_chunk_bounds(n, chunk, data_axis, pid, nproc)
            assert stop >= start
            covered += stop - start
            c, w = chunk_events(
                np.zeros((max(stop - start, 0), 3), np.float32), chunk,
                num_chunks=nc,
            )
            shapes.append(c.shape)
            assert float(w.sum()) == stop - start
            # per-host chunks divide the host's local data-axis devices
            assert nc % (data_axis // nproc) == 0
        assert covered == n, (n, chunk, data_axis, nproc)
        assert len(set(shapes)) == 1, shapes


@pytest.mark.slow
def test_two_process_collective_input_abort():
    """A NaN row in ONE rank's slice aborts BOTH ranks cleanly (the
    validity allgather), rather than stranding the clean rank in the
    moments collective until timeout."""
    import subprocess

    from .conftest import worker_env

    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_validate_worker.py")
    port = _free_port()
    env = worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True,
        )
        for i in range(2)
    ]
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        finally:
            if p.poll() is None:
                p.kill()
        assert p.returncode == 0, f"rank {i}:\n{out}\n{err[-2000:]}"
        # both ranks report the GLOBAL bad count (1), including the clean one
        assert f"ABORTED pid={i} nbad=1" in out, (i, out, err[-1000:])


def test_two_process_distributed_em_matches_single():
    outs = _run_workers(2)
    for rc, out, err in outs:
        if rc != 0 and "aren't implemented on the CPU backend" in err:
            # Older jaxlib CPU backends have no cross-process collectives
            # at all; nothing multi-controller can run on this image.
            pytest.skip("jax CPU backend lacks multiprocess collectives")
        assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err[-3000:]}"
    results = []
    for rc, out, err in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out}\n{err[-2000:]}"
        results.append(_parse(lines[0]))

    # Every host computes the identical replicated result (SPMD).
    (ll0, it0, m0), (ll1, it1, m1) = results
    assert it0 == it1 == 4
    np.testing.assert_allclose(ll1, ll0, rtol=1e-12)
    np.testing.assert_allclose(m1, m0, rtol=1e-12)

    # And it matches the plain single-device EM on the same problem.
    import jax

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    n, d, k = 509, 3, 3
    rng = np.random.default_rng(1234)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (
        centers[rng.integers(0, k, n)] + rng.normal(size=(n, d))
    ).astype(np.float64)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=64, dtype="float64")
    model = GMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    state = seed_clusters_host(data, k)
    s, ll, _ = model.run_em(
        state, np.asarray(chunks), np.asarray(wts), convergence_epsilon(n, d)
    )
    np.testing.assert_allclose(ll0, float(ll), rtol=1e-9)
    np.testing.assert_allclose(m0, np.asarray(jax.device_get(s.means))[0],
                               rtol=1e-7, atol=1e-10)


@pytest.mark.slow
def test_two_process_2d_mesh_matches_data_mesh():
    """2-D (data x cluster) sharding across a REAL process boundary: the
    cluster axis lives within each host, the data-axis psum crosses hosts,
    and the result must equal the pure data-parallel layout's."""
    outs_2d = _run_workers(2, mesh_kind="2d")
    outs_1d = _run_workers(2, mesh_kind="data")
    results = []
    for outs in (outs_2d, outs_1d):
        for rc, out, err in outs:  # every rank must have succeeded
            assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err[-3000:]}"
        rc, out, err = outs[0]
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out}\n{err[-2000:]}"
        results.append(_parse(lines[0]))
    (ll2, it2, m2), (ll1, it1, m1) = results
    assert it2 == it1 == 4
    np.testing.assert_allclose(ll2, ll1, rtol=1e-9)
    np.testing.assert_allclose(m2, m1, rtol=1e-7, atol=1e-10)


GATHER_WORKER = r"""
import os, sys
pid, nproc, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
import jax
jax.config.update("jax_platforms", "cpu")
from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices
force_cpu_devices(1)
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nproc, process_id=pid)
from cuda_gmm_mpi_tpu.parallel.distributed import (
    assemble_results_multihost, results_part_path,
)

def content(i):  # deterministic, different sizes per rank
    return "".join(f"rank{i} row {j} " + "x" * (17 + i) + "\n"
                   for j in range(1500 + 700 * i)).encode()

out_path = os.path.join(outdir, "final.results")
private = os.path.join(outdir, f"private_rank{pid}")  # NOT visible as a
os.makedirs(private, exist_ok=True)                   # sibling of out_path
part = results_part_path(out_path, part_dir=private)
with open(part, "wb") as f:
    f.write(content(pid))
# Small chunk forces multiple gather rounds.
assemble_results_multihost(out_path, part, chunk_bytes=4096)
assert not os.path.exists(part), "part not cleaned up"
if pid == 0:
    got = open(out_path, "rb").read()
    want = b"".join(content(i) for i in range(nproc))
    assert got == want, (len(got), len(want))
    print("GATHER_OK", flush=True)
jax.distributed.shutdown()
"""


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 3])
def test_results_gather_without_shared_fs(tmp_path, nproc):
    """Per-rank parts in rank-PRIVATE directories (simulating per-host local
    disks on a pod): assembly must take the chunked byte-gather over the
    runtime -- the MPI_Send/Recv membership gather equivalence,
    gaussian.cu:798-817 -- and produce rank-ordered byte-exact output.
    3 ranks exercise unequal part sizes across >2 gather participants."""
    from .conftest import worker_env

    port = _free_port()
    env = worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", GATHER_WORKER, str(i), str(nproc),
             str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {i} failed (rc={rc}):\n{out}\n{err[-3000:]}"
    assert "GATHER_OK" in outs[0][1]


CLI_N_EVENTS = 600
CLI_COMMON = [
    "6", None, None, "2", "--device=cpu", "--dtype=float64",
    "--mesh=4", "--chunk-size=64", "--min-iters=5", "--max-iters=5",
]


def _spawn_cli(infile, outbase, extra, ndev):
    from .conftest import worker_env

    argv = list(CLI_COMMON)
    argv[1], argv[2] = str(infile), str(outbase)
    cmd = [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli",
           *argv, f"--cpu-devices={ndev}", *extra]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE,
                            env=worker_env(), text=True)


@pytest.fixture(scope="module")
def cli_single_reference(tmp_path_factory):
    """(infile, single.summary bytes, single.results bytes): the
    single-process 4-device reference fit, run once and shared by both
    parametrizations of the byte-identity test."""
    root = tmp_path_factory.mktemp("cli_ref")
    rng = np.random.default_rng(99)
    k, d = 3, 4
    centers = rng.normal(scale=10.0, size=(k, d))
    data = (centers[rng.integers(0, k, CLI_N_EVENTS)]
            + rng.normal(size=(CLI_N_EVENTS, d))).astype(np.float32)
    infile = root / "events.csv"
    with open(infile, "w") as f:
        f.write(",".join(f"c{j}" for j in range(d)) + "\n")
        for row in data:
            f.write(",".join(f"{v:.6f}" for v in row) + "\n")
    p = _spawn_cli(infile, root / "single", [], 4)
    out, err = p.communicate(timeout=300)
    assert p.returncode == 0, f"single-proc CLI failed:\n{out}\n{err[-3000:]}"
    return (infile, (root / "single.summary").read_bytes(),
            (root / "single.results").read_bytes())


@pytest.mark.slow
@pytest.mark.parametrize("stream", [False, True], ids=["mem", "stream"])
def test_two_process_cli_byte_identical(tmp_path, stream,
                                        cli_single_reference):
    """The reference's end-to-end story -- ``mpirun -np 2 gaussianMPI K in
    out`` producing .summary/.results -- run through THIS CLI: the same
    command on 2 processes (2 CPU devices each, per-host sharded file
    loading, cross-process collectives, rank-0 output assembly) must produce
    byte-identical outputs to a single-process run on the same 4-device
    mesh. Matches gaussian.cu:128-207, 998-1061.

    ``stream`` additionally runs the multi-process side out-of-core
    (--stream-events, round 4): each rank streams its host slice block-wise
    over its local shards with the end-of-pass psum spanning the global
    mesh -- and must still match the in-memory single-process bytes."""
    infile, single_summary, single_results = cli_single_reference

    # Two processes x 2 devices over a localhost coordination service.
    # Each rank spools its .results part in a PRIVATE --part-dir, so the
    # assembly must take the byte-gather path (no shared-FS assumption).
    port = _free_port()
    for i in range(2):
        (tmp_path / f"scratch{i}").mkdir(exist_ok=True)
    stream_flags = ["--stream-events"] if stream else []
    procs = [
        _spawn_cli(infile, tmp_path / "multi",
                   [f"--coordinator=127.0.0.1:{port}", "--num-processes=2",
                    f"--process-id={i}",
                    f"--part-dir={tmp_path / ('scratch%d' % i)}",
                    *stream_flags], 2)
        for i in range(2)
    ]
    for i, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, \
            f"rank {i} CLI failed:\n{out}\n{err[-3000:]}"

    multi_summary = (tmp_path / "multi.summary").read_bytes()
    assert len(single_summary) > 100
    assert multi_summary == single_summary

    multi_results = (tmp_path / "multi.results").read_bytes()
    assert single_results.count(b"\n") == CLI_N_EVENTS
    assert multi_results == single_results
    # parts were cleaned up after assembly
    assert not list(tmp_path.glob("multi.results.part*"))
