"""Drift observability (stream rev v2.4; docs/OBSERVABILITY.md
"Drift detection"): training-score envelopes, streaming serve-time
sketches, and the `gmm drift` analytics CLI.

Contracts:
- StreamSketch MERGES exactly: any split of a stream, merged in any
  order, reproduces the one-shot sketch (buckets/count/min/max bit
  for bit, moments to float rounding) -- the property that lets
  per-rank/per-window/per-tenant sketches compose;
- PSI / KS / occupancy_l1 match hand-computed pinned fixtures,
  including the PSI_EPS clamp on empty buckets;
- a fit records the training envelope into run_summary, the registry
  sidecar (envelope.json) and the manifest stanza; envelope=False
  removes all three;
- the serve drift plane emits schema-valid `drift` windows vs the
  envelope (in-distribution traffic stays quiet; shifted traffic
  raises `drift_alarm`) and feeds the /metrics drift gauges;
- `gmm drift` honours the 0/1/2 exit contract for dataset AND stream
  targets, names the tripped metric, and --rebuild-envelope backfills
  envelope.json while leaving model.npz/manifest.json bit-identical;
- `gmm top` renders the drift rollup line; `gmm timeline` renders
  per-model PSI/KS counter tracks and drift_alarm instants;
- export_fleet republishes per-tenant envelopes next to the exported
  versions.
"""

import hashlib
import json
import math
import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, GaussianMixture, telemetry
from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry
from cuda_gmm_mpi_tpu.telemetry import sketch as tl_sketch
from cuda_gmm_mpi_tpu.telemetry.schema import (EVENT_FIELDS,
                                               validate_stream)
from cuda_gmm_mpi_tpu.telemetry.sketch import (SCORE_BOUNDS, StreamSketch,
                                               compare_to_envelope,
                                               envelope_stanza, ks,
                                               make_envelope,
                                               merge_envelopes,
                                               occupancy_l1, psi)

from .conftest import make_blobs


def fitted(rng, *, k=3, d=4, n=600, envelope=True, dtype="float32"):
    data, _ = make_blobs(rng, n=n, d=d, k=k, dtype=np.float64)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=4, max_iters=4, chunk_size=256,
                         dtype=dtype, envelope=envelope))
    gm.fit(data.astype(np.dtype(dtype)))
    return gm, data.astype(np.dtype(dtype))


def write_bin(path, arr):
    """The fit CLI's BIN input format: int32 [n, d] header + f32 rows."""
    arr = np.asarray(arr, np.float32)
    with open(str(path), "wb") as f:
        np.asarray(arr.shape, np.int32).tofile(f)
        arr.tofile(f)
    return str(path)


class _StreamSink:
    def __init__(self, records):
        self._records = records

    def write(self, line):
        self._records.append(json.loads(line))

    def flush(self):
        pass


# ------------------------------------------------------------- sketches


def test_sketch_merge_matches_oneshot_for_any_split(rng):
    """The mergeability property: random splits, merged in a shuffled
    order, reproduce the one-shot sketch -- counts exactly, moments to
    float rounding. This is what makes per-rank envelopes and windowed
    serve sketches re-aggregable."""
    values = np.concatenate([
        rng.normal(-40.0, 30.0, size=500),
        rng.exponential(200.0, size=300),
        [0.0, -1e5, 1e5, np.nan, np.inf, -np.inf],  # non-finite dropped
    ])
    one = StreamSketch().update(values)
    assert one.count == 500 + 300 + 3  # finite rows only

    for trial in range(5):
        cuts = np.sort(rng.integers(0, len(values), size=7))
        parts = np.split(values, cuts)
        rng.shuffle(parts)
        sketches = [StreamSketch().update(p) for p in parts]
        merged = sketches[0]
        for sk in sketches[1:]:
            merged.merge(sk)
        assert merged.buckets == one.buckets, trial
        assert merged.count == one.count
        assert merged.vmin == one.vmin and merged.vmax == one.vmax
        # Chan's formulas are associative only up to float rounding;
        # the error scale is the value spread, not the mean.
        spread = one.vmax - one.vmin
        assert merged.mean == pytest.approx(one.mean, abs=1e-9 * spread)
        assert merged.m2 == pytest.approx(one.m2, rel=1e-9)
        assert merged.variance == pytest.approx(one.variance, rel=1e-9)


def test_sketch_roundtrip_and_ladder_guards(rng):
    """to_dict/from_dict round-trips every field; merging mismatched
    ladders and deserializing a wrong-width histogram both fail loudly
    (a silent ladder mismatch would corrupt every PSI downstream)."""
    sk = StreamSketch().update(rng.normal(size=64))
    back = StreamSketch.from_dict(json.loads(json.dumps(sk.to_dict())))
    assert back.buckets == sk.buckets and back.count == sk.count
    assert back.mean == sk.mean and back.m2 == sk.m2
    assert back.vmin == sk.vmin and back.vmax == sk.vmax
    assert back.bounds == sk.bounds

    empty = StreamSketch().to_dict()
    assert empty["min"] is None and empty["max"] is None
    restored = StreamSketch.from_dict(empty)
    assert restored.count == 0 and restored.vmin == math.inf
    # merging an empty sketch is the identity
    before = sk.to_dict()
    assert sk.merge(restored).to_dict() == before

    with pytest.raises(ValueError, match="different bucket ladders"):
        sk.merge(StreamSketch(bounds=(0.0, 1.0)))
    bad = sk.to_dict()
    bad["buckets"] = bad["buckets"][:-1]
    with pytest.raises(ValueError, match="buckets"):
        StreamSketch.from_dict(bad)


def test_psi_ks_occupancy_pinned_fixtures():
    """Hand-computed drift statistics: the numbers `gmm drift` gates on
    are pinned here, including the PSI_EPS clamp behaviour."""
    # identical distributions: exactly zero
    assert psi([50, 50], [50, 50]) == 0.0
    assert ks([50, 50], [50, 50]) == 0.0
    # [.5,.5] -> [.9,.1]: psi = .4*ln(1.8) + (-.4)*ln(.2)
    expect = 0.4 * math.log(1.8) - 0.4 * math.log(0.2)
    assert psi([50, 50], [90, 10]) == pytest.approx(expect, rel=1e-12)
    assert ks([50, 50], [90, 10]) == pytest.approx(0.4, rel=1e-12)
    # disjoint mass: both sides clamp to PSI_EPS -> ~2*ln(1/eps)
    expect = 2 * (1 - tl_sketch.PSI_EPS) * math.log(1 / tl_sketch.PSI_EPS)
    assert psi([100, 0], [0, 100]) == pytest.approx(expect, rel=1e-9)
    assert ks([100, 0], [0, 100]) == 1.0
    # scale invariance: proportions, not counts
    assert psi([5, 5], [9, 1]) == pytest.approx(
        psi([500, 500], [900, 100]), rel=1e-12)
    with pytest.raises(ValueError, match="bucket count mismatch"):
        psi([1, 2], [1, 2, 3])
    with pytest.raises(ValueError, match="bucket count mismatch"):
        ks([1, 2], [1, 2, 3])

    assert occupancy_l1([1, 1], [3, 1]) == pytest.approx(0.5)
    assert occupancy_l1([4], [2, 2]) == pytest.approx(1.0)  # zero-pads
    assert occupancy_l1([7, 3], [70, 30]) == 0.0


def test_envelope_make_merge_stanza_compare(rng):
    """make_envelope/merge_envelopes/envelope_stanza/compare_to_envelope
    compose: per-shard envelopes merge into the whole-data envelope, and
    a window drawn from the training data itself scores ~0 drift."""
    scores = rng.normal(-12.0, 4.0, size=900)
    occ = [300, 450, 150]
    whole = make_envelope(StreamSketch().update(scores), occ,
                          k=3, num_events=900)
    parts = [make_envelope(StreamSketch().update(chunk),
                           [c // 3 for c in occ], k=3, num_events=300)
             for chunk in np.split(scores, 3)]
    merged = merge_envelopes(parts)
    assert merged["score"]["buckets"] == whole["score"]["buckets"]
    assert merged["score"]["count"] == 900 and merged["num_events"] == 900
    assert merged["occupancy"] == occ and merged["k"] == 3
    assert merge_envelopes([]) is None
    assert merge_envelopes([None, {}]) is None

    stanza = envelope_stanza(whole)
    assert stanza["rows"] == 900 and stanza["k"] == 3
    assert stanza["buckets"] == len(SCORE_BOUNDS) + 1
    assert stanza["version"] == tl_sketch.ENVELOPE_VERSION
    assert stanza["mean_score"] == pytest.approx(scores.mean(), rel=1e-9)

    stats = compare_to_envelope(
        whole, StreamSketch().update(scores), occ)
    assert stats == {"psi": 0.0, "ks": 0.0, "occupancy_l1": 0.0,
                     "window_rows": 900}
    with pytest.raises(ValueError, match="ladder"):
        compare_to_envelope(
            whole, StreamSketch(bounds=(0.0, 1.0)).update([0.5]), occ)


# ----------------------------------------------- training-time envelope


def test_fit_builds_envelope_into_summary_and_registry(rng, tmp_path):
    """The training half of the loop: a fit sketches its own scores and
    responsibilities into result.envelope, run_summary.envelope, the
    registry envelope.json sidecar AND the manifest stanza; envelope=False
    removes all of them (the pre-v2.4 stream shape)."""
    n = 600
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        gm, data = fitted(rng, n=n)
    env = gm.result_.envelope
    assert env is not None and env["score"]["count"] == n
    assert sum(env["occupancy"]) == n
    assert env["k"] == gm.n_components_
    assert validate_stream(stream) == []
    summary = [r for r in stream if r["event"] == "run_summary"][-1]
    assert summary["envelope"]["score"]["buckets"] == \
        env["score"]["buckets"]

    reg = ModelRegistry(str(tmp_path))
    v = gm.to_registry(reg, "m")
    assert os.path.exists(str(tmp_path / "m" / str(v) / "envelope.json"))
    served = reg.load("m")
    assert served.envelope["score"] == env["score"]
    assert served.manifest["envelope"]["rows"] == n
    assert reg.load_envelope("m") == served.envelope

    # envelope off: no sidecar, no stanza, no run_summary field
    stream2 = []
    rec2 = telemetry.RunRecorder(stream=_StreamSink(stream2))
    with telemetry.use(rec2), rec2:
        gm_off, _ = fitted(rng, envelope=False)
    assert gm_off.result_.envelope is None
    summary2 = [r for r in stream2 if r["event"] == "run_summary"][-1]
    assert "envelope" not in summary2
    gm_off.to_registry(reg, "off")
    assert not os.path.exists(str(tmp_path / "off" / "1" /
                                  "envelope.json"))
    off = reg.load("off")
    assert off.envelope is None and "envelope" not in off.manifest


# ------------------------------------------------- serve-time drift plane


def serve_traffic(server, data, shift=0.0, requests=12, rows=40):
    for i in range(requests):
        lo = (i * 17) % (len(data) - rows)
        x = (data[lo:lo + rows] + np.float32(shift)).tolist()
        resp = server.handle_requests(
            [{"id": i, "model": "m", "op": "score_samples", "x": x}])[0]
        assert resp["ok"], resp


def test_serve_drift_windows_and_alarm_end_to_end(rng, tmp_path):
    """The acceptance path: in-distribution traffic produces a quiet
    `drift` window (PSI under threshold, no alarm); mean-shifted traffic
    trips `drift_alarm`; both validate against rev v2.4 and feed the
    drift gauges and the serve rollup."""
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)),
                       drift_interval_s=3600.0, drift_psi_threshold=0.2)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        serve_traffic(server, data, shift=0.0)
        quiet = server.flush_drift()
        serve_traffic(server, data, shift=8.0)
        loud = server.flush_drift()

    assert len(quiet) == 1 and len(loud) == 1
    assert quiet[0]["psi"] < 0.2 and not quiet[0]["alarm"]
    assert loud[0]["psi"] > 0.2 and loud[0]["alarm"]
    assert loud[0]["ks"] > quiet[0]["ks"]
    assert quiet[0]["window_rows"] == loud[0]["window_rows"] == 480

    assert validate_stream(stream) == []
    drifts = [r for r in stream if r["event"] == "drift"]
    alarms = [r for r in stream if r["event"] == "drift_alarm"]
    assert len(drifts) == 2 and len(alarms) == 1
    # windows carry their mergeable raw summary for offline re-analysis
    for r in drifts:
        sk = StreamSketch.from_dict(r["score_sketch"])
        assert sk.count == r["window_rows"]
        assert sum(r["occupancy"]) == r["window_rows"]
        assert r["train_rows"] == 600
    assert alarms[0]["model"] == "m" and alarms[0]["threshold"] == 0.2
    assert alarms[0]["psi"] == loud[0]["psi"]
    assert alarms[0]["flag_names"] == ["drift_psi"]

    stats = server.drift_stats()
    assert stats["windows"] == 2 and stats["alarms"] == 1
    assert stats["threshold"] == 0.2
    assert stats["last"]["m@1"]["alarm"] is True
    gauges = server.live_gauges()
    assert gauges["gmm_drift_psi"] == loud[0]["psi"]
    assert gauges["gmm_drift_events_total"] == 2.0
    assert gauges["gmm_drift_alarms_total"] == 1.0


def test_serve_drain_flushes_final_partial_drift_window(rng, tmp_path):
    """Satellite regression (rev v2.6): a serve session that drains
    BEFORE its drift interval ever fires must still report the partial
    window -- ``emit_summary`` closes the windows first, so the final
    ``drift`` event (and its alarm, when tripped) precede the
    ``serve_summary`` in the stream instead of being silently dropped.
    """
    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)),
                       drift_interval_s=3600.0, drift_psi_threshold=0.2)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec), rec:
        # shifted traffic, then the shutdown path -- NO explicit
        # flush_drift(), only what the drain itself performs
        serve_traffic(server, data, shift=8.0)
        server.begin_drain("eof")
        server.emit_summary()

    assert validate_stream(stream) == []
    kinds = [r["event"] for r in stream]
    assert "drift" in kinds and "serve_summary" in kinds
    assert kinds.index("drift") < kinds.index("serve_summary")
    drift = [r for r in stream if r["event"] == "drift"]
    assert len(drift) == 1 and drift[0]["window_rows"] == 480
    # the shift trips the alarm even in the drain-flushed window
    alarms = [r for r in stream if r["event"] == "drift_alarm"]
    assert len(alarms) == 1 and alarms[0]["model"] == "m"
    # and the window is actually CLOSED: a second summary (idempotent
    # shutdown paths re-enter) reports no further drift events
    n_before = len([r for r in stream if r["event"] == "drift"])
    with telemetry.use(rec), rec:
        server.emit_summary()
    assert len([r for r in stream
                if r["event"] == "drift"]) == n_before


def test_drift_event_schema_pinned_both_directions():
    """Schema drift guard for the new rev v2.4 events, both ways: the
    field tables are exactly what the emit sites send (a field added to
    the emitter without a declaration fails the global emit-site scan;
    a field dropped from the emitter fails HERE), and drift-off servers
    expose no drift gauges."""
    req, opt = EVENT_FIELDS["drift"]
    assert set(req) == {"model", "psi", "ks", "occupancy_l1",
                        "window_rows"}
    for f in ("version", "alarm", "threshold", "score_sketch",
              "occupancy", "mean_score", "train_rows"):
        assert f in opt, f
    req_a, opt_a = EVENT_FIELDS["drift_alarm"]
    assert set(req_a) == {"model", "psi", "threshold"}
    for f in ("version", "ks", "occupancy_l1", "window_rows",
              "flag_names"):
        assert f in opt_a, f
    # the serve_summary rollup and run_summary envelope are DECLARED
    # optionals (drift-off streams stay byte-identical without them)
    assert "drift" in EVENT_FIELDS["serve_summary"][1]
    assert "envelope" in EVENT_FIELDS["run_summary"][1]
    # both events really have emit sites in the serve drift plane
    import inspect

    from cuda_gmm_mpi_tpu.serving import server as server_mod
    src = inspect.getsource(server_mod)
    assert '"drift"' in src and '"drift_alarm"' in src


# --------------------------------------------------------- gmm drift CLI


@pytest.fixture()
def drift_world(rng, tmp_path):
    """A registry with an enveloped model + in-distribution and shifted
    BIN datasets -- the shared stage for the CLI exit-code matrix."""
    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    in_dist = write_bin(tmp_path / "in.bin", data)
    shifted = write_bin(tmp_path / "shift.bin", data + np.float32(8.0))
    return {"reg": reg_dir, "in": in_dist, "shifted": shifted,
            "gm": gm, "data": data, "tmp": tmp_path}


def test_gmm_drift_exit_code_matrix(drift_world, capsys):
    """The 0/1/2 contract, dataset mode: clean gate -> 0 naming no
    failures; tripped gate -> 1 naming the metric; usage errors (bad
    spec, relative spec, missing --model, unknown model, stream-only
    flag on a dataset) -> 2."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    w = drift_world
    # in-distribution data scores PSI == 0 against its own envelope
    assert cli_main(["drift", w["in"], "--registry", w["reg"],
                     "--model", "m", "--fail-on", "psi>0.2"]) == 0
    out = capsys.readouterr().out
    assert "clean" in out and "psi" in out

    # shifted data trips the gate and NAMES the metric
    assert cli_main(["drift", w["shifted"], "--registry", w["reg"],
                     "--model", "m", "--fail-on", "psi>0.2",
                     "--fail-on", "ks>0.5"]) == 1
    out = capsys.readouterr().out
    assert "DRIFT psi:" in out and "DRIFT ks:" in out
    assert "2 gate(s) tripped" in out

    # --json carries the whole verdict machine-readably
    assert cli_main(["drift", w["shifted"], "--registry", w["reg"],
                     "--model", "m", "--fail-on", "psi>0.2",
                     "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["model"] == "m" and verdict["version"] == 1
    assert verdict["source"] == "dataset" and not verdict["clean"]
    assert verdict["psi"] > 0.2 and verdict["train_rows"] == 600
    assert verdict["failures"] and "psi" in verdict["failures"][0]

    # report-only (no gates) is exit 0 even on shifted data
    assert cli_main(["drift", w["shifted"], "--registry", w["reg"],
                     "--model", "m"]) == 0
    capsys.readouterr()

    # usage errors: all exit 2 with a reason on stdout
    cases = [
        (["drift", w["in"], "--registry", w["reg"], "--model", "m",
          "--fail-on", "totally_bogus>1"], "unknown drift metric"),
        (["drift", w["in"], "--registry", w["reg"], "--model", "m",
          "--fail-on", "psi>10%"], "absolute"),
        (["drift", w["in"], "--registry", w["reg"]], "need --model"),
        (["drift", w["in"], "--registry", w["reg"],
          "--model", "ghost"], "unknown model"),
        (["drift", str(w["tmp"] / "missing.bin"), "--registry",
          w["reg"], "--model", "m"], "gmm drift:"),
    ]
    for argv, needle in cases:
        assert cli_main(argv) == 2, argv
        assert needle in capsys.readouterr().out, argv


def test_gmm_drift_no_envelope_is_exit_2_with_backfill_hint(
        rng, drift_world, capsys):
    """A version without an envelope cannot be judged: exit 2 pointing
    at the --rebuild-envelope backfill, not a crash or a fake 0."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    w = drift_world
    gm_off, _ = fitted(rng, envelope=False)
    gm_off.to_registry(w["reg"], "bare")
    assert cli_main(["drift", w["in"], "--registry", w["reg"],
                     "--model", "bare", "--fail-on", "psi>0.2"]) == 2
    out = capsys.readouterr().out
    assert "no training envelope" in out
    assert "--rebuild-envelope" in out


def test_gmm_drift_rebuild_envelope_is_bit_identical(rng, drift_world,
                                                     capsys):
    """--rebuild-envelope backfills envelope.json for an envelope-less
    version WITHOUT touching model.npz or manifest.json (byte-hashed),
    after which the same data judges clean with psi == 0."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    w = drift_world
    gm_off, data = fitted(rng, envelope=False)
    gm_off.to_registry(w["reg"], "bare")
    vdir = w["tmp"] / "reg" / "bare" / "1"
    assert not (vdir / "envelope.json").exists()
    before = {f: hashlib.sha256((vdir / f).read_bytes()).hexdigest()
              for f in ("model.npz", "manifest.json")}
    dataset = write_bin(w["tmp"] / "bare.bin", data)

    # a stream target cannot rebuild (it only holds windowed sketches)
    assert cli_main(["drift", str(w["tmp"] / "s.jsonl"), "--registry",
                     w["reg"], "--model", "bare",
                     "--rebuild-envelope"]) == 2
    capsys.readouterr()

    assert cli_main(["drift", dataset, "--registry", w["reg"],
                     "--model", "bare", "--rebuild-envelope",
                     "--json"]) == 0
    rebuilt = json.loads(capsys.readouterr().out)
    assert rebuilt["rebuilt"] is True
    assert rebuilt["envelope"]["rows"] == len(data)
    assert (vdir / "envelope.json").exists()
    after = {f: hashlib.sha256((vdir / f).read_bytes()).hexdigest()
             for f in ("model.npz", "manifest.json")}
    assert after == before, "rebuild touched the immutable artifacts"

    assert cli_main(["drift", dataset, "--registry", w["reg"],
                     "--model", "bare", "--fail-on", "psi>0.2",
                     "--json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["clean"] and verdict["psi"] == 0.0


def test_gmm_drift_stream_mode_reaggregates_windows(rng, tmp_path,
                                                    capsys):
    """Stream mode: `gmm drift` merges a recorded stream's windowed
    sketches (exact merge) back into one window, infers the model from
    a single-model stream, and gates it -- shifted serve traffic exits
    1 naming psi; the same stream re-judged per --model also works."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    gm, data = fitted(rng)
    reg_dir = str(tmp_path / "reg")
    gm.to_registry(reg_dir, "m")
    server = GMMServer(ModelRegistry(reg_dir),
                       drift_interval_s=3600.0, drift_psi_threshold=0.2)
    stream = str(tmp_path / "serve.jsonl")
    rec = telemetry.RunRecorder(path=stream, run_id="drift-e2e")
    with telemetry.use(rec), rec:
        serve_traffic(server, data, shift=8.0, requests=6)
        server.flush_drift()
        serve_traffic(server, data, shift=8.0, requests=6)
        server.flush_drift()

    assert cli_main(["drift", stream, "--registry", reg_dir,
                     "--fail-on", "psi>0.2", "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["model"] == "m" and verdict["version"] == 1
    assert verdict["source"] == "stream"
    assert verdict["window_rows"] == 480  # both windows re-aggregated
    assert verdict["psi"] > 0.2
    assert "psi" in verdict["failures"][0]

    # window_rows is a gateable metric (catch an empty serve session)
    assert cli_main(["drift", stream, "--registry", reg_dir,
                     "--fail-on", "window_rows<100000"]) == 1
    capsys.readouterr()
    # a stream with no drift events is a usage error, not a clean pass
    empty = str(tmp_path / "empty.jsonl")
    with open(empty, "w") as f:
        f.write(json.dumps({"event": "run_start", "schema": 1,
                            "ts": 0.0, "run_id": "x"}) + "\n")
    assert cli_main(["drift", empty, "--registry", reg_dir]) == 2
    assert "no drift events" in capsys.readouterr().out


# -------------------------------------------------- top / timeline / fleet


def test_report_follow_renders_drift_rollup(rng, tmp_path):
    """`gmm top`'s renderer shows the drift rollup: window count, the
    worst model's PSI/KS, and the alarm count when alarms fired."""
    from cuda_gmm_mpi_tpu.telemetry.report import render_follow

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path), "m")
    server = GMMServer(ModelRegistry(str(tmp_path)),
                       drift_interval_s=3600.0, drift_psi_threshold=0.2)
    stream = []
    rec = telemetry.RunRecorder(stream=_StreamSink(stream))
    with telemetry.use(rec):
        serve_traffic(server, data, shift=8.0, requests=6)
        server.flush_drift()
    text = render_follow(stream)
    assert "drift: 1 window(s)" in text
    assert "worst psi" in text and "(m)" in text
    assert "1 ALARM(s)" in text

    # the static `gmm report` renders the same windows under Serving:
    # latest window per model@version plus the alarm-count line
    from cuda_gmm_mpi_tpu.telemetry.report import render_report
    static = render_report(stream)
    assert "drift m@1: psi " in static
    assert "(1 window(s)) ALARM" in static
    assert "1 drift alarm(s) (psi threshold 0.2)" in static

    # quiet windows render without the alarm suffix
    server2 = GMMServer(ModelRegistry(str(tmp_path)),
                        drift_interval_s=3600.0, drift_psi_threshold=0.2)
    quiet = []
    rec2 = telemetry.RunRecorder(stream=_StreamSink(quiet))
    with telemetry.use(rec2):
        serve_traffic(server2, data, shift=0.0, requests=6)
        server2.flush_drift()
    text = render_follow(quiet)
    assert "drift: 1 window(s)" in text and "ALARM" not in text


def test_timeline_renders_drift_counters_and_alarm_instant(rng,
                                                           tmp_path):
    """`gmm timeline`: drift windows become per-model PSI/KS counter
    tracks and drift_alarm becomes an instant, and the trace validates."""
    from cuda_gmm_mpi_tpu.telemetry.timeline import (build_timeline,
                                                     validate_trace)

    gm, data = fitted(rng)
    gm.to_registry(str(tmp_path / "reg"), "m")
    server = GMMServer(ModelRegistry(str(tmp_path / "reg")),
                       drift_interval_s=3600.0, drift_psi_threshold=0.2)
    stream = str(tmp_path / "serve.jsonl")
    rec = telemetry.RunRecorder(path=stream, run_id="drift-tl")
    with telemetry.use(rec), rec:
        serve_traffic(server, data, shift=8.0, requests=6)
        server.flush_drift()
    doc = build_timeline([stream])
    assert validate_trace(doc) == []
    events = doc["traceEvents"]
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert "drift psi (m)" in counters and "drift ks (m)" in counters
    psi_track = [e for e in events
                 if e["ph"] == "C" and e["name"] == "drift psi (m)"]
    assert psi_track[0]["args"]["psi"] > 0.2
    instants = [e for e in events if e["ph"] == "i"
                and "drift_alarm" in e["name"]]
    assert instants, "drift_alarm did not render as an instant"


def test_export_fleet_republishes_tenant_envelopes(rng, tmp_path):
    """S1: export_fleet carries per-tenant envelope.json sidecars from a
    fleet out-dir into the registry versions it publishes."""
    from cuda_gmm_mpi_tpu.io import write_summary
    from cuda_gmm_mpi_tpu.tenancy import TenantSpec, fit_fleet

    data, _ = make_blobs(rng, n=300, d=3, k=2, dtype=np.float64)
    spec = TenantSpec("acme", data, 2)
    fleet = fit_fleet([spec], GMMConfig(min_iters=2, max_iters=2,
                                        chunk_size=256, dtype="float64"))
    tr = fleet["acme"]
    assert tr.result.envelope is not None  # fleet fits sketch too
    assert tr.result.envelope["score"]["count"] == 300

    out = tmp_path / "out"
    out.mkdir()
    write_summary(str(out / "acme.summary"), tr.result)
    env_path = out / "acme.envelope.json"
    env_path.write_text(json.dumps(tr.result.envelope, sort_keys=True))
    (out / "fleet.json").write_text(json.dumps({
        "schema": 1,
        "tenants": [{"name": "acme", "dropped": False,
                     "summary": str(out / "acme.summary"),
                     "envelope": str(env_path),
                     "covariance_type": "full", "dtype": "float64"}],
    }))
    reg = ModelRegistry(str(tmp_path / "reg"))
    audit = reg.export_fleet(str(out))
    row = {r["name"]: r for r in audit}["acme"]
    assert row["version"] == 1 and row["envelope"] is True
    republished = reg.load_envelope("acme")
    assert republished == tr.result.envelope
