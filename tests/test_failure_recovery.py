"""Failure recovery: SIGKILL a sweep mid-flight, resume from the checkpoint.

The reference is fail-fast only (SURVEY.md SS5.3): a dead rank kills the MPI
job and the entire 100-iteration x K-sweep restarts from nothing. Here the
orbax sweep checkpoints (utils/checkpoint.py) must survive an actual
process kill -- not just the polite same-process resume of test_aux -- and
the resumed run must finish with the same answer as an uninterrupted one.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

def _steps_on_disk(sweep_dir):
    """Completed checkpoint steps: orbax digit-dirs (host sweep) or
    ``<step>.npz`` files (fused sweep's callback-safe format)."""
    if not os.path.isdir(sweep_dir):
        return []
    return [d for d in os.listdir(sweep_dir)
            if d.isdigit() or (d.endswith(".npz") and d[:-4].isdigit())]


def test_checkpoint_eio_retry_survives(tmp_path, rng):
    """A transient EIO during a checkpoint write is retried with jittered
    backoff instead of killing the run: the sweep completes, telemetry
    records the io_retry events, and the checkpoints are durable
    (testing.faults checkpoint_eio injection; satellite of ISSUE 3)."""
    import numpy as np

    from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm
    from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
    from cuda_gmm_mpi_tpu.testing import faults

    from .conftest import make_blobs

    data, _ = make_blobs(rng, n=1024, d=3, k=3)
    ck = str(tmp_path / "ck")
    mf = tmp_path / "m.jsonl"
    with faults.use({"checkpoint_eio": {"times": 2}}) as plan:
        r = fit_gmm(data, 4, 2, config=GMMConfig(
            min_iters=3, max_iters=3, chunk_size=256, dtype="float64",
            checkpoint_dir=ck, metrics_file=str(mf)))
    # the first save consumed both firings across its retry schedule:
    # attempts 1 and 2 failed, attempt 3 (budget spent) succeeded
    assert plan.fired["checkpoint_eio"] == 2
    assert r.health["io_retries"] >= 2
    assert r.health["flags"] == 0  # an IO fault is not a numerical fault
    # every sweep step still checkpointed durably (retry succeeded)
    assert len(_steps_on_disk(os.path.join(ck, "sweep"))) >= 1
    records = read_stream(str(mf))
    assert validate_stream(records) == []
    retries = [x for x in records if x["event"] == "io_retry"]
    assert [x["attempt"] for x in retries] == [1, 2]
    for x in retries:
        assert x["op"] in ("save", "save_local")
        assert not x["gave_up"] and x["delay_s"] > 0
        assert "injected checkpoint_eio" in x["error"]


def test_checkpoint_eio_exhausted_skips_save_loudly(tmp_path, rng):
    """When every bounded retry fails, the save is SKIPPED (a missing
    checkpoint only degrades resume granularity) and the run still
    completes -- with a gave_up io_retry record, not a crash."""
    from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.testing import faults

    from .conftest import make_blobs

    data, _ = make_blobs(rng, n=1024, d=3, k=3)
    mf = tmp_path / "m.jsonl"
    with faults.use({"checkpoint_eio": {"step": 0, "times": 3}}):
        r = fit_gmm(data, 4, 2, config=GMMConfig(
            min_iters=3, max_iters=3, chunk_size=256, dtype="float64",
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_retries=2,
            metrics_file=str(mf)))
    assert np.isfinite(r.final_loglik)  # the run survived
    retries = [x for x in read_stream(str(mf))
               if x["event"] == "io_retry"]
    assert retries and retries[-1]["gave_up"]
    # later steps (no fault armed) checkpointed normally
    assert len(_steps_on_disk(str(tmp_path / "ck" / "sweep"))) >= 1


WORKER = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
mesh = sys.argv[2] if len(sys.argv) > 2 else ""
mode = sys.argv[3] if len(sys.argv) > 3 else ""
fused = mode == "fused"
if mesh:
    from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices
    force_cpu_devices(8)
jax.config.update("jax_enable_x64", True)
import numpy as np
from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm

ckdir = sys.argv[1]
rng = np.random.default_rng(77)
centers = rng.normal(scale=9.0, size=(4, 3))
# The fused path's callback-safe npz saves are near-instant, so its sweep
# needs enough real work that SIGKILL can land mid-run (the host sweep's
# collective orbax saves throttle it naturally).
n, iters = (60_000, 40) if fused else (4000, 6)
data = (centers[rng.integers(0, 4, n)]
        + rng.normal(size=(n, 3))).astype(np.float64)
cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=512,
                dtype="float64",
                checkpoint_dir=ckdir, enable_print=True,
                fused_sweep=fused,
                stream_events=(mode == "stream"),
                mesh_shape=(tuple(int(x) for x in mesh.split(","))
                            if mesh else None))
r = fit_gmm(data, 12, 2, config=cfg)
print(json.dumps({
    "ideal_k": r.ideal_num_clusters,
    "min_rissanen": r.min_rissanen,
    "final_loglik": r.final_loglik,
    "means": np.asarray(r.means).tolist(),
    "sweep_ks": [int(row[0]) for row in r.sweep_log],
}))
"""


def _spawn(ckdir: str, mesh: str = "", fused: bool = False,
           mode: str = ""):
    from .conftest import worker_env

    if fused:
        mode = "fused"
    extra = [mesh, mode] if mode else ([mesh] if mesh else [])
    return subprocess.Popen(
        [sys.executable, "-c", WORKER, ckdir, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=worker_env(),
        text=True,
    )


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["", "4,2"])
def test_sigkill_mid_sweep_then_resume(tmp_path, mesh):
    """Kill/resume for the host-driven sweep -- plain single device AND a
    (4,2) sharded mesh (the deployment shape the reference ran on; round-3
    closure of 'no kill/resume test exists with mesh_shape set')."""
    ck = str(tmp_path / "ck")
    sweep_dir = os.path.join(ck, "sweep")

    # Run 1: killed (SIGKILL, no cleanup chance) once >= 2 checkpoint steps
    # exist but the 11-step sweep is far from done.
    p = _spawn(ck, mesh)
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            steps = _steps_on_disk(sweep_dir)
            if len(steps) >= 2:
                break
            if p.poll() is not None:
                out, err = p.communicate()
                raise AssertionError(
                    f"worker exited before kill (rc={p.returncode}):\n"
                    f"{out}\n{err[-3000:]}"
                )
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared within timeout")
        os.kill(p.pid, signal.SIGKILL)
    finally:
        if p.poll() is None:  # error path: don't leak a live worker
            p.kill()
        p.wait(timeout=60)
    assert p.returncode != 0  # really died

    # Run 2: resumes from the surviving checkpoint and completes.
    from .conftest import communicate_or_kill

    p2 = _spawn(ck, mesh)
    out, err = communicate_or_kill(p2, timeout=600)
    assert p2.returncode == 0, f"resume failed:\n{out}\n{err[-3000:]}"
    resumed = json.loads(out.splitlines()[-1])
    # The combined sweep log covers all 11 Ks (restored rows + new rows)...
    assert len(resumed["sweep_ks"]) == 11
    # ...but THIS process must not have redone the checkpointed Ks: verbose
    # mode prints one "K=..." line per EM run executed in-process.
    ran_here = [l for l in out.splitlines() if l.startswith("K=")]
    assert 0 < len(ran_here) < 11, out
    assert resumed["ideal_k"] >= 2

    # Uninterrupted reference run (fresh dir) for ground truth.
    p3 = _spawn(str(tmp_path / "ck_ref"), mesh)
    out3, err3 = communicate_or_kill(p3, timeout=600)
    assert p3.returncode == 0, f"reference run failed:\n{out3}\n{err3[-3000:]}"
    ref = json.loads(out3.splitlines()[-1])

    assert resumed["ideal_k"] == ref["ideal_k"]
    np.testing.assert_allclose(
        resumed["min_rissanen"], ref["min_rissanen"], rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(resumed["means"]), np.asarray(ref["means"]),
        rtol=1e-7, atol=1e-9,
    )


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["", "8,1"])
def test_sigkill_streaming_sweep_then_resume(tmp_path, mesh):
    """Kill/resume for the out-of-core streaming path: the host-driven loop
    checkpoints identically, and a killed streaming sweep resumes to the
    uninterrupted answer. The "8,1" case streams blocks sharded over a
    local data mesh (round 4)."""
    ck = str(tmp_path / "ck")
    sweep_dir = os.path.join(ck, "sweep")
    p = _spawn(ck, mesh, mode="stream")
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            steps = _steps_on_disk(sweep_dir)
            if len(steps) >= 2:
                break
            if p.poll() is not None:
                out, err = p.communicate()
                raise AssertionError(
                    f"worker exited before kill (rc={p.returncode}):\n"
                    f"{out}\n{err[-3000:]}"
                )
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared within timeout")
        os.kill(p.pid, signal.SIGKILL)
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=60)
    assert p.returncode != 0

    from .conftest import communicate_or_kill

    p2 = _spawn(ck, mesh, mode="stream")
    out, err = communicate_or_kill(p2, timeout=600)
    assert p2.returncode == 0, f"resume failed:\n{out}\n{err[-3000:]}"
    resumed = json.loads(out.splitlines()[-1])
    assert len(resumed["sweep_ks"]) == 11
    ran_here = [l for l in out.splitlines() if l.startswith("K=")]
    assert 0 < len(ran_here) < 11, out

    p3 = _spawn(str(tmp_path / "ck_ref"), mesh, mode="stream")
    out3, err3 = communicate_or_kill(p3, timeout=600)
    assert p3.returncode == 0, f"reference run failed:\n{out3}\n{err3[-3000:]}"
    ref = json.loads(out3.splitlines()[-1])
    assert resumed["ideal_k"] == ref["ideal_k"]
    np.testing.assert_allclose(resumed["min_rissanen"], ref["min_rissanen"],
                               rtol=1e-9)


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["", "4,2"])
def test_sigkill_fused_sweep_then_resume(tmp_path, mesh):
    """Kill/resume against the FUSED whole-sweep-on-device path: per-K
    checkpoints are emitted from inside the single device program via the
    ordered io_callback hook (--fused-sweep --checkpoint-dir, round-3
    composability item). The "4,2" case runs the sweep under shard_map on
    a data x cluster mesh -- emission fires per device shard with the
    cluster axis all-gathered (round-4: fused sweep + checkpointing now
    compose on sharded models too)."""
    from .conftest import communicate_or_kill

    ck = str(tmp_path / "ck")
    sweep_dir = os.path.join(ck, "sweep")

    p = _spawn(ck, mesh, fused=True)
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            steps = _steps_on_disk(sweep_dir)
            if len(steps) >= 2:
                break
            if p.poll() is not None:
                out, err = p.communicate()
                raise AssertionError(
                    f"worker exited before kill (rc={p.returncode}):\n"
                    f"{out}\n{err[-3000:]}"
                )
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared within timeout")
        os.kill(p.pid, signal.SIGKILL)
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=60)
    assert p.returncode != 0

    p2 = _spawn(ck, mesh, fused=True)
    out, err = communicate_or_kill(p2, timeout=600)
    assert p2.returncode == 0, f"fused resume failed:\n{out}\n{err[-3000:]}"
    resumed = json.loads(out.splitlines()[-1])
    assert len(resumed["sweep_ks"]) == 11
    assert resumed["sweep_ks"][0] == 12  # restored rows kept

    p3 = _spawn(str(tmp_path / "ck_ref"), mesh, fused=True)
    out3, err3 = communicate_or_kill(p3, timeout=600)
    assert p3.returncode == 0, f"reference run failed:\n{out3}\n{err3[-3000:]}"
    ref = json.loads(out3.splitlines()[-1])

    assert resumed["ideal_k"] == ref["ideal_k"]
    np.testing.assert_allclose(
        resumed["min_rissanen"], ref["min_rissanen"], rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(resumed["means"]), np.asarray(ref["means"]),
        rtol=1e-7, atol=1e-9,
    )


CKPT_WORKER = os.path.join(os.path.dirname(__file__),
                           "multihost_ckpt_worker.py")


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["", "fused", "stream"],
                         ids=["host", "fused", "stream"])
def test_two_process_kill_one_rank_then_restart_both(tmp_path, mode):
    """Distributed fault tolerance on the reference's actual deployment
    shape (MPI cluster, README.txt:18): SIGKILL ONE rank mid-sweep (the
    other is taken down too, as a dead rank kills an MPI job), restart BOTH
    ranks, and the resumed multi-host run must reproduce the uninterrupted
    answer. ``fused`` runs the whole sweep as one device program per rank
    with checkpoints emitted through the ordered io_callback hook -- the
    multi-controller composition that used to fall back to the host-driven
    sweep (VERDICT r3 item 4). ``stream`` runs it out-of-core: each rank
    streams its host slice over its local shards (round 4)."""
    import socket

    from .conftest import communicate_or_kill, worker_env

    fused = mode == "fused"

    def spawn_pair(ckdir):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        extra = [mode] if mode else []
        return [
            subprocess.Popen(
                [sys.executable, CKPT_WORKER, str(i), "2", str(port), ckdir,
                 *extra],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=worker_env(), text=True,
            )
            for i in range(2)
        ]

    ck = str(tmp_path / "ck")
    sweep_dir = os.path.join(ck, "sweep")
    procs = spawn_pair(ck)
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            steps = _steps_on_disk(sweep_dir)
            if len(steps) >= 2:
                break
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    out, err = p.communicate()
                    raise AssertionError(
                        f"rank {i} exited before kill (rc={p.returncode}):\n"
                        f"{out}\n{err[-3000:]}"
                    )
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared within timeout")
        os.kill(procs[1].pid, signal.SIGKILL)  # one rank dies...
        time.sleep(1.0)
        os.kill(procs[0].pid, signal.SIGKILL)  # ...taking the job with it
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=60)

    # Restart BOTH ranks (fresh coordinator port): resume and complete.
    procs2 = spawn_pair(ck)
    outs = [communicate_or_kill(p, timeout=600) for p in procs2]
    for i, (p, (out, err)) in enumerate(zip(procs2, outs)):
        assert p.returncode == 0, \
            f"restarted rank {i} failed:\n{out}\n{err[-3000:]}"
    out0 = outs[0][0]
    line = [l for l in out0.splitlines() if l.startswith("RESULT ")][0]
    resumed = json.loads(line[len("RESULT "):])
    assert len(resumed["sweep_ks"]) == 9  # K=10..2
    if not fused:
        # Host sweep prints one "K=" line per EM run executed in-process;
        # the fused path echoes the whole (restored + new) device log, so
        # in-process work can't be counted from stdout there.
        ran_here = [l for l in out0.splitlines() if l.startswith("K=")]
        assert 0 < len(ran_here) < 9, out0

    # Ground truth: uninterrupted 2-process run in a fresh dir.
    procs3 = spawn_pair(str(tmp_path / "ck_ref"))
    outs3 = [communicate_or_kill(p, timeout=600) for p in procs3]
    for i, (p, (out, err)) in enumerate(zip(procs3, outs3)):
        assert p.returncode == 0, \
            f"reference rank {i} failed:\n{out}\n{err[-3000:]}"
    line3 = [l for l in outs3[0][0].splitlines()
             if l.startswith("RESULT ")][0]
    ref = json.loads(line3[len("RESULT "):])

    assert resumed["ideal_k"] == ref["ideal_k"]
    np.testing.assert_allclose(
        resumed["min_rissanen"], ref["min_rissanen"], rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(resumed["means"]), np.asarray(ref["means"]),
        rtol=1e-7, atol=1e-9,
    )
