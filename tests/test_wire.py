"""The x-gmm-rows binary row codec (serving/wire.py, rev v2.8).

The zero-copy data plane's wire contract (docs/SERVING.md "Binary
payloads"): a 16-byte little-endian header (magic GMR1, dtype code,
reserved zeros, D, N) followed by exactly N*D packed float32/float64
row values. The codec must round-trip bit-exactly, reject every
malformed frame LOUDLY (bad magic, unknown dtype, nonzero reserved
bytes, zero D, truncation, trailing bytes), and hand decoders a
read-only np.frombuffer view -- no float stringification anywhere.
"""

import struct

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.serving import wire


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_round_trip_bit_exact(dtype):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(37, 5)).astype(dtype)
    buf = wire.encode_rows(x)
    assert buf[:4] == wire.MAGIC
    assert len(buf) == wire.HEADER.size + x.nbytes
    y = wire.decode_rows(buf)
    assert y.dtype == dtype and y.shape == x.shape
    np.testing.assert_array_equal(np.asarray(y), x)


def test_one_dim_promotes_to_single_row():
    y = wire.decode_rows(wire.encode_rows(np.arange(4.0)))
    assert y.shape == (1, 4) and y.dtype == np.float64


def test_non_float_input_packs_as_float64():
    x = np.arange(12, dtype=np.int64).reshape(3, 4)
    y = wire.decode_rows(wire.encode_rows(x))
    assert y.dtype == np.float64
    np.testing.assert_array_equal(np.asarray(y), x.astype(np.float64))


def test_decoded_view_is_read_only():
    """decode_rows returns a view over the received buffer -- zero-copy
    means shared memory, so the view must be immutable."""
    y = wire.decode_rows(wire.encode_rows(np.ones((2, 3))))
    assert not y.flags.writeable
    with pytest.raises((ValueError, RuntimeError)):
        y[0, 0] = 7.0


def test_frame_bytes_matches_encoder():
    x = np.zeros((9, 3), np.float32)
    assert wire.frame_bytes(9, 3, np.float32) == len(wire.encode_rows(x))


def _valid_frame(n=4, d=3, dtype=np.float64):
    return bytearray(wire.encode_rows(np.ones((n, d), dtype)))


def test_bad_magic_rejected():
    buf = _valid_frame()
    buf[:4] = b"NOPE"
    with pytest.raises(wire.WireError, match="magic"):
        wire.decode_rows(bytes(buf))


def test_unknown_dtype_code_rejected():
    buf = _valid_frame()
    buf[4] = 9
    with pytest.raises(wire.WireError, match="dtype"):
        wire.decode_rows(bytes(buf))


def test_nonzero_reserved_bytes_rejected():
    """The reserved pad bytes must be zero -- a future header revision
    must fail loudly against this decoder, not be silently misread."""
    for off in (5, 6):
        buf = _valid_frame()
        buf[off] = 1
        with pytest.raises(wire.WireError, match="reserved"):
            wire.decode_rows(bytes(buf))


def test_zero_d_rejected():
    hdr = wire.HEADER.pack(wire.MAGIC, 0, 0, 0, 0, 1)
    with pytest.raises(wire.WireError, match="D"):
        wire.decode_rows(hdr + struct.pack("<d", 1.0))


def test_truncated_frame_rejected():
    buf = bytes(_valid_frame())
    for cut in (0, 3, wire.HEADER.size - 1, len(buf) - 1):
        with pytest.raises(wire.WireError):
            wire.decode_rows(buf[:cut])


def test_trailing_bytes_rejected():
    """Exact length both ways: a frame with bytes past N*D values is as
    corrupt as a short one (the socket protocol's length prefix and the
    HTTP body length must agree with the header)."""
    with pytest.raises(wire.WireError, match="trailing"):
        wire.decode_rows(bytes(_valid_frame()) + b"\x00")
