"""Telemetry subsystem: schema-validated JSONL streams from every path.

Acceptance contract (ISSUE 1): a fit with ``--metrics-file out.jsonl``
produces a schema-valid stream containing run_start, per-iteration
em_iter (loglik, delta, wall time), per-K em_done, and a run_summary with
the 7-category phase profile, compile/execute split, and the
metrics-registry snapshot -- for the in-memory, streaming, and
8-fake-device sharded paths (sharded records carrying process/device
tags); ``gmm report`` renders the stream alone; and the legacy stderr
surfaces (metrics_line, --profile) stay byte-compatible when no metrics
file is given.
"""

import json

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, telemetry
from cuda_gmm_mpi_tpu.cli import main as cli_main
from cuda_gmm_mpi_tpu.telemetry import (MetricsRegistry, RunRecorder,
                                        read_stream, validate_record,
                                        validate_stream)
from cuda_gmm_mpi_tpu.utils.profiling import CATEGORIES

from .conftest import make_blobs


@pytest.fixture
def csv_file(tmp_path, rng):
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    p = tmp_path / "events.csv"
    header = ",".join(f"d{i}" for i in range(3))
    rows = "\n".join(",".join(f"{v:.6f}" for v in row) for row in data)
    p.write_text(header + "\n" + rows + "\n")
    return str(p)


def _events(records):
    return [r["event"] for r in records]


def _check_stream(records, *, start_k, stop_k, path):
    """The shared acceptance assertions for one fit's stream."""
    assert validate_stream(records) == []
    ev = _events(records)
    assert ev[0] == "run_start" and ev[-1] == "run_summary"
    assert ev.count("run_start") == 1 and ev.count("run_summary") == 1

    start = records[0]
    assert start["path"] == path
    assert start["start_k"] == start_k and start["epsilon"] > 0

    ks = [r["k"] for r in records if r["event"] == "em_done"]
    assert ks == list(range(start_k, stop_k - 1, -1))
    for r in records:
        if r["event"] != "em_done":
            continue
        assert np.isfinite(r["loglik"]) and np.isfinite(r["score"])
        assert r["iters"] >= 1 and r["seconds"] >= 0

    iters = [r for r in records if r["event"] == "em_iter"]
    assert len(iters) == sum(
        r["iters"] for r in records if r["event"] == "em_done")
    for r in iters:
        assert np.isfinite(r["loglik"]) and np.isfinite(r["delta"])
        assert r["wall_s"] >= 0 and r["epsilon"] > 0
        assert r["timing"] in ("measured", "amortized")
    # per-K iteration indices restart at 0 and count up
    for k in set(r["k"] for r in iters):
        idx = [r["iter"] for r in iters if r["k"] == k]
        assert idx == list(range(len(idx)))

    merges = [r for r in records if r["event"] == "merge"]
    assert len(merges) == len(ks) - 1
    for r in merges:
        assert r["next_k"] == r["k_active"] - 1
        # compaction-stable merged-pair indices: positions in the
        # post-elimination compacted ordering
        assert len(r["pair"]) == 2
        assert 0 <= r["pair"][0] < r["pair"][1] < r["k_active"]

    rebuckets = [r for r in records if r["event"] == "rebucket"]
    for r in rebuckets:
        assert r["to_width"] < r["from_width"]
        assert r["k_active"] <= r["to_width"]

    summary = records[-1]
    buckets = summary.get("buckets")
    if buckets is not None:  # host-driven sweeps report their widths
        assert buckets["rebuckets"] == len(rebuckets)
        assert buckets["em_compiles"] == len(buckets["em_widths"])
        if buckets["mode"] == "off":
            assert not rebuckets
    prof = summary["phase_profile"]
    assert set(CATEGORIES) <= set(prof["seconds"])
    assert set(CATEGORIES) <= set(prof["counts"])
    comp = summary["compile"]
    # Measured-only since rev v2.5: the est_compile_s heuristic is gone.
    assert set(comp) == {"first_call_s", "warm_call_s"}
    assert comp["first_call_s"] > 0
    counters = summary["metrics"]["counters"]
    assert counters["em_iters"] == len(iters)
    assert counters["h2d_bytes"] > 0
    assert summary["metrics"]["series"]["active_k"] == ks
    assert summary["total_iters"] == len(iters)
    return records


def test_cli_metrics_in_memory(csv_file, tmp_path):
    path = str(tmp_path / "m.jsonl")
    rc = cli_main(["4", csv_file, str(tmp_path / "o"), "2",
                   "--min-iters=3", "--max-iters=3", "--chunk-size=128",
                   f"--metrics-file={path}"])
    assert rc == 0
    recs = _check_stream(read_stream(path), start_k=4, stop_k=2,
                         path="in-memory")
    assert all(r["process"] == 0 for r in recs)
    # in-memory EM is a single dispatch: per-iteration walls are amortized
    assert all(r["timing"] == "amortized"
               for r in recs if r["event"] == "em_iter")


def test_cli_metrics_streaming(csv_file, tmp_path):
    path = str(tmp_path / "m.jsonl")
    rc = cli_main(["3", csv_file, str(tmp_path / "o"), "2",
                   "--min-iters=3", "--max-iters=3", "--chunk-size=128",
                   "--stream-events", f"--metrics-file={path}"])
    assert rc == 0
    recs = _check_stream(read_stream(path), start_k=3, stop_k=2,
                         path="streaming")
    # host-driven loop: REAL per-iteration walls
    assert all(r["timing"] == "measured"
               for r in recs if r["event"] == "em_iter")
    flushes = [r for r in recs if r["event"] == "chunk_flush"]
    assert flushes and all(r["bytes"] > 0 for r in flushes)
    # per-K passes: initial E-step (iter 0) + one per EM iteration, each
    # covering every chunk of the 400-event/128-chunk grid
    blocks_per_pass = {r["block"] for r in flushes}
    assert blocks_per_pass == {0, 1, 2, 3}


def test_cli_metrics_sharded_mesh8(csv_file, tmp_path):
    """8-fake-device sharded path: same stream contract, records carry
    the process/mesh/path tags (the multi-host stream's self-description;
    in-process the rank is 0 and the mesh is [8, 1])."""
    path = str(tmp_path / "m.jsonl")
    rc = cli_main(["3", csv_file, str(tmp_path / "o"), "2",
                   "--min-iters=3", "--max-iters=3", "--chunk-size=32",
                   "--mesh=8", f"--metrics-file={path}"])
    assert rc == 0
    recs = _check_stream(read_stream(path), start_k=3, stop_k=2,
                         path="sharded")
    assert recs[0]["local_device_count"] == 8
    for r in recs:
        assert r["process"] == 0
        if r["event"] in ("em_iter", "em_done"):
            assert r["mesh"] == [8, 1] and r["path"] == "sharded"


def test_fused_sweep_emits_per_k_records(csv_file, tmp_path):
    """The fused whole-sweep device program reports per-K granularity:
    em_done records with REAL per-K seconds (emission-arrival deltas) and
    no em_iter rows (its EM iterations never touch the host)."""
    path = str(tmp_path / "m.jsonl")
    rc = cli_main(["4", csv_file, str(tmp_path / "o"), "2",
                   "--min-iters=3", "--max-iters=3", "--chunk-size=128",
                   "--fused-sweep", f"--metrics-file={path}"])
    assert rc == 0
    recs = read_stream(path)
    assert validate_stream(recs) == []
    ev = _events(recs)
    assert ev.count("em_done") == 3 and ev.count("em_iter") == 0
    # fixed-width by design: the fused program never rebuckets
    assert ev.count("rebucket") == 0
    assert "buckets" not in recs[-1]
    assert recs[0]["fused_sweep"] is True
    assert all(r["seconds"] > 0 for r in recs if r["event"] == "em_done")
    assert recs[-1]["event"] == "run_summary"
    assert recs[-1]["metrics"]["series"]["active_k"] == [4, 3, 2]


def test_gmm_report_renders_stream_alone(csv_file, tmp_path, capsys):
    """`gmm report out.jsonl` renders the phase-profile table and loglik
    trajectory from the stream alone (no pickle/state needed)."""
    path = str(tmp_path / "m.jsonl")
    assert cli_main(["4", csv_file, str(tmp_path / "o"), "2",
                     "--min-iters=3", "--max-iters=3", "--chunk-size=128",
                     f"--metrics-file={path}"]) == 0
    capsys.readouterr()
    assert cli_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "Phase profile" in out
    for cat in CATEGORIES:
        assert cat in out
    assert "Loglik trajectory" in out
    assert "Model-order sweep" in out
    assert "Compile/execute split" in out
    # trajectory rows present for every K of the sweep
    for k in (4, 3, 2):
        assert f"\n  {k:>5d} " in out or f"  {k:>5d} " in out
    # --validate passes on a healthy stream; missing files are usage errors
    assert cli_main(["report", path, "--validate"]) == 0
    assert cli_main(["report", str(tmp_path / "nope.jsonl")]) == 2


def test_metrics_file_fails_fast_and_predict_rejects(csv_file, tmp_path):
    assert cli_main(["3", csv_file, str(tmp_path / "o"), "2",
                     f"--metrics-file={tmp_path}/no/such/dir/m.jsonl"]) == 1
    out = str(tmp_path / "m")
    assert cli_main(["3", csv_file, out, "3", "--min-iters=2",
                     "--max-iters=2", "--chunk-size=128"]) == 0
    assert cli_main(["3", csv_file, str(tmp_path / "p"),
                     f"--predict-from={out}.summary",
                     f"--metrics-file={tmp_path}/m.jsonl"]) == 1


def test_library_fit_and_restarts_share_one_stream(tmp_path, rng):
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    path = str(tmp_path / "m.jsonl")
    cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=128, n_init=2,
                    metrics_file=path, dtype="float64")
    fit_gmm(data, 3, 2, cfg)
    recs = read_stream(path)
    assert validate_stream(recs) == []
    assert len({r["run_id"] for r in recs}) == 1
    assert _events(recs).count("run_start") == 2  # one per init
    assert _events(recs).count("run_summary") == 2
    assert sorted({r["init"] for r in recs if "init" in r}) == [0, 1]
    summ = [r for r in recs if r["event"] == "run_summary"][-1]
    assert summ["metrics"]["counters"]["restarts"] == 1
    # the stream closes with the winner audit (restart_select, rev v1.4)
    sel = [r for r in recs if r["event"] == "restart_select"]
    assert len(sel) == 1 and len(sel[0]["scores"]) == 2
    assert sel[0]["winner"] in (0, 1)


def test_no_metrics_file_means_no_stream_and_same_stderr(tmp_path, rng,
                                                        capsys):
    """Off by default: no recorder activates, and metrics_line's stderr
    format is byte-stable (the backward-compatibility contract for
    existing scrapers)."""
    from cuda_gmm_mpi_tpu.utils.logging_ import metrics_line

    data, _ = make_blobs(rng, n=300, d=3, k=2, dtype=np.float32)
    fit_gmm(data, 2, 2, GMMConfig(min_iters=2, max_iters=2, chunk_size=128))
    assert not telemetry.current().active

    rec = metrics_line("em_done", k=3, loglik=-1.5, iters=7)
    err = capsys.readouterr().err.strip().splitlines()[-1]
    parsed = json.loads(err)
    assert parsed == rec
    assert list(parsed) == ["event", "ts", "k", "loglik", "iters"]
    assert "schema" not in parsed and "run_id" not in parsed


def test_registry_and_schema_units():
    reg = MetricsRegistry()
    reg.count("a")
    reg.count("a", 4)
    reg.gauge("g", 7)
    reg.observe("h", 2.0)
    reg.observe("h", 4.0)
    reg.series("s", 1)
    reg.series("s", 2)
    snap = reg.snapshot()
    assert snap["counters"]["a"] == 5
    assert snap["gauges"]["g"] == 7
    assert snap["histograms"]["h"] == {"count": 2, "sum": 6.0,
                                       "min": 2.0, "max": 4.0}
    assert snap["series"]["s"] == [1, 2]

    ok = {"event": "merge", "schema": 1, "ts": 0.0, "run_id": "x",
          "process": 0, "k_active": 3, "next_k": 2, "min_distance": 0.5}
    assert validate_record(ok) == []
    bad = dict(ok, event="nope")
    assert any("unknown event" in e for e in validate_record(bad))
    missing = {k: v for k, v in ok.items() if k != "min_distance"}
    assert any("min_distance" in e for e in validate_record(missing))
    assert validate_record([1, 2]) != []

    reb = {"event": "rebucket", "schema": 1, "ts": 0.0, "run_id": "x",
           "process": 0, "k_active": 4, "from_width": 8, "to_width": 4}
    assert validate_record(reb) == []
    for f in ("k_active", "from_width", "to_width"):
        partial = {k: v for k, v in reb.items() if k != f}
        assert any(f in e for e in validate_record(partial)), f


def test_compile_event_schema_and_profile_rollup():
    """rev v2.2 drift guards: the ``compile`` event validates with its
    two-field envelope, the enriched cost/memory fields are DECLARED
    optionals (readers may rely on the names), and run_summary /
    serve_summary both carry the optional ``profile`` rollup."""
    from cuda_gmm_mpi_tpu.telemetry.schema import EVENT_FIELDS

    comp = {"event": "compile", "schema": 1, "ts": 0.0, "run_id": "x",
            "process": 0, "source": "aot", "seconds": 0.25}
    assert validate_record(comp) == []
    enriched = dict(comp, site="em", phase="sweep", key="em:0",
                    flops=1e6, bytes_accessed=2e6, argument_bytes=100,
                    output_bytes=50, temp_bytes=9, generated_code_bytes=1)
    assert validate_record(enriched) == []
    assert any("seconds" in e for e in validate_record(
        {k: v for k, v in comp.items() if k != "seconds"}))
    req, opt = EVENT_FIELDS["compile"]
    assert set(req) == {"source", "seconds"}
    for f in ("site", "phase", "key", "flops", "bytes_accessed",
              "argument_bytes", "output_bytes", "temp_bytes",
              "generated_code_bytes"):
        assert f in opt, f
    assert "profile" in EVENT_FIELDS["run_summary"][1]
    assert "profile" in EVENT_FIELDS["serve_summary"][1]
    # rev v2.7: serve_summary's optional http rollup (the block `gmm
    # diff` gates on) is a declared name, not an ad-hoc extra
    assert "http" in EVENT_FIELDS["serve_summary"][1]


def test_ambient_recorder_is_reused(tmp_path, rng):
    """A library-activated recorder wins over config.metrics_file: the fit
    rides the ambient stream instead of truncating a second file."""
    data, _ = make_blobs(rng, n=300, d=3, k=2, dtype=np.float32)
    path = str(tmp_path / "ambient.jsonl")
    other = str(tmp_path / "ignored.jsonl")
    with telemetry.use(RunRecorder(path)) as rec, rec:
        fit_gmm(data, 2, 2, GMMConfig(min_iters=2, max_iters=2,
                                      chunk_size=128, metrics_file=other))
    recs = read_stream(path)
    assert validate_stream(recs) == []
    assert _events(recs).count("run_summary") == 1
    import os

    assert not os.path.exists(other)


def test_em_iter_trajectory_matches_final_loglik(tmp_path, rng):
    """The device-captured trajectory's last row IS the em_done loglik,
    and deltas telescope: iteration-0's base is the initial E-step."""
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float64)
    path = str(tmp_path / "m.jsonl")
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                    dtype="float64", metrics_file=path)
    r = fit_gmm(data, 3, 3, cfg)
    recs = read_stream(path)
    iters = [x for x in recs if x["event"] == "em_iter"]
    done = [x for x in recs if x["event"] == "em_done"][0]
    assert iters[-1]["loglik"] == pytest.approx(done["loglik"], rel=1e-12)
    assert iters[-1]["loglik"] == pytest.approx(r.final_loglik, rel=1e-12)
    # monotone non-decreasing loglik across the trajectory (EM guarantee)
    lls = [x["loglik"] for x in iters]
    assert all(b >= a - 1e-9 for a, b in zip(lls, lls[1:]))


def test_every_emitted_event_kind_is_declared_in_schema():
    """Static drift guard (the SESSION_BAND<->PERF.md test's spirit,
    applied to telemetry): scan the package for `<recorder>.emit("<kind>",
    ...)` call sites and assert every emitted kind has a field table in
    telemetry/schema.py -- a new event wired into production code without
    a schema entry (the v1.7 omission shape) fails HERE, not in whichever
    integration test happens to validate a stream containing it."""
    import pathlib
    import re

    import cuda_gmm_mpi_tpu
    from cuda_gmm_mpi_tpu.telemetry.schema import EVENT_FIELDS

    pkg = pathlib.Path(cuda_gmm_mpi_tpu.__file__).parent
    # \s* spans newlines: multi-line emit( calls still match.
    pat = re.compile(r'\.emit\(\s*["\']([a-z_]+)["\']')
    found = {}
    for py in sorted(pkg.rglob("*.py")):
        for m in pat.finditer(py.read_text(encoding="utf-8")):
            found.setdefault(m.group(1), set()).add(
                str(py.relative_to(pkg)))
    assert found, "no emit() call sites found -- the scan pattern rotted"
    # the known call-site spread: if these move wholesale the pattern
    # is probably matching the wrong thing
    assert "run_start" in found and "serve_request" in found
    # rev v2.6: the lifecycle plane's kinds are pinned BY NAME in both
    # directions -- `lifecycle` from the controller, `registry_torn`
    # from the registry's torn-version walk-back
    assert "lifecycle" in found and "registry_torn" in found
    assert any(p.endswith("lifecycle/controller.py")
               for p in found["lifecycle"])
    assert any(p.endswith("serving/registry.py")
               for p in found["registry_torn"])
    # rev v2.7: the network tier's kinds, pinned by name and call site
    # in both directions -- http_request from the front end, the worker
    # lifecycle pair from the pool supervisor
    assert "http_request" in found
    assert "worker_spawn" in found and "worker_exit" in found
    assert any(p.endswith("serving/http.py") for p in found["http_request"])
    assert any(p.endswith("serving/pool.py") for p in found["worker_spawn"])
    assert any(p.endswith("serving/pool.py") for p in found["worker_exit"])
    undeclared = {k: sorted(v) for k, v in found.items()
                  if k not in EVENT_FIELDS}
    assert undeclared == {}, (
        f"emit() call sites with no telemetry/schema.py entry: "
        f"{undeclared}")
    # and the inverse: a declared event nobody can emit is dead schema
    unemitted = sorted(set(EVENT_FIELDS) - set(found))
    assert unemitted == [], (
        f"schema declares events no code emits: {unemitted}")
