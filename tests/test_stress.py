"""Degenerate-regime stress tests (SURVEY.md SS7 "hard parts").

The reference survives singular covariances at large K via three guards:
avgvar diagonal loading (gaussian_kernel.cu:673-675), empty-cluster
identity reset (gaussian.cu:669-678), and the pi floor
(gaussian_kernel.cu:186). These tests drive the regimes where those guards
are load-bearing and assert the fit stays finite and sane.
"""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm

from .conftest import make_blobs


def cfg(**kw):
    base = dict(min_iters=5, max_iters=5, chunk_size=128, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


def assert_finite_result(r):
    assert np.isfinite(r.final_loglik)
    assert np.isfinite(r.min_rissanen)
    for name in ("means", "covariances", "weights"):
        a = getattr(r, name)
        assert np.isfinite(a).all(), f"non-finite {name}"


def test_k_close_to_n():
    """Many clusters, few events: most clusters start near-empty."""
    rng = np.random.default_rng(0)
    data = rng.normal(size=(60, 3))
    r = fit_gmm(data, 32, 0, config=cfg())
    assert_finite_result(r)
    assert 1 <= r.ideal_num_clusters <= 32


def test_duplicate_points_and_constant_dimension():
    """Exact duplicates + a zero-variance dimension: every per-cluster
    covariance is singular along that axis; only avgvar loading keeps the
    Cholesky alive."""
    rng = np.random.default_rng(1)
    base = rng.normal(size=(50, 2))
    data = np.repeat(base, 8, axis=0)  # 400 events, each 8x duplicated
    data = np.concatenate([data, np.full((400, 1), 3.25)], axis=1)  # const dim
    r = fit_gmm(data, 6, 2, config=cfg())
    assert_finite_result(r)
    # the constant dimension's mean must be recovered exactly
    np.testing.assert_allclose(r.means[:, 2], 3.25, atol=1e-6)


def test_all_identical_points():
    """Zero total variance: avgvar = 0, covariance identically zero.
    The identity-reset + pi-floor guards must keep the state finite."""
    data = np.full((200, 3), 7.0)
    r = fit_gmm(data, 3, 3, config=cfg())
    for name in ("means", "weights"):
        assert np.isfinite(getattr(r, name)).all()
    np.testing.assert_allclose(r.means, 7.0, atol=1e-5)


def test_extreme_offset_float32_shift_equivariant():
    """Events at a huge offset in float32: without the global centering the
    expanded quadratic form x.Rinv.x - 2b.x + c catastrophically cancels;
    with it (default) the offset run must track the zero-offset run --
    EM is shift-equivariant, so loglik and (shifted) means must agree.
    (Which local optimum EM lands in is seeding's business, not this test's.)
    """
    rng = np.random.default_rng(3)
    data64, _ = make_blobs(rng, n=1000, d=3, k=3)
    c = cfg(dtype="float32", min_iters=10, max_iters=10)
    r0 = fit_gmm(data64.astype(np.float32), 3, 3, config=c)
    r1 = fit_gmm((data64 + 1.0e5).astype(np.float32), 3, 3, config=c)
    assert_finite_result(r1)
    # float32 resolution at 1e5 is ~0.012 per coordinate; the two runs see
    # slightly different (quantized) data, so agreement is approximate.
    np.testing.assert_allclose(r1.final_loglik, r0.final_loglik, rtol=5e-4)
    np.testing.assert_allclose(r1.means - 1.0e5, r0.means, atol=0.1)


def test_single_cluster_k1():
    """K=1 degenerate sweep: seeding divides by K-1 (guarded), no merges."""
    rng = np.random.default_rng(4)
    data = rng.normal(loc=2.0, size=(300, 4))
    r = fit_gmm(data, 1, 1, config=cfg())
    assert_finite_result(r)
    assert r.ideal_num_clusters == 1
    np.testing.assert_allclose(r.means[0], data.mean(0), atol=0.05)
    np.testing.assert_allclose(r.weights[0], 1.0, atol=1e-6)


def test_univariate_d1():
    """D=1 (univariate mixture): R is [K,1,1], the feature expansion is a
    single column, the merge distance reduces to a scalar formula. The
    reference never exercises this (NUM_DIMENSIONS is a compile-time 21+);
    a runtime-D framework must not break on the smallest case. The sweep
    must also recover the true K=2."""
    rng = np.random.default_rng(5)
    x = np.concatenate([rng.normal(-5, 1.0, 600),
                        rng.normal(5, 0.5, 400)])[:, None]
    r = fit_gmm(x, 4, 2, config=cfg(min_iters=5, max_iters=30))
    assert_finite_result(r)
    assert r.ideal_num_clusters == 2
    np.testing.assert_allclose(np.sort(r.means.ravel()[:2]), [-5.0, 5.0],
                               atol=0.2)
    np.testing.assert_allclose(np.sort(r.weights[:2]), [0.4, 0.6], atol=0.05)


@pytest.mark.slow
def test_beyond_reference_envelope():
    """K and D past the reference's compile-time caps (MAX_CLUSTERS=512,
    NUM_DIMENSIONS=32, gaussian.h:10,16 -- its shared-memory sizing makes
    both HARD limits): runtime config here, so K=600 and D=64 just work."""
    rng = np.random.default_rng(4)
    # K > 512 (needs --max-clusters raised, like the reference would need a
    # recompile -- but no kernel limits behind it here)
    data = rng.normal(size=(1500, 4)).astype(np.float32)
    r = fit_gmm(data, 600, 599,
                config=cfg(min_iters=1, max_iters=1, chunk_size=512,
                           dtype="float32", max_clusters=600))
    assert_finite_result(r)
    assert r.ideal_num_clusters == 599
    # D > 32 (the reference's estep shared-memory staging caps D at 32)
    data = rng.normal(size=(1024, 64)).astype(np.float32)
    r = fit_gmm(data, 8, 8,
                config=cfg(min_iters=2, max_iters=2, chunk_size=256,
                           dtype="float32"))
    assert_finite_result(r)
    assert r.state.means.shape[1] == 64


@pytest.mark.slow
def test_reference_envelope_k512_d32():
    """The reference's first-class supported envelope -- MAX_CLUSTERS=512,
    NUM_DIMENSIONS=32 (gaussian.h:10,16) -- exercised end to end at small N
    on CPU: fit at K=512 plus one merge-scan step (target 511 forces the
    O(K^2) pair scan + merge through the full K=512 state). The TPU-scale
    characterization (1M events) is bench.py --config=6 / docs/PERF.md."""
    rng = np.random.default_rng(3)
    data = rng.normal(size=(2048, 32)).astype(np.float32)
    r = fit_gmm(data, 512, 511,
                config=cfg(min_iters=1, max_iters=1, chunk_size=512,
                           dtype="float32"))
    assert_finite_result(r)
    assert r.ideal_num_clusters == 511
    assert r.state.means.shape[1] == 32
