"""Profile-guided autotuner (ISSUE 17).

Acceptance contract: the tuning DB round-trips atomically and resolves
nearest-key within a (platform, device_kind, covariance, dtype) family;
explicitly-set knobs always win over the resolver; the microprobe ranks
candidates deterministically (fixed candidate order, stable tie-breaks);
tuned configs never change numerical results -- bit-parity when the
resolved knobs equal the defaults, the documented reduction-order
tolerance class otherwise -- across the plain, sharded, restart, and
serving paths; the v2.5 ``tune`` event is schema-pinned in both
directions; and the ``restart_batch_size`` auto cap respects the batched
Pallas path's per-lane VMEM blocks.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm, telemetry
from cuda_gmm_mpi_tpu.telemetry.schema import (EVENT_FIELDS,
                                               validate_record,
                                               validate_stream)
from cuda_gmm_mpi_tpu.tuning import (FIT_KNOBS, PROBEABLE, TuningDB,
                                     TuningKey, default_db_path,
                                     emit_decisions, explicit_knobs,
                                     pow2_bucket, probe_knob,
                                     resolve_fit_config_ex,
                                     resolve_serving_blocks)
from cuda_gmm_mpi_tpu.tuning import probe as probe_mod
from cuda_gmm_mpi_tpu.tuning.autotune import _platform_key

from .conftest import make_blobs


def _key(n=20000, d=16, k=8, cov="full", dtype="float32"):
    return TuningKey.for_shape("cpu", "cpu", n, d, k, cov, dtype)


# ------------------------------------------------------------------ db


def test_pow2_bucket_and_key_roundtrip():
    assert pow2_bucket(1) == 1
    assert pow2_bucket(4096) == 4096
    assert pow2_bucket(4097) == 8192
    k = _key()
    assert k.as_str() == "cpu|cpu|n32768|d16|k8|full|float32"
    assert TuningKey.from_str(k.as_str()) == k
    assert TuningKey.from_str("garbage") is None
    assert TuningKey.from_str("a|b|c|d|e|f|g") is None


def test_db_roundtrip_and_atomic_persistence(tmp_path):
    p = str(tmp_path / "tuning.json")
    db = TuningDB.open(p)
    assert db.entries == {} and db.load_error is None
    key = _key()
    db.record(key, "chunk_size", 4096, {"wall_per_iter_s": 0.02}, "probe")
    db.record(key, "chunk_size", 8192, {"wall_per_iter_s": 0.01}, "probe")
    db.save()
    # the file is well-formed, versioned JSON...
    raw = json.loads(open(p).read())
    assert raw["version"] == 1
    # ...and a fresh open reads back the argmin choice
    db2 = TuningDB.open(p)
    slot = db2.lookup(key, "chunk_size")
    assert slot["chosen"] == "8192" and slot["distance"] == 0.0
    assert set(slot["candidates"]) == {"4096", "8192"}


def test_db_chosen_ties_break_toward_smaller_candidate(tmp_path):
    db = TuningDB(str(tmp_path / "t.json"))
    key = _key()
    db.record(key, "chunk_size", 8192, {"wall_per_iter_s": 0.01})
    db.record(key, "chunk_size", 4096, {"wall_per_iter_s": 0.01})
    assert db.lookup(key, "chunk_size")["chosen"] == "4096"


def test_db_unreadable_or_alien_version_degrades_to_empty(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    db = TuningDB.open(str(p))
    assert db.entries == {} and "unreadable" in db.load_error
    p.write_text(json.dumps({"version": 999, "entries": {"x": {}}}))
    db = TuningDB.open(str(p))
    assert db.entries == {} and "version" in db.load_error


def test_nearest_key_resolution_stays_in_family(tmp_path):
    db = TuningDB(str(tmp_path / "t.json"))
    near = _key(n=40000)    # n65536: one octave from n32768
    far = _key(n=500000)    # n524288
    db.record(near, "chunk_size", 8192, {"wall_per_iter_s": 0.01})
    db.record(far, "chunk_size", 65536, {"wall_per_iter_s": 0.05})
    got = db.nearest(_key(), "chunk_size")
    assert got["chosen"] == "8192"
    assert got["key"] == near.as_str() and got["distance"] == 1.0
    # a different dtype/covariance is a different family: no transfer
    assert db.nearest(_key(dtype="float64"), "chunk_size") is None
    assert db.nearest(_key(cov="diag"), "chunk_size") is None
    # exact rows win over nearer neighbors
    db.record(_key(), "chunk_size", 2048, {"wall_per_iter_s": 0.02})
    assert db.nearest(_key(), "chunk_size")["chosen"] == "2048"


def test_default_db_path_env_precedence(monkeypatch, tmp_path):
    monkeypatch.setenv("GMM_TUNING_DB", str(tmp_path / "x.json"))
    assert default_db_path() == str(tmp_path / "x.json")
    monkeypatch.delenv("GMM_TUNING_DB")
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "cache"))
    assert default_db_path() == str(tmp_path / "cache" / "gmm"
                                    / "tuning.json")


# -------------------------------------------------------------- resolver


def test_autotune_field_validated():
    with pytest.raises(ValueError, match="autotune"):
        GMMConfig(autotune="always")


def test_explicit_knob_precedence(tmp_path, rng):
    """A user-pinned knob is never overwritten, even when the DB has a
    measured row saying otherwise."""
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    dbp = str(tmp_path / "t.json")
    db = TuningDB(dbp)
    cfg = GMMConfig(autotune="db", tuning_db=dbp, chunk_size=12345,
                    min_iters=2, max_iters=2)
    key = _platform_key(cfg, data.shape[0], data.shape[1], 3)
    db.record(key, "chunk_size", 256, {"wall_per_iter_s": 1e-6})
    db.save()
    assert "chunk_size" in explicit_knobs(cfg)
    resolved, decisions = resolve_fit_config_ex(cfg, data, 3)
    assert resolved.chunk_size == 12345
    assert resolved.autotune == "off"  # sub-fits must not re-resolve
    assert "chunk_size" not in {d["knob"] for d in decisions}


def test_resolver_prefers_db_row_over_static(tmp_path, rng):
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    dbp = str(tmp_path / "t.json")
    db = TuningDB(dbp)
    cfg = GMMConfig(autotune="db", tuning_db=dbp, min_iters=2,
                    max_iters=2)
    key = _platform_key(cfg, data.shape[0], data.shape[1], 3)
    db.record(key, "chunk_size", 256, {"wall_per_iter_s": 1e-6})
    db.save()
    resolved, decisions = resolve_fit_config_ex(cfg, data, 3)
    assert resolved.chunk_size == 256
    by_knob = {d["knob"]: d for d in decisions}
    assert by_knob["chunk_size"]["source"] == "db"
    assert by_knob["chunk_size"]["predicted_s"] == pytest.approx(1e-6)
    # knobs with no row fall down the ladder to the static model
    assert by_knob["estep_backend"]["source"] == "static"
    assert by_knob["estep_backend"]["chosen"] == "jnp"


def test_resolver_corrupt_db_row_falls_back_to_static(tmp_path, rng):
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    dbp = str(tmp_path / "t.json")
    db = TuningDB(dbp)
    cfg = GMMConfig(autotune="db", tuning_db=dbp, min_iters=2,
                    max_iters=2)
    key = _platform_key(cfg, data.shape[0], data.shape[1], 3)
    db.record(key, "chunk_size", "not-a-number", {"wall_per_iter_s": 0.1})
    db.save()
    _, decisions = resolve_fit_config_ex(cfg, data, 3)
    by_knob = {d["knob"]: d for d in decisions}
    assert by_knob["chunk_size"]["source"] == "static"


# ---------------------------------------------------------------- probe


def _fake_clock(walls):
    """A deterministic _time_fit: wall keyed by the candidate value the
    probe wrote into the config."""

    def fake(config, data, num_clusters):
        w = walls[config.chunk_size]
        return w + 0.5, w  # first call pays a fixed fake compile

    return fake


def test_probe_determinism_fixed_candidate_order(tmp_path, monkeypatch,
                                                 rng):
    data, _ = make_blobs(rng, n=5000, d=4, k=3, dtype=np.float32)
    walls = {1024: 0.04, 2048: 0.03, 4096: 0.01, 8192: 0.01}
    monkeypatch.setattr(probe_mod, "_time_fit", _fake_clock(walls))
    rows = []
    for i in range(2):
        db = TuningDB(str(tmp_path / f"t{i}.json"))
        key = _key(n=5000, d=4, k=3)
        slot = probe_knob(GMMConfig(), data, 3, key, db, "chunk_size",
                          iters=2, full_ladder=True)
        rows.append(slot)
    # identical ranking both runs, tie (4096 vs 8192) broken small
    assert rows[0]["chosen"] == rows[1]["chosen"] == "4096"
    assert list(rows[0]["candidates"]) == list(rows[1]["candidates"])
    prof = rows[0]["candidates"]["4096"]
    assert prof["wall_per_iter_s"] == pytest.approx(0.01 / 2)
    assert prof["compile_s"] == pytest.approx(0.5)
    assert prof["probe_iters"] == 2 and prof["flops"] > 0


def test_probe_skips_single_candidate_knobs(tmp_path, rng):
    """estep_backend off-TPU admits only jnp: the probe must answer None
    (static is free) instead of timing a foregone conclusion."""
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    db = TuningDB(str(tmp_path / "t.json"))
    got = probe_knob(GMMConfig(), data, 3, _key(n=500, d=4, k=3), db,
                     "estep_backend", iters=1)
    assert got is None and db.entries == {}


def test_probe_mode_records_rows_and_reuses_them(tmp_path, monkeypatch,
                                                 rng):
    """autotune='probe' measures missing rows once, persists them, and a
    second resolution reads the row back as a db hit.

    N=40000 so the bounded in-fit ladder (+/- 2 octaves around the
    incumbent 65536, clamped to the data) holds several candidates."""
    data, _ = make_blobs(rng, n=40000, d=4, k=3, dtype=np.float32)
    walls = {16384: 0.03, 32768: 0.01, 65536: 0.02, 131072: 0.04}
    monkeypatch.setattr(probe_mod, "_time_fit", _fake_clock(walls))
    dbp = str(tmp_path / "t.json")
    cfg = GMMConfig(autotune="probe", tuning_db=dbp, min_iters=2,
                    max_iters=2)
    resolved, decisions = resolve_fit_config_ex(cfg, data, 3)
    by_knob = {d["knob"]: d for d in decisions}
    assert by_knob["chunk_size"]["source"] == "probe"
    assert resolved.chunk_size == 32768
    assert os.path.exists(dbp)  # probe rows persist for the next run
    _, decisions2 = resolve_fit_config_ex(
        dataclasses.replace(cfg), data, 3)
    by_knob2 = {d["knob"]: d for d in decisions2}
    assert by_knob2["chunk_size"]["source"] == "db"
    assert by_knob2["chunk_size"]["chosen"] == 32768


# ----------------------------------------------------- parity matrix


def _fit_pair(data, k, base, tmp_path):
    """(off_result, tuned_result, tuned_config) for one parity leg."""
    dbp = str(tmp_path / "parity.json")
    off = fit_gmm(data, k, k, GMMConfig(**base))
    cfg = GMMConfig(autotune="db", tuning_db=dbp, **base)
    tuned_cfg, _ = resolve_fit_config_ex(cfg, data, k)
    tuned = fit_gmm(data, k, k, tuned_cfg)
    return off, tuned, tuned_cfg


def _assert_parity(off, tuned, tuned_cfg, base):
    """Bit-parity when every resolved knob equals the default; else the
    documented reduction-order class (float64 rel <= 1e-12)."""
    d0 = GMMConfig(**base)
    same_knobs = all(getattr(tuned_cfg, kn) == getattr(d0, kn)
                     for kn in FIT_KNOBS)
    if same_knobs:
        assert tuned.final_loglik == off.final_loglik
        np.testing.assert_array_equal(np.asarray(tuned.state.means),
                                      np.asarray(off.state.means))
    else:
        assert np.dtype(d0.dtype) == np.float64  # the <=1e-12 claim
        rel = abs(tuned.final_loglik - off.final_loglik) / abs(
            off.final_loglik)
        assert rel <= 1e-12
        # canonicalize component order: a restart sweep may return the
        # same mixture with its components permuted
        def canon(m):
            m = np.asarray(m)
            return m[np.lexsort(m.T[::-1])]

        np.testing.assert_allclose(canon(tuned.state.means),
                                   canon(off.state.means),
                                   rtol=1e-12, atol=1e-12)
    assert tuned.ideal_num_clusters == off.ideal_num_clusters


def test_parity_plain(rng, tmp_path):
    data, _ = make_blobs(rng, n=4000, d=5, k=3, dtype=np.float64)
    base = dict(dtype="float64", min_iters=4, max_iters=4, seed=0)
    _assert_parity(*_fit_pair(data, 3, base, tmp_path), base)


def test_parity_sharded(rng, tmp_path):
    data, _ = make_blobs(rng, n=4096, d=5, k=3, dtype=np.float64)
    base = dict(dtype="float64", min_iters=4, max_iters=4, seed=0,
                mesh_shape=(8, 1))
    _assert_parity(*_fit_pair(data, 3, base, tmp_path), base)


def test_parity_restarts(rng, tmp_path):
    data, _ = make_blobs(rng, n=2000, d=4, k=3, dtype=np.float64)
    base = dict(dtype="float64", min_iters=3, max_iters=3, seed=0,
                n_init=3)
    _assert_parity(*_fit_pair(data, 3, base, tmp_path), base)


def test_parity_serving_blocks_bit_identical(rng, tmp_path):
    """A tuned serving executor (different min/max block) scores the
    exact same bits: block geometry is padding, never math."""
    from cuda_gmm_mpi_tpu import GaussianMixture
    from cuda_gmm_mpi_tpu.serving.executor import (_shared_executor,
                                                   executor_for_config)

    data, _ = make_blobs(rng, n=600, d=4, k=3, dtype=np.float64)
    data = data.astype(np.float32)
    gm = GaussianMixture(3, target_components=3,
                         config=GMMConfig(min_iters=4, max_iters=4,
                                          chunk_size=256))
    gm.fit(data)
    state = gm.result_.state
    X = data[:333]

    dbp = str(tmp_path / "serve.json")
    db = TuningDB(dbp)
    skey = _platform_key(GMMConfig(), 65536, 4, 3)
    db.record(skey, "serve_min_block", 64, {"wall_per_iter_s": 0.01},
              source="bench")
    db.record(skey, "serve_max_block", 1024, {"wall_per_iter_s": 0.01},
              source="bench")
    db.save()
    blocks, decisions = resolve_serving_blocks("float32", False, 4, 3,
                                               tuning_db=dbp)
    assert blocks == {"min_block": 64, "max_block": 1024}
    assert {d["source"] for d in decisions} == {"db"}

    ex_default = executor_for_config(gm.config)
    ex_tuned = _shared_executor("float32", False, "expanded", "highest",
                                blocks["max_block"], blocks["min_block"])
    np.testing.assert_array_equal(ex_tuned.score_samples(state, X),
                                  ex_default.score_samples(state, X))


def test_serving_blocks_torn_pair_guard(tmp_path):
    """min_block > max_block from two stale rows must not build an
    impossible executor."""
    dbp = str(tmp_path / "serve.json")
    db = TuningDB(dbp)
    skey = _platform_key(GMMConfig(), 65536, 4, 3)
    db.record(skey, "serve_min_block", 4096, {"wall_per_iter_s": 0.01})
    db.record(skey, "serve_max_block", 512, {"wall_per_iter_s": 0.01})
    db.save()
    blocks, _ = resolve_serving_blocks("float32", False, 4, 3,
                                       tuning_db=dbp)
    assert blocks["min_block"] <= blocks["max_block"]


def test_autotune_off_emits_no_tune_events(rng, tmp_path):
    """The default path stays byte-identical: zero tune records."""
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    path = str(tmp_path / "m.jsonl")
    fit_gmm(data, 3, 3, GMMConfig(min_iters=2, max_iters=2,
                                  metrics_file=path))
    recs = [json.loads(ln) for ln in open(path)]
    assert validate_stream(recs) == []
    assert not any(r["event"] == "tune" for r in recs)


def test_autotune_db_emits_schema_valid_tune_events(rng, tmp_path):
    data, _ = make_blobs(rng, n=500, d=4, k=3, dtype=np.float32)
    path = str(tmp_path / "m.jsonl")
    fit_gmm(data, 3, 3, GMMConfig(autotune="db", min_iters=2,
                                  max_iters=2, metrics_file=path,
                                  tuning_db=str(tmp_path / "t.json")))
    recs = [json.loads(ln) for ln in open(path)]
    assert validate_stream(recs) == []
    tunes = [r for r in recs if r["event"] == "tune"]
    assert {t["knob"] for t in tunes} >= {"chunk_size", "estep_backend"}
    assert all(t["source"] in ("db", "probe", "static") for t in tunes)
    assert all(t["surface"] == "fit" for t in tunes)
    summary = recs[-1]
    assert summary["metrics"]["counters"]["tune_decisions"] == len(tunes)


# -------------------------------------------------------- schema drift


def test_tune_event_schema_pinned_both_directions():
    """v2.5 drift test: the declared shape is pinned here, and an
    emitted record must carry exactly what the schema declares."""
    required, optional = EVENT_FIELDS["tune"]
    assert set(required) == {"knob", "chosen", "source"}
    assert set(optional) == {"candidates", "predicted_s", "key",
                             "surface", "default", "distance"}

    stream = []

    class Sink:
        def write(self, line):
            stream.append(json.loads(line))

        def flush(self):
            pass

        def close(self):
            pass

    rec = telemetry.RunRecorder(stream=Sink())
    with telemetry.use(rec):
        emit_decisions([{
            "knob": "chunk_size", "chosen": 8192, "source": "db",
            "candidates": {"8192": 0.01}, "predicted_s": 0.01,
            "key": _key().as_str(), "default": 65536,
        }])
    tune = [r for r in stream if r["event"] == "tune"]
    assert len(tune) == 1
    assert validate_record(tune[0]) == []
    # ...and a record missing a required field / an undeclared event
    # kind both fail (the other drift direction -- emitting a kind the
    # schema never declared -- is covered stream-wide by
    # test_telemetry.test_every_emitted_event_kind_is_declared_in_schema)
    bad = dict(tune[0])
    del bad["source"]
    assert validate_record(bad)
    assert validate_record(dict(tune[0], event="tune_v2"))


def test_fit_knobs_are_probeable_or_resolvable():
    assert set(PROBEABLE) <= set(FIT_KNOBS)


# ---------------------------------------------- restart auto cap (VMEM)


def test_restart_auto_cap_accounts_for_pallas_vmem(monkeypatch):
    """Satellite: the batched Pallas path's per-lane VMEM blocks bound
    the restart batch; the jnp path keeps the host-memory-only cap."""
    from cuda_gmm_mpi_tpu.models.restarts import restart_batch_auto_cap

    jnp_cap = restart_batch_auto_cap(GMMConfig(), 20000, 32, 64)
    # a 1 MiB VMEM budget binds hard at D=32, K=64 full covariance
    monkeypatch.setenv("GMM_RESTART_VMEM_BYTES", str(1 << 20))
    pal_cap = restart_batch_auto_cap(
        GMMConfig(estep_backend="pallas"), 20000, 32, 64)
    assert 1 <= pal_cap < jnp_cap
    # per-lane bytes: f32 * (2*F*K + 2*D*K + 2*K + 2), F = D*D
    per_lane = 4 * (2 * 32 * 32 * 64 + 2 * 32 * 64 + 2 * 64 + 2)
    tile = 4 * GMMConfig().pallas_block_b * (32 + 1)
    assert pal_cap == max(1, ((1 << 20) - tile) // per_lane)
    # diag covariance shrinks F from D^2 to D: a larger cap fits
    diag_cap = restart_batch_auto_cap(
        GMMConfig(estep_backend="pallas", covariance_type="diag"),
        20000, 32, 64)
    assert diag_cap > pal_cap


# -------------------------------------------------------- diff gate


def test_diff_tune_regression_metric():
    """`gmm diff`'s default gate input: a measured wall/iter >20% over a
    db/probe prediction counts; static predictions and within-tolerance
    measurements never do; tune-free streams carry no tune.* metrics at
    all (the gate self-skips)."""
    from cuda_gmm_mpi_tpu.telemetry.diff import (DEFAULT_FAIL_ON,
                                                 summarize_run)

    assert "tune.regressions>0" in DEFAULT_FAIL_ON

    def stream(pred, source):
        return [
            {"event": "run_start", "run_id": "r", "path": "in-memory"},
            {"event": "tune", "knob": "chunk_size", "chosen": 2048,
             "source": source, "predicted_s": pred},
            # measured wall/iter = 10 / 10 = 1.0 s
            {"event": "run_summary", "wall_s": 10.0, "total_iters": 10},
        ]

    m = summarize_run(stream(0.5, "db"))["metrics"]     # 1.0 > 1.2*0.5
    assert m["tune.decisions"] == 1.0
    assert m["tune.regressions"] == 1.0
    m = summarize_run(stream(0.9, "db"))["metrics"]     # within 20%
    assert m["tune.regressions"] == 0.0
    m = summarize_run(stream(0.5, "static"))["metrics"]  # never gated
    assert m["tune.regressions"] == 0.0
    m = summarize_run(stream(0.5, "db")[:1] + stream(0.5, "db")[2:])
    assert "tune.regressions" not in m["metrics"]
