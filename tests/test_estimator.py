"""GaussianMixture estimator API: fit/predict/score/sample round trips."""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GaussianMixture, GMMConfig

from .conftest import make_blobs


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(7)
    centers = rng.normal(scale=10.0, size=(3, 3))
    labels = rng.integers(0, 3, size=600)
    data = (centers[labels] + rng.normal(size=(600, 3))).astype(np.float32)
    # Start above the true K and let the merge search reduce to 3: robust to
    # the deterministic seeding's local optima (the reference's own recommended
    # usage, README.txt:66-70 -- start high, give a target).
    gm = GaussianMixture(
        6, target_components=3, min_iters=12, max_iters=12, chunk_size=128
    )
    gm.fit(data)
    return gm, data, labels


def test_fit_attributes(fitted):
    gm, data, _ = fitted
    assert gm.n_components_ == 3
    assert gm.weights_.shape == (3,)
    np.testing.assert_allclose(gm.weights_.sum(), 1.0, rtol=1e-4)
    assert gm.means_.shape == (3, 3)
    assert gm.covariances_.shape == (3, 3, 3)
    assert np.isfinite(gm.loglik_)
    assert np.isfinite(gm.rissanen_)


def test_predict_recovers_blobs(fitted):
    """Hard assignments agree with ground-truth blob labels up to relabeling."""
    gm, data, labels = fitted
    pred = gm.predict(data)
    assert pred.shape == (600,)
    agree = 0
    for c in range(3):
        vals, counts = np.unique(pred[labels == c], return_counts=True)
        agree += counts.max()
    assert agree / len(labels) > 0.95


def test_from_summary_roundtrip(fitted, tmp_path):
    """.summary is a round-trippable model interchange: write the fitted
    model, reload it, and reproduce the hard assignments (means carry the
    format's 3-decimal precision, so posteriors agree approximately and
    well-separated hard labels exactly)."""
    from cuda_gmm_mpi_tpu.io.writers import write_summary

    gm, data, _ = fitted
    path = str(tmp_path / "model.summary")
    write_summary(path, gm.result_)
    gm2 = GaussianMixture.from_summary(path, chunk_size=128)
    assert gm2.n_components_ == gm.n_components_
    np.testing.assert_allclose(gm2.means_, gm.means_, atol=5e-4)
    np.testing.assert_allclose(gm2.weights_, gm.weights_, atol=1e-5)
    np.testing.assert_array_equal(gm2.predict(data), gm.predict(data))
    np.testing.assert_allclose(gm2.predict_proba(data),
                               gm.predict_proba(data), atol=5e-3)


def test_sklearn_params_interop(fitted):
    gm, data, _ = fitted
    p = gm.get_params()
    clone = GaussianMixture(**p)
    assert clone.n_components == gm.n_components
    assert clone.config == gm.config
    clone.set_params(n_components=4, min_iters=2, max_iters=2)
    assert clone.n_components == 4
    assert clone.config.min_iters == 2
    with pytest.raises(ValueError, match="unknown parameter"):
        clone.set_params(bogus=1)
    # the coupled diag_only flag must not snap an explicit covariance_type
    # update back to the old family
    gd = GaussianMixture(3, covariance_type="diag")
    gd.set_params(covariance_type="full")
    assert gd.config.covariance_type == "full"
    assert gd.config.diag_only is False
    gd.set_params(covariance_type="spherical")
    assert gd.config.covariance_type == "spherical"
    assert gd.config.diag_only is True
    # the symmetric direction: an explicit diag_only update wins too
    gd.set_params(diag_only=False)
    assert gd.config.covariance_type == "full"
    assert gd.config.diag_only is False
    gd.set_params(diag_only=True)
    assert gd.config.covariance_type == "diag"


def test_from_summary_diag_config_rejects_full_model(fitted, tmp_path):
    """Loading a full-covariance model under a diag config must error, not
    silently drop the off-diagonal terms."""
    from cuda_gmm_mpi_tpu.io.writers import write_summary

    gm, data, _ = fitted
    path = str(tmp_path / "full.summary")
    write_summary(path, gm.result_)
    with pytest.raises(ValueError, match="off-diagonals"):
        GaussianMixture.from_summary(path, diag_only=True)


def test_from_summary_family_guards(fitted, tmp_path):
    """spherical/tied configs get the same structural cross-check as diag:
    a model whose covariances don't satisfy the requested family must be
    rejected, not silently rescored under the wrong densities."""
    from cuda_gmm_mpi_tpu.io.writers import write_summary

    gm, data, _ = fitted
    path = str(tmp_path / "full.summary")
    write_summary(path, gm.result_)
    with pytest.raises(ValueError, match="spherical"):
        GaussianMixture.from_summary(path, covariance_type="spherical")
    with pytest.raises(ValueError, match="tied"):
        GaussianMixture.from_summary(path, covariance_type="tied")
    # A genuinely spherical/tied model loads under its own family.
    for family in ("spherical", "tied"):
        own = GaussianMixture(2, target_components=2, covariance_type=family,
                              min_iters=5, max_iters=5, chunk_size=128)
        own.fit(data)
        fpath = str(tmp_path / f"{family}.summary")
        write_summary(fpath, own.result_)
        back = GaussianMixture.from_summary(fpath, covariance_type=family)
        assert back.n_components_ == own.n_components_


def test_fit_predict_forwards_sample_weight(rng):
    """fit_predict(X, sample_weight=...) must reach fit(): the fitted model
    matches an explicit fit(X, sample_weight=...) exactly, and differs from
    the unweighted fit."""
    centers = np.array([[-8.0, -8.0], [8.0, 8.0]])
    labels = rng.integers(0, 2, 400)
    X = (centers[labels] + rng.normal(size=(400, 2))).astype(np.float32)
    w = rng.uniform(0.1, 4.0, size=400).astype(np.float32)
    kw = dict(target_components=2, min_iters=8, max_iters=8, chunk_size=128)
    ref = GaussianMixture(2, **kw).fit(X, sample_weight=w)
    gm = GaussianMixture(2, **kw)
    pred = gm.fit_predict(X, sample_weight=w)
    assert pred.shape == (400,)
    np.testing.assert_array_equal(np.asarray(gm.means_),
                                  np.asarray(ref.means_))
    unw = GaussianMixture(2, **kw).fit(X)
    assert np.abs(np.asarray(unw.means_) - np.asarray(gm.means_)).max() > 0


def test_means_init(rng):
    """User-supplied starting means (sklearn means_init): seeded exactly
    (modulo centering) and dominant over the seeding policy."""
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    centers = rng.normal(scale=8.0, size=(3, 4))
    data = (centers[rng.integers(0, 3, 600)]
            + rng.normal(size=(600, 4))).astype(np.float64)
    # 0 EM iterations isn't allowed by min_iters>=... use 1 iteration and
    # check convergence to the right assignment instead of exact means.
    gm = GaussianMixture(3, target_components=3, means_init=centers,
                         min_iters=8, max_iters=8, chunk_size=128,
                         dtype="float64").fit(data)
    # Means initialized at the true centers must stay matched to them
    # (no label permutation ambiguity to resolve).
    np.testing.assert_allclose(gm.means_, centers, atol=0.5)
    # shape mismatch is a clear error
    with pytest.raises(ValueError, match="init_means"):
        fit_gmm(data, 3, 3, GMMConfig(min_iters=1, max_iters=1,
                                      chunk_size=128, dtype="float64"),
                init_means=centers[:2])


def test_read_summary_fuzz_no_crash(tmp_path, rng):
    """Hostile/garbage .summary inputs raise ValueError (or parse), never
    crash with an unrelated exception or hang."""
    from cuda_gmm_mpi_tpu.io.readers import read_summary

    p = tmp_path / "fuzz.summary"
    fragments = ["Cluster #0\n", "Probability: 0.5\n", "N: nope\n",
                 "Means: 1.0 2.0 \n", "R Matrix:\n", "1.0 0.0 \n",
                 "\n", "::::\n", "Probability: \n", "Means:\n",
                 "R Matrix:\nx y\n"]
    for trial in range(30):
        n = rng.integers(1, 8)
        p.write_text("".join(
            fragments[i] for i in rng.integers(0, len(fragments), n)))
        try:
            read_summary(str(p))
        except ValueError:
            pass  # the documented failure mode


def test_from_summary_malformed(tmp_path):
    from cuda_gmm_mpi_tpu.io.readers import read_summary

    p = tmp_path / "bad.summary"
    p.write_text("this is not a model\n")
    with pytest.raises(ValueError, match="well-formed"):
        read_summary(str(p))
    # truncated block: Means present but R rows missing
    p.write_text("Cluster #0\nProbability: 0.5\nN: 10.0\n"
                 "Means: 1.000 2.000 \n\nR Matrix:\n1.000 0.000 \n")
    with pytest.raises(ValueError, match="R blocks"):
        read_summary(str(p))


def test_fit_predict_and_n_iter(fitted):
    gm, data, _ = fitted
    # n_iter_ reads the selected K's row of the sweep log; with min==max
    # iters the loop runs exactly that many (reference semantics).
    assert gm.n_iter_ == 12
    gm2 = GaussianMixture(3, target_components=3, min_iters=6, max_iters=6,
                          chunk_size=128)
    pred = gm2.fit_predict(data)
    assert pred.shape == (len(data),)
    np.testing.assert_array_equal(pred, gm2.predict(data))


def test_predict_proba_normalized(fitted):
    gm, data, _ = fitted
    w = gm.predict_proba(data[:100])
    assert w.shape == (100, 3)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, rtol=1e-4)


def test_score_samples_matches_loglik(fitted):
    """sum(score_samples(train)) equals the fit's final log-likelihood."""
    gm, data, _ = fitted
    z = gm.score_samples(data)
    np.testing.assert_allclose(z.sum(), gm.loglik_, rtol=1e-4)
    assert gm.score(data) == pytest.approx(z.mean(), rel=1e-6)


def test_sample_statistics(fitted):
    """Samples from the fitted mixture have ~the mixture's global mean, and
    sample() returns (X, y) exactly like sklearn's GaussianMixture."""
    gm, data, _ = fitted
    xs, ys = gm.sample(20000, seed=0)
    assert xs.shape == (20000, 3)
    assert ys.shape == (20000,) and ys.min() >= 0
    assert ys.max() < gm.n_components_
    global_mean = (gm.weights_[:, None] * gm.means_).sum(axis=0)
    np.testing.assert_allclose(xs.mean(axis=0), global_mean, atol=0.2)
    # Per-component: events labeled c were drawn from component c.
    for c in range(gm.n_components_):
        if (ys == c).sum() > 1000:
            np.testing.assert_allclose(xs[ys == c].mean(axis=0),
                                       gm.means_[c], atol=0.3)


def test_order_search_selects_k():
    rng = np.random.default_rng(3)
    data, _ = make_blobs(rng, n=800, d=2, k=3, dtype=np.float32)
    gm = GaussianMixture(6, min_iters=10, max_iters=10, chunk_size=256)
    gm.fit(data)
    assert 1 <= gm.n_components_ <= 6
    assert gm.result_.sweep_log  # searched multiple K


def test_unfitted_raises():
    gm = GaussianMixture(2)
    with pytest.raises(RuntimeError):
        gm.predict(np.zeros((4, 2), np.float32))


def test_config_exclusivity():
    with pytest.raises(ValueError):
        GaussianMixture(2, config=GMMConfig(), min_iters=5)


def test_bic_aic(fitted):
    from cuda_gmm_mpi_tpu.ops.formulas import n_free_params

    gm, data, _ = fitted
    n, d = data.shape
    ll = float(np.sum(gm.score_samples(data)))
    p = n_free_params(gm.n_components_, d)
    np.testing.assert_allclose(gm.bic(data), -2 * ll + p * np.log(n),
                               rtol=1e-12)
    np.testing.assert_allclose(gm.aic(data), -2 * ll + 2 * p, rtol=1e-12)
    # a 1-component fit of clearly multi-modal data must score worse
    gm1 = GaussianMixture(1, 1, config=gm.config).fit(data)
    assert gm1.bic(data) > gm.bic(data)


def test_bic_counts_diagonal_params(fitted):
    """Diagonal-covariance fits must count D variance params per cluster,
    not D(D+1)/2 (sklearn's covariance_type-aware convention)."""
    from cuda_gmm_mpi_tpu.ops.formulas import n_free_params

    _, data, _ = fitted
    n, d = data.shape
    gm = GaussianMixture(3, 3, min_iters=6, max_iters=6, chunk_size=128,
                         diag_only=True).fit(data)
    ll = float(np.sum(gm.score_samples(data)))
    p = n_free_params(3, d, diag_only=True)
    assert p == 3 * (1 + 2 * d) - 1
    np.testing.assert_allclose(gm.bic(data), -2 * ll + p * np.log(n),
                               rtol=1e-12)


def test_estimator_with_mesh_matches_plain(rng):
    """A mesh-sharded fit keeps its sharded model for inference: predict/
    predict_proba/score run on all local devices and match the plain
    estimator (round-3 closure of 'GaussianMixture.fit builds a plain
    GMMModel for all inference regardless of mesh_shape')."""
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel

    data, _ = make_blobs(rng, n=640, d=3, k=3, dtype=np.float64)
    kw = dict(min_iters=4, max_iters=4, chunk_size=64, dtype="float64")
    gm_p = GaussianMixture(3, target_components=3, **kw).fit(data)
    gm_s = GaussianMixture(3, target_components=3, mesh_shape=(4, 2),
                           **kw).fit(data)
    assert isinstance(gm_s._model, ShardedGMMModel)
    np.testing.assert_allclose(gm_s.predict_proba(data),
                               gm_p.predict_proba(data),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_array_equal(gm_s.predict(data), gm_p.predict(data))
    np.testing.assert_allclose(gm_s.score(data), gm_p.score(data), rtol=1e-10)
