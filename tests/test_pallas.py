"""Pallas fused E+M kernel vs the jnp reference path (interpret mode on CPU).

SURVEY.md SS4: 'kernel tests: Pallas kernels in interpret=True mode vs the jnp
reference implementation'.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.constants import compute_constants
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.mstep import accumulate_stats, apply_mstep
from cuda_gmm_mpi_tpu.ops.pallas import (
    resolve_estep_backend, should_use_pallas,
)
from cuda_gmm_mpi_tpu.ops.pallas.fused_stats import (
    fused_mstep_pallas, fused_stats_pallas, fused_stats_pallas_batched,
    fused_stats_pallas_sharded,
)
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

from .conftest import make_blobs
from .test_estep import make_state

pallas_interp = functools.partial(fused_stats_pallas, block_b=64,
                                  interpret=True)
pallas_batched_interp = functools.partial(fused_stats_pallas_batched,
                                          block_b=64, interpret=True)


def to_f32(state):
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32) if a.dtype != bool else a, state
    )


def test_fused_stats_matches_jnp(rng):
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    out = pallas_interp(state, chunks, wts)

    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_fused_stats_masking(rng):
    """Padded events and inactive clusters contribute exactly nothing."""
    k, d, n, b = 4, 3, 128, 64
    state = to_f32(make_state(rng, k, d, inactive=(2,)))
    data = rng.normal(size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts_np = np.ones((n // b, b), np.float32)
    wts_np[-1, 32:] = 0.0  # pad out the tail
    out = pallas_interp(state, chunks, jnp.asarray(wts_np))
    ref = accumulate_stats(state, chunks, jnp.asarray(wts_np),
                           matmul_precision="highest")
    assert float(out.Nk[2]) == 0.0
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)


def test_fused_stats_uneven_tiles(rng):
    """Event count not divisible by block_b: internal padding handles it."""
    k, d = 3, 3
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(size=(96, d)).astype(np.float32)  # 96 = 1.5 * 64
    chunks = jnp.asarray(data.reshape(2, 48, d))
    wts = jnp.ones((2, 48), jnp.float32)
    out = pallas_interp(state, chunks, wts)
    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_backend(rng):
    """Full EM through GMMModel with the kernel as stats backend."""
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32")
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=pallas_interp)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_fused_stats_diag_matches_jnp(rng):
    """DIAG_ONLY mode (gaussian_kernel.cu:215-223,430-433,621-628)."""
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))  # both paths read only diag(Rinv)
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, diag_only=True,
                           matmul_precision="highest")
    out = pallas_interp(state, chunks, wts, diag_only=True)

    assert out.M2.shape == (k, d)  # diagonal stats, like the jnp path
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_diag_backend(rng):
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32",
                    diag_only=True)
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=functools.partial(pallas_interp,
                                                     diag_only=True))
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_should_use_pallas_gating():
    assert not should_use_pallas(GMMConfig(use_pallas="never"))
    assert should_use_pallas(GMMConfig(use_pallas="always", diag_only=True))
    assert not should_use_pallas(GMMConfig(use_pallas="always",
                                           dtype="float64"))
    assert should_use_pallas(GMMConfig(use_pallas="always"))
    # Cluster-sharded: the 2-pass kernel covers diagonal covariance; full
    # covariance stays on the jnp collective-LSE path (matmul-bound).
    assert should_use_pallas(GMMConfig(use_pallas="always", diag_only=True),
                             cluster_sharded=True)
    assert not should_use_pallas(GMMConfig(use_pallas="always"),
                                 cluster_sharded=True)
    # 'auto' resolves to the jnp/XLA path everywhere: at matched matmul
    # precision XLA meets or beats the kernel at every measured shape
    # (docs/PERF.md round-3 precision study).
    assert not should_use_pallas(GMMConfig(use_pallas="auto"))
    assert not should_use_pallas(GMMConfig(use_pallas="auto",
                                           diag_only=True))
    # 'high' + kernel is a supported combination (manual 3-dot bf16_3x
    # decomposition in _kdot; Mosaic rejects only native Precision.HIGH).
    GMMConfig(use_pallas="always", matmul_precision="high")


def test_use_pallas_always_interprets_on_cpu(rng):
    """use_pallas='always' on a non-TPU backend auto-selects interpret mode
    (make_stats_fn), so the kernel path is drivable end-to-end everywhere."""
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    data, _ = make_blobs(rng, n=256, d=3, k=2, dtype=np.float32)
    kw = dict(min_iters=3, max_iters=3, chunk_size=64)
    r_kernel = fit_gmm(data, 2, 2, GMMConfig(use_pallas="always", **kw))
    r_xla = fit_gmm(data, 2, 2, GMMConfig(use_pallas="never", **kw))
    np.testing.assert_allclose(r_kernel.final_loglik, r_xla.final_loglik,
                               rtol=1e-4)
    np.testing.assert_allclose(np.sort(r_kernel.means, 0),
                               np.sort(r_xla.means, 0), rtol=1e-3, atol=1e-3)


def test_fused_stats_manual_bf16_3x_matches_xla_high(rng):
    """Kernel precision='high' (manual split dots) ~= XLA Precision.HIGH.

    Both compute ah.bh + ah.bl + al.bh in fp32, so they agree to bf16_3x
    rounding (~2^-16 relative) while 'default' (1-pass bf16) would be ~2^-8
    off -- the tolerance below separates the two regimes.
    """
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    exact = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    out = fused_stats_pallas(state, chunks, wts, block_b=64, interpret=True,
                             precision="high")
    np.testing.assert_allclose(float(out.loglik), float(exact.loglik),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(exact.M1),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(exact.M2),
                               rtol=5e-4, atol=5e-3)


# --------------------------------------------- batched (leading-R) kernel


def _stack_states(*states):
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)


@pytest.mark.parametrize("diag", [False, True])
@pytest.mark.parametrize("precision", ["highest", "high"])
def test_batched_kernel_matches_unbatched(rng, diag, precision):
    """The leading-R batched kernel is BIT-IDENTICAL per lane to the
    unbatched kernel (same tile math, the grid just gains a restart
    axis), across full/diag covariance, both supported precisions, and
    lanes with masked (inactive) clusters."""
    k, d, n, b = 5, 4, 256, 64
    s0 = to_f32(make_state(rng, k, d))
    s1 = to_f32(make_state(rng, k, d, inactive=(2, 4)))  # masked lanes
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts_np = np.ones((n // b, b), np.float32)
    wts_np[-1, 40:] = 0.0  # padded events
    wts = jnp.asarray(wts_np)

    out_b = pallas_batched_interp(_stack_states(s0, s1), chunks, wts,
                                  diag_only=diag, precision=precision)
    for r, s in enumerate((s0, s1)):
        out_u = pallas_interp(s, chunks, wts, diag_only=diag,
                              precision=precision)
        for name in ("loglik", "Nk", "M1", "M2"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out_b, name))[r],
                np.asarray(getattr(out_u, name)), err_msg=name)
    assert out_b.sanitized.shape == (2,)


@pytest.mark.parametrize("diag", [False, True])
def test_batched_kernel_matches_jnp(rng, diag):
    """Batched kernel vs the jnp fused pass, per lane: same SuffStats to
    f32 matmul-association tolerance (the two paths order the quadratic
    form differently, so exact bit-equality is the batched-vs-unbatched
    KERNEL contract above, not this one)."""
    k, d, n, b = 5, 4, 256, 64
    s0 = to_f32(make_state(rng, k, d))
    s1 = to_f32(make_state(rng, k, d, inactive=(1,)))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    out = pallas_batched_interp(_stack_states(s0, s1), chunks, wts,
                                diag_only=diag)
    for r, s in enumerate((s0, s1)):
        ref = accumulate_stats(s, chunks, wts, diag_only=diag,
                               matmul_precision="highest")
        np.testing.assert_allclose(float(out.loglik[r]), float(ref.loglik),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out.Nk)[r],
                                   np.asarray(ref.Nk), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out.M1)[r],
                                   np.asarray(ref.M1), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(out.M2)[r],
                                   np.asarray(ref.M2), rtol=1e-4, atol=1e-3)
        # the health-relevant scalar matches exactly (structurally zero
        # on both paths for finite inputs)
        assert int(out.sanitized[r]) == int(ref.sanitized)


def test_batched_kernel_lane_mask_freezes_lane(rng):
    """The per-lane freeze-out mask folds into the event mask: a frozen
    lane's every statistic (and loglik) is exactly zero while its
    siblings' are bit-identical to an unmasked run."""
    k, d, n, b = 4, 3, 128, 64
    s0 = to_f32(make_state(rng, k, d))
    s1 = to_f32(make_state(rng, k, d))
    states = _stack_states(s0, s1)
    data = rng.normal(size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    full = pallas_batched_interp(states, chunks, wts)
    masked = pallas_batched_interp(states, chunks, wts,
                                   lane_mask=jnp.asarray([0.0, 1.0]))
    for name in ("loglik", "Nk", "M1", "M2"):
        a = np.asarray(getattr(masked, name))
        assert np.all(a[0] == 0.0), name
        np.testing.assert_array_equal(a[1],
                                      np.asarray(getattr(full, name))[1],
                                      err_msg=name)


# ----------------------------------------------- fused M-step epilogue


@pytest.mark.parametrize("diag", [False, True])
def test_fused_mstep_matches_apply_mstep(rng, diag):
    """Kernel epilogue + constants == jitted apply_mstep, BIT-IDENTICAL
    (same expressions through the same XLA ops in interpret mode),
    including the empty-cluster guards: one empty lane (Nk=0), one in
    the (0.5, 1) dead zone, and a nonzero variance floor."""
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d)).replace(
        avgvar=jnp.asarray(rng.uniform(0.01, 0.1, size=(k,)), jnp.float32))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)
    stats = accumulate_stats(state, chunks, wts, diag_only=diag,
                             matmul_precision="highest")
    # Force the guard branches: lane 3 empty, lane 4 in the dead zone.
    stats = dataclasses_replace_stats(stats, Nk=stats.Nk.at[3].set(0.0)
                                      .at[4].set(0.7))

    s_ref = jax.jit(functools.partial(apply_mstep, diag_only=diag))(
        state, stats)
    s_ker = jax.jit(functools.partial(
        lambda s, st: compute_constants(
            fused_mstep_pallas(s, st, diag_only=diag, interpret=True),
            diag_only=diag)))(state, stats)
    for name in ("N", "means", "R", "Rinv", "constant", "pi"):
        np.testing.assert_array_equal(np.asarray(getattr(s_ker, name)),
                                      np.asarray(getattr(s_ref, name)),
                                      err_msg=name)


def dataclasses_replace_stats(stats, **kw):
    import dataclasses

    return dataclasses.replace(stats, **kw)


# ------------------------------------- batched EM loop on the kernel path


def _pallas_cfg(**kw):
    base = dict(min_iters=4, max_iters=4, chunk_size=128,
                pallas_block_b=64, dtype="float32")
    base.update(kw)
    return GMMConfig(estep_backend="pallas", **base)


@pytest.mark.parametrize("diag", [False, True])
def test_em_batched_pallas_matches_unbatched_pallas(rng, diag):
    """run_em_batched on the kernel path (em_while_loop_batched: one
    batched kernel round-trip per iteration) is BIT-IDENTICAL per lane
    to run_em on the unbatched kernel path -- the drivers must not be
    able to tell the two loops apart except by speed."""
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    m = GMMModel(_pallas_cfg(diag_only=diag))
    assert m.batched_stats_fn is not None  # kernel path actually selected
    chunks, wts = map(jnp.asarray, chunk_events(data, 128))
    eps = convergence_epsilon(*data.shape)
    s0 = seed_clusters_host(data, 3)
    s1 = seed_clusters_host(data[::-1].copy(), 3)
    batched = _stack_states(s0, s1)
    out_b, ll_b, it_b = m.run_em_batched(batched, chunks, wts, eps)
    h_b = np.asarray(jax.device_get(m.last_health))
    assert h_b.shape[0] == 2
    for r, s in enumerate((s0, s1)):
        s_u, ll_u, it_u = m.run_em(s, chunks, wts, eps)
        h_u = np.asarray(jax.device_get(m.last_health))
        assert int(it_u) == int(np.asarray(it_b)[r])
        np.testing.assert_array_equal(np.asarray(ll_b)[r], np.asarray(ll_u))
        np.testing.assert_array_equal(np.asarray(out_b.means)[r],
                                      np.asarray(s_u.means))
        np.testing.assert_array_equal(np.asarray(out_b.R)[r],
                                      np.asarray(s_u.R))
        # health flags: per-lane rows equal the solo runs' exactly
        np.testing.assert_array_equal(h_b[r], h_u)


def test_em_batched_pallas_matches_jnp_loop(rng):
    """Kernel-path batched EM vs the vmapped jnp batched EM: same model
    to f32 tolerance, same iteration counts, same (clean) health rows."""
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    chunks, wts = map(jnp.asarray, chunk_events(data, 128))
    eps = convergence_epsilon(*data.shape)
    batched = _stack_states(seed_clusters_host(data, 3),
                            seed_clusters_host(data[::-1].copy(), 3))
    m_pal = GMMModel(_pallas_cfg())
    m_jnp = GMMModel(GMMConfig(estep_backend="jnp", min_iters=4,
                               max_iters=4, chunk_size=128,
                               dtype="float32"))
    out_p, ll_p, it_p = m_pal.run_em_batched(batched, chunks, wts, eps)
    h_p = np.asarray(jax.device_get(m_pal.last_health))
    out_j, ll_j, it_j = m_jnp.run_em_batched(batched, chunks, wts, eps)
    h_j = np.asarray(jax.device_get(m_jnp.last_health))
    np.testing.assert_array_equal(np.asarray(it_p), np.asarray(it_j))
    np.testing.assert_allclose(np.asarray(ll_p), np.asarray(ll_j),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out_p.means),
                               np.asarray(out_j.means),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_array_equal(h_p, h_j)


def test_em_batched_pallas_freeze_out(rng):
    """max_iters=0 lanes pass through bit-identically on the kernel path
    (the explicit loop's masked freeze-out == the vmapped loop's)."""
    data, _ = make_blobs(rng, n=256, d=3, k=2, dtype=np.float32)
    m = GMMModel(_pallas_cfg(min_iters=1, max_iters=8))
    chunks, wts = map(jnp.asarray, chunk_events(data, 128))
    eps = convergence_epsilon(*data.shape)
    s0 = seed_clusters_host(data, 2)
    batched = _stack_states(s0, s0)
    out, ll, it = m.run_em_batched(batched, chunks, wts, eps,
                                   max_iters=np.asarray([0, 8], np.int32))
    it = np.asarray(it)
    assert it[0] == 0 and it[1] >= 1
    np.testing.assert_array_equal(np.asarray(out.means)[0],
                                  np.asarray(jnp.asarray(s0.means)))


def test_r_bucket_pads_and_slices(rng):
    """run_em_batched(r_bucket=4) on an R=2 batch returns R=2 outputs
    whose live lanes ran the same iteration counts, and reuses the
    4-lane executable for both shapes (one trace)."""
    data, _ = make_blobs(rng, n=256, d=3, k=2, dtype=np.float32)
    m = GMMModel(_pallas_cfg())
    chunks, wts = map(jnp.asarray, chunk_events(data, 128))
    eps = convergence_epsilon(*data.shape)
    lanes4 = [seed_clusters_host(np.roll(data, i, 0), 2) for i in range(4)]
    b4 = _stack_states(*lanes4)
    b2 = _stack_states(*lanes4[:2])
    out4, ll4, it4 = m.run_em_batched(b4, chunks, wts, eps, r_bucket=4)
    out2, ll2, it2 = m.run_em_batched(b2, chunks, wts, eps, r_bucket=4)
    assert np.asarray(ll2).shape == (2,)
    assert np.asarray(jax.device_get(m.last_health)).shape[0] == 2
    np.testing.assert_array_equal(np.asarray(it2), np.asarray(it4)[:2])
    np.testing.assert_array_equal(np.asarray(ll2), np.asarray(ll4)[:2])
    key = ("batched", 0, False)
    fn = m._em_exec_cache[key]
    assert fn._cache_size() == 1  # both calls served by ONE trace


# ----------------------------------------------------- backend routing


def test_resolve_estep_backend():
    # 'auto' and legacy 'never' route to jnp with a reason
    b, why = resolve_estep_backend(GMMConfig())
    assert b == "jnp" and why
    b, _ = resolve_estep_backend(GMMConfig(use_pallas="never"))
    assert b == "jnp"
    # explicit kernel request off-TPU resolves to interpret mode
    b, why = resolve_estep_backend(GMMConfig(estep_backend="pallas"))
    assert b == "pallas-interpret" and "interpret" in why
    # structural fallbacks carry their cause
    b, why = resolve_estep_backend(
        GMMConfig(estep_backend="pallas", dtype="float64"))
    assert b == "jnp" and "float32" in why
    b, why = resolve_estep_backend(
        GMMConfig(estep_backend="pallas"), cluster_sharded=True)
    assert b == "jnp" and "cluster-sharded" in why
    b, _ = resolve_estep_backend(
        GMMConfig(estep_backend="pallas", diag_only=True),
        cluster_sharded=True)
    assert b == "pallas-interpret"


def test_estep_backend_use_pallas_coherence():
    # the two spellings are one setting
    assert GMMConfig(use_pallas="always").estep_backend == "pallas"
    assert GMMConfig(use_pallas="never").estep_backend == "jnp"
    assert GMMConfig(estep_backend="pallas").use_pallas == "always"
    assert GMMConfig(estep_backend="jnp").use_pallas == "never"
    with pytest.raises(ValueError, match="contradicts"):
        GMMConfig(estep_backend="pallas", use_pallas="never")
    with pytest.raises(ValueError, match="contradicts"):
        GMMConfig(estep_backend="jnp", use_pallas="always")
    with pytest.raises(ValueError, match="estep_backend"):
        GMMConfig(estep_backend="sometimes")
    # kernel + streaming stays rejected through the new spelling too
    with pytest.raises(ValueError, match="use_pallas"):
        GMMConfig(estep_backend="pallas", stream_events=True)


def test_em_backend_in_telemetry_stream(rng, tmp_path):
    """run_start/run_summary carry which backend ACTUALLY ran (a silent
    fallback is observable), and the stream stays schema-valid."""
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream

    data, _ = make_blobs(rng, n=256, d=3, k=2, dtype=np.float32)
    kw = dict(min_iters=2, max_iters=2, chunk_size=64, pallas_block_b=64,
              dtype="float32")
    mf = str(tmp_path / "pal.jsonl")
    fit_gmm(data, 2, 2, GMMConfig(estep_backend="pallas",
                                  metrics_file=mf, **kw))
    recs = read_stream(mf)
    assert not validate_stream(recs)
    starts = [r for r in recs if r["event"] == "run_start"]
    summaries = [r for r in recs if r["event"] == "run_summary"]
    assert starts and starts[0]["em_backend"] == "pallas-interpret"
    assert summaries and summaries[0]["em_backend"] == "pallas-interpret"

    mf2 = str(tmp_path / "jnp.jsonl")
    fit_gmm(data, 2, 2, GMMConfig(metrics_file=mf2, **kw))
    recs2 = read_stream(mf2)
    s2 = [r for r in recs2 if r["event"] == "run_start"][0]
    assert s2["em_backend"] == "jnp"
    assert s2["em_backend_reason"]  # the fallback reason rides along


sharded_interp = functools.partial(
    fused_stats_pallas_sharded, block_b=64, interpret=True,
    cluster_axis="cluster",
)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8)])
@pytest.mark.parametrize("diag", [True, False])
def test_sharded_kernel_matches_single(rng, mesh_shape, diag):
    """2-pass cluster-sharded kernel under shard_map == unsharded EM.

    The cross-device generalization of estep1's per-cluster grid axis
    (gaussian_kernel.cu:383): parity on (2,4) and (1,8) meshes, full and
    diagonal covariance, through a real multi-iteration EM loop.
    """
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel, make_mesh

    data, _ = make_blobs(rng, n=1024, d=3, k=5, dtype=np.float32)
    k = 5
    cfg32 = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                      dtype="float32", diag_only=diag)

    # Unsharded reference (jnp path, float32 to match the kernel dtype).
    m_ref = GMMModel(cfg32)
    chunks, wts = chunk_events(data, cfg32.chunk_size)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(
        state, jnp.asarray(chunks), jnp.asarray(wts), eps)

    cfg_mesh = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                         dtype="float32", diag_only=diag,
                         mesh_shape=mesh_shape)
    model = ShardedGMMModel(
        cfg_mesh, stats_fn=functools.partial(sharded_interp, diag_only=diag))
    chunks_s, wts_s = chunk_events(data, cfg_mesh.chunk_size, model.data_size)
    state_s = seed_clusters_host(data, k)
    state_s, chunks_s, wts_s = model.prepare(state_s, chunks_s, wts_s)
    s_sh, ll_sh, _ = model.run_em(state_s, chunks_s, wts_s, eps)

    np.testing.assert_allclose(float(ll_sh), float(ll_ref), rtol=1e-5)
    kp = np.asarray(s_ref.means).shape[0]
    np.testing.assert_allclose(np.asarray(s_sh.means)[:kp],
                               np.asarray(s_ref.means), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s_sh.N)[:kp], np.asarray(s_ref.N),
                               rtol=1e-4, atol=1e-3)


def test_sharded_kernel_padded_clusters(rng):
    """K not divisible by the cluster axis: the padded shard's all-masked
    tail must contribute exactly nothing through the collective LSE."""
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel

    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=3, max_iters=3, chunk_size=128,
                    dtype="float32", mesh_shape=(1, 8), diag_only=True)
    model = ShardedGMMModel(
        cfg, stats_fn=functools.partial(sharded_interp, diag_only=True))
    chunks, wts = chunk_events(data, cfg.chunk_size, model.data_size)
    state = seed_clusters_host(data, 3)  # K=3 padded to 8
    state, chunks, wts = model.prepare(state, chunks, wts)
    eps = convergence_epsilon(*data.shape)
    s_sh, ll_sh, _ = model.run_em(state, chunks, wts, eps)

    m_ref = GMMModel(GMMConfig(min_iters=3, max_iters=3, chunk_size=128,
                               dtype="float32", diag_only=True))
    chunks_r, wts_r = chunk_events(data, 128)
    s_ref, ll_ref, _ = m_ref.run_em(
        seed_clusters_host(data, 3), jnp.asarray(chunks_r),
        jnp.asarray(wts_r), eps)
    np.testing.assert_allclose(float(ll_sh), float(ll_ref), rtol=1e-5)
    act = np.asarray(s_sh.active)
    assert act[:3].all() and not act[3:].any()
    assert np.asarray(s_sh.N)[3:].max() == 0.0
