"""Pallas fused E+M kernel vs the jnp reference path (interpret mode on CPU).

SURVEY.md SS4: 'kernel tests: Pallas kernels in interpret=True mode vs the jnp
reference implementation'.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.mstep import accumulate_stats
from cuda_gmm_mpi_tpu.ops.pallas import should_use_pallas
from cuda_gmm_mpi_tpu.ops.pallas.fused_stats import (
    fused_stats_pallas, fused_stats_pallas_sharded,
)
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

from .conftest import make_blobs
from .test_estep import make_state

pallas_interp = functools.partial(fused_stats_pallas, block_b=64,
                                  interpret=True)


def to_f32(state):
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32) if a.dtype != bool else a, state
    )


def test_fused_stats_matches_jnp(rng):
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    out = pallas_interp(state, chunks, wts)

    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_fused_stats_masking(rng):
    """Padded events and inactive clusters contribute exactly nothing."""
    k, d, n, b = 4, 3, 128, 64
    state = to_f32(make_state(rng, k, d, inactive=(2,)))
    data = rng.normal(size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts_np = np.ones((n // b, b), np.float32)
    wts_np[-1, 32:] = 0.0  # pad out the tail
    out = pallas_interp(state, chunks, jnp.asarray(wts_np))
    ref = accumulate_stats(state, chunks, jnp.asarray(wts_np),
                           matmul_precision="highest")
    assert float(out.Nk[2]) == 0.0
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)


def test_fused_stats_uneven_tiles(rng):
    """Event count not divisible by block_b: internal padding handles it."""
    k, d = 3, 3
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(size=(96, d)).astype(np.float32)  # 96 = 1.5 * 64
    chunks = jnp.asarray(data.reshape(2, 48, d))
    wts = jnp.ones((2, 48), jnp.float32)
    out = pallas_interp(state, chunks, wts)
    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_backend(rng):
    """Full EM through GMMModel with the kernel as stats backend."""
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32")
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=pallas_interp)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_fused_stats_diag_matches_jnp(rng):
    """DIAG_ONLY mode (gaussian_kernel.cu:215-223,430-433,621-628)."""
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))  # both paths read only diag(Rinv)
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, diag_only=True,
                           matmul_precision="highest")
    out = pallas_interp(state, chunks, wts, diag_only=True)

    assert out.M2.shape == (k, d)  # diagonal stats, like the jnp path
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_diag_backend(rng):
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32",
                    diag_only=True)
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=functools.partial(pallas_interp,
                                                     diag_only=True))
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_should_use_pallas_gating():
    assert not should_use_pallas(GMMConfig(use_pallas="never"))
    assert should_use_pallas(GMMConfig(use_pallas="always", diag_only=True))
    assert not should_use_pallas(GMMConfig(use_pallas="always",
                                           dtype="float64"))
    assert should_use_pallas(GMMConfig(use_pallas="always"))
    # Cluster-sharded: the 2-pass kernel covers diagonal covariance; full
    # covariance stays on the jnp collective-LSE path (matmul-bound).
    assert should_use_pallas(GMMConfig(use_pallas="always", diag_only=True),
                             cluster_sharded=True)
    assert not should_use_pallas(GMMConfig(use_pallas="always"),
                                 cluster_sharded=True)
    # 'auto' resolves to the jnp/XLA path everywhere: at matched matmul
    # precision XLA meets or beats the kernel at every measured shape
    # (docs/PERF.md round-3 precision study).
    assert not should_use_pallas(GMMConfig(use_pallas="auto"))
    assert not should_use_pallas(GMMConfig(use_pallas="auto",
                                           diag_only=True))
    # 'high' + kernel is a supported combination (manual 3-dot bf16_3x
    # decomposition in _kdot; Mosaic rejects only native Precision.HIGH).
    GMMConfig(use_pallas="always", matmul_precision="high")


def test_use_pallas_always_interprets_on_cpu(rng):
    """use_pallas='always' on a non-TPU backend auto-selects interpret mode
    (make_stats_fn), so the kernel path is drivable end-to-end everywhere."""
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    data, _ = make_blobs(rng, n=256, d=3, k=2, dtype=np.float32)
    kw = dict(min_iters=3, max_iters=3, chunk_size=64)
    r_kernel = fit_gmm(data, 2, 2, GMMConfig(use_pallas="always", **kw))
    r_xla = fit_gmm(data, 2, 2, GMMConfig(use_pallas="never", **kw))
    np.testing.assert_allclose(r_kernel.final_loglik, r_xla.final_loglik,
                               rtol=1e-4)
    np.testing.assert_allclose(np.sort(r_kernel.means, 0),
                               np.sort(r_xla.means, 0), rtol=1e-3, atol=1e-3)


def test_fused_stats_manual_bf16_3x_matches_xla_high(rng):
    """Kernel precision='high' (manual split dots) ~= XLA Precision.HIGH.

    Both compute ah.bh + ah.bl + al.bh in fp32, so they agree to bf16_3x
    rounding (~2^-16 relative) while 'default' (1-pass bf16) would be ~2^-8
    off -- the tolerance below separates the two regimes.
    """
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    exact = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    out = fused_stats_pallas(state, chunks, wts, block_b=64, interpret=True,
                             precision="high")
    np.testing.assert_allclose(float(out.loglik), float(exact.loglik),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(exact.M1),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(exact.M2),
                               rtol=5e-4, atol=5e-3)


sharded_interp = functools.partial(
    fused_stats_pallas_sharded, block_b=64, interpret=True,
    cluster_axis="cluster",
)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8)])
@pytest.mark.parametrize("diag", [True, False])
def test_sharded_kernel_matches_single(rng, mesh_shape, diag):
    """2-pass cluster-sharded kernel under shard_map == unsharded EM.

    The cross-device generalization of estep1's per-cluster grid axis
    (gaussian_kernel.cu:383): parity on (2,4) and (1,8) meshes, full and
    diagonal covariance, through a real multi-iteration EM loop.
    """
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel, make_mesh

    data, _ = make_blobs(rng, n=1024, d=3, k=5, dtype=np.float32)
    k = 5
    cfg32 = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                      dtype="float32", diag_only=diag)

    # Unsharded reference (jnp path, float32 to match the kernel dtype).
    m_ref = GMMModel(cfg32)
    chunks, wts = chunk_events(data, cfg32.chunk_size)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(
        state, jnp.asarray(chunks), jnp.asarray(wts), eps)

    cfg_mesh = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                         dtype="float32", diag_only=diag,
                         mesh_shape=mesh_shape)
    model = ShardedGMMModel(
        cfg_mesh, stats_fn=functools.partial(sharded_interp, diag_only=diag))
    chunks_s, wts_s = chunk_events(data, cfg_mesh.chunk_size, model.data_size)
    state_s = seed_clusters_host(data, k)
    state_s, chunks_s, wts_s = model.prepare(state_s, chunks_s, wts_s)
    s_sh, ll_sh, _ = model.run_em(state_s, chunks_s, wts_s, eps)

    np.testing.assert_allclose(float(ll_sh), float(ll_ref), rtol=1e-5)
    kp = np.asarray(s_ref.means).shape[0]
    np.testing.assert_allclose(np.asarray(s_sh.means)[:kp],
                               np.asarray(s_ref.means), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s_sh.N)[:kp], np.asarray(s_ref.N),
                               rtol=1e-4, atol=1e-3)


def test_sharded_kernel_padded_clusters(rng):
    """K not divisible by the cluster axis: the padded shard's all-masked
    tail must contribute exactly nothing through the collective LSE."""
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel

    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=3, max_iters=3, chunk_size=128,
                    dtype="float32", mesh_shape=(1, 8), diag_only=True)
    model = ShardedGMMModel(
        cfg, stats_fn=functools.partial(sharded_interp, diag_only=True))
    chunks, wts = chunk_events(data, cfg.chunk_size, model.data_size)
    state = seed_clusters_host(data, 3)  # K=3 padded to 8
    state, chunks, wts = model.prepare(state, chunks, wts)
    eps = convergence_epsilon(*data.shape)
    s_sh, ll_sh, _ = model.run_em(state, chunks, wts, eps)

    m_ref = GMMModel(GMMConfig(min_iters=3, max_iters=3, chunk_size=128,
                               dtype="float32", diag_only=True))
    chunks_r, wts_r = chunk_events(data, 128)
    s_ref, ll_ref, _ = m_ref.run_em(
        seed_clusters_host(data, 3), jnp.asarray(chunks_r),
        jnp.asarray(wts_r), eps)
    np.testing.assert_allclose(float(ll_sh), float(ll_ref), rtol=1e-5)
    act = np.asarray(s_sh.active)
    assert act[:3].all() and not act[3:].any()
    assert np.asarray(s_sh.N)[3:].max() == 0.0
